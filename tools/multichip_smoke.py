"""multichip-smoke: the live mesh path's boot gate (`make multichip-smoke`).

Self-provisions a virtual multi-device CPU mesh (the same
``--xla_force_host_platform_device_count`` re-exec dance as
__graft_entry__.dryrun_multichip — jax may already be pinned to an axon
tunnel by sitecustomize, so the child prepares its environment before
jax initialises) and drives ONE REAL BLOCK through the live
prepare→process proposal lifecycle with the mesh configured
(CELESTIA_TPU_MESH) and tracing armed.  Asserts:

* the block committed through the SHARDED path: the prepare trace
  carries the ``extend.sharded`` host span with the mesh factoring in
  its args, and the EDS cache (content-addressed, leg-agnostic) served
  the process leg warm;
* the merged Chrome trace is schema-valid and contains the sharded
  dispatch span (``device.extend_sharded``) on >= 2 DISTINCT per-chip
  device tracks (``device:<platform>:<id>`` thread_name metadata) —
  device occupancy across chips is a measured number, not a guess;
* the mesh provider reports the sharded extend in its stats.

Exit 0 + one summary JSON line on success; non-zero with the reason on
any failure.  Runs entirely on the CPU backend (no device required).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_DEVICES = 4
MESH_SPEC = "1x2"  # 2 row shards -> 2 distinct device tracks


def parent() -> int:
    from celestia_tpu.utils.device import force_host_devices_env

    env = force_host_devices_env(dict(os.environ), N_DEVICES)
    # opt level 0: the shard_map compile is structure-bound XLA wall;
    # the programs are integer-only, so the level cannot change bytes
    # (and the dryrun/byte-identity gates would catch it if it could)
    if "--xla_backend_optimization_level" not in env["XLA_FLAGS"]:
        env["XLA_FLAGS"] += " --xla_backend_optimization_level=0"
    env["CELESTIA_TPU_MESH"] = MESH_SPEC
    env["_MULTICHIP_SMOKE_CHILD"] = "1"
    env.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__)], cwd=REPO, env=env,
        timeout=600,
    )
    return proc.returncode


def child() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from celestia_tpu.client.signer import Signer
    from celestia_tpu.da import eds_cache
    from celestia_tpu.da.blob import Blob, BlobTx
    from celestia_tpu.da.inclusion import create_commitment
    from celestia_tpu.da.namespace import Namespace
    from celestia_tpu.node.testnode import TestNode
    from celestia_tpu.parallel import mesh as mesh_mod
    from celestia_tpu.state.tx import MsgPayForBlobs
    from celestia_tpu.utils import tracing
    from celestia_tpu.utils.secp256k1 import PrivateKey

    if len(jax.devices()) < N_DEVICES:
        print(
            f"multichip-smoke: device provisioning failed: "
            f"{jax.devices()}",
            file=sys.stderr,
        )
        return 1
    m = mesh_mod.device_mesh()
    if m is None:
        print(
            f"multichip-smoke: mesh did not resolve: {mesh_mod.stats()}",
            file=sys.stderr,
        )
        return 1

    tracing.enable(4)
    tracing.clear()
    eds_cache.clear()
    key = PrivateKey.from_seed(b"multichip-smoke")
    node = TestNode(funded_accounts=[(key, 10**12)], auto_produce=False)
    signer = Signer(node, key)
    # a small blob: the square must land at k >= 2 so the row axis can
    # shard it (a bare MsgSend block is the k=1 min square — the
    # fallback path, deliberately NOT what this gate proves)
    ns = Namespace.v0(b"\x33" * 10)
    blob = Blob(ns, b"\x42" * 600)
    msg = MsgPayForBlobs(
        signer=signer.address,
        namespaces=(ns.raw,),
        blob_sizes=(len(blob.data),),
        share_commitments=(create_commitment(blob),),
        share_versions=(0,),
    )
    tx = signer.sign_tx([msg], gas_limit=2_000_000, sequence=0)
    res = node.broadcast_tx(BlobTx(tx.marshal(), [blob]).marshal())
    if res.code != 0:
        print(f"multichip-smoke: broadcast failed: {res.log}", file=sys.stderr)
        return 1
    # one REAL block: reap -> PrepareProposal -> ProcessProposal ->
    # commit, with the extend routed through the mesh
    node.produce_block()

    app = node.app
    if app.telemetry.counters.get("extend_sharded", 0) < 1:
        print(
            f"multichip-smoke: no sharded extend on the live path "
            f"(counters: {dict(app.telemetry.counters)}, "
            f"mesh: {mesh_mod.stats()})",
            file=sys.stderr,
        )
        return 1
    if app.telemetry.counters.get("eds_cache_hit_process", 0) < 1:
        print(
            "multichip-smoke: process leg did not hit the mesh-warmed "
            "EDS cache",
            file=sys.stderr,
        )
        return 1

    traces = tracing.block_traces()
    prep = [t for t in traces if t.name == "prepare_proposal"]
    if not prep:
        print("multichip-smoke: no prepare trace", file=sys.stderr)
        return 1
    prep = prep[-1]
    sharded_spans = [s for s in prep.spans if s.name == "extend.sharded"]
    if not sharded_spans:
        print(
            f"multichip-smoke: no extend.sharded span "
            f"(spans: {sorted({s.name for s in prep.spans})})",
            file=sys.stderr,
        )
        return 1
    args = getattr(sharded_spans[0], "args", {}) or {}
    if args.get("mesh_row") != 2:
        print(
            f"multichip-smoke: extend.sharded span lacks mesh args: {args}",
            file=sys.stderr,
        )
        return 1
    dispatch_spans = [
        s for s in prep.spans
        if s.cat == "device" and s.name == "device.extend_sharded"
    ]
    tracks = {s.thread_name for s in dispatch_spans}
    if len(tracks) < 2:
        print(
            f"multichip-smoke: sharded dispatch on {len(tracks)} device "
            f"track(s), need >= 2 ({sorted(tracks)})",
            file=sys.stderr,
        )
        return 1

    # the merged doc must stay a valid Chrome trace with the per-chip
    # tracks surfacing as named Perfetto threads
    dump = tracing.trace_dump()
    problems = tracing.validate_chrome_trace(dump)
    if problems:
        print(
            f"multichip-smoke: invalid trace JSON: {problems[:5]}",
            file=sys.stderr,
        )
        return 1
    thread_names = {
        ev["args"]["name"]
        for ev in dump["traceEvents"]
        if ev.get("ph") == "M" and ev.get("name") == "thread_name"
    }
    device_tracks = sorted(
        n for n in thread_names if n.startswith("device:")
    )
    if len(device_tracks) < 2:
        print(
            f"multichip-smoke: merged trace has {len(device_tracks)} "
            f"device track(s), need >= 2 ({sorted(thread_names)})",
            file=sys.stderr,
        )
        return 1

    # join the background AOT cost-compile before interpreter teardown:
    # a daemon thread still inside XLA at exit dies on a GIL check
    from celestia_tpu.utils import devprof

    devprof.flush_compiles(timeout_s=120.0)
    print(
        json.dumps(
            {
                "multichip_smoke": "ok",
                "height": node.height,
                "mesh": mesh_mod.stats(),
                "sharded_dispatch_spans": len(dispatch_spans),
                "device_tracks": device_tracks,
            }
        )
    )
    return 0


def main() -> int:
    if os.environ.get("_MULTICHIP_SMOKE_CHILD") == "1":
        return child()
    return parent()


if __name__ == "__main__":
    sys.exit(main())
