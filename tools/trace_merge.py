"""trace_merge: fold N nodes' TraceDump outputs into one Perfetto timeline.

Usage:
    python tools/trace_merge.py NODE1.json NODE2.json ... -o merged.json

Each input file is any of:
  * a ``collect_trace`` part: {"node_id", "clock_offset_s", "trace"},
  * a raw TraceDump RPC response: {"enabled", "blocks", "trace"},
  * a bare Chrome trace document (tracing.trace_dump() output).

The merge gives every node its own Chrome "process" (named by node id),
shifts each node's timestamps by its recorded clock offset, and resolves
every span that carries explicit cross-node parent args
(``remote_node``/``remote_span``) into a Chrome flow arrow from the
sender's span to the receiver's.  The output opens unchanged in
Perfetto (ui.perfetto.dev) / chrome://tracing.

Exit 0 with a summary JSON line on success; non-zero with the reason on
unreadable inputs or a schema-invalid merge.  Merge semantics:
specs/observability.md "Distributed tracing".
"""

import argparse
import json
import os
import sys

# runnable as `python tools/trace_merge.py` from the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_part(path: str) -> dict:
    """Normalize one input file into the merge part shape."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a JSON object")
    if "trace" in doc and isinstance(doc["trace"], dict):
        # collect_trace part or TraceDump RPC response
        return {
            "node_id": doc.get("node_id", ""),
            "clock_offset_s": doc.get("clock_offset_s", 0.0),
            "rtt_s": doc.get("rtt_s", 0.0),
            "trace": doc["trace"],
        }
    if "traceEvents" in doc:
        # bare Chrome document: node id from its otherData when present
        return {
            "node_id": doc.get("otherData", {}).get("node_id", ""),
            "clock_offset_s": 0.0,
            "trace": doc,
        }
    raise ValueError(f"{path}: neither a trace part nor a Chrome document")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="trace_merge")
    p.add_argument("inputs", nargs="+", help="per-node trace JSON files")
    p.add_argument("-o", "--out", default="cluster.trace.json")
    args = p.parse_args(argv)

    from celestia_tpu.node.cluster import merge_node_dumps
    from celestia_tpu.utils.tracing import validate_chrome_trace

    try:
        parts = [load_part(path) for path in args.inputs]
    except (OSError, ValueError) as e:
        print(f"trace_merge: {e}", file=sys.stderr)
        return 1
    merged = merge_node_dumps(parts)
    problems = validate_chrome_trace(merged)
    if problems:
        print(f"trace_merge: invalid merged trace: {problems[:5]}",
              file=sys.stderr)
        return 1
    with open(args.out, "w") as f:
        json.dump(merged, f)
    print(
        json.dumps(
            {
                "merged": args.out,
                "nodes": [n["node_id"] for n in merged["otherData"]["nodes"]],
                "events": len(merged["traceEvents"]),
                "cross_node_flows": merged["otherData"]["cross_node_flows"],
            }
        )
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
