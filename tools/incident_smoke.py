"""incident-smoke: the host-profiling + flight-recorder boot gate
(`make incident-smoke`, tier-1 twin: tests/test_incident_smoke.py).

Leg 1 (armed, one real node subprocess): starts a traced tiny-k
validator with the host sampler armed (``--host-profile``), a flight
dir (``--flight-dir``), the plain-HTTP endpoint and a fast
time-series cadence, plus a synthetic height-stall rule injected via
CELESTIA_TPU_ALERT_RULES.  Drives ONE block through the real
ConsPrepare/ConsCommit RPCs (the node is then height-stalled by
construction: nothing drives it further), waits for the stall rule to
fire, and asserts against the LIVE RPC surface:

* `query incidents` lists >= 1 bundle,
* `query incident --out DIR` retrieves it; the written manifest passes
  ``flight.validate_manifest``, the written trace passes
  ``tracing.validate_chrome_trace`` and carries >= 1 ``cat="sample"``
  event on a NAMED host thread track, and the folded stacks are
  non-empty,
* `query host-profile` reports live sampling,
* ``GET /healthz`` answers degraded and names the stall rule.

Leg 2 (disarmed): a node WITHOUT ``--host-profile``/``--flight-dir``
must write no flight dir and report a disabled profiler over the same
RPCs, and the disarmed sampler surface must add <1% to a 10k-iteration
work loop (the in-process overhead pin).

Exit 0 + one summary JSON line per leg; non-zero with the reason on
any failure.  CPU backend, tiny squares — tier-1 compatible."""

import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

STALL_RULE = {
    "name": "smoke_height_stall",
    "metric": "height",
    "kind": "stall",
    "for_s": 0.5,
}


def _readline_deadline(proc, timeout_s: float = 180.0):
    import threading

    out = []
    t = threading.Thread(
        target=lambda: out.append(proc.stdout.readline()), daemon=True
    )
    t.start()
    t.join(timeout_s)
    if not out or not out[0]:
        return None
    return out[0]


def _env(extra=None):
    env = {
        **os.environ,
        "CELESTIA_JAX_PLATFORM": "cpu",
        "JAX_PLATFORMS": "cpu",
        "TF_CPP_MIN_LOG_LEVEL": "3",
    }
    env.update(extra or {})
    return env


def _cli(env, *args):
    return subprocess.run(
        [sys.executable, "-m", "celestia_tpu.cli", *args],
        capture_output=True, text=True, timeout=120, cwd=REPO, env=env,
    )


def _start_node(base, name, env, extra_flags):
    home = os.path.join(base, name)
    r = _cli(env, "--home", home, "init", "--chain-id", f"{name}-1")
    if r.returncode != 0:
        print(f"incident-smoke: init failed: {r.stderr}", file=sys.stderr)
        return None, home
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "celestia_tpu.cli",
            "--home", home, "start", "--validator",
            "--grpc-address", "127.0.0.1:0",
            "--metrics-port", "0",
            "--timeseries-interval", "0.2",
            "--warm-squares", "",
            *extra_flags,
        ],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, cwd=REPO, env=env,
    )
    return proc, home


def _stop_node(proc):
    proc.send_signal(signal.SIGINT)
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        proc.kill()


def _produce_one_block(addr):
    """One real block over the consensus RPCs: prepare on the validator,
    commit the proposal straight back (single-validator quorum)."""
    from celestia_tpu.client.remote import RemoteNode

    remote = RemoteNode(addr, timeout_s=120.0)
    try:
        st = remote.status()
        prop = remote.cons_prepare()
        now_ns = int(
            st.get("time_ns") or st.get("genesis_time_ns") or 0
        ) + 10**9
        remote.cons_commit(
            prop["block_txs"], int(st["height"]) + 1, now_ns,
            prop["data_root"], prop["square_size"],
        )
        return remote.status()["height"]
    finally:
        remote.close()


def leg1() -> int:
    from celestia_tpu.utils import flight as flight_mod
    from celestia_tpu.utils import tracing

    base = tempfile.mkdtemp(prefix="incident-smoke-")
    flight_dir = os.path.join(base, "flight")
    env = _env({
        "CELESTIA_TPU_TRACE": "1",
        "CELESTIA_TPU_ALERT_RULES": json.dumps([STALL_RULE]),
        "CELESTIA_TPU_NODE_ID": "incident-smoke-node",
    })
    proc, _home = _start_node(
        base, "armed", env,
        ["--host-profile", "200", "--flight-dir", flight_dir],
    )
    if proc is None:
        return 1
    try:
        line = _readline_deadline(proc)
        if line is None or proc.poll() is not None:
            why = "died" if proc.poll() is not None else "hung"
            print(f"incident-smoke: validator {why} at startup",
                  file=sys.stderr)
            return 1
        started = json.loads(line)
        addr, http_addr = started["grpc"], started.get("metrics_http")
        height = _produce_one_block(addr)
        if height < 1:
            print(f"incident-smoke: no block produced (h={height})",
                  file=sys.stderr)
            return 1
        # the node is now height-stalled by construction; the injected
        # stall rule needs for_s of flat samples at the 0.2 s cadence
        time.sleep(1.5)

        inc = _cli(env, "query", "--node", addr, "incidents")
        if inc.returncode != 0:
            print(f"incident-smoke: query incidents failed: {inc.stderr}",
                  file=sys.stderr)
            return 1
        listing = json.loads(inc.stdout)
        if not listing.get("enabled") or not listing.get("incidents"):
            print(
                f"incident-smoke: no incident captured ({inc.stdout[:300]})",
                file=sys.stderr,
            )
            return 1
        newest = listing["incidents"][-1]
        if STALL_RULE["name"] not in newest.get("reason", ""):
            print(
                f"incident-smoke: wrong trigger: {newest.get('reason')!r}",
                file=sys.stderr,
            )
            return 1

        out_dir = os.path.join(base, "fetched")
        fetched = _cli(
            env, "query", "--node", addr, "incident",
            "--id", newest["id"], "--out", out_dir,
        )
        if fetched.returncode != 0:
            print(f"incident-smoke: query incident failed: {fetched.stderr}",
                  file=sys.stderr)
            return 1
        bundle_dir = os.path.join(out_dir, newest["id"])
        with open(os.path.join(bundle_dir, "manifest.json")) as f:
            manifest = json.load(f)
        problems = flight_mod.validate_manifest(manifest)
        if problems:
            print(f"incident-smoke: invalid manifest: {problems[:5]}",
                  file=sys.stderr)
            return 1
        with open(os.path.join(bundle_dir, "trace.json")) as f:
            trace = json.load(f)
        problems = tracing.validate_chrome_trace(trace)
        if problems:
            print(f"incident-smoke: invalid bundle trace: {problems[:5]}",
                  file=sys.stderr)
            return 1
        samples = [
            ev for ev in trace["traceEvents"] if ev.get("cat") == "sample"
        ]
        if not samples:
            print("incident-smoke: bundle trace has no cat=sample events",
                  file=sys.stderr)
            return 1
        tracks = {
            ev["tid"]: ev["args"]["name"]
            for ev in trace["traceEvents"]
            if ev.get("ph") == "M" and ev.get("name") == "thread_name"
        }
        bad = [
            ev["tid"] for ev in samples
            if not tracks.get(ev["tid"])
            or tracks[ev["tid"]].startswith("device:")
        ]
        if bad:
            print(
                f"incident-smoke: samples on unnamed/device tracks: {bad[:3]}",
                file=sys.stderr,
            )
            return 1
        with open(os.path.join(bundle_dir, "stacks.folded")) as f:
            folded = f.read()
        if not folded.strip():
            print("incident-smoke: bundle folded stacks are empty",
                  file=sys.stderr)
            return 1

        prof = _cli(env, "query", "--node", addr, "host-profile")
        if prof.returncode != 0:
            print(f"incident-smoke: query host-profile failed: {prof.stderr}",
                  file=sys.stderr)
            return 1
        prof_doc = json.loads(prof.stdout)
        if not prof_doc["stats"]["enabled"] or (
            prof_doc["stats"]["samples_total"] < 1
        ):
            print(f"incident-smoke: profiler not live: {prof_doc['stats']}",
                  file=sys.stderr)
            return 1

        hz_doc = json.loads(urllib.request.urlopen(
            f"http://{http_addr}/healthz", timeout=30
        ).read().decode())
        if hz_doc.get("status") != "degraded" or (
            STALL_RULE["name"] not in hz_doc.get("alerts_firing", [])
        ):
            print(f"incident-smoke: healthz did not degrade: {hz_doc}",
                  file=sys.stderr)
            return 1

        print(json.dumps({
            "incident_smoke": "ok",
            "height": height,
            "incident": newest["id"],
            "reason": newest["reason"],
            "sample_events": len(samples),
            "folded_lines": len(folded.strip().splitlines()),
            "healthz": hz_doc["status"],
        }))
        return 0
    finally:
        _stop_node(proc)


def leg2() -> int:
    # in-process half: the disarmed sampler surface must stay under 1%
    # of a 10k-iteration work loop (one bool check per call)
    from celestia_tpu.utils import hostprof
    from celestia_tpu.utils.telemetry import clock

    hostprof.stop()
    payload = b"\xcd" * 49152
    t0 = clock()
    for _ in range(10_000):
        hashlib.sha256(payload).digest()
    t_loop = clock() - t0
    t0 = clock()
    for _ in range(10_000):
        hostprof.sample_once()
    t_calls = clock() - t0
    ratio = t_calls / max(1e-9, t_loop)
    if ratio >= 0.01:
        print(
            f"incident-smoke: disarmed sampler cost {ratio * 100:.2f}% "
            f"of the 10k loop (calls {t_calls * 1e3:.2f} ms, work "
            f"{t_loop * 1e3:.1f} ms)",
            file=sys.stderr,
        )
        return 1

    # subprocess half: a node without the flags writes NOTHING
    base = tempfile.mkdtemp(prefix="incident-smoke-off-")
    env = _env({"CELESTIA_TPU_ALERT_RULES": json.dumps([STALL_RULE])})
    proc, home = _start_node(base, "disarmed", env, [])
    if proc is None:
        return 1
    try:
        line = _readline_deadline(proc)
        if line is None or proc.poll() is not None:
            why = "died" if proc.poll() is not None else "hung"
            print(f"incident-smoke: disarmed validator {why} at startup",
                  file=sys.stderr)
            return 1
        addr = json.loads(line)["grpc"]
        _produce_one_block(addr)
        time.sleep(1.0)  # the stall rule fires; nothing may be written
        inc = _cli(env, "query", "--node", addr, "incidents")
        listing = json.loads(inc.stdout)
        if listing.get("enabled") or listing.get("incidents"):
            print(f"incident-smoke: disarmed node captured: {inc.stdout}",
                  file=sys.stderr)
            return 1
        prof = json.loads(
            _cli(env, "query", "--node", addr, "host-profile").stdout
        )
        if prof["stats"]["enabled"] or prof["stats"]["samples_total"]:
            print(
                f"incident-smoke: disarmed node sampled: {prof['stats']}",
                file=sys.stderr,
            )
            return 1
        flight_dirs = [
            p for p in os.listdir(base)
            if "flight" in p
        ]
        if flight_dirs:
            print(f"incident-smoke: unexpected flight dirs: {flight_dirs}",
                  file=sys.stderr)
            return 1
        print(json.dumps({
            "incident_smoke_disarmed": "ok",
            "overhead_pct_of_loop": round(ratio * 100, 3),
            "incidents": 0,
        }))
        return 0
    finally:
        _stop_node(proc)


def main(argv) -> int:
    legs = argv[1:] or ["--leg1", "--leg2"]
    if "--leg1" in legs:
        rc = leg1()
        if rc != 0:
            return rc
    if "--leg2" in legs:
        rc = leg2()
        if rc != 0:
            return rc
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
