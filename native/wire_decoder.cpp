// Standalone cross-language decoder for the external wire contract
// (specs/wire.md).  Deliberately NOT linked against anything in this
// repo and free of third-party libraries: if this program can decode a
// node's bytes with only the spec and the C++ standard library, so can
// any other language.
//
// Usage:  wire_decoder <mode>   (tx | blobtx | dah | account)
// Input:  one hex string on stdin (for `account`: the raw JSON).
// Output: one JSON object on stdout; exit 1 on malformed input.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

static std::vector<uint8_t> from_hex(const std::string& s) {
    if (s.size() % 2) throw std::runtime_error("odd hex length");
    std::vector<uint8_t> out(s.size() / 2);
    for (size_t i = 0; i < out.size(); i++) {
        unsigned v;
        if (sscanf(s.c_str() + 2 * i, "%2x", &v) != 1)
            throw std::runtime_error("bad hex");
        out[i] = (uint8_t)v;
    }
    return out;
}

static std::string json_escape(const uint8_t* p, size_t n) {
    // memo bytes are attacker-chosen; quotes/backslashes/control chars
    // must not corrupt the decoder's own JSON output, and the output
    // must always be valid UTF-8.  The Python encoder writes memos as
    // UTF-8 (state/tx.py Tx.marshal: memo.encode()), so well-formed
    // sequences pass through verbatim — escaping them would diverge
    // from the Python decode of the same bytes — and only malformed
    // bytes are replaced (U+FFFD), keeping strict JSON parsers happy.
    std::string out;
    out.reserve(n);
    size_t i = 0;
    while (i < n) {
        uint8_t c = p[i];
        if (c == '"' || c == '\\') {
            out += '\\';
            out += (char)c;
            i++;
        } else if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            out += buf;
            i++;
        } else if (c < 0x80) {
            out += (char)c;
            i++;
        } else {
            // validate one multi-byte sequence: length from the lead
            // byte, continuation bytes, overlongs, surrogates, >10FFFF
            size_t len = 0;
            uint32_t cp = 0;
            if (c >= 0xC2 && c <= 0xDF) { len = 2; cp = c & 0x1F; }
            else if (c >= 0xE0 && c <= 0xEF) { len = 3; cp = c & 0x0F; }
            else if (c >= 0xF0 && c <= 0xF4) { len = 4; cp = c & 0x07; }
            bool ok = len != 0 && i + len <= n;
            for (size_t j = 1; ok && j < len; j++) {
                uint8_t cc = p[i + j];
                ok = (cc & 0xC0) == 0x80;
                cp = (cp << 6) | (cc & 0x3F);
            }
            if (ok && len == 3 &&
                (cp < 0x800 || (cp >= 0xD800 && cp <= 0xDFFF)))
                ok = false;
            if (ok && len == 4 && (cp < 0x10000 || cp > 0x10FFFF))
                ok = false;
            if (ok) {
                out.append((const char*)(p + i), len);
                i += len;
            } else {
                out += "\xEF\xBF\xBD";  // U+FFFD replacement character
                i++;
            }
        }
    }
    return out;
}

static std::string to_hex(const uint8_t* p, size_t n) {
    static const char* d = "0123456789abcdef";
    std::string out;
    out.reserve(2 * n);
    for (size_t i = 0; i < n; i++) {
        out += d[p[i] >> 4];
        out += d[p[i] & 15];
    }
    return out;
}

struct Reader {
    const uint8_t* p;
    size_t n, pos = 0;
    Reader(const std::vector<uint8_t>& v) : p(v.data()), n(v.size()) {}
    Reader(const uint8_t* data, size_t len) : p(data), n(len) {}

    // unsigned LEB128, bounded to uint64, MINIMAL encoding required
    // (spec "Primitives"): a multi-byte varint must not end in a zero
    // group, or the same value has many wire forms (malleability)
    uint64_t varint() {
        uint64_t out = 0;
        int shift = 0;
        while (true) {
            if (pos >= n) throw std::runtime_error("truncated varint");
            uint8_t b = p[pos++];
            out |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) {
                if (b == 0 && shift > 0)
                    throw std::runtime_error("non-minimal varint");
                return out;
            }
            shift += 7;
            if (shift > 63) throw std::runtime_error("varint too long");
        }
    }

    std::pair<const uint8_t*, size_t> bytes() {
        uint64_t len = varint();
        // overflow-safe form: pos + len can wrap for hostile 64-bit lens
        if (len > n - pos) throw std::runtime_error("truncated bytes");
        const uint8_t* out = p + pos;
        pos += len;
        return {out, (size_t)len};
    }

    uint32_t u32_be() {
        if (pos + 4 > n) throw std::runtime_error("truncated u32");
        uint32_t v = ((uint32_t)p[pos] << 24) | ((uint32_t)p[pos + 1] << 16) |
                     ((uint32_t)p[pos + 2] << 8) | p[pos + 3];
        pos += 4;
        return v;
    }

    void expect_done(const char* what) {
        if (pos != n)
            throw std::runtime_error(std::string("trailing bytes in ") + what);
    }
};

// one msg body per the TYPE registry (specs/wire.md table)
static std::string decode_msg(const uint8_t* data, size_t len) {
    Reader r(data, len);
    uint64_t type = r.varint();
    std::ostringstream out;
    out << "{\"type\":" << type;
    if (type == 1) {  // MsgSend
        auto from = r.bytes();
        auto to = r.bytes();
        uint64_t amount = r.varint();
        out << ",\"from\":\"" << to_hex(from.first, from.second)
            << "\",\"to\":\"" << to_hex(to.first, to.second)
            << "\",\"amount\":" << amount;
    } else if (type == 2) {  // MsgPayForBlobs
        auto signer = r.bytes();
        uint64_t count = r.varint();
        out << ",\"signer\":\"" << to_hex(signer.first, signer.second)
            << "\",\"blobs\":[";
        for (uint64_t i = 0; i < count; i++) {
            auto ns = r.bytes();
            uint64_t size = r.varint();
            auto comm = r.bytes();
            uint64_t ver = r.varint();
            out << (i ? "," : "") << "{\"namespace\":\""
                << to_hex(ns.first, ns.second) << "\",\"blob_size\":" << size
                << ",\"commitment\":\"" << to_hex(comm.first, comm.second)
                << "\",\"share_version\":" << ver << "}";
        }
        out << "]";
    } else {
        // other msg types: expose the raw body so the caller still sees
        // a well-formed envelope (registry lives in state/tx.py)
        out << ",\"raw\":\"" << to_hex(data + r.pos, len - r.pos) << "\"";
        r.pos = len;
    }
    r.expect_done("msg");
    out << "}";
    return out.str();
}

static std::string decode_tx(const std::vector<uint8_t>& raw) {
    Reader r(raw);
    auto body = r.bytes();
    auto auth = r.bytes();
    auto sig = r.bytes();
    r.expect_done("tx");

    Reader br(body.first, body.second);
    uint64_t n_msgs = br.varint();
    std::ostringstream out;
    out << "{\"msgs\":[";
    for (uint64_t i = 0; i < n_msgs; i++) {
        auto m = br.bytes();
        out << (i ? "," : "") << decode_msg(m.first, m.second);
    }
    auto memo = br.bytes();
    uint64_t timeout_height = br.varint();
    br.expect_done("tx body");

    Reader ar(auth.first, auth.second);
    uint64_t fee_amount = ar.varint();
    uint64_t gas_limit = ar.varint();
    auto pubkey = ar.bytes();
    uint64_t sequence = ar.varint();
    uint64_t account_number = ar.varint();
    auto granter = ar.bytes();
    ar.expect_done("tx auth");

    out << "],\"memo\":\"" << json_escape(memo.first, memo.second)
        << "\",\"timeout_height\":" << timeout_height
        << ",\"fee_amount\":" << fee_amount << ",\"gas_limit\":" << gas_limit
        << ",\"pubkey\":\"" << to_hex(pubkey.first, pubkey.second)
        << "\",\"sequence\":" << sequence
        << ",\"account_number\":" << account_number << ",\"fee_granter\":\""
        << to_hex(granter.first, granter.second) << "\",\"signature\":\""
        << to_hex(sig.first, sig.second) << "\"}";
    return out.str();
}

static std::string decode_blobtx(const std::vector<uint8_t>& raw) {
    static const char MAGIC[8] = {'C', 'T', 'P', 'U', 'B', 'L', 'B', '0'};
    if (raw.size() < 8 || memcmp(raw.data(), MAGIC, 8) != 0)
        throw std::runtime_error("missing BlobTx magic");
    Reader r(raw.data() + 8, raw.size() - 8);
    auto tx = r.bytes();
    uint64_t n_blobs = r.varint();
    std::ostringstream out;
    out << "{\"tx_bytes\":" << tx.second << ",\"blobs\":[";
    for (uint64_t i = 0; i < n_blobs; i++) {
        if (r.pos + 29 > r.n) throw std::runtime_error("truncated namespace");
        std::string ns = to_hex(r.p + r.pos, 29);  // fixed width, no prefix
        r.pos += 29;
        uint64_t ver = r.varint();
        auto data = r.bytes();
        out << (i ? "," : "") << "{\"namespace\":\"" << ns
            << "\",\"data_len\":" << data.second
            << ",\"share_version\":" << ver << "}";
    }
    r.expect_done("blobtx");
    out << "]}";
    return out.str();
}

static std::string decode_dah(const std::vector<uint8_t>& raw) {
    Reader r(raw);
    uint32_t n_rows = r.u32_be();
    std::ostringstream out;
    out << "{\"row_roots\":[";
    for (uint32_t i = 0; i < n_rows; i++) {
        if (r.pos + 90 > r.n) throw std::runtime_error("truncated root");
        out << (i ? "," : "") << "\"" << to_hex(r.p + r.pos, 90) << "\"";
        r.pos += 90;
    }
    uint32_t n_cols = r.u32_be();
    out << "],\"col_roots\":[";
    for (uint32_t i = 0; i < n_cols; i++) {
        if (r.pos + 90 > r.n) throw std::runtime_error("truncated root");
        out << (i ? "," : "") << "\"" << to_hex(r.p + r.pos, 90) << "\"";
        r.pos += 90;
    }
    r.expect_done("dah");
    out << "]}";
    return out.str();
}

// AccountInfo JSON response: {"account_number": N, "sequence": N}.
// A 20-line scan is all the "client library" this contract requires.
static std::string decode_account(const std::string& json) {
    long long acct = -1, seq = -1;
    const char* p = strstr(json.c_str(), "\"account_number\"");
    if (p && sscanf(p, "\"account_number\"%*[: ]%lld", &acct) != 1) acct = -1;
    p = strstr(json.c_str(), "\"sequence\"");
    if (p && sscanf(p, "\"sequence\"%*[: ]%lld", &seq) != 1) seq = -1;
    if (acct < 0 || seq < 0)
        throw std::runtime_error("account response missing fields");
    std::ostringstream out;
    out << "{\"account_number\":" << acct << ",\"sequence\":" << seq << "}";
    return out.str();
}

int main(int argc, char** argv) {
    if (argc != 2) {
        fprintf(stderr, "usage: wire_decoder <tx|blobtx|dah|account>\n");
        return 2;
    }
    std::string input, line;
    while (std::getline(std::cin, line)) input += line;
    try {
        std::string mode = argv[1];
        if (mode == "account") {
            std::cout << decode_account(input) << "\n";
            return 0;
        }
        auto raw = from_hex(input);
        if (mode == "tx")
            std::cout << decode_tx(raw) << "\n";
        else if (mode == "blobtx")
            std::cout << decode_blobtx(raw) << "\n";
        else if (mode == "dah")
            std::cout << decode_dah(raw) << "\n";
        else {
            fprintf(stderr, "unknown mode %s\n", mode.c_str());
            return 2;
        }
    } catch (const std::exception& e) {
        fprintf(stderr, "decode error: %s\n", e.what());
        return 1;
    }
    return 0;
}
