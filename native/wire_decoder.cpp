// Standalone cross-language decoder for the external wire contract
// (specs/wire.md).  Deliberately NOT linked against anything in this
// repo and free of third-party libraries: if this program can decode a
// node's bytes with only the spec and the C++ standard library, so can
// any other language.
//
// Usage:  wire_decoder <mode>   (tx | blobtx | dah | account)
// Input:  one hex string on stdin (for `account`: the raw JSON).
// Output: one JSON object on stdout; exit 1 on malformed input.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <iostream>
#include <sstream>
#include <stdexcept>
#include <string>
#include <vector>

static std::vector<uint8_t> from_hex(const std::string& s) {
    if (s.size() % 2) throw std::runtime_error("odd hex length");
    std::vector<uint8_t> out(s.size() / 2);
    for (size_t i = 0; i < out.size(); i++) {
        unsigned v;
        if (sscanf(s.c_str() + 2 * i, "%2x", &v) != 1)
            throw std::runtime_error("bad hex");
        out[i] = (uint8_t)v;
    }
    return out;
}

static std::string json_escape(const uint8_t* p, size_t n) {
    // memo bytes are attacker-chosen; quotes/backslashes/control chars
    // must not corrupt the decoder's own JSON output, and the output
    // must always be valid UTF-8.  The Python encoder writes memos as
    // UTF-8 (state/tx.py Tx.marshal: memo.encode()), so well-formed
    // sequences pass through verbatim — escaping them would diverge
    // from the Python decode of the same bytes — and only malformed
    // bytes are replaced (U+FFFD), keeping strict JSON parsers happy.
    std::string out;
    out.reserve(n);
    size_t i = 0;
    while (i < n) {
        uint8_t c = p[i];
        if (c == '"' || c == '\\') {
            out += '\\';
            out += (char)c;
            i++;
        } else if (c < 0x20) {
            char buf[8];
            snprintf(buf, sizeof(buf), "\\u%04x", c);
            out += buf;
            i++;
        } else if (c < 0x80) {
            out += (char)c;
            i++;
        } else {
            // validate one multi-byte sequence: length from the lead
            // byte, continuation bytes, overlongs, surrogates, >10FFFF
            size_t len = 0;
            uint32_t cp = 0;
            if (c >= 0xC2 && c <= 0xDF) { len = 2; cp = c & 0x1F; }
            else if (c >= 0xE0 && c <= 0xEF) { len = 3; cp = c & 0x0F; }
            else if (c >= 0xF0 && c <= 0xF4) { len = 4; cp = c & 0x07; }
            bool ok = len != 0 && i + len <= n;
            for (size_t j = 1; ok && j < len; j++) {
                uint8_t cc = p[i + j];
                ok = (cc & 0xC0) == 0x80;
                cp = (cp << 6) | (cc & 0x3F);
            }
            if (ok && len == 3 &&
                (cp < 0x800 || (cp >= 0xD800 && cp <= 0xDFFF)))
                ok = false;
            if (ok && len == 4 && (cp < 0x10000 || cp > 0x10FFFF))
                ok = false;
            if (ok) {
                out.append((const char*)(p + i), len);
                i += len;
            } else {
                out += "\xEF\xBF\xBD";  // U+FFFD replacement character
                i++;
            }
        }
    }
    return out;
}

static std::string to_hex(const uint8_t* p, size_t n) {
    static const char* d = "0123456789abcdef";
    std::string out;
    out.reserve(2 * n);
    for (size_t i = 0; i < n; i++) {
        out += d[p[i] >> 4];
        out += d[p[i] & 15];
    }
    return out;
}

struct Reader {
    const uint8_t* p;
    size_t n, pos = 0;
    Reader(const std::vector<uint8_t>& v) : p(v.data()), n(v.size()) {}
    Reader(const uint8_t* data, size_t len) : p(data), n(len) {}

    // unsigned LEB128, bounded to uint64, MINIMAL encoding required
    // (spec "Primitives"): a multi-byte varint must not end in a zero
    // group, or the same value has many wire forms (malleability)
    uint64_t varint() {
        uint64_t out = 0;
        int shift = 0;
        while (true) {
            if (pos >= n) throw std::runtime_error("truncated varint");
            uint8_t b = p[pos++];
            out |= (uint64_t)(b & 0x7F) << shift;
            if (!(b & 0x80)) {
                if (b == 0 && shift > 0)
                    throw std::runtime_error("non-minimal varint");
                return out;
            }
            shift += 7;
            if (shift > 63) throw std::runtime_error("varint too long");
        }
    }

    std::pair<const uint8_t*, size_t> bytes() {
        uint64_t len = varint();
        // overflow-safe form: pos + len can wrap for hostile 64-bit lens
        if (len > n - pos) throw std::runtime_error("truncated bytes");
        const uint8_t* out = p + pos;
        pos += len;
        return {out, (size_t)len};
    }

    uint32_t u32_be() {
        if (pos + 4 > n) throw std::runtime_error("truncated u32");
        uint32_t v = ((uint32_t)p[pos] << 24) | ((uint32_t)p[pos + 1] << 16) |
                     ((uint32_t)p[pos + 2] << 8) | p[pos + 3];
        pos += 4;
        return v;
    }

    void expect_done(const char* what) {
        if (pos != n)
            throw std::runtime_error(std::string("trailing bytes in ") + what);
    }
};

// one msg body per the TYPE registry (specs/wire.md table)
static std::string decode_msg(const uint8_t* data, size_t len) {
    Reader r(data, len);
    uint64_t type = r.varint();
    std::ostringstream out;
    out << "{\"type\":" << type;
    if (type == 1) {  // MsgSend
        auto from = r.bytes();
        auto to = r.bytes();
        uint64_t amount = r.varint();
        out << ",\"from\":\"" << to_hex(from.first, from.second)
            << "\",\"to\":\"" << to_hex(to.first, to.second)
            << "\",\"amount\":" << amount;
    } else if (type == 2) {  // MsgPayForBlobs
        auto signer = r.bytes();
        uint64_t count = r.varint();
        out << ",\"signer\":\"" << to_hex(signer.first, signer.second)
            << "\",\"blobs\":[";
        for (uint64_t i = 0; i < count; i++) {
            auto ns = r.bytes();
            uint64_t size = r.varint();
            auto comm = r.bytes();
            uint64_t ver = r.varint();
            out << (i ? "," : "") << "{\"namespace\":\""
                << to_hex(ns.first, ns.second) << "\",\"blob_size\":" << size
                << ",\"commitment\":\"" << to_hex(comm.first, comm.second)
                << "\",\"share_version\":" << ver << "}";
        }
        out << "]";
    } else {
        // other msg types: expose the raw body so the caller still sees
        // a well-formed envelope (registry lives in state/tx.py)
        out << ",\"raw\":\"" << to_hex(data + r.pos, len - r.pos) << "\"";
        r.pos = len;
    }
    r.expect_done("msg");
    out << "}";
    return out.str();
}

static std::string decode_tx(const std::vector<uint8_t>& raw) {
    Reader r(raw);
    auto body = r.bytes();
    auto auth = r.bytes();
    auto sig = r.bytes();
    r.expect_done("tx");

    Reader br(body.first, body.second);
    uint64_t n_msgs = br.varint();
    std::ostringstream out;
    out << "{\"msgs\":[";
    for (uint64_t i = 0; i < n_msgs; i++) {
        auto m = br.bytes();
        out << (i ? "," : "") << decode_msg(m.first, m.second);
    }
    auto memo = br.bytes();
    uint64_t timeout_height = br.varint();
    br.expect_done("tx body");

    Reader ar(auth.first, auth.second);
    uint64_t fee_amount = ar.varint();
    uint64_t gas_limit = ar.varint();
    auto pubkey = ar.bytes();
    uint64_t sequence = ar.varint();
    uint64_t account_number = ar.varint();
    auto granter = ar.bytes();
    ar.expect_done("tx auth");

    out << "],\"memo\":\"" << json_escape(memo.first, memo.second)
        << "\",\"timeout_height\":" << timeout_height
        << ",\"fee_amount\":" << fee_amount << ",\"gas_limit\":" << gas_limit
        << ",\"pubkey\":\"" << to_hex(pubkey.first, pubkey.second)
        << "\",\"sequence\":" << sequence
        << ",\"account_number\":" << account_number << ",\"fee_granter\":\""
        << to_hex(granter.first, granter.second) << "\",\"signature\":\""
        << to_hex(sig.first, sig.second) << "\"}";
    return out.str();
}

static std::string decode_blobtx(const std::vector<uint8_t>& raw) {
    static const char MAGIC[8] = {'C', 'T', 'P', 'U', 'B', 'L', 'B', '0'};
    if (raw.size() < 8 || memcmp(raw.data(), MAGIC, 8) != 0)
        throw std::runtime_error("missing BlobTx magic");
    Reader r(raw.data() + 8, raw.size() - 8);
    auto tx = r.bytes();
    uint64_t n_blobs = r.varint();
    std::ostringstream out;
    out << "{\"tx_bytes\":" << tx.second << ",\"blobs\":[";
    for (uint64_t i = 0; i < n_blobs; i++) {
        if (r.pos + 29 > r.n) throw std::runtime_error("truncated namespace");
        std::string ns = to_hex(r.p + r.pos, 29);  // fixed width, no prefix
        r.pos += 29;
        uint64_t ver = r.varint();
        auto data = r.bytes();
        out << (i ? "," : "") << "{\"namespace\":\"" << ns
            << "\",\"data_len\":" << data.second
            << ",\"share_version\":" << ver << "}";
    }
    r.expect_done("blobtx");
    out << "]}";
    return out.str();
}

static std::string decode_dah(const std::vector<uint8_t>& raw) {
    Reader r(raw);
    uint32_t n_rows = r.u32_be();
    std::ostringstream out;
    out << "{\"row_roots\":[";
    for (uint32_t i = 0; i < n_rows; i++) {
        if (r.pos + 90 > r.n) throw std::runtime_error("truncated root");
        out << (i ? "," : "") << "\"" << to_hex(r.p + r.pos, 90) << "\"";
        r.pos += 90;
    }
    uint32_t n_cols = r.u32_be();
    out << "],\"col_roots\":[";
    for (uint32_t i = 0; i < n_cols; i++) {
        if (r.pos + 90 > r.n) throw std::runtime_error("truncated root");
        out << (i ? "," : "") << "\"" << to_hex(r.p + r.pos, 90) << "\"";
        r.pos += 90;
    }
    r.expect_done("dah");
    out << "]}";
    return out.str();
}

// AccountInfo JSON response: {"account_number": N, "sequence": N}.
// A 20-line scan is all the "client library" this contract requires.
static std::string decode_account(const std::string& json) {
    long long acct = -1, seq = -1;
    const char* p = strstr(json.c_str(), "\"account_number\"");
    if (p && sscanf(p, "\"account_number\"%*[: ]%lld", &acct) != 1) acct = -1;
    p = strstr(json.c_str(), "\"sequence\"");
    if (p && sscanf(p, "\"sequence\"%*[: ]%lld", &seq) != 1) seq = -1;
    if (acct < 0 || seq < 0)
        throw std::runtime_error("account response missing fields");
    std::ostringstream out;
    out << "{\"account_number\":" << acct << ",\"sequence\":" << seq << "}";
    return out.str();
}

// ---------------------------------------------------------------------------
// ENCODER (spec "Transaction" + "Message bodies" + sign-bytes rule).
// Proves the wire contract works in BOTH directions from the spec alone
// (VERDICT r4 #5): a third party can CONSTRUCT a valid signed MsgSend tx,
// not just read one.  Everything below is standard-library C++ —
// including SHA-256 (FIPS 180-4) and a small, correctness-first
// secp256k1 signer (Jacobian double-and-add over a generic binary-
// reduction mulmod; a CLI signs once, so clarity beats speed).
// ---------------------------------------------------------------------------

// --- SHA-256 ---------------------------------------------------------------

static const uint32_t SHA_K[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr32(uint32_t x, int r) {
    return (x >> r) | (x << (32 - r));
}

static void sha256(const uint8_t* msg, size_t len, uint8_t out[32]) {
    uint32_t st[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                      0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    std::vector<uint8_t> buf(msg, msg + len);
    buf.push_back(0x80);
    while (buf.size() % 64 != 56) buf.push_back(0);
    uint64_t bits = (uint64_t)len * 8;
    for (int i = 7; i >= 0; i--) buf.push_back((uint8_t)(bits >> (8 * i)));
    for (size_t off = 0; off < buf.size(); off += 64) {
        const uint8_t* b = buf.data() + off;
        uint32_t w[64];
        for (int i = 0; i < 16; i++)
            w[i] = (uint32_t)b[4 * i] << 24 | (uint32_t)b[4 * i + 1] << 16 |
                   (uint32_t)b[4 * i + 2] << 8 | b[4 * i + 3];
        for (int i = 16; i < 64; i++) {
            uint32_t s0 = rotr32(w[i - 15], 7) ^ rotr32(w[i - 15], 18) ^
                          (w[i - 15] >> 3);
            uint32_t s1 = rotr32(w[i - 2], 17) ^ rotr32(w[i - 2], 19) ^
                          (w[i - 2] >> 10);
            w[i] = w[i - 16] + s0 + w[i - 7] + s1;
        }
        uint32_t a = st[0], bb = st[1], c = st[2], d = st[3], e = st[4],
                 f = st[5], g = st[6], h = st[7];
        for (int i = 0; i < 64; i++) {
            uint32_t S1 = rotr32(e, 6) ^ rotr32(e, 11) ^ rotr32(e, 25);
            uint32_t ch = (e & f) ^ (~e & g);
            uint32_t t1 = h + S1 + ch + SHA_K[i] + w[i];
            uint32_t S0 = rotr32(a, 2) ^ rotr32(a, 13) ^ rotr32(a, 22);
            uint32_t mj = (a & bb) ^ (a & c) ^ (bb & c);
            uint32_t t2 = S0 + mj;
            h = g; g = f; f = e; e = d + t1;
            d = c; c = bb; bb = a; a = t1 + t2;
        }
        st[0] += a; st[1] += bb; st[2] += c; st[3] += d;
        st[4] += e; st[5] += f; st[6] += g; st[7] += h;
    }
    for (int i = 0; i < 8; i++) {
        out[4 * i] = (uint8_t)(st[i] >> 24);
        out[4 * i + 1] = (uint8_t)(st[i] >> 16);
        out[4 * i + 2] = (uint8_t)(st[i] >> 8);
        out[4 * i + 3] = (uint8_t)st[i];
    }
}

// --- 256-bit modular arithmetic (correctness-first) ------------------------

struct N256 {
    uint32_t w[8];  // little-endian limbs
};

static N256 n256_from_hex(const char* hex) {
    N256 r{};
    size_t len = strlen(hex);
    for (size_t i = 0; i < len; i++) {
        char c = hex[len - 1 - i];
        uint32_t v = c <= '9' ? (uint32_t)(c - '0')
                              : (uint32_t)(10 + (c | 32) - 'a');
        r.w[i / 8] |= v << (4 * (i % 8));
    }
    return r;
}

static N256 n256_from_bytes(const uint8_t b[32]) {
    N256 r{};
    for (int i = 0; i < 32; i++)
        r.w[(31 - i) / 4] |= (uint32_t)b[i] << (8 * ((31 - i) % 4));
    return r;
}

static void n256_to_bytes(const N256& a, uint8_t b[32]) {
    for (int i = 0; i < 32; i++)
        b[i] = (uint8_t)(a.w[(31 - i) / 4] >> (8 * ((31 - i) % 4)));
}

static int n256_cmp(const N256& a, const N256& b) {
    for (int i = 7; i >= 0; i--) {
        if (a.w[i] != b.w[i]) return a.w[i] < b.w[i] ? -1 : 1;
    }
    return 0;
}

static int n256_is_zero(const N256& a) {
    for (int i = 0; i < 8; i++)
        if (a.w[i]) return 0;
    return 1;
}

static void n256_sub(N256& r, const N256& a, const N256& b) {
    int64_t borrow = 0;
    for (int i = 0; i < 8; i++) {
        int64_t d = (int64_t)a.w[i] - b.w[i] - borrow;
        borrow = d < 0;
        r.w[i] = (uint32_t)(d + (borrow ? 0x100000000LL : 0));
    }
}

static void n256_addmod(N256& r, const N256& a, const N256& b,
                        const N256& m) {
    uint64_t carry = 0;
    N256 s;
    for (int i = 0; i < 8; i++) {
        uint64_t t = (uint64_t)a.w[i] + b.w[i] + carry;
        s.w[i] = (uint32_t)t;
        carry = t >> 32;
    }
    if (carry || n256_cmp(s, m) >= 0) n256_sub(s, s, m);
    r = s;
}

static void n256_submod(N256& r, const N256& a, const N256& b,
                        const N256& m) {
    if (n256_cmp(a, b) >= 0) {
        n256_sub(r, a, b);
    } else {
        N256 t;
        n256_sub(t, m, b);
        n256_addmod(r, a, t, m);
    }
}

// r = a*b mod m via 512-bit product + binary long reduction: slow
// (~512 shift/compare/sub passes) but transparently correct, and a
// one-shot CLI signer runs it a few thousand times (<0.5 s).
static void n256_mulmod(N256& r, const N256& a, const N256& b,
                        const N256& m) {
    uint32_t prod[16] = {0};
    for (int i = 0; i < 8; i++) {
        uint64_t carry = 0;
        for (int j = 0; j < 8; j++) {
            uint64_t t = (uint64_t)a.w[i] * b.w[j] + prod[i + j] + carry;
            prod[i + j] = (uint32_t)t;
            carry = t >> 32;
        }
        prod[i + 8] = (uint32_t)carry;
    }
    N256 rem{};
    for (int bit = 511; bit >= 0; bit--) {
        // rem = rem*2 + bit
        uint32_t carry = (prod[bit / 32] >> (bit % 32)) & 1;
        for (int i = 0; i < 8; i++) {
            uint32_t nc = rem.w[i] >> 31;
            rem.w[i] = (rem.w[i] << 1) | carry;
            carry = nc;
        }
        if (carry || n256_cmp(rem, m) >= 0) n256_sub(rem, rem, m);
    }
    r = rem;
}

static void n256_powmod(N256& r, const N256& base, const N256& e,
                        const N256& m) {
    N256 acc{};
    acc.w[0] = 1;
    N256 b = base;
    for (int bit = 0; bit < 256; bit++) {
        if ((e.w[bit / 32] >> (bit % 32)) & 1) n256_mulmod(acc, acc, b, m);
        n256_mulmod(b, b, b, m);
    }
    r = acc;
}

static void n256_invmod(N256& r, const N256& a, const N256& m) {
    // Fermat: a^(m-2) mod m (m prime)
    N256 e = m;
    N256 two{};
    two.w[0] = 2;
    n256_sub(e, e, two);
    n256_powmod(r, a, e, m);
}

// --- secp256k1 signing -----------------------------------------------------

struct EcPt {
    N256 x, y, z;  // Jacobian; z == 0 => infinity
    int inf;
};

struct Secp {
    N256 p, n, gx, gy;
};

static Secp secp_params() {
    Secp s;
    s.p = n256_from_hex(
        "fffffffffffffffffffffffffffffffffffffffffffffffffffffffefffffc2f");
    s.n = n256_from_hex(
        "fffffffffffffffffffffffffffffffebaaedce6af48a03bbfd25e8cd0364141");
    s.gx = n256_from_hex(
        "79be667ef9dcbbac55a06295ce870b07029bfcdb2dce28d959f2815b16f81798");
    s.gy = n256_from_hex(
        "483ada7726a3c4655da4fbfc0e1108a8fd17b448a68554199c47d08ffb10d4b8");
    return s;
}

static void ec_dbl(EcPt& r, const EcPt& a, const N256& p) {
    if (a.inf || n256_is_zero(a.y)) {
        r = EcPt{};  // fields defined even at infinity (no UB on copy)
        r.inf = 1;
        return;
    }
    N256 ysq, s, m, t, x3, y3, z3;
    n256_mulmod(ysq, a.y, a.y, p);             // y^2
    n256_mulmod(s, a.x, ysq, p);               // x*y^2
    n256_addmod(s, s, s, p);
    n256_addmod(s, s, s, p);                   // s = 4xy^2
    n256_mulmod(m, a.x, a.x, p);               // x^2
    n256_addmod(t, m, m, p);
    n256_addmod(m, t, m, p);                   // m = 3x^2 (a=0 curve)
    n256_mulmod(x3, m, m, p);                  // m^2
    N256 s2;
    n256_addmod(s2, s, s, p);
    n256_submod(x3, x3, s2, p);                // x3 = m^2 - 2s
    n256_submod(t, s, x3, p);
    n256_mulmod(y3, m, t, p);                  // m(s - x3)
    N256 ysq2;
    n256_mulmod(ysq2, ysq, ysq, p);            // y^4
    for (int i = 0; i < 3; i++) n256_addmod(ysq2, ysq2, ysq2, p);  // 8y^4
    n256_submod(y3, y3, ysq2, p);
    n256_mulmod(z3, a.y, a.z, p);
    n256_addmod(z3, z3, z3, p);                // z3 = 2yz
    r.x = x3; r.y = y3; r.z = z3; r.inf = 0;
}

static void ec_add(EcPt& r, const EcPt& a, const EcPt& b, const N256& p) {
    if (a.inf) { r = b; return; }
    if (b.inf) { r = a; return; }
    N256 z1z1, z2z2, u1, u2, s1, s2, t;
    n256_mulmod(z1z1, a.z, a.z, p);
    n256_mulmod(z2z2, b.z, b.z, p);
    n256_mulmod(u1, a.x, z2z2, p);
    n256_mulmod(u2, b.x, z1z1, p);
    n256_mulmod(t, b.z, z2z2, p);
    n256_mulmod(s1, a.y, t, p);
    n256_mulmod(t, a.z, z1z1, p);
    n256_mulmod(s2, b.y, t, p);
    if (n256_cmp(u1, u2) == 0) {
        if (n256_cmp(s1, s2) == 0) {
            ec_dbl(r, a, p);
            return;
        }
        r = EcPt{};
        r.inf = 1;
        return;
    }
    N256 h, rr, h2, h3, u1h2, x3, y3, z3;
    n256_submod(h, u2, u1, p);
    n256_submod(rr, s2, s1, p);
    n256_mulmod(h2, h, h, p);
    n256_mulmod(h3, h2, h, p);
    n256_mulmod(u1h2, u1, h2, p);
    n256_mulmod(x3, rr, rr, p);
    n256_submod(x3, x3, h3, p);
    N256 two_u1h2;
    n256_addmod(two_u1h2, u1h2, u1h2, p);
    n256_submod(x3, x3, two_u1h2, p);
    n256_submod(t, u1h2, x3, p);
    n256_mulmod(y3, rr, t, p);
    n256_mulmod(t, s1, h3, p);
    n256_submod(y3, y3, t, p);
    n256_mulmod(z3, a.z, b.z, p);
    n256_mulmod(z3, z3, h, p);
    r.x = x3; r.y = y3; r.z = z3; r.inf = 0;
}

// k*G -> affine (x, y); returns 0 on infinity
static int ec_mul_g(const Secp& c, const N256& k, N256& out_x, N256& out_y) {
    EcPt g;
    g.x = c.gx; g.y = c.gy;
    g.z = N256{}; g.z.w[0] = 1;
    g.inf = 0;
    EcPt acc{};
    acc.inf = 1;
    for (int bit = 255; bit >= 0; bit--) {
        EcPt t{};
        ec_dbl(t, acc, c.p);
        acc = t;
        if ((k.w[bit / 32] >> (bit % 32)) & 1) {
            ec_add(t, acc, g, c.p);
            acc = t;
        }
    }
    if (acc.inf) return 0;
    N256 zinv, zinv2, zinv3;
    n256_invmod(zinv, acc.z, c.p);
    n256_mulmod(zinv2, zinv, zinv, c.p);
    n256_mulmod(zinv3, zinv2, zinv, c.p);
    n256_mulmod(out_x, acc.x, zinv2, c.p);
    n256_mulmod(out_y, acc.y, zinv3, c.p);
    return 1;
}

// ECDSA sign (low-s).  Nonce: deterministic sha256(priv || z || ctr) mod
// n — any valid (r, s) verifies, so byte-equality with the Python
// signer's nonce scheme is NOT required by the contract.
static void ecdsa_sign(const Secp& c, const uint8_t priv[32],
                       const uint8_t z32[32], uint8_t sig_out[64]) {
    N256 d = n256_from_bytes(priv);
    N256 z = n256_from_bytes(z32);
    if (n256_cmp(z, c.n) >= 0) n256_sub(z, z, c.n);
    for (uint8_t ctr = 0;; ctr++) {
        uint8_t seed[65];
        memcpy(seed, priv, 32);
        memcpy(seed + 32, z32, 32);
        seed[64] = ctr;
        uint8_t kb[32];
        sha256(seed, 65, kb);
        N256 k = n256_from_bytes(kb);
        if (n256_cmp(k, c.n) >= 0) n256_sub(k, k, c.n);
        if (n256_is_zero(k)) continue;
        N256 rx, ry;
        if (!ec_mul_g(c, k, rx, ry)) continue;
        N256 r = rx;
        if (n256_cmp(r, c.n) >= 0) n256_sub(r, r, c.n);
        if (n256_is_zero(r)) continue;
        N256 kinv, rd, num, s;
        n256_invmod(kinv, k, c.n);
        n256_mulmod(rd, r, d, c.n);
        n256_addmod(num, z, rd, c.n);
        n256_mulmod(s, kinv, num, c.n);
        if (n256_is_zero(s)) continue;
        // low-s rule (spec "signature")
        N256 half = c.n;
        for (int i = 0; i < 8; i++) {  // half = n >> 1
            uint32_t lo = i + 1 < 8 ? (half.w[i + 1] & 1) << 31 : 0;
            half.w[i] = (half.w[i] >> 1) | lo;
        }
        if (n256_cmp(s, half) > 0) n256_sub(s, c.n, s);
        n256_to_bytes(r, sig_out);
        n256_to_bytes(s, sig_out + 32);
        return;
    }
}

// compressed pubkey (02/03 || x) for priv
static void pubkey_compressed(const Secp& c, const uint8_t priv[32],
                              uint8_t out33[33]) {
    N256 d = n256_from_bytes(priv);
    N256 px, py;
    if (!ec_mul_g(c, d, px, py))
        throw std::runtime_error("invalid private key");
    out33[0] = (uint8_t)(0x02 | (py.w[0] & 1));
    n256_to_bytes(px, out33 + 1);
}

// --- wire writers (spec "Primitives" — minimal varints by construction) ----

static void put_varint(std::vector<uint8_t>& out, uint64_t v) {
    while (true) {
        uint8_t b = v & 0x7F;
        v >>= 7;
        if (v) {
            out.push_back(b | 0x80);
        } else {
            out.push_back(b);
            return;
        }
    }
}

static void put_bytes(std::vector<uint8_t>& out, const uint8_t* p,
                      size_t n2) {
    put_varint(out, n2);
    out.insert(out.end(), p, p + n2);
}

static void put_bytes(std::vector<uint8_t>& out,
                      const std::vector<uint8_t>& v) {
    put_bytes(out, v.data(), v.size());
}

// Build + sign a MsgSend tx purely from the spec.  stdin (whitespace-
// separated): priv_hex chain_id to_hex amount fee_amount gas_limit
// sequence account_number [memo].  stdout: signed tx hex.
static std::string encode_send(const std::string& input) {
    std::istringstream in(input);
    std::string priv_hex, chain_id, to_addr_hex, memo;
    uint64_t amount, fee_amount, gas_limit, sequence, account_number;
    if (!(in >> priv_hex >> chain_id >> to_addr_hex >> amount >>
          fee_amount >> gas_limit >> sequence >> account_number))
        throw std::runtime_error(
            "need: priv chain_id to amount fee gas seq acctnum [memo]");
    // memo = everything after the fixed fields (may contain spaces —
    // the wire contract allows arbitrary UTF-8 memos)
    std::getline(in, memo);
    size_t start = memo.find_first_not_of(" \t");
    memo = start == std::string::npos ? "" : memo.substr(start);
    auto priv = from_hex(priv_hex);
    auto to = from_hex(to_addr_hex);
    if (priv.size() != 32) throw std::runtime_error("priv must be 32 bytes");
    if (to.size() != 20) throw std::runtime_error("to must be 20 bytes");
    Secp c = secp_params();
    uint8_t pub[33];
    pubkey_compressed(c, priv.data(), pub);
    // address = sha256(compressed pubkey)[:20] (spec "Accounts")
    uint8_t from_addr[32];
    sha256(pub, 33, from_addr);
    // msg: TYPE 1 = bytes(from,20) || bytes(to,20) || varint(amount)
    std::vector<uint8_t> msg;
    put_varint(msg, 1);
    put_bytes(msg, from_addr, 20);
    put_bytes(msg, to);
    put_varint(msg, amount);
    // body = varint(n_msgs) || msgs || bytes(memo) || varint(timeout)
    std::vector<uint8_t> body;
    put_varint(body, 1);
    put_bytes(body, msg);
    put_bytes(body, (const uint8_t*)memo.data(), memo.size());
    put_varint(body, 0);
    // auth = varint(fee) || varint(gas) || bytes(pubkey) || varint(seq)
    //        || varint(acctnum) || bytes(fee_granter)
    std::vector<uint8_t> auth;
    put_varint(auth, fee_amount);
    put_varint(auth, gas_limit);
    put_bytes(auth, pub, 33);
    put_varint(auth, sequence);
    put_varint(auth, account_number);
    put_varint(auth, 0);  // empty fee_granter
    // sign bytes = sha256(bytes(chain_id) || bytes(body) || bytes(auth))
    std::vector<uint8_t> doc;
    put_bytes(doc, (const uint8_t*)chain_id.data(), chain_id.size());
    put_bytes(doc, body);
    put_bytes(doc, auth);
    uint8_t doc_digest[32];
    sha256(doc.data(), doc.size(), doc_digest);
    // the ECDSA message digest is sha256 of the sign bytes (the signer
    // hashes its input): z = sha256(sha256(doc)) — spec "signature"
    uint8_t z[32];
    sha256(doc_digest, 32, z);
    uint8_t sig[64];
    ecdsa_sign(c, priv.data(), z, sig);
    // Tx = bytes(body) || bytes(auth) || bytes(signature)
    std::vector<uint8_t> tx;
    put_bytes(tx, body);
    put_bytes(tx, auth);
    put_bytes(tx, sig, 64);
    return to_hex(tx.data(), tx.size());
}

int main(int argc, char** argv) {
    if (argc != 2) {
        fprintf(stderr,
                "usage: wire_decoder <tx|blobtx|dah|account|encode-send>\n");
        return 2;
    }
    std::string input, line;
    while (std::getline(std::cin, line)) input += line;
    try {
        std::string mode = argv[1];
        if (mode == "account") {
            std::cout << decode_account(input) << "\n";
            return 0;
        }
        if (mode == "encode-send") {
            std::cout << encode_send(input) << "\n";
            return 0;
        }
        auto raw = from_hex(input);
        if (mode == "tx")
            std::cout << decode_tx(raw) << "\n";
        else if (mode == "blobtx")
            std::cout << decode_blobtx(raw) << "\n";
        else if (mode == "dah")
            std::cout << decode_dah(raw) << "\n";
        else {
            fprintf(stderr, "unknown mode %s\n", mode.c_str());
            return 2;
        }
    } catch (const std::exception& e) {
        fprintf(stderr, "decode error: %s\n", e.what());
        return 1;
    }
    return 0;
}
