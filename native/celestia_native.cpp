// Native host library: GF(256) Reed-Solomon square extension + SHA-256 /
// NMT hashing on the CPU.
//
// Role: the TPU framework's equivalent of the reference's performance-native
// dependencies (Leopard-RS SIMD codec via klauspost/reedsolomon and
// crypto/sha256 — SURVEY.md §2.2).  Used as (a) the honest CPU comparison
// leg for bench.py, and (b) a host-side fallback behind the same Python
// interfaces as the device kernels.  Exposed via a C ABI for ctypes.
//
// GF(256): primitive polynomial 0x11D, multiply via a 64 KiB full product
// table (the classic table method; with -O3 and auto-vectorization this is
// the strongest portable single-thread baseline short of hand-written
// pshufb kernels).  Encode matrices arrive from Python (the same Lagrange
// matrices the device uses), so native and device outputs are bit-identical.

#include <cstdint>
#include <cstring>
#if defined(__AVX2__)
#include <immintrin.h>
#endif

#include <array>
#include <atomic>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// GF(256)
// ---------------------------------------------------------------------------

static uint8_t MUL[256][256];
static int gf_ready = 0;

static uint8_t gf_mul_slow(uint8_t a, uint8_t b) {
    uint16_t p = 0;
    uint16_t aa = a;
    for (int i = 0; i < 8; i++) {
        if (b & 1) p ^= aa;
        b >>= 1;
        aa <<= 1;
        if (aa & 0x100) aa ^= 0x11D;
    }
    return (uint8_t)p;
}

void gf_init(void) {
    if (gf_ready) return;
    for (int a = 0; a < 256; a++)
        for (int b = 0; b < 256; b++)
            MUL[a][b] = gf_mul_slow((uint8_t)a, (uint8_t)b);
    gf_ready = 1;
}

// Override the multiplication table with a caller-supplied 256x256 one —
// the Python side loads the active codec's field representation (the
// leopard codec works in the Cantor-index domain, gf256.mul_table) so
// every table-method leg here computes in the same field as the device.
//
// INVARIANT (ADVICE r5): MUL is process-global and this write is not
// synchronized against readers.  The Python binding
// (celestia_tpu/utils/native.py) therefore holds one lock across BOTH
// the gf_load_mul call and every table-method entry point
// (rs_extend_square / extend_block_cpu / gf_matmul_axes), so a codec
// switch can never interleave with an in-flight table-method call and
// compute in a mixed field.  Callers bypassing the Python binding must
// uphold the same discipline: never call gf_load_mul while a
// table-method function is running on another thread.
void gf_load_mul(const uint8_t* table) {
    memcpy(MUL, table, 256 * 256);
    gf_ready = 1;  // later gf_init() calls must not clobber the load
}

// parity[i][b] ^= MUL[E[i][j]][data[j][b]] for a row of k shares of B bytes.
// E: k*k row-major; data: k*B; parity out: k*B.
static void rs_encode_axis(const uint8_t* E, const uint8_t* data,
                           uint8_t* parity, int k, int B) {
    memset(parity, 0, (size_t)k * B);
    for (int i = 0; i < k; i++) {
        uint8_t* out = parity + (size_t)i * B;
        for (int j = 0; j < k; j++) {
            const uint8_t c = E[i * k + j];
            if (c == 0) continue;
            const uint8_t* row = MUL[c];
            const uint8_t* in = data + (size_t)j * B;
            for (int b = 0; b < B; b++) out[b] ^= row[in[b]];
        }
    }
}

// Extend a k x k x B square into a 2k x 2k x B EDS (quadrant layout as the
// device kernel: Q1 row parity, Q2 column parity, Q3 parity of parity).
// square: k*k*B row-major; eds out: 2k*2k*B; E: k*k encode matrix.
void rs_extend_square(const uint8_t* square, const uint8_t* E, uint8_t* eds,
                      int k, int B) {
    gf_init();
    const int n = 2 * k;
    const size_t row_bytes = (size_t)n * B;
    // Q0
    for (int r = 0; r < k; r++)
        memcpy(eds + r * row_bytes, square + (size_t)r * k * B, (size_t)k * B);
    // Q1: row parity
    for (int r = 0; r < k; r++)
        rs_encode_axis(E, eds + r * row_bytes, eds + r * row_bytes + (size_t)k * B,
                       k, B);
    // Q2/Q3: column parity over the top half. Gather each column, encode,
    // scatter. (Columns are strided; gather keeps the inner loop dense.)
    uint8_t* col = new uint8_t[(size_t)k * B];
    uint8_t* par = new uint8_t[(size_t)k * B];
    for (int c = 0; c < n; c++) {
        for (int r = 0; r < k; r++)
            memcpy(col + (size_t)r * B, eds + r * row_bytes + (size_t)c * B, B);
        rs_encode_axis(E, col, par, k, B);
        for (int r = 0; r < k; r++)
            memcpy(eds + (size_t)(k + r) * row_bytes + (size_t)c * B,
                   par + (size_t)r * B, B);
    }
    delete[] col;
    delete[] par;
}

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4), portable
// ---------------------------------------------------------------------------

static const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

#if defined(__SHA__) && defined(__AVX2__)
// SHA-NI block compress (~5-8x the portable loop).  The reference's Go
// crypto/sha256 uses these instructions on every validator, so the CPU
// comparison legs must too or the bench baseline is understated.
// Message-schedule recurrence per 4-word group X_g (g >= 4):
//   X_g = sha256msg2( sha256msg1(X_{g-4}, X_{g-3})
//                     + alignr(X_{g-1}, X_{g-2}, 4), X_{g-1} )
static void sha256_compress_ni(uint32_t st[8], const uint8_t* block) {
    // the sha256* instructions have no VEX encoding (legacy SSE); with
    // surrounding -march=native code leaving ymm uppers dirty, every
    // one of them pays an AVX->SSE transition/merge penalty (~100x
    // observed here).  Clearing the uppers first makes them run at
    // native speed.
    _mm256_zeroupper();
    const __m128i MASK = _mm_set_epi64x(
        0x0c0d0e0f08090a0bULL, 0x0405060700010203ULL);
    __m128i tmp = _mm_loadu_si128((const __m128i*)&st[0]);   // DCBA
    __m128i s1 = _mm_loadu_si128((const __m128i*)&st[4]);    // HGFE
    tmp = _mm_shuffle_epi32(tmp, 0xB1);                      // CDAB
    s1 = _mm_shuffle_epi32(s1, 0x1B);                        // EFGH
    __m128i s0 = _mm_alignr_epi8(tmp, s1, 8);                // ABEF
    s1 = _mm_blend_epi16(s1, tmp, 0xF0);                     // CDGH
    const __m128i abef_save = s0, cdgh_save = s1;
    __m128i m[4];
    for (int i = 0; i < 4; i++)
        m[i] = _mm_shuffle_epi8(
            _mm_loadu_si128((const __m128i*)(block + 16 * i)), MASK);
    for (int g = 0; g < 16; g++) {
        __m128i msg = _mm_add_epi32(
            m[g & 3], _mm_loadu_si128((const __m128i*)&K256[4 * g]));
        s1 = _mm_sha256rnds2_epu32(s1, s0, msg);
        msg = _mm_shuffle_epi32(msg, 0x0E);
        s0 = _mm_sha256rnds2_epu32(s0, s1, msg);
        if (g >= 3 && g < 15) {
            // m[(g+1)&3] holds X_{g-3}, m[(g+2)&3] holds X_{g-2}
            __m128i t = _mm_alignr_epi8(m[g & 3], m[(g + 3) & 3], 4);
            m[(g + 1) & 3] = _mm_sha256msg2_epu32(
                _mm_add_epi32(
                    _mm_sha256msg1_epu32(m[(g + 1) & 3], m[(g + 2) & 3]),
                    t),
                m[g & 3]);
        }
    }
    s0 = _mm_add_epi32(s0, abef_save);
    s1 = _mm_add_epi32(s1, cdgh_save);
    tmp = _mm_shuffle_epi32(s0, 0x1B);                       // FEBA
    s1 = _mm_shuffle_epi32(s1, 0xB1);                        // DCHG
    s0 = _mm_blend_epi16(tmp, s1, 0xF0);                     // DCBA
    s1 = _mm_alignr_epi8(s1, tmp, 8);                        // HGFE
    _mm_storeu_si128((__m128i*)&st[0], s0);
    _mm_storeu_si128((__m128i*)&st[4], s1);
}
#endif

static void sha256_compress(uint32_t st[8], const uint8_t* block) {
#if defined(__SHA__) && defined(__AVX2__)
    sha256_compress_ni(st, block);
    return;
#endif
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)block[4 * i] << 24) | ((uint32_t)block[4 * i + 1] << 16) |
               ((uint32_t)block[4 * i + 2] << 8) | block[4 * i + 3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = st[0], b = st[1], c = st[2], d = st[3];
    uint32_t e = st[4], f = st[5], g = st[6], h = st[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + K256[i] + w[i];
        uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
        uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

static void sha256_one(const uint8_t* msg, size_t len, uint8_t out[32]) {
    uint32_t st[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                      0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    size_t i = 0;
    for (; i + 64 <= len; i += 64) sha256_compress(st, msg + i);
    uint8_t tail[128];
    size_t rem = len - i;
    memcpy(tail, msg + i, rem);
    tail[rem] = 0x80;
    size_t padded = (rem + 9 <= 64) ? 64 : 128;
    memset(tail + rem + 1, 0, padded - rem - 9);
    uint64_t bits = (uint64_t)len * 8;
    for (int j = 0; j < 8; j++) tail[padded - 1 - j] = (uint8_t)(bits >> (8 * j));
    for (size_t o = 0; o < padded; o += 64) sha256_compress(st, tail + o);
    for (int j = 0; j < 8; j++) {
        out[4 * j] = (uint8_t)(st[j] >> 24);
        out[4 * j + 1] = (uint8_t)(st[j] >> 16);
        out[4 * j + 2] = (uint8_t)(st[j] >> 8);
        out[4 * j + 3] = (uint8_t)st[j];
    }
}

// Batch API: n equal-length messages.
void sha256_batch(const uint8_t* msgs, int n, int len, uint8_t* out) {
    for (int i = 0; i < n; i++)
        sha256_one(msgs + (size_t)i * len, len, out + (size_t)i * 32);
}

// ---------------------------------------------------------------------------
// NMT roots over an EDS (namespaced digests, ignore-max rule)
// ---------------------------------------------------------------------------

static const int NS = 29;
static const int DIGEST = 2 * NS + 32;  // 90

static void nmt_leaf(const uint8_t* ns_prefixed, int len, uint8_t* out) {
    uint8_t buf[1 + 29 + 4096];
    buf[0] = 0x00;
    memcpy(buf + 1, ns_prefixed, len);
    memcpy(out, ns_prefixed, NS);
    memcpy(out + NS, ns_prefixed, NS);
    sha256_one(buf, len + 1, out + 2 * NS);
}

static void nmt_node(const uint8_t* l, const uint8_t* r, uint8_t* out) {
    uint8_t buf[1 + 2 * DIGEST];
    buf[0] = 0x01;
    memcpy(buf + 1, l, DIGEST);
    memcpy(buf + 1 + DIGEST, r, DIGEST);
    memcpy(out, l, NS);  // min = left.min
    int r_min_is_max = 1;
    for (int i = 0; i < NS; i++)
        if (r[i] != 0xFF) { r_min_is_max = 0; break; }
    memcpy(out + NS, r_min_is_max ? l + NS : r + NS, NS);
    sha256_one(buf, 1 + 2 * DIGEST, out + 2 * NS);
}

// Root of one tree whose leaves are ns-prefixed payloads (n a power of two).
void nmt_root(const uint8_t* leaves, int n, int leaf_len, uint8_t* out) {
    uint8_t* lvl = new uint8_t[(size_t)n * DIGEST];
    for (int i = 0; i < n; i++)
        nmt_leaf(leaves + (size_t)i * leaf_len, leaf_len, lvl + (size_t)i * DIGEST);
    int m = n;
    while (m > 1) {
        for (int i = 0; i < m / 2; i++)
            nmt_node(lvl + (size_t)(2 * i) * DIGEST,
                     lvl + (size_t)(2 * i + 1) * DIGEST,
                     lvl + (size_t)i * DIGEST);
        m /= 2;
    }
    memcpy(out, lvl, DIGEST);
    delete[] lvl;
}

// All 4k NMT axis roots of an EDS (2k x 2k x B): rows then columns, each
// with the Q0 namespace-prefix rule. out: (4k) x 90.
void eds_nmt_roots(const uint8_t* eds, int k, int B, uint8_t* out) {
    const int n = 2 * k;
    const int leaf_len = NS + B;
    uint8_t* leaves = new uint8_t[(size_t)n * leaf_len];
    // rows
    for (int r = 0; r < n; r++) {
        for (int c = 0; c < n; c++) {
            const uint8_t* cell = eds + ((size_t)r * n + c) * B;
            uint8_t* leaf = leaves + (size_t)c * leaf_len;
            if (r < k && c < k) memcpy(leaf, cell, NS);
            else memset(leaf, 0xFF, NS);
            memcpy(leaf + NS, cell, B);
        }
        nmt_root(leaves, n, leaf_len, out + (size_t)r * DIGEST);
    }
    // columns
    for (int c = 0; c < n; c++) {
        for (int r = 0; r < n; r++) {
            const uint8_t* cell = eds + ((size_t)r * n + c) * B;
            uint8_t* leaf = leaves + (size_t)r * leaf_len;
            if (r < k && c < k) memcpy(leaf, cell, NS);
            else memset(leaf, 0xFF, NS);
            memcpy(leaf + NS, cell, B);
        }
        nmt_root(leaves, n, leaf_len, out + (size_t)(n + c) * DIGEST);
    }
    delete[] leaves;
}

// RFC-6962 merkle root over n leaves of leaf_len bytes (any n >= 1):
// leaf = sha256(0x00||data), node = sha256(0x01||l||r), split at the
// largest power of two < n.
static void rfc6962_rec(const uint8_t* leaves, int n, int leaf_len,
                        uint8_t* out32) {
    if (n == 1) {
        uint8_t buf[1 + 256];
        buf[0] = 0x00;
        memcpy(buf + 1, leaves, leaf_len);
        sha256_one(buf, 1 + leaf_len, out32);
        return;
    }
    int split = 1;
    while (split * 2 < n) split *= 2;
    uint8_t lr[1 + 64];
    lr[0] = 0x01;
    rfc6962_rec(leaves, split, leaf_len, lr + 1);
    rfc6962_rec(leaves + (size_t)split * leaf_len, n - split, leaf_len,
                lr + 33);
    sha256_one(lr, 65, out32);
}

// Blob share commitment (go-square/inclusion.CreateCommitment role): the
// RFC-6962 root over the NMT roots of the blob's merkle-mountain-range
// subtrees.  leaves: n x leaf_len ns-prefixed shares (contiguous); sizes:
// m mountain widths summing to n.  One call replaces one ctypes crossing
// PER SUBTREE (~62/blob) — the host cost that dominated commitment
// recompute in PrepareProposal/ProcessProposal.
void create_commitment(const uint8_t* leaves, int n, int leaf_len,
                       const int32_t* sizes, int m, uint8_t* out32) {
    (void)n;
    uint8_t* roots = new uint8_t[(size_t)m * DIGEST];
    size_t off = 0;
    for (int i = 0; i < m; i++) {
        nmt_root(leaves + off * leaf_len, sizes[i], leaf_len,
                 roots + (size_t)i * DIGEST);
        off += (size_t)sizes[i];
    }
    rfc6962_rec(roots, m, DIGEST, out32);
    delete[] roots;
}

static void run_striped(void (*fn)(void*, int, int), void* ctx, int count,
                        int nthreads);

// Batched commitment computation: ONE ctypes crossing for ALL blobs of a
// proposal (512-PFB FilterTxs paid ~27 us of call overhead per blob).
// Blob b's leaves are rows [blob_off[b], blob_off[b+1]) of the contiguous
// leaves array; its mountain widths are sizes[size_off[b], size_off[b+1]).
// Threaded across blobs.
void create_commitments_batch(const uint8_t* leaves, int leaf_len,
                              const int32_t* blob_off,
                              const int32_t* sizes,
                              const int32_t* size_off, int nblobs,
                              uint8_t* out, int nthreads) {
    if (nthreads <= 0) {
        nthreads = (int)std::thread::hardware_concurrency();
        if (nthreads <= 0) nthreads = 1;
    }
    struct Ctx {
        const uint8_t* leaves;
        int leaf_len;
        const int32_t* blob_off;
        const int32_t* sizes;
        const int32_t* size_off;
        int nblobs;
        uint8_t* out;
    } ctx = {leaves, leaf_len, blob_off, sizes, size_off, nblobs, out};
    run_striped(
        [](void* p, int t, int nt) {
            Ctx& c = *(Ctx*)p;
            for (int b = t; b < c.nblobs; b += nt) {
                const int n = c.blob_off[b + 1] - c.blob_off[b];
                const int m = c.size_off[b + 1] - c.size_off[b];
                create_commitment(
                    c.leaves + (size_t)c.blob_off[b] * c.leaf_len, n,
                    c.leaf_len, c.sizes + c.size_off[b], m,
                    c.out + (size_t)b * 32);
            }
        },
        &ctx, nblobs, nthreads);
}

// Batched per-axis GF(256) matmul: out[i] = D[i] (rows_out x k) * X[i]
// (k x B), striped across nthreads threads.  The decode step of
// rsmt2d.Repair-style reconstruction: one matrix per axis (every axis can
// carry a different availability mask).
void gf_matmul_axes(const uint8_t* D, const uint8_t* X, uint8_t* out, int n,
                    int rows_out, int k, int B, int nthreads) {
    gf_init();
    if (nthreads <= 0) {
        nthreads = (int)std::thread::hardware_concurrency();
        if (nthreads <= 0) nthreads = 1;
    }
    if (nthreads > n) nthreads = n > 0 ? n : 1;
    auto work = [=](int t) {
        for (int i = t; i < n; i += nthreads) {
            const uint8_t* Di = D + (size_t)i * rows_out * k;
            const uint8_t* Xi = X + (size_t)i * k * B;
            uint8_t* Oi = out + (size_t)i * rows_out * B;
            memset(Oi, 0, (size_t)rows_out * B);
            for (int r = 0; r < rows_out; r++) {
                uint8_t* orow = Oi + (size_t)r * B;
                for (int j = 0; j < k; j++) {
                    const uint8_t c = Di[r * k + j];
                    if (c == 0) continue;
                    const uint8_t* mul = MUL[c];
                    const uint8_t* in = Xi + (size_t)j * B;
                    for (int b = 0; b < B; b++) orow[b] ^= mul[in[b]];
                }
            }
        }
    };
    if (nthreads == 1) {
        work(0);
    } else {
        std::vector<std::thread> ts;
        for (int t = 0; t < nthreads; t++) ts.emplace_back(work, t);
        for (auto& th : ts) th.join();
    }
}

// ---------------------------------------------------------------------------
// Threaded full CPU pipeline: extend + all NMT axis roots + data root.
// This is the honest CPU comparison leg for bench.py (the role Leopard-RS +
// crypto/sha256 play for the reference, SURVEY.md §2.2): full k, threaded,
// no extrapolation.
// ---------------------------------------------------------------------------

static void rfc6962_root_pow2_cpu(const uint8_t* leaves, int n, int leaf_len,
                                  uint8_t* out32) {
    // n a power of two; leaf hash = sha256(0x00||leaf), inner = sha256(0x01||l||r)
    uint8_t* lvl = new uint8_t[(size_t)n * 32];
    uint8_t buf[1 + 256];
    for (int i = 0; i < n; i++) {
        buf[0] = 0x00;
        memcpy(buf + 1, leaves + (size_t)i * leaf_len, leaf_len);
        sha256_one(buf, 1 + leaf_len, lvl + (size_t)i * 32);
    }
    int m = n;
    while (m > 1) {
        for (int i = 0; i < m / 2; i++) {
            buf[0] = 0x01;
            memcpy(buf + 1, lvl + (size_t)(2 * i) * 32, 32);
            memcpy(buf + 33, lvl + (size_t)(2 * i + 1) * 32, 32);
            sha256_one(buf, 65, lvl + (size_t)i * 32);
        }
        m /= 2;
    }
    memcpy(out32, lvl, 32);
    delete[] lvl;
}

// Thread-striping helper shared by the CPU pipelines.
static void run_striped(void (*fn)(void*, int, int), void* ctx, int count,
                        int nthreads) {
    int nt = nthreads < count ? nthreads : count;
    if (nt <= 1) {
        fn(ctx, 0, 1);
        return;
    }
    std::vector<std::thread> ts;
    for (int t = 0; t < nt; t++) ts.emplace_back(fn, ctx, t, nt);
    for (auto& th : ts) th.join();
}

// Atomic work-queue scheduler: tasks are pulled one at a time from a
// shared counter, so unevenly sized work items (an NMT axis root costs
// ~4x a Leopard column encode) load-balance across the pool — the
// property the overlapped extend->roots phase depends on.  Task order is
// PRESERVED in dispatch (item i is claimed before item i+n), which lets
// a mixed phase list its latency-critical items first.
static void run_pool(void (*fn)(void*, int), void* ctx, int count,
                     int nthreads) {
    int nt = nthreads < count ? nthreads : count;
    if (nt <= 1) {
        for (int i = 0; i < count; i++) fn(ctx, i);
        return;
    }
    std::atomic<int> next(0);
    auto work = [&]() {
        int i;
        while ((i = next.fetch_add(1, std::memory_order_relaxed)) < count)
            fn(ctx, i);
    };
    std::vector<std::thread> ts;
    for (int t = 0; t < nt; t++) ts.emplace_back(work);
    for (auto& th : ts) th.join();
}

static int resolve_threads(int nthreads) {
    if (nthreads > 0) return nthreads;
    int hc = (int)std::thread::hardware_concurrency();
    return hc > 0 ? hc : 1;
}

struct RootsCtx {
    const uint8_t* eds;
    uint8_t* roots;
    int k, B, n;
};

// One NMT axis root of an EDS.  a in [0, 2n): rows first, then columns;
// the Q0 namespace-prefix rule matches eds_nmt_roots.  The ~139 KB leaf
// scratch (k=128) is thread_local — one allocation per worker thread,
// not one mmap/munmap pair per axis on the hot path.
static void eds_axis_root(const RootsCtx& c, int a) {
    const int leaf_len = NS + c.B;
    thread_local std::vector<uint8_t> scratch;
    if (scratch.size() < (size_t)c.n * leaf_len)
        scratch.resize((size_t)c.n * leaf_len);
    uint8_t* leaves = scratch.data();
    const int is_col = a >= c.n;
    const int idx = is_col ? a - c.n : a;
    for (int j = 0; j < c.n; j++) {
        const int r = is_col ? j : idx;
        const int col = is_col ? idx : j;
        const uint8_t* cell = c.eds + ((size_t)r * c.n + col) * c.B;
        uint8_t* leaf = leaves + (size_t)j * leaf_len;
        if (r < c.k && col < c.k) memcpy(leaf, cell, NS);
        else memset(leaf, 0xFF, NS);
        memcpy(leaf + NS, cell, c.B);
    }
    nmt_root(leaves, c.n, leaf_len, c.roots + (size_t)a * DIGEST);
}

// Standalone threaded exports of the hashing stage: the Python host
// pipeline (celestia_tpu/ops/nmt.py, da/dah.py host regime) calls these
// directly so Python-side hashing disappears from the hot loop.

// All 4k NMT axis roots of an EDS, sharded across nthreads worker
// threads (0 = hardware concurrency).  out: 4k x 90, rows then columns.
void eds_nmt_roots_mt(const uint8_t* eds, int k, int B, uint8_t* out,
                      int nthreads) {
    nthreads = resolve_threads(nthreads);
    const int n = 2 * k;
    RootsCtx ctx = {eds, out, k, B, n};
    run_pool(
        [](void* p, int i) { eds_axis_root(*(RootsCtx*)p, i); },
        &ctx, 2 * n, nthreads);
}

// Threaded batch SHA-256 over n equal-length messages (rows), striped
// across nthreads threads — the batched SHA-256-over-rows entry point.
void sha256_batch_mt(const uint8_t* msgs, int n, int len, uint8_t* out,
                     int nthreads) {
    nthreads = resolve_threads(nthreads);
    struct Ctx {
        const uint8_t* msgs;
        int n, len;
        uint8_t* out;
    } ctx = {msgs, n, len, out};
    run_striped(
        [](void* p, int t, int nt) {
            Ctx& c = *(Ctx*)p;
            for (int i = t; i < c.n; i += nt)
                sha256_one(c.msgs + (size_t)i * c.len, c.len,
                           c.out + (size_t)i * 32);
        },
        &ctx, n, nthreads);
}

// ---------------------------------------------------------------------------
// Overlapped extend -> roots pipeline (shared by the table-method and
// leopard legs).  Three phases over one worker pool:
//
//   1. Q0 + Q1 per original row (the top half of the EDS is complete
//      at the barrier);
//   2. column extension (produces Q2/Q3) INTERLEAVED with the top-half
//      ROW roots, which depend only on phase 1 — row-root hashing
//      starts while the extension is still producing the remaining
//      quadrants instead of waiting for the whole square;
//   3. the remaining axis roots (bottom rows + all columns), then the
//      RFC-6962 data root.
//
// Phase 2 lists the columns first: the critical path runs through the
// extension, and run_pool's in-order dispatch makes the roots pure
// filler for threads that run out of column work.
// ---------------------------------------------------------------------------

void leo_encode(const uint8_t* data, int k, int B, uint8_t* parity);

struct ExtendRootsCtx {
    const uint8_t* square;
    const uint8_t* E;  // encode matrix (table method); null for leopard
    uint8_t* eds;
    RootsCtx roots;
    int k, B, n, use_leo;
    size_t row_bytes;
};

static void ext_row_task(ExtendRootsCtx& c, int r) {
    uint8_t* row = c.eds + (size_t)r * c.row_bytes;
    memcpy(row, c.square + (size_t)r * c.k * c.B, (size_t)c.k * c.B);
    if (c.use_leo) leo_encode(row, c.k, c.B, row + (size_t)c.k * c.B);
    else rs_encode_axis(c.E, row, row + (size_t)c.k * c.B, c.k, c.B);
}

static void ext_col_task(ExtendRootsCtx& c, int cc) {
    thread_local std::vector<uint8_t> gather;
    if (gather.size() < 2 * (size_t)c.k * c.B)
        gather.resize(2 * (size_t)c.k * c.B);
    uint8_t* col = gather.data();
    uint8_t* par = col + (size_t)c.k * c.B;
    for (int r = 0; r < c.k; r++)
        memcpy(col + (size_t)r * c.B,
               c.eds + (size_t)r * c.row_bytes + (size_t)cc * c.B, c.B);
    if (c.use_leo) leo_encode(col, c.k, c.B, par);
    else rs_encode_axis(c.E, col, par, c.k, c.B);
    for (int r = 0; r < c.k; r++)
        memcpy(c.eds + (size_t)(c.k + r) * c.row_bytes + (size_t)cc * c.B,
               par + (size_t)r * c.B, c.B);
}

static void extend_block_overlapped(const uint8_t* square, const uint8_t* E,
                                    int use_leo, int k, int B, int nthreads,
                                    uint8_t* eds, uint8_t* roots,
                                    uint8_t* data_root) {
    nthreads = resolve_threads(nthreads);
    const int n = 2 * k;
    ExtendRootsCtx ctx = {square, E,     eds, {eds, roots, k, B, n},
                          k,      B,     n,   use_leo,
                          (size_t)n * B};
    // phase 1: Q0 + Q1 rows
    run_pool(
        [](void* p, int i) { ext_row_task(*(ExtendRootsCtx*)p, i); },
        &ctx, k, nthreads);
    // phase 2: columns + top-half row roots, overlapped
    run_pool(
        [](void* p, int i) {
            ExtendRootsCtx& c = *(ExtendRootsCtx*)p;
            if (i < c.n) ext_col_task(c, i);
            else eds_axis_root(c.roots, i - c.n);  // row roots [0, k)
        },
        &ctx, n + k, nthreads);
    // phase 3: remaining axis roots (rows [k, n) + columns [n, 2n))
    run_pool(
        [](void* p, int i) {
            ExtendRootsCtx& c = *(ExtendRootsCtx*)p;
            eds_axis_root(c.roots, c.k + i);
        },
        &ctx, 3 * k, nthreads);
    rfc6962_root_pow2_cpu(roots, 2 * n, DIGEST, data_root);
}

// Full ExtendBlock on the CPU: square k*k*B -> EDS 2k*2k*B, 4k NMT axis
// roots (4k x 90) and the RFC-6962 data root (32 bytes), using nthreads
// worker threads (0 = hardware concurrency), extend and roots overlapped.
void extend_block_cpu(const uint8_t* square, const uint8_t* E, int k, int B,
                      int nthreads, uint8_t* eds, uint8_t* roots,
                      uint8_t* data_root) {
    gf_init();
    extend_block_overlapped(square, E, 0, k, B, nthreads, eds, roots,
                            data_root);
}

// ---------------------------------------------------------------------------
// Leopard-compatible O(n log n) codec: the LCH novel-basis FFT over
// GF(2^8)/0x11D with the catid/leopard Cantor basis, high-rate layout
// (parity at positions [0, k), data at [k, 2k)).  This is the reference
// chain's erasure code (rsmt2d.NewLeoRSCodec ->
// klauspost/reedsolomon's leopard FF8 port; selected at
// /root/reference/pkg/appconsts/global_consts.go:91-92).  Field elements
// are represented in the Cantor-index domain exactly as leopard's tables
// do (see celestia_tpu/ops/gf256.py "codec selection"); correctness is
// pinned by tests/test_leopard_codec.py: this FFT must agree
// byte-for-byte with the independent Lagrange-matrix construction.
// Role here: the honest CPU comparison leg for bench.py (vs_leopard_cpu)
// and a fast host encode for the leopard codec.
// ---------------------------------------------------------------------------

static uint8_t LEO_MUL_TAB[256][256];
static uint8_t LEO_SKEW[8][256];  // SKEW[j][x] = W_j(x) / W_j(2^j) in F'
static int leo_ready = 0;

static void leo_init(void) {
    if (leo_ready) return;
    // standard log/exp over 0x11D (LFSR), then remap through the Cantor
    // index bijection C so multiplication is leopard's conjugated form
    uint8_t lg[256] = {0};
    uint8_t ex[255];
    int x = 1;
    for (int i = 0; i < 255; i++) {
        ex[i] = (uint8_t)x;
        lg[x] = (uint8_t)i;
        x <<= 1;
        if (x & 0x100) x ^= 0x11D;
    }
    static const uint8_t basis[8] = {1, 214, 152, 146, 86, 200, 88, 230};
    uint8_t C[256];
    C[0] = 0;
    for (int j = 0; j < 8; j++) {
        int w = 1 << j;
        for (int i = 0; i < w; i++) C[w + i] = C[i] ^ basis[j];
    }
    uint8_t Cinv[256];
    for (int i = 0; i < 256; i++) Cinv[C[i]] = (uint8_t)i;
    uint8_t leo_log[256] = {0};
    uint8_t leo_exp[255];
    for (int v = 1; v < 256; v++) leo_log[v] = lg[C[v]];
    for (int e = 0; e < 255; e++) leo_exp[e] = Cinv[ex[e]];
    for (int a = 0; a < 256; a++)
        for (int b = 0; b < 256; b++)
            LEO_MUL_TAB[a][b] =
                (a && b) ? leo_exp[(leo_log[a] + leo_log[b]) % 255] : 0;
    // subspace vanishing polynomials: W_0(x) = x,
    // W_{j+1}(x) = W_j(x) * W_j(x ^ 2^j)  (evaluated over all 256 points)
    uint8_t W[256];
    for (int xv = 0; xv < 256; xv++) W[xv] = (uint8_t)xv;
    for (int j = 0; j < 8; j++) {
        const uint8_t wj = W[1 << j];  // W_j(v_j) != 0 (v_j not in V_j)
        const uint8_t inv = leo_exp[(255 - leo_log[wj]) % 255];
        for (int xv = 0; xv < 256; xv++)
            LEO_SKEW[j][xv] = LEO_MUL_TAB[W[xv]][inv];
        if (j < 7) {
            uint8_t Wn[256];
            for (int xv = 0; xv < 256; xv++)
                Wn[xv] = LEO_MUL_TAB[W[xv]][W[xv ^ (1 << j)]];
            memcpy(W, Wn, 256);
        }
    }
    leo_ready = 1;
}

static inline void leo_mul_add(uint8_t* x, const uint8_t* y, uint8_t c,
                               int B) {
    if (c == 0) return;
    const uint8_t* row = LEO_MUL_TAB[c];
#if defined(__AVX2__)
    // pshufb 4-bit-split constant multiply — the same kernel shape real
    // Leopard uses, so the bench leg is an honest SIMD comparison:
    // y = ylo ^ (yhi << 4), mul(c, y) = LO[ylo] ^ HI[yhi] by linearity
    // of GF multiplication over XOR.
    if (B >= 32) {
        uint8_t lot[16], hit[16];
        for (int v = 0; v < 16; v++) {
            lot[v] = row[v];
            hit[v] = row[v << 4];
        }
        const __m256i lo =
            _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)lot));
        const __m256i hi =
            _mm256_broadcastsi128_si256(_mm_loadu_si128((const __m128i*)hit));
        const __m256i mask = _mm256_set1_epi8(0x0F);
        int b = 0;
        for (; b + 32 <= B; b += 32) {
            __m256i yv = _mm256_loadu_si256((const __m256i*)(y + b));
            __m256i pl = _mm256_shuffle_epi8(lo, _mm256_and_si256(yv, mask));
            __m256i ph = _mm256_shuffle_epi8(
                hi, _mm256_and_si256(_mm256_srli_epi16(yv, 4), mask));
            __m256i xv = _mm256_loadu_si256((const __m256i*)(x + b));
            _mm256_storeu_si256(
                (__m256i*)(x + b),
                _mm256_xor_si256(xv, _mm256_xor_si256(pl, ph)));
        }
        for (; b < B; b++) x[b] ^= row[y[b]];
        return;
    }
#endif
    for (int b = 0; b < B; b++) x[b] ^= row[y[b]];
}

static inline void leo_xor_blk(uint8_t* x, const uint8_t* y, int B) {
    for (int b = 0; b < B; b++) x[b] ^= y[b];
}

// FFT: novel-basis coefficients -> evaluations at coset ^ [0, n).
// Butterfly (a, b) -> (a + s*b, a + (s+1)*b) with s the coset skew; the
// paired point differs by v_j, and W_j(x + v_j)/W_j(v_j) = s + 1 because
// W_j is GF(2)-linearized.
static void leo_fft(uint8_t* work, int n, int coset, int B) {
    for (int dist = n >> 1, j = 0; dist >= 1; dist >>= 1) {
        for (j = 0; (1 << j) < dist; j++) {}
        for (int b0 = 0; b0 < n; b0 += 2 * dist) {
            const uint8_t skew = LEO_SKEW[j][coset ^ b0];
            for (int i = b0; i < b0 + dist; i++) {
                uint8_t* a = work + (size_t)i * B;
                uint8_t* b = work + (size_t)(i + dist) * B;
                leo_mul_add(a, b, skew, B);  // a += s*b
                leo_xor_blk(b, a, B);        // b  = a_old + (s+1)*b_old
            }
        }
    }
}

// exact inverse of leo_fft (same skews, reversed order + inverted
// butterfly: b' = a ^ b recovers the f1 half, then a ^= s*b')
static void leo_ifft(uint8_t* work, int n, int coset, int B) {
    for (int dist = 1; dist < n; dist <<= 1) {
        int j = 0;
        for (j = 0; (1 << j) < dist; j++) {}
        for (int b0 = 0; b0 < n; b0 += 2 * dist) {
            const uint8_t skew = LEO_SKEW[j][coset ^ b0];
            for (int i = b0; i < b0 + dist; i++) {
                uint8_t* a = work + (size_t)i * B;
                uint8_t* b = work + (size_t)(i + dist) * B;
                leo_xor_blk(b, a, B);
                leo_mul_add(a, b, skew, B);
            }
        }
    }
}

// One axis: k data shards (B bytes each) -> k parity shards.  High-rate
// m = k (k a power of two): recover the interpolating polynomial's
// novel-basis coefficients from the data coset (offset k), then evaluate
// at the parity coset (offset 0).  O(k log k) block operations.
void leo_encode(const uint8_t* data, int k, int B, uint8_t* parity) {
    leo_init();
    memcpy(parity, data, (size_t)k * B);
    leo_ifft(parity, k, k, B);
    leo_fft(parity, k, 0, B);
}

// Leopard-codec square extension (quadrant layout as rs_extend_square).
void leo_extend_square_cpu(const uint8_t* square, uint8_t* eds, int k, int B,
                           int nthreads) {
    leo_init();
    if (nthreads <= 0) {
        nthreads = (int)std::thread::hardware_concurrency();
        if (nthreads <= 0) nthreads = 1;
    }
    const int n = 2 * k;
    const size_t row_bytes = (size_t)n * B;
    struct Ctx {
        const uint8_t* square;
        uint8_t* eds;
        int k, B, n;
        size_t row_bytes;
    } ctx = {square, eds, k, B, n, row_bytes};
    // Q0 + Q1 per original row
    run_striped(
        [](void* p, int t, int nt) {
            Ctx& c = *(Ctx*)p;
            for (int r = t; r < c.k; r += nt) {
                memcpy(c.eds + r * c.row_bytes,
                       c.square + (size_t)r * c.k * c.B, (size_t)c.k * c.B);
                leo_encode(c.eds + r * c.row_bytes, c.k, c.B,
                           c.eds + r * c.row_bytes + (size_t)c.k * c.B);
            }
        },
        &ctx, k, nthreads);
    // Q2/Q3 per column (gather, encode, scatter)
    run_striped(
        [](void* p, int t, int nt) {
            Ctx& c = *(Ctx*)p;
            uint8_t* col = new uint8_t[(size_t)c.k * c.B];
            uint8_t* par = new uint8_t[(size_t)c.k * c.B];
            for (int cc = t; cc < c.n; cc += nt) {
                for (int r = 0; r < c.k; r++)
                    memcpy(col + (size_t)r * c.B,
                           c.eds + r * c.row_bytes + (size_t)cc * c.B, c.B);
                leo_encode(col, c.k, c.B, par);
                for (int r = 0; r < c.k; r++)
                    memcpy(c.eds + (size_t)(c.k + r) * c.row_bytes +
                               (size_t)cc * c.B,
                           par + (size_t)r * c.B, c.B);
            }
            delete[] col;
            delete[] par;
        },
        &ctx, n, nthreads);
}

// --- Leopard O(n log n) ERASURE DECODE -------------------------------------
//
// Forney-style over the novel basis: with erasure set M and data poly F
// (deg < k), let E(x) = prod_{m in M} (x ^ x_m).  W = E*F has known
// evaluations EVERYWHERE on the n-point domain: W(x_i) = r_i*E(x_i) at
// received points, 0 at erased ones.  IFFT yields W's novel-basis
// coefficients (deg(E*F) <= |M|+k-1 <= n-1 since |M| <= k).  Both W and
// E vanish at x_m, so F(x_m) = W'(x_m) / E'(x_m).
//
// The formal derivative is CLEAN in the normalized novel basis: each
// basis factor s_i = W_i/W_i(v_i) is a linearized polynomial, so
// s_i' is the constant c_i = W_i'(0)/W_i(v_i) with
// W_i'(0) = prod_{v in V_i, v != 0} v, and
//   (X_j)' = sum_{i in bits(j)} c_i * X_{j - 2^i}
// i.e. derivative = for each bit level i: coeff[j - 2^i] ^= c_i * coeff[j].
//
// E' at an erased point: E'(x_m) = prod_{m' != m} (x_m ^ x_{m'}) (the
// product rule collapses — every other term contains the (x ^ x_m)
// factor).  All in the Cantor-index field; position -> point is XOR k.

static uint8_t LEO_DERIV_C[8];  // c_i per bit level
static int leo_deriv_ready = 0;

static void leo_deriv_init(void) {
    if (leo_deriv_ready) return;
    leo_init();
    for (int i = 0; i < 8; i++) {
        // W_i'(0) = prod of nonzero elements of V_i = span{v_0..v_{i-1}}
        uint8_t num = 1;
        for (int v = 1; v < (1 << i); v++) num = LEO_MUL_TAB[num][(uint8_t)v];
        // W_i(v_i): evaluate prod_{v in V_i} (v_i ^ v) directly
        uint8_t den = 1;
        for (int v = 0; v < (1 << i); v++)
            den = LEO_MUL_TAB[den][(uint8_t)((1 << i) ^ v)];
        // c_i = num / den
        uint8_t inv = 1, acc = den;  // den^254 = den^-1 (Fermat, 2^8)
        for (int e = 0; e < 7; e++) {
            acc = LEO_MUL_TAB[acc][acc];
            inv = LEO_MUL_TAB[inv][acc];
        }
        LEO_DERIV_C[i] = LEO_MUL_TAB[num][inv];
    }
    leo_deriv_ready = 1;
}

static inline uint8_t leo_inv_scalar(uint8_t a) {
    uint8_t inv = 1, acc = a;  // a^254
    for (int e = 0; e < 7; e++) {
        acc = LEO_MUL_TAB[acc][acc];
        inv = LEO_MUL_TAB[inv][acc];
    }
    return inv;
}

// Decode ONE axis in place.  shards: n x B rows in EDS POSITION order
// (data rows [0,k), parity rows [k,2k)); present: n bytes (0/1).
// Erased rows are overwritten with the reconstruction.  Returns 1 on
// success, 0 if fewer than k rows are present.  work must hold 2*n*B
// (coefficients + the derivative output).
int leo_decode_axis(uint8_t* shards, const uint8_t* present, int n, int B,
                    uint8_t* work) {
    leo_deriv_init();
    const int k = n / 2;
    int n_present = 0;
    for (int i = 0; i < n; i++) n_present += present[i] ? 1 : 0;
    if (n_present < k) return 0;
    if (n_present == n) return 1;
    // point domain: point j <-> position j ^ k
    uint8_t eloc[256];  // E evaluated at every domain point
    uint8_t is_erased[256];
    for (int j = 0; j < n; j++) {
        is_erased[j] = !present[j ^ k];
        eloc[j] = 1;
    }
    for (int m = 0; m < n; m++) {
        if (!is_erased[m]) continue;
        for (int j = 0; j < n; j++) {
            if (j == m) continue;  // skip only the OWN factor
            eloc[j] = LEO_MUL_TAB[eloc[j]][(uint8_t)(j ^ m)];
        }
    }
    // After the passes: a RECEIVED point j accumulated every erased
    // factor -> eloc[j] = E(x_j); an ERASED point m accumulated every
    // factor but its own -> eloc[m] = prod_{m' != m}(x_m ^ x_{m'})
    // = E'(x_m) (the product-rule survivor).  Never zero an entry: the
    // is_erased flag is what distinguishes the two meanings.
    // W evaluations into work (point order)
    for (int j = 0; j < n; j++) {
        uint8_t* dst = work + (size_t)j * B;
        if (is_erased[j]) {
            memset(dst, 0, B);
        } else {
            const uint8_t* row = LEO_MUL_TAB[eloc[j]];
            const uint8_t* src = shards + (size_t)(j ^ k) * B;
            if (eloc[j] == 0) {
                memset(dst, 0, B);
            } else {
                for (int b = 0; b < B; b++) dst[b] = row[src[b]];
            }
        }
    }
    leo_ifft(work, n, 0, B);  // novel-basis coefficients of W
    // formal derivative into a SEPARATE buffer: b'_m = sum over clear
    // bits i of m of c_i * b_{m + 2^i}.  It must not run in place — the
    // original b_m does not belong in the output, and later levels must
    // read unmutated inputs.
    uint8_t* deriv = work + (size_t)n * B;
    memset(deriv, 0, (size_t)n * B);
    for (int i = 0; (1 << i) < n; i++) {
        const uint8_t c = LEO_DERIV_C[i];
        for (int m = 0; m < n; m++) {
            if (m & (1 << i)) continue;
            leo_mul_add(deriv + (size_t)m * B,
                        work + (size_t)(m + (1 << i)) * B, c, B);
        }
    }
    leo_fft(deriv, n, 0, B);  // W' evaluated at every domain point
    for (int m = 0; m < n; m++) {
        if (!is_erased[m]) continue;
        const uint8_t scale = leo_inv_scalar(eloc[m]);  // 1 / E'(x_m)
        uint8_t* dst = shards + (size_t)(m ^ k) * B;
        const uint8_t* row = LEO_MUL_TAB[scale];
        const uint8_t* src = deriv + (size_t)m * B;
        for (int b = 0; b < B; b++) dst[b] = row[src[b]];
    }
    return 1;
}

// Threaded batch: axes x n x B, one availability row each.
void leo_decode_axes(uint8_t* data, const uint8_t* present, int n_axes,
                     int n, int B, uint8_t* ok, int nthreads) {
    leo_deriv_init();
    if (nthreads <= 0) {
        nthreads = (int)std::thread::hardware_concurrency();
        if (nthreads <= 0) nthreads = 1;
    }
    struct Ctx {
        uint8_t* data;
        const uint8_t* present;
        int n_axes, n, B;
        uint8_t* ok;
    } ctx = {data, present, n_axes, n, B, ok};
    run_striped(
        [](void* p, int t, int nt) {
            Ctx& c = *(Ctx*)p;
            std::vector<uint8_t> work(2 * (size_t)c.n * c.B);
            for (int a = t; a < c.n_axes; a += nt)
                c.ok[a] = (uint8_t)leo_decode_axis(
                    c.data + (size_t)a * c.n * c.B,
                    c.present + (size_t)a * c.n, c.n, c.B, work.data());
        },
        &ctx, n_axes, nthreads);
}

// Full leopard-codec ExtendBlock: the O(n log n) FFT extension with the
// NMT/data-root stage overlapped into it — the honest vs_leopard_cpu
// bench leg and the host-regime hot path.
void extend_block_leopard_cpu(const uint8_t* square, int k, int B,
                              int nthreads, uint8_t* eds, uint8_t* roots,
                              uint8_t* data_root) {
    leo_init();
    extend_block_overlapped(square, nullptr, 1, k, B, nthreads, eds, roots,
                            data_root);
}

// ---------------------------------------------------------------------------
// secp256k1 point arithmetic (host-native signature verification)
//
// Role: the reference leans on a C secp256k1 library for tx signature
// verification (decred secp256k1, SURVEY.md §2.2; go.mod:82) — a full square
// of PFBs means hundreds of ECDSA verifies per ProcessProposal, which would
// dominate block time in pure Python.  This implements the expensive part
// (double-scalar point multiplication u1*G + u2*Q over the curve) natively;
// the cheap scalar arithmetic mod the group order stays in Python, where
// CPython's pow() is already C.
//
// Field: GF(p), p = 2^256 - 0x1000003D1, four 64-bit limbs (little-endian),
// fully reduced between ops; products via unsigned __int128 with the
// standard two-stage fold of the high 256 bits (2^256 ≡ 0x1000003D1 mod p).
// Points: Jacobian coordinates, a=0 curve.  Scalars arrive as 32-byte
// big-endian from Python; the double multiplication runs a joint wNAF loop
// (w=8 fixed table of odd multiples of G built once; w=5 odd multiples of Q
// per call).  Verification-only — nothing here handles secret data, so no
// constant-time discipline is needed.
// ---------------------------------------------------------------------------

typedef unsigned __int128 u128;

struct Fe {
    uint64_t v[4];  // little-endian limbs, fully reduced (< p)
};

static const Fe FE_P = {{0xFFFFFFFEFFFFFC2FULL, 0xFFFFFFFFFFFFFFFFULL,
                         0xFFFFFFFFFFFFFFFFULL, 0xFFFFFFFFFFFFFFFFULL}};
static const uint64_t P_C = 0x1000003D1ULL;  // 2^256 - p

static inline int fe_is_zero(const Fe& a) {
    return (a.v[0] | a.v[1] | a.v[2] | a.v[3]) == 0;
}

static inline int fe_cmp(const Fe& a, const Fe& b) {
    for (int i = 3; i >= 0; i--) {
        if (a.v[i] < b.v[i]) return -1;
        if (a.v[i] > b.v[i]) return 1;
    }
    return 0;
}

// r = a - b, assuming a >= b.
static inline void fe_sub_raw(Fe& r, const Fe& a, const Fe& b) {
    u128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        u128 d = (u128)a.v[i] - b.v[i] - borrow;
        r.v[i] = (uint64_t)d;
        borrow = (d >> 64) & 1;
    }
}

static inline void fe_add(Fe& r, const Fe& a, const Fe& b) {
    u128 carry = 0;
    for (int i = 0; i < 4; i++) {
        u128 s = (u128)a.v[i] + b.v[i] + carry;
        r.v[i] = (uint64_t)s;
        carry = s >> 64;
    }
    if (carry) {
        // r held (a+b) mod 2^256; a+b-p = r + C, which stays < p (a,b < p).
        u128 c = P_C;
        for (int i = 0; i < 4 && c; i++) {
            u128 s = (u128)r.v[i] + c;
            r.v[i] = (uint64_t)s;
            c = s >> 64;
        }
    } else if (fe_cmp(r, FE_P) >= 0) {
        fe_sub_raw(r, r, FE_P);
    }
}

static inline void fe_sub(Fe& r, const Fe& a, const Fe& b) {
    if (fe_cmp(a, b) >= 0) {
        fe_sub_raw(r, a, b);
    } else {
        Fe t;
        fe_sub_raw(t, b, a);      // t = b - a
        fe_sub_raw(r, FE_P, t);   // r = p - t = a - b + p
    }
}

static void fe_mul(Fe& r, const Fe& a, const Fe& b) {
    uint64_t t[8] = {0};
    for (int i = 0; i < 4; i++) {
        u128 carry = 0;
        for (int j = 0; j < 4; j++) {
            u128 cur = (u128)a.v[i] * b.v[j] + t[i + j] + carry;
            t[i + j] = (uint64_t)cur;
            carry = cur >> 64;
        }
        t[i + 4] = (uint64_t)carry;
    }
    // fold high 256 bits: t[0..3] += t[4..7] * C
    uint64_t r4 = 0;
    {
        u128 carry = 0;
        for (int i = 0; i < 4; i++) {
            u128 cur = (u128)t[i + 4] * P_C + t[i] + carry;
            t[i] = (uint64_t)cur;
            carry = cur >> 64;
        }
        r4 = (uint64_t)carry;  // < 2^34
    }
    // fold the overflow limb
    {
        u128 carry = (u128)r4 * P_C;
        for (int i = 0; i < 4 && carry; i++) {
            u128 cur = (u128)t[i] + (uint64_t)carry;
            t[i] = (uint64_t)cur;
            carry = (carry >> 64) + (cur >> 64);
        }
        if (carry) {  // wrapped past 2^256 once more: add C
            u128 c = P_C;
            for (int i = 0; i < 4 && c; i++) {
                u128 cur = (u128)t[i] + c;
                t[i] = (uint64_t)cur;
                c = cur >> 64;
            }
        }
    }
    Fe out = {{t[0], t[1], t[2], t[3]}};
    if (fe_cmp(out, FE_P) >= 0) fe_sub_raw(out, out, FE_P);
    r = out;
}

static inline void fe_sqr(Fe& r, const Fe& a) { fe_mul(r, a, a); }

// r = base^e (e big-endian bytes), square-and-multiply.
static void fe_pow(Fe& r, const Fe& base, const uint8_t e[32]) {
    Fe acc = {{1, 0, 0, 0}};
    for (int i = 0; i < 32; i++) {
        for (int bit = 7; bit >= 0; bit--) {
            fe_sqr(acc, acc);
            if ((e[i] >> bit) & 1) fe_mul(acc, acc, base);
        }
    }
    r = acc;
}

static void fe_inv(Fe& r, const Fe& a) {
    static const uint8_t P_MINUS_2[32] = {
        0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF,
        0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF,
        0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFE, 0xFF, 0xFF, 0xFC, 0x2D};
    fe_pow(r, a, P_MINUS_2);
}

static void fe_sqrt(Fe& r, const Fe& a) {
    // p ≡ 3 (mod 4): sqrt = a^((p+1)/4); caller must check r^2 == a.
    static const uint8_t EXP[32] = {
        0x3F, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF,
        0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF,
        0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xFF, 0xBF, 0xFF, 0xFF, 0x0C};
    fe_pow(r, a, EXP);
}

static void fe_from_bytes(Fe& r, const uint8_t b[32]) {
    for (int i = 0; i < 4; i++) {
        uint64_t limb = 0;
        for (int j = 0; j < 8; j++) limb = (limb << 8) | b[(3 - i) * 8 + j];
        r.v[i] = limb;
    }
}

static void fe_to_bytes(uint8_t b[32], const Fe& a) {
    for (int i = 0; i < 4; i++)
        for (int j = 0; j < 8; j++)
            b[(3 - i) * 8 + j] = (uint8_t)(a.v[i] >> (8 * (7 - j)));
}

static inline void fe_neg(Fe& r, const Fe& a) {
    if (fe_is_zero(a)) { r = a; return; }
    fe_sub_raw(r, FE_P, a);
}

// Jacobian point; infinity encoded as z == 0.
struct Jac {
    Fe x, y, z;
};
struct Aff {
    Fe x, y;
};

static const Jac JAC_INF = {{{0, 0, 0, 0}}, {{1, 0, 0, 0}}, {{0, 0, 0, 0}}};

static inline int jac_is_inf(const Jac& p) { return fe_is_zero(p.z); }

static void jac_dbl(Jac& r, const Jac& p) {
    // Writes go through temporaries: callers double in place (r aliases p).
    if (jac_is_inf(p) || fe_is_zero(p.y)) { r = JAC_INF; return; }
    Fe A, B, C, D, E, F, t, t2, x3, y3, z3;
    fe_sqr(A, p.x);               // A = X^2
    fe_sqr(B, p.y);               // B = Y^2
    fe_sqr(C, B);                 // C = B^2
    fe_add(t, p.x, B);
    fe_sqr(t, t);
    fe_sub(t, t, A);
    fe_sub(t, t, C);
    fe_add(D, t, t);              // D = 2((X+B)^2 - A - C)
    fe_add(E, A, A);
    fe_add(E, E, A);              // E = 3A
    fe_sqr(F, E);                 // F = E^2
    fe_add(t, D, D);
    fe_sub(x3, F, t);             // X3 = F - 2D
    fe_sub(t, D, x3);
    fe_mul(t, E, t);
    fe_add(t2, C, C);
    fe_add(t2, t2, t2);
    fe_add(t2, t2, t2);           // 8C
    fe_sub(y3, t, t2);            // Y3 = E(D - X3) - 8C
    fe_mul(t, p.y, p.z);
    fe_add(z3, t, t);             // Z3 = 2YZ
    r.x = x3;
    r.y = y3;
    r.z = z3;
}

static void jac_add(Jac& r, const Jac& p, const Jac& q) {
    if (jac_is_inf(p)) { r = q; return; }
    if (jac_is_inf(q)) { r = p; return; }
    Fe z1z1, z2z2, u1, u2, s1, s2, t;
    fe_sqr(z1z1, p.z);
    fe_sqr(z2z2, q.z);
    fe_mul(u1, p.x, z2z2);
    fe_mul(u2, q.x, z1z1);
    fe_mul(t, q.z, z2z2);
    fe_mul(s1, p.y, t);
    fe_mul(t, p.z, z1z1);
    fe_mul(s2, q.y, t);
    Fe h, rr;
    fe_sub(h, u2, u1);
    fe_sub(rr, s2, s1);
    if (fe_is_zero(h)) {
        if (fe_is_zero(rr)) { jac_dbl(r, p); return; }
        r = JAC_INF;
        return;
    }
    Fe h2, h3, u1h2;
    fe_sqr(h2, h);
    fe_mul(h3, h, h2);
    fe_mul(u1h2, u1, h2);
    fe_sqr(t, rr);
    fe_sub(t, t, h3);
    fe_sub(t, t, u1h2);
    fe_sub(r.x, t, u1h2);         // X3 = R^2 - H^3 - 2 U1 H^2
    fe_sub(t, u1h2, r.x);
    fe_mul(t, rr, t);
    Fe s1h3;
    fe_mul(s1h3, s1, h3);
    fe_sub(r.y, t, s1h3);         // Y3 = R(U1 H^2 - X3) - S1 H^3
    fe_mul(t, p.z, q.z);
    fe_mul(r.z, t, h);            // Z3 = Z1 Z2 H
}

// Mixed addition: q affine (z = 1).
static void jac_add_aff(Jac& r, const Jac& p, const Aff& q) {
    if (jac_is_inf(p)) {
        r.x = q.x;
        r.y = q.y;
        r.z = {{1, 0, 0, 0}};
        return;
    }
    Fe z1z1, u2, s2, t;
    fe_sqr(z1z1, p.z);
    fe_mul(u2, q.x, z1z1);
    fe_mul(t, p.z, z1z1);
    fe_mul(s2, q.y, t);
    Fe h, rr;
    fe_sub(h, u2, p.x);
    fe_sub(rr, s2, p.y);
    if (fe_is_zero(h)) {
        if (fe_is_zero(rr)) { jac_dbl(r, p); return; }
        r = JAC_INF;
        return;
    }
    Fe h2, h3, u1h2;
    fe_sqr(h2, h);
    fe_mul(h3, h, h2);
    fe_mul(u1h2, p.x, h2);
    fe_sqr(t, rr);
    fe_sub(t, t, h3);
    fe_sub(t, t, u1h2);
    fe_sub(r.x, t, u1h2);
    fe_sub(t, u1h2, r.x);
    fe_mul(t, rr, t);
    Fe s1h3;
    fe_mul(s1h3, p.y, h3);
    fe_sub(r.y, t, s1h3);
    fe_mul(r.z, p.z, h);
}

static int jac_to_aff(Aff& r, const Jac& p) {
    if (jac_is_inf(p)) return 0;
    Fe zi, zi2;
    fe_inv(zi, p.z);
    fe_sqr(zi2, zi);
    fe_mul(r.x, p.x, zi2);
    fe_mul(zi2, zi2, zi);
    fe_mul(r.y, p.y, zi2);
    return 1;
}

// --- fixed G table: odd multiples 1G, 3G, ..., 255G (wNAF window 8) ---
// plus the same table mapped through the GLV endomorphism phi(x, y) =
// (beta*x, y), where beta is a primitive cube root of unity mod p:
// lambda*(x, y) = phi(x, y) for the matching cube root lambda mod n.

static Aff G_TAB[128];
static Aff PHI_G_TAB[128];
static Fe FE_BETA;
static int g_tab_ready = 0;

static void secp_init(void) {
    if (g_tab_ready) return;
    static const uint8_t GX[32] = {
        0x79, 0xBE, 0x66, 0x7E, 0xF9, 0xDC, 0xBB, 0xAC, 0x55, 0xA0, 0x62,
        0x95, 0xCE, 0x87, 0x0B, 0x07, 0x02, 0x9B, 0xFC, 0xDB, 0x2D, 0xCE,
        0x28, 0xD9, 0x59, 0xF2, 0x81, 0x5B, 0x16, 0xF8, 0x17, 0x98};
    static const uint8_t GY[32] = {
        0x48, 0x3A, 0xDA, 0x77, 0x26, 0xA3, 0xC4, 0x65, 0x5D, 0xA4, 0xFB,
        0xFC, 0x0E, 0x11, 0x08, 0xA8, 0xFD, 0x17, 0xB4, 0x48, 0xA6, 0x85,
        0x54, 0x19, 0x9C, 0x47, 0xD0, 0x8F, 0xFB, 0x10, 0xD4, 0xB8};
    static const uint8_t BETA[32] = {
        0x7A, 0xE9, 0x6A, 0x2B, 0x65, 0x7C, 0x07, 0x10, 0x6E, 0x64, 0x47,
        0x9E, 0xAC, 0x34, 0x34, 0xE9, 0x9C, 0xF0, 0x49, 0x75, 0x12, 0xF5,
        0x89, 0x95, 0xC1, 0x39, 0x6C, 0x28, 0x71, 0x95, 0x01, 0xEE};
    fe_from_bytes(FE_BETA, BETA);
    Jac g;
    fe_from_bytes(g.x, GX);
    fe_from_bytes(g.y, GY);
    g.z = {{1, 0, 0, 0}};
    Jac g2;
    jac_dbl(g2, g);
    Jac cur = g;
    for (int i = 0; i < 128; i++) {
        jac_to_aff(G_TAB[i], cur);
        fe_mul(PHI_G_TAB[i].x, G_TAB[i].x, FE_BETA);
        PHI_G_TAB[i].y = G_TAB[i].y;
        jac_add(cur, cur, g2);
    }
    g_tab_ready = 1;
}

// wNAF encoding of a 256-bit big-endian scalar. digits out (LSB first),
// values odd in (-2^(w-1), 2^(w-1)); returns length.
static int wnaf_encode(const uint8_t scalar_be[32], int w, int8_t* digits) {
    uint64_t k[5] = {0, 0, 0, 0, 0};
    for (int i = 0; i < 4; i++) {
        uint64_t limb = 0;
        for (int j = 0; j < 8; j++) limb = (limb << 8) | scalar_be[(3 - i) * 8 + j];
        k[i] = limb;
    }
    int len = 0;
    const uint64_t mask = (1ULL << w) - 1;
    const int64_t half = 1LL << (w - 1);
    while (k[0] | k[1] | k[2] | k[3] | k[4]) {
        int8_t d = 0;
        if (k[0] & 1) {
            int64_t m = (int64_t)(k[0] & mask);
            if (m >= half) m -= (int64_t)(mask + 1);
            d = (int8_t)m;
            if (m >= 0) {
                u128 borrow = 0;
                uint64_t sub = (uint64_t)m;
                for (int i = 0; i < 5; i++) {
                    u128 diff = (u128)k[i] - (i == 0 ? sub : 0) - borrow;
                    k[i] = (uint64_t)diff;
                    borrow = (diff >> 64) & 1;
                }
            } else {
                u128 carry = (uint64_t)(-m);
                for (int i = 0; i < 5 && carry; i++) {
                    u128 s = (u128)k[i] + carry;
                    k[i] = (uint64_t)s;
                    carry = s >> 64;
                }
            }
        }
        digits[len++] = d;
        // k >>= 1
        for (int i = 0; i < 4; i++) k[i] = (k[i] >> 1) | (k[i + 1] << 63);
        k[4] >>= 1;
    }
    return len;
}

// Decompress a 33-byte SEC1 public key into affine coords. Returns 1 if ok.
static int pubkey_decompress(const uint8_t pub33[33], Aff& out) {
    if (pub33[0] != 0x02 && pub33[0] != 0x03) return 0;
    Fe x;
    fe_from_bytes(x, pub33 + 1);
    if (fe_cmp(x, FE_P) >= 0) return 0;  // fe_from_bytes does not reduce
    Fe y2, t;
    fe_sqr(t, x);
    fe_mul(y2, t, x);
    Fe seven = {{7, 0, 0, 0}};
    fe_add(y2, y2, seven);
    Fe y;
    fe_sqrt(y, y2);
    fe_sqr(t, y);
    if (fe_cmp(t, y2) != 0) return 0;  // not a quadratic residue
    if ((y.v[0] & 1) != (uint64_t)(pub33[0] & 1)) fe_neg(y, y);
    out.x = x;
    out.y = y;
    return 1;
}

// R = u1*G + u2*Q for a compressed pubkey Q; returns 1 and writes the affine
// coordinates of R unless R is infinity / pubkey invalid.  This is the hot
// inner op of ECDSA verification; the caller (Python) computes u1, u2 and
// checks x(R) mod n == r.
int secp256k1_ecmul_double(const uint8_t* u1_be, const uint8_t* u2_be,
                           const uint8_t* pub33, uint8_t* out_x,
                           uint8_t* out_y) {
    secp_init();
    Aff q;
    if (!pubkey_decompress(pub33, q)) return 0;
    // odd multiples of Q: 1Q, 3Q, ..., 15Q (w = 5)
    Jac qtab[8];
    qtab[0].x = q.x;
    qtab[0].y = q.y;
    qtab[0].z = {{1, 0, 0, 0}};
    Jac q2;
    jac_dbl(q2, qtab[0]);
    for (int i = 1; i < 8; i++) jac_add(qtab[i], qtab[i - 1], q2);

    int8_t n1[260], n2[260];
    int l1 = wnaf_encode(u1_be, 8, n1);
    int l2 = wnaf_encode(u2_be, 5, n2);
    int len = l1 > l2 ? l1 : l2;
    Jac r = JAC_INF;
    for (int i = len - 1; i >= 0; i--) {
        jac_dbl(r, r);
        if (i < l1 && n1[i]) {
            int8_t d = n1[i];
            Aff a = G_TAB[(d > 0 ? d : -d) >> 1];
            if (d < 0) fe_neg(a.y, a.y);
            jac_add_aff(r, r, a);
        }
        if (i < l2 && n2[i]) {
            int8_t d = n2[i];
            Jac p = qtab[(d > 0 ? d : -d) >> 1];
            if (d < 0) fe_neg(p.y, p.y);
            jac_add(r, r, p);
        }
    }
    Aff ra;
    if (!jac_to_aff(ra, r)) return 0;
    fe_to_bytes(out_x, ra.x);
    fe_to_bytes(out_y, ra.y);
    return 1;
}

// GLV double-multiplication: u1*G + u2*Q with both scalars pre-split by
// the caller (Python bigints do the lattice rounding) into half-length
// components u = k1 + k2*lambda (mod n), |k1|,|k2| ~ 2^128.  The joint
// wNAF loop then runs ~128 doublings instead of ~256 — the dominant cost
// of the non-GLV path — against four tables: G, phi(G) (both static),
// Q and phi(Q) (built per call, normalized to affine with one shared
// Montgomery inversion so every addition is the cheap mixed form).
//
// ks: 4 scalars of 32 bytes big-endian (|k1_G|, |k2_G|, |k1_Q|, |k2_Q|);
// signs: 4 bytes, 1 = that component is negative (fold into the digit's
// point sign).  Verification-only, like everything here.
// Validate an uncompressed pubkey (x||y, 32+32 big-endian) and build the
// odd-multiple table 1Q..15Q (w = 5) in Jacobian form.  Shared by the
// per-call core (which keeps the table Jacobian) and the batched
// precomputation path (which normalizes ALL tables of a stripe to affine
// with one Montgomery inversion).  Returns 1 iff the key decodes onto
// the curve.
static int glv_build_qtab(const uint8_t* pub64, Jac qt[8]) {
    // the caller decompresses once per distinct key (cached Python-side),
    // saving the ~sqrt-sized field exponentiation every verify paid before
    Aff q;
    fe_from_bytes(q.x, pub64);
    fe_from_bytes(q.y, pub64 + 32);
    if (fe_cmp(q.x, FE_P) >= 0 || fe_cmp(q.y, FE_P) >= 0) return 0;
    {
        // on-curve check (y^2 == x^3 + 7): cheap insurance that a bad
        // uncompressed encoding can never validate a signature
        Fe y2, x3, t;
        fe_sqr(y2, q.y);
        fe_sqr(t, q.x);
        fe_mul(x3, t, q.x);
        Fe seven = {{7, 0, 0, 0}};
        fe_add(x3, x3, seven);
        if (fe_cmp(y2, x3) != 0) return 0;
    }
    qt[0].x = q.x;
    qt[0].y = q.y;
    qt[0].z = {{1, 0, 0, 0}};
    Jac q2;
    jac_dbl(q2, qt[0]);
    for (int i = 1; i < 8; i++) jac_add(qt[i], qt[i - 1], q2);
    return 1;
}

static int ecmul_double_glv_core(const uint8_t* ks, const uint8_t* signs,
                                 const uint8_t* pub64, Jac& out) {
    // odd multiples 1Q..15Q (w = 5), Jacobian (an affine normalization
    // would cost a field inversion per call — more than it saves; the
    // batched _pre path amortizes exactly that inversion), plus the
    // endomorphism image: phi(X:Y:Z) = (beta*X : Y : Z)
    Jac qt[8], pqt[8];
    if (!glv_build_qtab(pub64, qt)) return 0;
    for (int i = 0; i < 8; i++) {
        fe_mul(pqt[i].x, qt[i].x, FE_BETA);
        pqt[i].y = qt[i].y;
        pqt[i].z = qt[i].z;
    }
    // sized for FULL 256-bit scalars (like the non-GLV path): the caller
    // contract is ~128-bit split components, but an exported symbol must
    // not turn a fat scalar into a stack smash
    int8_t d[4][260];
    int len[4];
    len[0] = wnaf_encode(ks + 0, 8, d[0]);
    len[1] = wnaf_encode(ks + 32, 8, d[1]);
    len[2] = wnaf_encode(ks + 64, 5, d[2]);
    len[3] = wnaf_encode(ks + 96, 5, d[3]);
    int maxlen = 0;
    for (int j = 0; j < 4; j++)
        if (len[j] > maxlen) maxlen = len[j];
    Jac r = JAC_INF;
    for (int i = maxlen - 1; i >= 0; i--) {
        jac_dbl(r, r);
        for (int j = 0; j < 2; j++) {
            if (i >= len[j] || !d[j][i]) continue;
            int8_t dg = d[j][i];
            Aff a = (j == 0 ? G_TAB : PHI_G_TAB)[(dg > 0 ? dg : -dg) >> 1];
            // component sign XOR digit sign picks the point's sign
            if ((dg < 0) != (signs[j] != 0)) fe_neg(a.y, a.y);
            jac_add_aff(r, r, a);
        }
        for (int j = 2; j < 4; j++) {
            if (i >= len[j] || !d[j][i]) continue;
            int8_t dg = d[j][i];
            Jac p = (j == 2 ? qt : pqt)[(dg > 0 ? dg : -dg) >> 1];
            if ((dg < 0) != (signs[j] != 0)) fe_neg(p.y, p.y);
            jac_add(r, r, p);
        }
    }
    if (jac_is_inf(r)) return 0;
    out = r;
    return 1;
}

// Batched GLV double-multiplication across worker threads.
// ks: n*128 (four 32-byte components per verify); signs: n*4;
// pubs: n*64 UNCOMPRESSED affine keys.  The final Jacobian->affine
// normalization is batched per thread (one field inversion for the whole
// stripe via Montgomery's trick) — per-call inversions were a visible
// fixed cost of each verification.
void secp256k1_ecmul_double_glv_batch(const uint8_t* ks, const uint8_t* signs,
                                      const uint8_t* pubs, int n,
                                      uint8_t* out_x, uint8_t* ok,
                                      int nthreads) {
    secp_init();
    if (nthreads <= 0) {
        nthreads = (int)std::thread::hardware_concurrency();
        if (nthreads <= 0) nthreads = 1;
    }
    if (nthreads > n) nthreads = n > 0 ? n : 1;
    auto work = [&](int t) {
        // the thread's results stay Jacobian until one shared inversion
        std::vector<Jac> rs;
        std::vector<int> idx;
        for (int i = t; i < n; i += nthreads) {
            Jac r;
            if (ecmul_double_glv_core(ks + (size_t)i * 128,
                                      signs + (size_t)i * 4,
                                      pubs + (size_t)i * 64, r)) {
                rs.push_back(r);
                idx.push_back(i);
            } else {
                ok[i] = 0;
            }
        }
        size_t m = rs.size();
        if (!m) return;
        std::vector<Fe> pref(m + 1);
        pref[0] = {{1, 0, 0, 0}};
        for (size_t i = 0; i < m; i++) fe_mul(pref[i + 1], pref[i], rs[i].z);
        Fe acc;
        fe_inv(acc, pref[m]);
        for (size_t i = m; i-- > 0;) {
            Fe zinv, zi2;
            fe_mul(zinv, pref[i], acc);
            fe_mul(acc, acc, rs[i].z);
            fe_sqr(zi2, zinv);
            Fe x;
            fe_mul(x, rs[i].x, zi2);
            fe_to_bytes(out_x + (size_t)idx[i] * 32, x);
            ok[idx[i]] = 1;
        }
    };
    if (nthreads == 1) {
        work(0);
    } else {
        std::vector<std::thread> ts;
        for (int t = 0; t < nthreads; t++) ts.emplace_back(work, t);
        for (auto& th : ts) th.join();
    }
}

// Digit loop of the GLV double-mult with the Q and phi(Q) tables ALREADY
// normalized to affine: every table addition is the cheap mixed form
// (~11 fe_mul vs ~16 for Jacobian-Jacobian), across all four tables.
// The per-call field inversion that makes affine tables a loss in the
// single-shot path is amortized by the caller over the whole stripe
// (Montgomery's trick across all 8*live z-coordinates).
static int ecmul_double_glv_core_aff(const uint8_t* ks, const uint8_t* signs,
                                     const Aff qt[8], const Aff pqt[8],
                                     Jac& out) {
    int8_t d[4][260];
    int len[4];
    len[0] = wnaf_encode(ks + 0, 8, d[0]);
    len[1] = wnaf_encode(ks + 32, 8, d[1]);
    len[2] = wnaf_encode(ks + 64, 5, d[2]);
    len[3] = wnaf_encode(ks + 96, 5, d[3]);
    int maxlen = 0;
    for (int j = 0; j < 4; j++)
        if (len[j] > maxlen) maxlen = len[j];
    Jac r = JAC_INF;
    for (int i = maxlen - 1; i >= 0; i--) {
        jac_dbl(r, r);
        for (int j = 0; j < 4; j++) {
            if (i >= len[j] || !d[j][i]) continue;
            int8_t dg = d[j][i];
            const Aff* tab = (j == 0)   ? G_TAB
                             : (j == 1) ? PHI_G_TAB
                             : (j == 2) ? qt
                                        : pqt;
            Aff a = tab[(dg > 0 ? dg : -dg) >> 1];
            // component sign XOR digit sign picks the point's sign
            if ((dg < 0) != (signs[j] != 0)) fe_neg(a.y, a.y);
            jac_add_aff(r, r, a);
        }
    }
    if (jac_is_inf(r)) return 0;
    out = r;
    return 1;
}

// Batched GLV double-multiplication WITH per-stripe table precomputation.
// Same ABI as secp256k1_ecmul_double_glv_batch.  Three amortizations per
// stripe: (1) the fixed-base G / phi(G) wNAF tables are the static w=8
// precomputation shared by every call since secp_init; (2) phase A builds
// every live verify's Jacobian Q-table, then ONE Montgomery inversion
// over all 8*live z-coordinates normalizes them to affine, so (3) phase
// B's digit loops run all-mixed-affine (~5 fe_mul cheaper per Q-table
// addition, ~42 such additions per verify).  The final result
// normalization is batched exactly like the legacy symbol.  Worth it
// from roughly batch >= 4; below that the legacy symbol wins.
void secp256k1_ecmul_double_glv_batch_pre(const uint8_t* ks,
                                          const uint8_t* signs,
                                          const uint8_t* pubs, int n,
                                          uint8_t* out_x, uint8_t* ok,
                                          int nthreads) {
    secp_init();
    if (nthreads <= 0) {
        nthreads = (int)std::thread::hardware_concurrency();
        if (nthreads <= 0) nthreads = 1;
    }
    if (nthreads > n) nthreads = n > 0 ? n : 1;
    auto work = [&](int t) {
        // phase A: Jacobian odd-multiple tables for the stripe's live
        // verifies; all z-coordinates share one inversion
        std::vector<std::array<Jac, 8>> jtabs;
        std::vector<int> live;
        for (int i = t; i < n; i += nthreads) {
            std::array<Jac, 8> qt;
            if (glv_build_qtab(pubs + (size_t)i * 64, qt.data())) {
                jtabs.push_back(qt);
                live.push_back(i);
            } else {
                ok[i] = 0;
            }
        }
        size_t m = jtabs.size();
        if (!m) return;
        size_t nz = m * 8;
        std::vector<Fe> pref(nz + 1);
        pref[0] = {{1, 0, 0, 0}};
        for (size_t i = 0; i < nz; i++)
            fe_mul(pref[i + 1], pref[i], jtabs[i >> 3][i & 7].z);
        Fe acc;
        fe_inv(acc, pref[nz]);
        std::vector<std::array<Aff, 8>> atabs(m), patabs(m);
        for (size_t i = nz; i-- > 0;) {
            const Jac& p = jtabs[i >> 3][i & 7];
            Fe zinv, zi2, zi3;
            fe_mul(zinv, pref[i], acc);
            fe_mul(acc, acc, p.z);
            fe_sqr(zi2, zinv);
            fe_mul(zi3, zi2, zinv);
            Aff& a = atabs[i >> 3][i & 7];
            fe_mul(a.x, p.x, zi2);
            fe_mul(a.y, p.y, zi3);
            // endomorphism image on affine coords: phi(x, y) = (beta*x, y)
            Aff& pa = patabs[i >> 3][i & 7];
            fe_mul(pa.x, a.x, FE_BETA);
            pa.y = a.y;
        }
        // phase B: all-mixed-affine digit loops; results stay Jacobian
        // until the stripe's one result normalization
        std::vector<Jac> rs;
        std::vector<int> idx;
        for (size_t s = 0; s < m; s++) {
            int i = live[s];
            Jac r;
            if (ecmul_double_glv_core_aff(ks + (size_t)i * 128,
                                          signs + (size_t)i * 4,
                                          atabs[s].data(), patabs[s].data(),
                                          r)) {
                rs.push_back(r);
                idx.push_back(i);
            } else {
                ok[i] = 0;
            }
        }
        m = rs.size();
        if (!m) return;
        std::vector<Fe> rp(m + 1);
        rp[0] = {{1, 0, 0, 0}};
        for (size_t i = 0; i < m; i++) fe_mul(rp[i + 1], rp[i], rs[i].z);
        fe_inv(acc, rp[m]);
        for (size_t i = m; i-- > 0;) {
            Fe zinv, zi2;
            fe_mul(zinv, rp[i], acc);
            fe_mul(acc, acc, rs[i].z);
            fe_sqr(zi2, zinv);
            Fe x;
            fe_mul(x, rs[i].x, zi2);
            fe_to_bytes(out_x + (size_t)idx[i] * 32, x);
            ok[idx[i]] = 1;
        }
    };
    if (nthreads == 1) {
        work(0);
    } else {
        std::vector<std::thread> ts;
        for (int t = 0; t < nthreads; t++) ts.emplace_back(work, t);
        for (auto& th : ts) th.join();
    }
}

// Batched double-multiplication across worker threads.
// u1s/u2s: n*32 big-endian scalars; pubs: n*33; out_x: n*32; ok: n flags.
void secp256k1_ecmul_double_batch(const uint8_t* u1s, const uint8_t* u2s,
                                  const uint8_t* pubs, int n, uint8_t* out_x,
                                  uint8_t* ok, int nthreads) {
    secp_init();
    if (nthreads <= 0) {
        nthreads = (int)std::thread::hardware_concurrency();
        if (nthreads <= 0) nthreads = 1;
    }
    if (nthreads > n) nthreads = n > 0 ? n : 1;
    auto work = [&](int t) {
        uint8_t oy[32];
        for (int i = t; i < n; i += nthreads)
            ok[i] = (uint8_t)secp256k1_ecmul_double(
                u1s + (size_t)i * 32, u2s + (size_t)i * 32,
                pubs + (size_t)i * 33, out_x + (size_t)i * 32, oy);
    };
    if (nthreads == 1) {
        work(0);
    } else {
        std::vector<std::thread> ts;
        for (int t = 0; t < nthreads; t++) ts.emplace_back(work, t);
        for (auto& th : ts) th.join();
    }
}

}  // extern "C"
