// Native host library: GF(256) Reed-Solomon square extension + SHA-256 /
// NMT hashing on the CPU.
//
// Role: the TPU framework's equivalent of the reference's performance-native
// dependencies (Leopard-RS SIMD codec via klauspost/reedsolomon and
// crypto/sha256 — SURVEY.md §2.2).  Used as (a) the honest CPU comparison
// leg for bench.py, and (b) a host-side fallback behind the same Python
// interfaces as the device kernels.  Exposed via a C ABI for ctypes.
//
// GF(256): primitive polynomial 0x11D, multiply via a 64 KiB full product
// table (the classic table method; with -O3 and auto-vectorization this is
// the strongest portable single-thread baseline short of hand-written
// pshufb kernels).  Encode matrices arrive from Python (the same Lagrange
// matrices the device uses), so native and device outputs are bit-identical.

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// GF(256)
// ---------------------------------------------------------------------------

static uint8_t MUL[256][256];
static int gf_ready = 0;

static uint8_t gf_mul_slow(uint8_t a, uint8_t b) {
    uint16_t p = 0;
    uint16_t aa = a;
    for (int i = 0; i < 8; i++) {
        if (b & 1) p ^= aa;
        b >>= 1;
        aa <<= 1;
        if (aa & 0x100) aa ^= 0x11D;
    }
    return (uint8_t)p;
}

void gf_init(void) {
    if (gf_ready) return;
    for (int a = 0; a < 256; a++)
        for (int b = 0; b < 256; b++)
            MUL[a][b] = gf_mul_slow((uint8_t)a, (uint8_t)b);
    gf_ready = 1;
}

// parity[i][b] ^= MUL[E[i][j]][data[j][b]] for a row of k shares of B bytes.
// E: k*k row-major; data: k*B; parity out: k*B.
static void rs_encode_axis(const uint8_t* E, const uint8_t* data,
                           uint8_t* parity, int k, int B) {
    memset(parity, 0, (size_t)k * B);
    for (int i = 0; i < k; i++) {
        uint8_t* out = parity + (size_t)i * B;
        for (int j = 0; j < k; j++) {
            const uint8_t c = E[i * k + j];
            if (c == 0) continue;
            const uint8_t* row = MUL[c];
            const uint8_t* in = data + (size_t)j * B;
            for (int b = 0; b < B; b++) out[b] ^= row[in[b]];
        }
    }
}

// Extend a k x k x B square into a 2k x 2k x B EDS (quadrant layout as the
// device kernel: Q1 row parity, Q2 column parity, Q3 parity of parity).
// square: k*k*B row-major; eds out: 2k*2k*B; E: k*k encode matrix.
void rs_extend_square(const uint8_t* square, const uint8_t* E, uint8_t* eds,
                      int k, int B) {
    gf_init();
    const int n = 2 * k;
    const size_t row_bytes = (size_t)n * B;
    // Q0
    for (int r = 0; r < k; r++)
        memcpy(eds + r * row_bytes, square + (size_t)r * k * B, (size_t)k * B);
    // Q1: row parity
    for (int r = 0; r < k; r++)
        rs_encode_axis(E, eds + r * row_bytes, eds + r * row_bytes + (size_t)k * B,
                       k, B);
    // Q2/Q3: column parity over the top half. Gather each column, encode,
    // scatter. (Columns are strided; gather keeps the inner loop dense.)
    uint8_t* col = new uint8_t[(size_t)k * B];
    uint8_t* par = new uint8_t[(size_t)k * B];
    for (int c = 0; c < n; c++) {
        for (int r = 0; r < k; r++)
            memcpy(col + (size_t)r * B, eds + r * row_bytes + (size_t)c * B, B);
        rs_encode_axis(E, col, par, k, B);
        for (int r = 0; r < k; r++)
            memcpy(eds + (size_t)(k + r) * row_bytes + (size_t)c * B,
                   par + (size_t)r * B, B);
    }
    delete[] col;
    delete[] par;
}

// ---------------------------------------------------------------------------
// SHA-256 (FIPS 180-4), portable
// ---------------------------------------------------------------------------

static const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

static inline uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
}

static void sha256_compress(uint32_t st[8], const uint8_t* block) {
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)block[4 * i] << 24) | ((uint32_t)block[4 * i + 1] << 16) |
               ((uint32_t)block[4 * i + 2] << 8) | block[4 * i + 3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = st[0], b = st[1], c = st[2], d = st[3];
    uint32_t e = st[4], f = st[5], g = st[6], h = st[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + K256[i] + w[i];
        uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
        uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + maj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    st[0] += a; st[1] += b; st[2] += c; st[3] += d;
    st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

static void sha256_one(const uint8_t* msg, size_t len, uint8_t out[32]) {
    uint32_t st[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
                      0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    size_t i = 0;
    for (; i + 64 <= len; i += 64) sha256_compress(st, msg + i);
    uint8_t tail[128];
    size_t rem = len - i;
    memcpy(tail, msg + i, rem);
    tail[rem] = 0x80;
    size_t padded = (rem + 9 <= 64) ? 64 : 128;
    memset(tail + rem + 1, 0, padded - rem - 9);
    uint64_t bits = (uint64_t)len * 8;
    for (int j = 0; j < 8; j++) tail[padded - 1 - j] = (uint8_t)(bits >> (8 * j));
    for (size_t o = 0; o < padded; o += 64) sha256_compress(st, tail + o);
    for (int j = 0; j < 8; j++) {
        out[4 * j] = (uint8_t)(st[j] >> 24);
        out[4 * j + 1] = (uint8_t)(st[j] >> 16);
        out[4 * j + 2] = (uint8_t)(st[j] >> 8);
        out[4 * j + 3] = (uint8_t)st[j];
    }
}

// Batch API: n equal-length messages.
void sha256_batch(const uint8_t* msgs, int n, int len, uint8_t* out) {
    for (int i = 0; i < n; i++)
        sha256_one(msgs + (size_t)i * len, len, out + (size_t)i * 32);
}

// ---------------------------------------------------------------------------
// NMT roots over an EDS (namespaced digests, ignore-max rule)
// ---------------------------------------------------------------------------

static const int NS = 29;
static const int DIGEST = 2 * NS + 32;  // 90

static void nmt_leaf(const uint8_t* ns_prefixed, int len, uint8_t* out) {
    uint8_t buf[1 + 29 + 4096];
    buf[0] = 0x00;
    memcpy(buf + 1, ns_prefixed, len);
    memcpy(out, ns_prefixed, NS);
    memcpy(out + NS, ns_prefixed, NS);
    sha256_one(buf, len + 1, out + 2 * NS);
}

static void nmt_node(const uint8_t* l, const uint8_t* r, uint8_t* out) {
    uint8_t buf[1 + 2 * DIGEST];
    buf[0] = 0x01;
    memcpy(buf + 1, l, DIGEST);
    memcpy(buf + 1 + DIGEST, r, DIGEST);
    memcpy(out, l, NS);  // min = left.min
    int r_min_is_max = 1;
    for (int i = 0; i < NS; i++)
        if (r[i] != 0xFF) { r_min_is_max = 0; break; }
    memcpy(out + NS, r_min_is_max ? l + NS : r + NS, NS);
    sha256_one(buf, 1 + 2 * DIGEST, out + 2 * NS);
}

// Root of one tree whose leaves are ns-prefixed payloads (n a power of two).
void nmt_root(const uint8_t* leaves, int n, int leaf_len, uint8_t* out) {
    uint8_t* lvl = new uint8_t[(size_t)n * DIGEST];
    for (int i = 0; i < n; i++)
        nmt_leaf(leaves + (size_t)i * leaf_len, leaf_len, lvl + (size_t)i * DIGEST);
    int m = n;
    while (m > 1) {
        for (int i = 0; i < m / 2; i++)
            nmt_node(lvl + (size_t)(2 * i) * DIGEST,
                     lvl + (size_t)(2 * i + 1) * DIGEST,
                     lvl + (size_t)i * DIGEST);
        m /= 2;
    }
    memcpy(out, lvl, DIGEST);
    delete[] lvl;
}

// All 4k NMT axis roots of an EDS (2k x 2k x B): rows then columns, each
// with the Q0 namespace-prefix rule. out: (4k) x 90.
void eds_nmt_roots(const uint8_t* eds, int k, int B, uint8_t* out) {
    const int n = 2 * k;
    const int leaf_len = NS + B;
    uint8_t* leaves = new uint8_t[(size_t)n * leaf_len];
    // rows
    for (int r = 0; r < n; r++) {
        for (int c = 0; c < n; c++) {
            const uint8_t* cell = eds + ((size_t)r * n + c) * B;
            uint8_t* leaf = leaves + (size_t)c * leaf_len;
            if (r < k && c < k) memcpy(leaf, cell, NS);
            else memset(leaf, 0xFF, NS);
            memcpy(leaf + NS, cell, B);
        }
        nmt_root(leaves, n, leaf_len, out + (size_t)r * DIGEST);
    }
    // columns
    for (int c = 0; c < n; c++) {
        for (int r = 0; r < n; r++) {
            const uint8_t* cell = eds + ((size_t)r * n + c) * B;
            uint8_t* leaf = leaves + (size_t)r * leaf_len;
            if (r < k && c < k) memcpy(leaf, cell, NS);
            else memset(leaf, 0xFF, NS);
            memcpy(leaf + NS, cell, B);
        }
        nmt_root(leaves, n, leaf_len, out + (size_t)(n + c) * DIGEST);
    }
    delete[] leaves;
}

}  // extern "C"
