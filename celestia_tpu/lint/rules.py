"""The celint rule catalog (R1-R4).  See specs/static_analysis.md.

Each rule encodes one invariant PRs 4-6 established by hand and cannot
afford to re-lose by review drift:

* ``guarded-by`` (R1) — annotated shared state mutates only under its
  declared lock (the unlocked commitment cache was the founding bug).
* ``no-handrolled-cache`` (R2) — the OrderedDict+eviction-loop pattern
  lives ONLY in utils/lru.py; everything else builds on LruCache, so
  bounding/locking/stats can't silently fork again.
* ``consensus-determinism`` (R3) — state/ and da/ never read wall
  clocks, OS entropy, or unordered-set iteration into consensus bytes;
  telemetry timestamps go through utils/telemetry clock(), the one
  auditable channel.
* ``hostpool-discipline`` (R4) — native ``nthreads`` always comes from
  utils/hostpool (or None, which resolves there); a literal thread count
  re-creates the oversubscription the process-wide pool exists to end.
* ``sanctioned-retry`` (R5) — bare ``except:``, ``except Exception:
  pass``-style swallows and hand-rolled ``time.sleep`` retry loops are
  forbidden outside utils/faults.py: failures are recorded via
  ``faults.note`` or propagate, and every sleep-retry goes through the
  one RetryPolicy (seeded backoff, deadline budgets) so recovery paths
  stay testable under the chaos harness.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from celestia_tpu.lint.engine import (
    Finding,
    ModuleContext,
    Rule,
    normalize_expr,
    register,
)

# ---------------------------------------------------------------------------
# R1: guarded-by
# ---------------------------------------------------------------------------

# methods that mutate their receiver (dict/list/set/OrderedDict/deque)
_MUTATING_METHODS = {
    "pop", "popitem", "clear", "update", "setdefault",
    "append", "extend", "insert", "remove", "discard", "add",
    "move_to_end", "appendleft", "popleft",
}

# ("name", global_name) or ("self", attr_name)
_GuardKey = Tuple[str, str]


def _target_key(node: ast.AST) -> Optional[_GuardKey]:
    if isinstance(node, ast.Name):
        return ("name", node.id)
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return ("self", node.attr)
    return None


@register
class GuardedByRule(Rule):
    id = "guarded-by"
    summary = "annotated shared state must be mutated under its declared lock"
    doc = (
        "A variable annotated `# celint: guarded-by(<lock>)` may only be "
        "mutated lexically inside `with <lock>:`.  Methods named *_locked "
        "are exempt (they document that the caller holds the lock)."
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        guards: Dict[_GuardKey, Tuple[str, int]] = {}
        for g in ctx.guards:
            found = False
            for node in ast.walk(ctx.tree):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                if node.lineno != g.target_line:
                    continue
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    key = _target_key(t)
                    if key is not None:
                        guards[key] = (g.lock, g.target_line)
                        found = True
            if not found:
                yield Finding(
                    self.id, ctx.relpath, g.line, 0,
                    "guarded-by annotation matches no assignment target "
                    "on its line",
                )
        if not guards:
            return
        for node in ast.walk(ctx.tree):
            for key, mutated in _mutations(node):
                entry = guards.get(key)
                if entry is None:
                    continue
                lock, decl_line = entry
                if mutated.lineno == decl_line:
                    continue  # the annotated initialization itself
                if lock in ctx.held_locks(mutated):
                    continue
                if any(
                    fn.endswith("_locked")
                    for fn in ctx.enclosing_functions(mutated)
                ):
                    continue
                name = key[1] if key[0] == "name" else f"self.{key[1]}"
                yield Finding(
                    self.id, ctx.relpath, mutated.lineno, mutated.col_offset,
                    f"{name} is guarded-by({lock}) but mutated outside "
                    f"`with {lock}:`",
                )


def _mutations(node: ast.AST) -> Iterator[Tuple[_GuardKey, ast.AST]]:
    """(guard key, offending node) for every mutation ``node`` performs."""
    if isinstance(node, ast.Assign):
        for t in node.targets:
            yield from _store_targets(t, node)
    elif isinstance(node, ast.AnnAssign) and node.value is not None:
        yield from _store_targets(node.target, node)
    elif isinstance(node, ast.AugAssign):
        yield from _store_targets(node.target, node)
    elif isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                key = _target_key(t.value)
            else:
                key = _target_key(t)
            if key is not None:
                yield key, node
    elif isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATING_METHODS:
            key = _target_key(f.value)
            if key is not None:
                yield key, node


def _store_targets(
    t: ast.AST, node: ast.AST
) -> Iterator[Tuple[_GuardKey, ast.AST]]:
    if isinstance(t, ast.Subscript):
        key = _target_key(t.value)  # x[k] = v mutates x
    else:
        key = _target_key(t)  # x = v rebinds x
    if key is not None:
        yield key, node
    if isinstance(t, (ast.Tuple, ast.List)):
        for elt in t.elts:
            yield from _store_targets(elt, node)


# ---------------------------------------------------------------------------
# R2: no-handrolled-cache
# ---------------------------------------------------------------------------

# the one module allowed to implement the pattern
_SANCTIONED = "celestia_tpu/utils/lru.py"


@register
class NoHandrolledCacheRule(Rule):
    id = "no-handrolled-cache"
    summary = "bounded caches are built on utils/lru.LruCache, nowhere else"
    doc = (
        "Flags the hand-rolled LRU pattern outside utils/lru.py: "
        "OrderedDict use, move_to_end/popitem calls, pop(next(iter(d))) "
        "FIFO eviction, and `while len(d) > cap` eviction loops.  Five "
        "independent copies of this pattern each drifted differently; "
        "LruCache is the audited implementation with locking and stats."
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.relpath == _SANCTIONED:
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                if node.module == "collections" and any(
                    a.name == "OrderedDict" for a in node.names
                ):
                    yield Finding(
                        self.id, ctx.relpath, node.lineno, node.col_offset,
                        "OrderedDict import outside utils/lru.py — build "
                        "on celestia_tpu.utils.lru.LruCache instead",
                    )
            elif isinstance(node, ast.Attribute) and node.attr == "OrderedDict":
                yield Finding(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    "collections.OrderedDict outside utils/lru.py — build "
                    "on celestia_tpu.utils.lru.LruCache instead",
                )
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) and f.attr in (
                    "move_to_end",
                    "popitem",
                ):
                    yield Finding(
                        self.id, ctx.relpath, node.lineno, node.col_offset,
                        f".{f.attr}() is LRU bookkeeping — use "
                        "celestia_tpu.utils.lru.LruCache",
                    )
                elif (
                    isinstance(f, ast.Attribute)
                    and f.attr == "pop"
                    and node.args
                    and _is_next_iter(node.args[0])
                ):
                    yield Finding(
                        self.id, ctx.relpath, node.lineno, node.col_offset,
                        ".pop(next(iter(...))) is a hand-rolled eviction — "
                        "use celestia_tpu.utils.lru.LruCache",
                    )
            elif isinstance(node, ast.While) and _is_eviction_loop(node):
                yield Finding(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    "`while len(...)` eviction loop — use "
                    "celestia_tpu.utils.lru.LruCache",
                )


def _is_next_iter(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Name)
        and node.func.id == "next"
        and node.args
        and isinstance(node.args[0], ast.Call)
        and isinstance(node.args[0].func, ast.Name)
        and node.args[0].func.id == "iter"
    )


def _is_eviction_loop(node: ast.While) -> bool:
    test_has_len = any(
        isinstance(n, ast.Call)
        and isinstance(n.func, ast.Name)
        and n.func.id == "len"
        for n in ast.walk(node.test)
    )
    if not test_has_len:
        return False
    for n in ast.walk(node):
        if n is node:
            continue
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            if n.func.attr in ("pop", "popitem", "popleft"):
                return True
        if isinstance(n, ast.Delete):
            return True
    return False


# ---------------------------------------------------------------------------
# R3: consensus-determinism
# ---------------------------------------------------------------------------

_CONSENSUS_PREFIXES = ("celestia_tpu/state/", "celestia_tpu/da/")
_TIME_FNS = {
    "time", "time_ns", "monotonic", "monotonic_ns",
    "perf_counter", "perf_counter_ns",
}

# The sanctioned wall-clock channels: the ONLY modules whose time.*
# reads are part of the design (utils/telemetry clock() feeds durations;
# utils/tracing builds spans on that same clock).  Consensus modules
# reach clocks exclusively THROUGH these; the channels themselves are
# scanned for the entropy bans (a tracer span id derived from random
# bits would be exactly the nondeterminism R3 exists to stop), while
# their wall-clock reads are, by definition, sanctioned.
SANCTIONED_CHANNELS = (
    "celestia_tpu/utils/telemetry.py",
    "celestia_tpu/utils/tracing.py",
    # the device half of the plane (PR 11): dispatch brackets and the
    # occupancy window read the clock; its span/track identifiers must
    # stay as deterministic as the tracer's, so the entropy bans apply
    "celestia_tpu/utils/devprof.py",
    # the continuous-telemetry ring stamps snapshot timestamps
    "celestia_tpu/utils/timeseries.py",
    # the host sampling profiler stamps sample timestamps and measures
    # its own tick cost; its ids are thread ids + folded strings, so the
    # entropy bans apply (a randomized sampler would launder
    # nondeterminism through the one open door)
    "celestia_tpu/utils/hostprof.py",
    # the flight recorder stamps incident timestamps; incident ids are
    # SEQUENCE numbers, never random — entropy bans enforced
    "celestia_tpu/utils/flight.py",
)


@register
class ConsensusDeterminismRule(Rule):
    id = "consensus-determinism"
    summary = "no wall clocks, entropy, or set-iteration in state/ and da/"
    doc = (
        "In consensus modules (celestia_tpu/state/, celestia_tpu/da/) "
        "flags calls to time.time/time_ns/monotonic/perf_counter, any "
        "random.* / numpy .random.* / secrets.*, os.urandom, and "
        "iteration directly over a set (unordered -> nondeterministic "
        "bytes).  Telemetry durations go through the sanctioned-channel "
        "modules (utils/telemetry clock(), utils/tracing spans — "
        "SANCTIONED_CHANNELS); anything else needs an explicit allow "
        "with a reason.  The channel modules themselves are scanned for "
        "the ENTROPY bans only: their clock reads are the channel, but "
        "a random/urandom draw there (e.g. a random span id) would "
        "launder nondeterminism through the one door left open."
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        in_channel = ctx.relpath in SANCTIONED_CHANNELS
        if not in_channel and not ctx.relpath.startswith(_CONSENSUS_PREFIXES):
            return
        time_aliases: Set[str] = set()
        random_aliases: Set[str] = set()
        numpy_aliases: Set[str] = set()
        os_aliases: Set[str] = set()
        secrets_aliases: Set[str] = set()
        bare_banned: Dict[str, str] = {}  # local name -> origin description
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name
                    if a.name == "time":
                        time_aliases.add(local)
                    elif a.name == "random":
                        random_aliases.add(local)
                    elif a.name == "numpy":
                        numpy_aliases.add(local)
                    elif a.name == "os":
                        os_aliases.add(local)
                    elif a.name == "secrets":
                        secrets_aliases.add(local)
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    local = a.asname or a.name
                    if node.module == "time" and a.name in _TIME_FNS:
                        bare_banned[local] = f"time.{a.name}"
                    elif node.module == "random":
                        bare_banned[local] = f"random.{a.name}"
                    elif node.module == "os" and a.name == "urandom":
                        bare_banned[local] = "os.urandom"
                    elif node.module == "secrets":
                        bare_banned[local] = f"secrets.{a.name}"
                    elif node.module == "numpy" and a.name == "random":
                        random_aliases.add(local)
        if in_channel:
            # the channel's wall-clock reads ARE the sanctioned channel;
            # only the entropy bans apply inside it
            time_aliases = set()
            bare_banned = {
                k: v for k, v in bare_banned.items()
                if not v.startswith("time.")
            }
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                msg = self._call_verdict(
                    node, time_aliases, random_aliases, numpy_aliases,
                    os_aliases, secrets_aliases, bare_banned,
                )
                if msg:
                    yield Finding(
                        self.id, ctx.relpath, node.lineno, node.col_offset,
                        msg,
                    )
            elif in_channel:
                continue  # set-iteration ban stays consensus-only
            elif isinstance(node, (ast.For, ast.comprehension)):
                if _iterates_set(node.iter):
                    yield Finding(
                        self.id, ctx.relpath,
                        getattr(node, "lineno", node.iter.lineno),
                        getattr(node, "col_offset", node.iter.col_offset),
                        "iteration over a set is unordered — sort it (or "
                        "iterate an insertion-ordered dict) before bytes "
                        "derived from it can reach consensus",
                    )

    def _call_verdict(
        self, node, time_aliases, random_aliases, numpy_aliases,
        os_aliases, secrets_aliases, bare_banned,
    ) -> Optional[str]:
        f = node.func
        if isinstance(f, ast.Name):
            origin = bare_banned.get(f.id)
            if origin:
                return (
                    f"{origin} in a consensus module — route telemetry "
                    "timestamps through utils/telemetry clock(), entropy "
                    "through explicitly seeded channels"
                )
            return None
        if not isinstance(f, ast.Attribute):
            return None
        src = ast.unparse(f)
        head = src.split(".", 1)[0]
        if head in time_aliases and f.attr in _TIME_FNS and "." in src:
            return (
                f"{src}() reads the wall clock in a consensus module — "
                "use utils/telemetry clock() (telemetry-only channel) or "
                "carry an explicit allow"
            )
        if head in random_aliases:
            return f"{src}() draws nondeterministic randomness in a consensus module"
        if any(src.startswith(a + ".random.") for a in numpy_aliases):
            return (
                f"{src}() uses numpy randomness in a consensus module — "
                "seed it explicitly and carry an allow if intentional"
            )
        if head in os_aliases and f.attr == "urandom":
            return f"{src}() reads OS entropy in a consensus module"
        if head in secrets_aliases:
            return f"{src}() reads OS entropy in a consensus module"
        return None


def _iterates_set(it: ast.AST) -> bool:
    if isinstance(it, ast.Set):
        return True
    return (
        isinstance(it, ast.Call)
        and isinstance(it.func, ast.Name)
        and it.func.id == "set"
    )


# ---------------------------------------------------------------------------
# R4: hostpool-discipline
# ---------------------------------------------------------------------------


@register
class HostpoolDisciplineRule(Rule):
    id = "hostpool-discipline"
    summary = "nthreads comes from utils/hostpool, never a literal"
    doc = (
        "Flags nthreads=<int literal> at call sites and non-None literal "
        "defaults on nthreads parameters.  None means 'resolve from the "
        "process-wide pool' (utils/hostpool cpu_threads()); a hard-coded "
        "count either oversubscribes the pool or silently serializes — "
        "deliberate serial paths (nested pool workers) carry an allow."
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (
                        kw.arg == "nthreads"
                        and isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, int)
                        and not isinstance(kw.value.value, bool)
                    ):
                        yield Finding(
                            self.id, ctx.relpath,
                            kw.value.lineno, kw.value.col_offset,
                            f"literal nthreads={kw.value.value} — thread "
                            "counts come from utils/hostpool (pass None to "
                            "resolve from the pool)",
                        )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_defaults(ctx, node)

    def _check_defaults(self, ctx, node) -> Iterator[Finding]:
        args = node.args
        pos = args.posonlyargs + args.args
        for arg, default in zip(pos[len(pos) - len(args.defaults):], args.defaults):
            if arg.arg == "nthreads" and _literal_int(default):
                yield Finding(
                    self.id, ctx.relpath, default.lineno, default.col_offset,
                    f"literal default nthreads={default.value} on "
                    f"{node.name}() — default to None and resolve via "
                    "utils/hostpool",
                )
        for arg, default in zip(args.kwonlyargs, args.kw_defaults):
            if default is not None and arg.arg == "nthreads" and _literal_int(default):
                yield Finding(
                    self.id, ctx.relpath, default.lineno, default.col_offset,
                    f"literal default nthreads={default.value} on "
                    f"{node.name}() — default to None and resolve via "
                    "utils/hostpool",
                )


def _literal_int(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Constant)
        and isinstance(node.value, int)
        and not isinstance(node.value, bool)
    )


# ---------------------------------------------------------------------------
# R5: sanctioned-retry
# ---------------------------------------------------------------------------

# the one module allowed to sleep in loops / implement retry primitives
_RETRY_SANCTIONED = "celestia_tpu/utils/faults.py"

# exception names whose silent swallow is a finding (anything this broad
# hides real failures; narrower types document what is being tolerated)
_BROAD_EXCEPTIONS = {"Exception", "BaseException"}


@register
class SanctionedRetryRule(Rule):
    id = "sanctioned-retry"
    summary = "no silent exception swallows or hand-rolled sleep retry loops"
    doc = (
        "Outside utils/faults.py flags: (a) bare `except:`; (b) an "
        "`except Exception`/`except BaseException` handler whose body is "
        "only pass/continue — a silently swallowed failure (record it "
        "with faults.note(<point>, e) or re-raise); (c) a time.sleep "
        "call lexically inside a for/while loop — a hand-rolled retry/"
        "poll loop (use faults.RetryPolicy: seeded decorrelated-jitter "
        "backoff + deadline budget).  Deliberate pacing sleeps carry an "
        "allow with a reason."
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.relpath == _RETRY_SANCTIONED:
            return
        sleep_names = _sleep_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler):
                yield from self._check_handler(ctx, node)
            elif isinstance(node, ast.Call) and _is_sleep_call(
                node, sleep_names
            ):
                if any(
                    isinstance(anc, (ast.For, ast.While, ast.AsyncFor))
                    for anc in ctx.ancestors(node)
                ):
                    yield Finding(
                        self.id, ctx.relpath, node.lineno, node.col_offset,
                        "time.sleep inside a loop is a hand-rolled retry/"
                        "poll — use utils/faults.RetryPolicy (run/poll) "
                        "or carry an allow naming the pacing reason",
                    )

    def _check_handler(
        self, ctx: ModuleContext, node: ast.ExceptHandler
    ) -> Iterator[Finding]:
        if node.type is None:
            yield Finding(
                self.id, ctx.relpath, node.lineno, node.col_offset,
                "bare `except:` swallows KeyboardInterrupt/SystemExit too "
                "— name the exception type",
            )
            return
        if not _catches_broad(node.type):
            return
        if all(isinstance(s, (ast.Pass, ast.Continue)) for s in node.body):
            yield Finding(
                self.id, ctx.relpath, node.lineno, node.col_offset,
                "`except Exception` with a pass/continue body silently "
                "drops the failure — record it with "
                "faults.note(<point>, e) or re-raise",
            )


def _catches_broad(t: ast.AST) -> bool:
    if isinstance(t, ast.Name):
        return t.id in _BROAD_EXCEPTIONS
    if isinstance(t, ast.Attribute):
        return t.attr in _BROAD_EXCEPTIONS
    if isinstance(t, ast.Tuple):
        return any(_catches_broad(e) for e in t.elts)
    return False


def _sleep_aliases(tree: ast.Module) -> Set[str]:
    """Local names bound to time.sleep via `from time import sleep`."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "sleep":
                    out.add(a.asname or a.name)
    return out


def _is_sleep_call(node: ast.Call, sleep_names: Set[str]) -> bool:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id in sleep_names
    # <any alias>.sleep(...): time is routinely imported as _time; a
    # non-time object with a .sleep() method would be novel enough in
    # this tree to deserve the allow it would need
    return isinstance(f, ast.Attribute) and f.attr == "sleep"


# ---------------------------------------------------------------------------
# R6/R7/R8 live in their own modules (lock-order is a whole-program
# pass; host-sync/layering are the hot-path and architecture rules) —
# imported here so the registry sees them whenever the catalog loads.
# ---------------------------------------------------------------------------

from celestia_tpu.lint import hotpath as _hotpath  # noqa: E402,F401
from celestia_tpu.lint import lockorder as _lockorder  # noqa: E402,F401
