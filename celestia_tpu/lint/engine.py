"""celint engine: directive parsing, module contexts, rule registry, runner.

celint is the repo's own static analyzer: a consensus state machine whose
hot path is aggressively concurrent (process-wide hostpool, overlapped
native extend, shared bounded LRUs) cannot rely on reviewer memory to
keep the safety invariants of PRs 4-6 true — each parallelization is only
admissible while its invariants hold, and those invariants are exactly
the kind of thing that drifts one innocent edit at a time (the unlocked
commitment cache shipped that way for two PRs).  The rules live in
``rules.py``; this module is the machinery they share.

Directive syntax (comments, parsed with ``tokenize`` so strings that
merely LOOK like directives — e.g. lint test fixtures — never register):

``# celint: allow(<rule>[, <rule>...]) — <reason>``
    Suppress findings of the named rule(s).  A directive on a statement
    line suppresses findings on that line; a directive on a comment-only
    line suppresses findings on the next statement line (so multi-line
    calls can carry the allow inside their parentheses).  The reason is
    MANDATORY: an allow without one is itself a finding
    (``bad-suppression``), and an allow that suppresses nothing is dead
    weight and reported too (``unused-suppression``) — suppressions must
    stay explained and alive, per the audit-sweep contract.

``# celint: guarded-by(<lock>)``
    Declares that the variable assigned on this line (a module global or
    a ``self.<attr>``) may only be MUTATED while ``<lock>`` is held —
    i.e. lexically inside ``with <lock>:`` — enforced by the
    ``guarded-by`` rule.  Helper methods whose name ends in ``_locked``
    are exempt by convention: they document that the CALLER holds the
    lock (utils/lru.py's ``_insert_locked``).

Adding a rule: subclass :class:`Rule`, set ``id``/``summary``/``doc``,
implement ``check(ctx)`` yielding :class:`Finding`, and decorate with
``@register``.  Import it from ``rules.py`` so the registry sees it.
Whole-program rules (lock-order needs every module's acquisition graph
at once) subclass :class:`ProgramRule` instead and implement
``check_program(program)`` over the :class:`Program` context.
See specs/static_analysis.md for the catalog and worked examples.
"""

from __future__ import annotations

import ast
import io
import json
import re
import time
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]

# em-dash, hyphen or colon may introduce the reason
_DIRECTIVE_RE = re.compile(
    r"celint:\s*(?P<kind>allow|guarded-by)\s*"
    r"\((?P<args>[^)]*)\)\s*(?:[—:-]+\s*(?P<reason>.*\S))?"
)

# findings the engine itself emits about directive hygiene
BAD_SUPPRESSION = "bad-suppression"
UNUSED_SUPPRESSION = "unused-suppression"
PARSE_ERROR = "parse-error"


@dataclass
class Finding:
    rule: str
    path: str  # repo-relative
    line: int
    col: int
    message: str
    suppressed: bool = False
    suppress_reason: str = ""

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}{tag}"

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "suppressed": self.suppressed,
            "suppress_reason": self.suppress_reason,
        }


@dataclass
class AllowDirective:
    line: int  # line the directive appears on
    target_line: int  # line whose findings it suppresses
    rules: Tuple[str, ...]
    reason: str
    used: bool = False


@dataclass
class GuardDirective:
    line: int  # line of the annotated assignment
    target_line: int
    lock: str  # normalized source of the guarding lock expression


class ModuleContext:
    """Everything a rule needs about one source file: AST, directives,
    parent links, and the repo-relative path rules scope on."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.allows: List[AllowDirective] = []
        self.guards: List[GuardDirective] = []
        self.directive_errors: List[Finding] = []
        self._parse_directives()

    # -- directives ----------------------------------------------------

    def _next_statement_line(self, line: int) -> int:
        """First line at or after ``line`` that is not blank/comment-only
        (where a comment-line directive's findings will anchor)."""
        i = line - 1
        while i < len(self.lines):
            stripped = self.lines[i].strip()
            if stripped and not stripped.startswith("#"):
                return i + 1
            i += 1
        return line

    def _parse_directives(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [
                (t.start[0], t.string)
                for t in tokens
                if t.type == tokenize.COMMENT
            ]
        except tokenize.TokenError:
            comments = []
        for line, text in comments:
            m = _DIRECTIVE_RE.search(text)
            if m is None:
                if "celint:" in text:
                    self.directive_errors.append(
                        Finding(
                            BAD_SUPPRESSION, self.relpath, line, 0,
                            f"unparseable celint directive: {text.strip()!r}",
                        )
                    )
                continue
            kind = m.group("kind")
            args = [a.strip() for a in m.group("args").split(",") if a.strip()]
            reason = (m.group("reason") or "").strip()
            own_line_is_comment = (
                self.lines[line - 1].strip().startswith("#")
                if line - 1 < len(self.lines)
                else False
            )
            target = self._next_statement_line(line) if own_line_is_comment else line
            if kind == "allow":
                if not args:
                    self.directive_errors.append(
                        Finding(
                            BAD_SUPPRESSION, self.relpath, line, 0,
                            "allow() names no rule",
                        )
                    )
                    continue
                if not reason:
                    self.directive_errors.append(
                        Finding(
                            BAD_SUPPRESSION, self.relpath, line, 0,
                            f"allow({', '.join(args)}) without a reason — "
                            "every suppression must explain itself",
                        )
                    )
                    continue
                self.allows.append(
                    AllowDirective(line, target, tuple(args), reason)
                )
            else:  # guarded-by
                if len(args) != 1:
                    self.directive_errors.append(
                        Finding(
                            BAD_SUPPRESSION, self.relpath, line, 0,
                            "guarded-by() takes exactly one lock expression",
                        )
                    )
                    continue
                self.guards.append(
                    GuardDirective(line, target, normalize_expr(args[0]))
                )

    def allow_for(self, rule: str, line: int) -> Optional[AllowDirective]:
        for d in self.allows:
            if line in (d.line, d.target_line) and rule in d.rules:
                return d
        return None

    # -- AST helpers ---------------------------------------------------

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def held_locks(self, node: ast.AST) -> List[str]:
        """Normalized context exprs of every ``with`` enclosing ``node``."""
        out: List[str] = []
        for anc in self.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    out.append(normalize_expr(ast.unparse(item.context_expr)))
        return out

    def enclosing_functions(self, node: ast.AST) -> List[str]:
        return [
            anc.name
            for anc in self.ancestors(node)
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]


def normalize_expr(text: str) -> str:
    return re.sub(r"\s+", "", text)


# -- rule registry -----------------------------------------------------


class Rule:
    id: str = ""
    summary: str = ""
    doc: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError


class Program:
    """Whole-program context: every module's :class:`ModuleContext` plus
    run-scope facts program rules need (whether this run covers the
    default full package, so drift checks only fire on complete views)."""

    def __init__(self, contexts: List[ModuleContext], full_tree: bool = False):
        self.contexts = contexts
        self.by_path: Dict[str, ModuleContext] = {
            c.relpath: c for c in contexts
        }
        self.full_tree = full_tree


class ProgramRule(Rule):
    """A rule that needs every module at once (cross-module lock order).
    ``check`` is never called; the runner calls ``check_program``."""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        return iter(())

    def check_program(self, program: Program) -> Iterator[Finding]:
        raise NotImplementedError


REGISTRY: Dict[str, Rule] = {}

# short aliases accepted by --rules (ISSUE numbering)
ALIASES = {
    "r1": "guarded-by",
    "r2": "no-handrolled-cache",
    "r3": "consensus-determinism",
    "r4": "hostpool-discipline",
    "r5": "sanctioned-retry",
    "r6": "lock-order",
    "r7": "host-sync",
    "r8": "layering",
}


def register(cls):
    rule = cls()
    if not rule.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if rule.id in REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id}")
    REGISTRY[rule.id] = rule
    return cls


def resolve_rules(names: Optional[Iterable[str]]) -> List[Rule]:
    import celestia_tpu.lint.rules  # noqa: F401 — populate REGISTRY

    if names is None:
        return list(REGISTRY.values())
    out: List[Rule] = []
    for n in names:
        rid = ALIASES.get(n.lower(), n)
        if rid not in REGISTRY:
            raise KeyError(
                f"unknown rule {n!r} (known: {', '.join(sorted(REGISTRY))})"
            )
        out.append(REGISTRY[rid])
    return out


# -- runner ------------------------------------------------------------


class LintStats:
    """Per-rule wall time + finding counts for ``--stats`` and bench's
    ``extras.lint_stats`` — the whole-program pass must stay a watched
    number, not a silently growing tax on tier-1."""

    def __init__(self):
        self.rules: Dict[str, dict] = {}
        self.files = 0
        self.total_wall_ms = 0.0

    def add(self, rule_id: str, wall_s: float) -> None:
        rec = self.rules.setdefault(
            rule_id, {"wall_ms": 0.0, "findings": 0, "suppressed": 0}
        )
        rec["wall_ms"] += wall_s * 1000.0

    def count(self, findings: Iterable[Finding]) -> None:
        for f in findings:
            rec = self.rules.setdefault(
                f.rule, {"wall_ms": 0.0, "findings": 0, "suppressed": 0}
            )
            if f.suppressed:
                rec["suppressed"] += 1
            else:
                rec["findings"] += 1

    def to_dict(self) -> dict:
        return {
            "files": self.files,
            "total_wall_ms": round(self.total_wall_ms, 3),
            "rules": {
                rid: {
                    "wall_ms": round(rec["wall_ms"], 3),
                    "findings": rec["findings"],
                    "suppressed": rec["suppressed"],
                }
                for rid, rec in sorted(self.rules.items())
            },
        }


def _mark_allow(ctx: Optional[ModuleContext], f: Finding) -> Finding:
    if ctx is not None:
        allow = ctx.allow_for(f.rule, f.line)
        if allow is not None:
            allow.used = True
            f.suppressed = True
            f.suppress_reason = allow.reason
    return f


def lint_program(
    sources: Dict[str, str],
    rules: Optional[Iterable[str]] = None,
    *,
    full_tree: bool = False,
    stats: Optional[LintStats] = None,
) -> List[Finding]:
    """Lint a set of ``{relpath: source}`` modules as ONE program:
    per-module rules see each file, program rules (lock-order) see the
    whole set.  The entry point for both the CLI and the cross-module
    test fixtures."""
    t_start = time.perf_counter()
    active = resolve_rules(rules)
    findings: List[Finding] = []
    contexts: List[ModuleContext] = []
    for relpath, source in sorted(sources.items()):
        try:
            ctx = ModuleContext(relpath, source)
        except SyntaxError as e:
            findings.append(
                Finding(
                    PARSE_ERROR, relpath, e.lineno or 0, e.offset or 0,
                    f"syntax error: {e.msg}",
                )
            )
            continue
        contexts.append(ctx)
        findings.extend(ctx.directive_errors)
    program = Program(contexts, full_tree=full_tree)
    enabled = {r.id for r in active}
    for rule in active:
        t0 = time.perf_counter()
        if isinstance(rule, ProgramRule):
            for f in rule.check_program(program):
                findings.append(_mark_allow(program.by_path.get(f.path), f))
        else:
            for ctx in contexts:
                for f in rule.check(ctx):
                    findings.append(_mark_allow(ctx, f))
        if stats is not None:
            stats.add(rule.id, time.perf_counter() - t0)
    for ctx in contexts:
        for d in ctx.allows:
            if not d.used and any(r in enabled for r in d.rules):
                findings.append(
                    Finding(
                        UNUSED_SUPPRESSION, ctx.relpath, d.line, 0,
                        f"allow({', '.join(d.rules)}) suppresses nothing — "
                        "remove it or re-justify it",
                    )
                )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    if stats is not None:
        stats.files = len(sources)
        stats.total_wall_ms = (time.perf_counter() - t_start) * 1000.0
        stats.count(findings)
    return findings


def lint_source(
    source: str, relpath: str, rules: Optional[Iterable[str]] = None
) -> List[Finding]:
    """Lint one source text as if it lived at ``relpath`` (repo-relative,
    forward slashes).  The entry point the single-module fixtures use."""
    return lint_program({relpath: source}, rules)


def iter_py_files(paths: Iterable[Path]) -> Iterator[Path]:
    for p in paths:
        p = Path(p)
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for sub in sorted(p.rglob("*.py")):
                if "__pycache__" in sub.parts or ".git" in sub.parts:
                    continue
                yield sub


def run_lint(
    paths: Optional[Iterable[Path]] = None,
    rules: Optional[Iterable[str]] = None,
    *,
    stats: Optional[LintStats] = None,
) -> List[Finding]:
    """Lint files/directories (default: the celestia_tpu package, which
    is the only run shape the whole-program drift checks fire on)."""
    full_tree = paths is None
    if paths is None:
        paths = [REPO_ROOT / "celestia_tpu"]
    sources: Dict[str, str] = {}
    for path in iter_py_files(paths):
        try:
            rel = str(path.resolve().relative_to(REPO_ROOT))
        except ValueError:
            rel = str(path)
        sources[rel.replace("\\", "/")] = path.read_text()
    return lint_program(sources, rules, full_tree=full_tree, stats=stats)


def failing(findings: Iterable[Finding]) -> List[Finding]:
    """The findings that make a lint run exit non-zero: everything not
    suppressed (directive-hygiene findings are never suppressible)."""
    return [f for f in findings if not f.suppressed]


def render_human(findings: List[Finding], show_suppressed: bool = False) -> str:
    shown = [f for f in findings if show_suppressed or not f.suppressed]
    lines = [f.format() for f in shown]
    bad = len(failing(findings))
    sup = sum(1 for f in findings if f.suppressed)
    lines.append(
        f"celint: {bad} finding(s), {sup} suppressed"
        + ("" if bad else " — clean")
    )
    return "\n".join(lines)


def render_json(
    findings: List[Finding], stats: Optional[LintStats] = None
) -> str:
    doc = {
        "findings": [f.to_dict() for f in findings],
        "failing": len(failing(findings)),
        "suppressed": sum(1 for f in findings if f.suppressed),
    }
    if stats is not None:
        doc["stats"] = stats.to_dict()
    return json.dumps(doc, indent=2)


def render_sarif(findings: List[Finding]) -> str:
    """SARIF 2.1.0 — the machine-readable format CI annotators ingest.
    Rule ids are the stable celint ids; suppressed findings carry a
    ``suppressions`` entry (state ``accepted``) instead of vanishing, so
    an auditor sees the allow AND its reason in the same document."""
    rule_ids = sorted({f.rule for f in findings})
    import celestia_tpu.lint.rules  # noqa: F401 — populate REGISTRY

    known = dict(REGISTRY)
    sarif_rules = []
    for rid in rule_ids:
        rule = known.get(rid)
        desc = rule.summary if rule is not None else rid
        sarif_rules.append(
            {
                "id": rid,
                "shortDescription": {"text": desc},
            }
        )
    results = []
    for f in findings:
        result = {
            "ruleId": f.rule,
            "level": "warning" if f.suppressed else "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        "region": {
                            "startLine": max(1, f.line),
                            "startColumn": max(1, f.col + 1),
                        },
                    }
                }
            ],
        }
        if f.suppressed:
            result["suppressions"] = [
                {
                    "kind": "inSource",
                    "status": "accepted",
                    "justification": f.suppress_reason,
                }
            ]
        results.append(result)
    return json.dumps(
        {
            "$schema": (
                "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json"
            ),
            "version": "2.1.0",
            "runs": [
                {
                    "tool": {
                        "driver": {
                            "name": "celint",
                            "informationUri": "specs/static_analysis.md",
                            "rules": sarif_rules,
                        }
                    },
                    "results": results,
                }
            ],
        },
        indent=2,
    )
