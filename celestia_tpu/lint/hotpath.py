"""R7 ``host-sync`` and R8 ``layering``: hot-path and architecture rules.

**R7** keeps implicit device→host synchronization out of the extend hot
path.  PAPERS.md 2108.02692's lesson — the kernel pipeline is only as
fast as its slowest serializing host round-trip — became mechanical
telemetry in PR 11 (devprof dispatch brackets); this rule is the
enforcement half: in ``da/``, ``ops/`` and ``state/`` a device value may
only cross to the host through a devprof ``dispatch()`` bracket (whose
``done()`` drains the device ON the profiled timeline) or an explicitly
sanctioned function.  Banned forms: ``.item()``, bare
``block_until_ready``, and ``np.asarray``/``np.array``/``float``/
``int``/``bool`` applied to a value the rule can infer is device-
resident (assigned from a ``jnp.*`` call, ``jax.device_put``, or a call
through a jitted-program handle — a name bound from ``jax.jit(...)`` or
a ``*_fn``/``*_jit`` program factory).  Inference is deliberately
conservative: attribute chains and unresolved calls are not tainted —
missing a sync is a known cost, flagging a host-only numpy path would
teach people to sprinkle allows.

**R8** enforces the package DAG so the sharding refactor cannot tangle
imports::

    appconsts → utils → ops → da → parallel → state → node → client → cli

An import (module-level OR lazy, inside a function) from a package at
the same or a higher layer is a back-edge finding.  ``lint`` sits above
everything and is imported by nothing in the tree.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from celestia_tpu.lint.engine import Finding, ModuleContext, Rule, register

# ---------------------------------------------------------------------------
# R7: host-sync
# ---------------------------------------------------------------------------

_HOT_PREFIXES = (
    "celestia_tpu/da/",
    "celestia_tpu/ops/",
    "celestia_tpu/state/",
)

# functions whose host syncs are the design, not an accident: diagnostic
# breakdowns that exist to MEASURE the transfer boundary.  Entries are
# (relpath, function name); keep this list short and argued — everything
# else carries a per-line allow with a reason.
HOT_SYNC_SANCTIONED: Tuple[Tuple[str, str], ...] = (
    # three-sync variant kept for bench attribution; its docstring says
    # "never on the hot path" and bench is its only caller
    ("celestia_tpu/da/dah.py", "extend_and_header_breakdown"),
)

_JIT_FACTORY_SUFFIXES = ("_fn", "_jit", "_JIT")


def _is_jit_factory_name(name: str) -> bool:
    return name.endswith(_JIT_FACTORY_SUFFIXES)


class _ScopeFacts:
    """Flow-insensitive per-function dataflow: which names hold device
    values, which hold devprof brackets, which were drained by a
    bracket's done()."""

    def __init__(self):
        self.tainted: Set[str] = set()
        self.brackets: Set[str] = set()
        self.jit_handles: Set[str] = set()
        self.drained: Set[str] = set()


@register
class HostSyncRule(Rule):
    id = "host-sync"
    summary = "no implicit device->host syncs in the da/ops/state hot path"
    doc = (
        "In celestia_tpu/{da,ops,state}/ flags .item(), bare "
        "block_until_ready, and np.asarray/np.array/float/int/bool on a "
        "value inferred device-resident (assigned from jnp.*, "
        "jax.device_put, or a jitted-program handle call) unless the "
        "value went through a devprof dispatch() bracket's done() — the "
        "one sanctioned drain — or the enclosing function is on the "
        "HOT_SYNC_SANCTIONED list (measurement paths).  Host round-trips "
        "serialize the device pipeline (2108.02692); every survivor must "
        "be deliberate."
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if not ctx.relpath.startswith(_HOT_PREFIXES):
            return
        aliases = _collect_aliases(ctx.tree)
        sanctioned = {
            fn for (rel, fn) in HOT_SYNC_SANCTIONED if rel == ctx.relpath
        }
        for scope_node, scope_name in _scopes(ctx.tree):
            if scope_name in sanctioned:
                continue
            facts = _scope_facts(scope_node, aliases)
            yield from self._check_scope(ctx, scope_node, facts, aliases)

    def _check_scope(self, ctx, scope_node, facts, aliases) -> Iterator[Finding]:
        for node in _walk_scope(scope_node):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            # .item()
            if isinstance(f, ast.Attribute) and f.attr == "item" and not node.args:
                yield Finding(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    ".item() forces a device->host sync per element — "
                    "fetch through a devprof dispatch() bracket (or batch "
                    "with jax.device_get) instead",
                )
                continue
            # bare block_until_ready
            if _is_block_until_ready(f):
                yield Finding(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    "bare block_until_ready in the hot path — route the "
                    "dispatch through devprof.dispatch()/done(), which "
                    "drains the device on the profiled timeline",
                )
                continue
            # np.asarray/np.array/float/int/bool on an inferred device value
            sync_kind = _sync_call_kind(f, aliases)
            if sync_kind is None or len(node.args) < 1:
                continue
            arg = node.args[0]
            if (
                isinstance(arg, ast.Name)
                and arg.id in facts.tainted
                and arg.id not in facts.drained
            ):
                yield Finding(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    f"{sync_kind}({arg.id}) implicitly syncs a device "
                    "value to the host outside a devprof dispatch() "
                    "bracket — wrap the dispatch (out = d.done(fn(x))) "
                    "or keep the value on-device",
                )


class _Aliases:
    def __init__(self):
        self.numpy: Set[str] = set()
        self.jnp: Set[str] = set()
        self.jax: Set[str] = set()
        self.devprof: Set[str] = set()
        self.dispatch_fns: Set[str] = set()  # from celestia_tpu... import dispatch


def _collect_aliases(tree: ast.AST) -> _Aliases:
    out = _Aliases()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                local = a.asname or a.name.split(".")[0]
                if a.name == "numpy":
                    out.numpy.add(local)
                elif a.name == "jax.numpy":
                    if a.asname is not None:
                        out.jnp.add(a.asname)
                    else:
                        # `import jax.numpy` binds the name `jax`; calls
                        # arrive as jax.numpy.<fn> (handled via the jax
                        # set + the dotted check), NOT as a jnp alias —
                        # putting "jax" in the jnp set would taint every
                        # jax.* call, including host-returning device_get
                        out.jax.add("jax")
                elif a.name == "jax":
                    out.jax.add(local)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "numpy":
                        out.jnp.add(a.asname or "numpy")
            elif node.module and node.module.startswith("celestia_tpu"):
                for a in node.names:
                    if a.name == "devprof":
                        out.devprof.add(a.asname or a.name)
                    elif a.name == "dispatch":
                        out.dispatch_fns.add(a.asname or a.name)
    return out


def _scopes(tree: ast.AST):
    """(scope node, name) for the module body and every function."""
    yield tree, "<module>"
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.name


def _walk_scope(scope_node: ast.AST):
    """Walk a scope without descending into nested function defs (each
    function is its own dataflow scope)."""
    stack = list(ast.iter_child_nodes(scope_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _scope_facts(scope_node: ast.AST, aliases: _Aliases) -> _ScopeFacts:
    facts = _ScopeFacts()
    nodes = list(_walk_scope(scope_node))
    # two passes so order of definition within the scope doesn't matter
    # (flow-insensitive: a name EVER drained is treated as drained)
    for _ in range(2):
        for node in nodes:
            if isinstance(node, ast.Assign):
                _note_assign(facts, node.targets, node.value, aliases)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                _note_assign(facts, [node.target], node.value, aliases)
            elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                _note_done_statement(facts, node.value)
    return facts


def _note_assign(facts, targets, value, aliases) -> None:
    names: List[str] = []
    for t in targets:
        if isinstance(t, ast.Name):
            names.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            names.extend(e.id for e in t.elts if isinstance(e, ast.Name))
    if not names:
        return
    if _is_device_producing(facts, value, aliases):
        facts.tainted.update(names)
    if _is_bracket_ctor(value, aliases):
        facts.brackets.update(names)
    if _is_jit_handle_ctor(value, aliases):
        facts.jit_handles.update(names)
    if _is_done_call(facts, value):
        facts.drained.update(names)
        _mark_done_arg(facts, value)
    # propagation: unpack/copy of an already-classified name
    if isinstance(value, ast.Name):
        if value.id in facts.drained:
            facts.drained.update(names)
        elif value.id in facts.tainted:
            facts.tainted.update(names)


def _note_done_statement(facts, call: ast.Call) -> None:
    if _is_done_call(facts, call):
        _mark_done_arg(facts, call)


def _mark_done_arg(facts, call: ast.Call) -> None:
    for arg in call.args:
        if isinstance(arg, ast.Name):
            facts.drained.add(arg.id)


def _is_done_call(facts, node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "done"
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id in facts.brackets
    )


def _is_bracket_ctor(node: ast.AST, aliases: _Aliases) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id in aliases.devprof and f.attr == "dispatch"
    if isinstance(f, ast.Name):
        return f.id in aliases.dispatch_fns
    return False


def _is_jit_handle_ctor(node: ast.AST, aliases: _Aliases) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id in aliases.jax and f.attr == "jit"
    if isinstance(f, ast.Name):
        return _is_jit_factory_name(f.id)
    return False


def _is_device_producing(facts, node: ast.AST, aliases: _Aliases) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        head = f.value.id
        if head in aliases.jnp:
            return True
        if head in aliases.jax and f.attr == "device_put":
            return True
        return False
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Attribute)
        and isinstance(f.value.value, ast.Name)
        and f.value.value.id in aliases.jax
        and f.value.attr == "numpy"
    ):
        # the un-aliased `import jax.numpy` spelling: jax.numpy.<fn>(...)
        return True
    if isinstance(f, ast.Name):
        # a call THROUGH a jitted-program handle produces device output
        return f.id in facts.jit_handles or _is_jit_factory_name(f.id)
    return False


def _sync_call_kind(f: ast.AST, aliases: _Aliases) -> Optional[str]:
    """'np.asarray'-style label when ``f`` is a banned implicit-sync
    callable (numpy converters, scalar builtins), else None."""
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.value.id in aliases.numpy and f.attr in ("asarray", "array"):
            return f"{f.value.id}.{f.attr}"
        return None
    if isinstance(f, ast.Name) and f.id in ("float", "int", "bool"):
        return f.id
    return None


def _is_block_until_ready(f: ast.AST) -> bool:
    # jax.block_until_ready(x) and x.block_until_ready() both count
    return isinstance(f, ast.Attribute) and f.attr == "block_until_ready"


# ---------------------------------------------------------------------------
# R8: layering
# ---------------------------------------------------------------------------

# the package DAG, base to top; an import may only reach STRICTLY lower
# layers (same-package imports are free)
LAYERS: Dict[str, int] = {
    "appconsts": 0,
    "utils": 1,
    "ops": 2,
    "da": 3,
    "parallel": 4,
    "state": 5,
    "node": 6,
    "client": 7,
    "cli": 8,
    "lint": 9,
    "__init__": 10,  # the package root may touch anything (env arming)
}

_DAG_TEXT = "appconsts → utils → ops → da → parallel → state → node → client → cli"


def _layer_of(relpath: str) -> Optional[Tuple[str, int]]:
    parts = relpath.split("/")
    if len(parts) < 2 or parts[0] != "celestia_tpu":
        return None
    seg = parts[1]
    if seg.endswith(".py"):
        seg = seg[:-3]
    rank = LAYERS.get(seg)
    return (seg, rank) if rank is not None else None


@register
class LayeringRule(Rule):
    id = "layering"
    summary = "package imports follow the DAG; no back-edges, no cycles"
    doc = (
        f"Enforces {_DAG_TEXT} (lint above all): an import — module-"
        "level or lazy — from a package at the same or a higher layer is "
        "a back-edge.  The upcoming sharding refactor reworks da/state/"
        "node heavily; the DAG is what keeps 'just import it from node' "
        "from quietly inverting the architecture.  Deliberate inversions "
        "carry allow(layering) with the architectural argument."
    )

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        me = _layer_of(ctx.relpath)
        if me is None:
            return
        my_seg, my_rank = me
        for node in ast.walk(ctx.tree):
            targets: Set[Tuple[str, int]] = set()
            if isinstance(node, ast.Import):
                for a in node.names:
                    t = _import_target(a.name)
                    if t is not None:
                        targets.add(t)
            elif isinstance(node, ast.ImportFrom):
                # resolve relative imports against this file's package
                # so `from ..node import x` can't slip under the rule
                base = _absolute_module(ctx.relpath, node.level, node.module)
                if base is not None:
                    t = _import_target(base)
                    if t is not None:
                        targets.add(t)
                    # `from celestia_tpu import node` names the package
                    # in the ALIAS, not in node.module — check each one
                    for a in node.names:
                        t = _import_target(f"{base}.{a.name}")
                        if t is not None:
                            targets.add(t)
            for seg, rank in sorted(targets):
                if seg == my_seg:
                    continue
                if rank >= my_rank:
                    yield Finding(
                        self.id, ctx.relpath, node.lineno, node.col_offset,
                        f"layering back-edge: {my_seg}/ may not import "
                        f"{seg}/ (DAG: {_DAG_TEXT})",
                    )


def _absolute_module(
    relpath: str, level: int, module: Optional[str]
) -> Optional[str]:
    """Dotted absolute module an ImportFrom refers to.  ``level`` 0 is
    already absolute; level k resolves against this file's package
    (``from ..node import x`` in state/modules/ → celestia_tpu.node)."""
    if level == 0:
        return module
    pkg_parts = relpath.split("/")[:-1]  # drop the filename
    if level > 1:
        if level - 1 > len(pkg_parts):
            return None
        pkg_parts = pkg_parts[: len(pkg_parts) - (level - 1)]
    base = ".".join(pkg_parts)
    if not base:
        return None
    return f"{base}.{module}" if module else base


def _import_target(dotted: str) -> Optional[Tuple[str, int]]:
    parts = dotted.split(".")
    if parts[0] != "celestia_tpu" or len(parts) < 2:
        return None
    rank = LAYERS.get(parts[1])
    return (parts[1], rank) if rank is not None else None
