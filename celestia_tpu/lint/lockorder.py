"""R6 ``lock-order``: whole-program lock-acquisition graph analysis.

PRs 7-11 multiplied the lock population (LruCache registry + per-cache
locks, BreakerRegistry, the telemetry/tracing/timeseries rings, devprof,
the faults registry) and nothing stops an innocent edit from acquiring
two of them in the order OPPOSITE to some other thread's — the classic
cross-module deadlock that no single-file rule can see.  This pass
builds ONE directed graph over every lock in the package and fails on
any cycle.

**Lock discovery.**  A lock node is created for every

* assignment of ``threading.Lock()`` / ``threading.RLock()`` to a module
  global (``_lock = threading.Lock()`` → ``utils/faults.py::_lock``) or
  a ``self`` attribute inside a class (``self._lock = threading.Lock()``
  → ``utils/lru.py::LruCache.self._lock``);
* lock expression named by a ``# celint: guarded-by(<lock>)`` directive
  (annotation-only locks: state guarded by a lock that is created
  dynamically or in another scope still participates in ordering).

Instance locks are identified per CLASS, not per object: two LruCache
instances share one node.  That is deliberately conservative — a
cross-instance AB/BA order on the same class is reported even though a
disjoint pair of instances cannot deadlock, because nothing in the
source proves the instances ARE disjoint.

**Edge construction.**

* Lexical nesting: ``with A:`` containing ``with B:`` adds A → B.
* Call-mediated: a call made while lexically holding A, resolved to a
  function in the package whose transitive may-acquire set contains B,
  adds A → B.  Calls resolve intra-package only: same-module functions,
  ``self.method()`` on the enclosing class, ``<imported module>.fn()``
  through ``celestia_tpu`` imports, and attribute calls whose method
  name is defined by exactly ONE class in the program (unique-name
  resolution; ambiguous names are skipped — missing an edge is a known
  cost, inventing one is a false positive).
* ``*_locked`` convention: a function named ``*_locked`` is analyzed as
  if its own class/module locks are held at entry (the suffix is the
  repo's caller-holds-the-lock contract), so an acquisition inside it
  becomes an edge from the assumed-held lock.

**Findings.**

* Any cycle in the graph (potential deadlock) — the message carries the
  full acquisition chain with the file:line of every edge.
* A self-edge on a non-reentrant ``threading.Lock`` (A acquired while A
  is held): not an ordering bug but an immediate self-deadlock.
* Drift between the derived hierarchy and ``specs/lock_hierarchy.md``
  (full-tree runs only): the committed doc must always match the code.
  Regenerate with ``python -m celestia_tpu.lint --write-lock-hierarchy``.

The derived graph is also the static half of the runtime shadow checker
(utils/lockwatch.py): :func:`lock_decl_sites` maps source declaration
sites to lock ids so lockwatch's observed acquisition pairs can be
cross-checked against this graph (:func:`runtime_crosscheck`).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Set, Tuple

from celestia_tpu.lint.engine import (
    Finding,
    ModuleContext,
    Program,
    ProgramRule,
    REPO_ROOT,
    normalize_expr,
    register,
)

HIERARCHY_PATH = "specs/lock_hierarchy.md"
REGEN_CMD = "python -m celestia_tpu.lint --write-lock-hierarchy"

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}


@dataclass
class LockInfo:
    lock_id: str  # "<relpath>::<name>" or "<relpath>::<Class>.self.<attr>"
    relpath: str
    line: int
    kind: str  # lock | rlock | condition | annotation


@dataclass
class _Call:
    line: int
    held: Tuple[str, ...]  # lock ids held at the call site
    # resolution candidates, tried in order: ("func", module, name),
    # ("method", module, class, name), ("unique", name)
    keys: Tuple[Tuple, ...]


@dataclass
class _FuncInfo:
    qualname: str  # "<relpath>::<Class.>name"
    relpath: str
    cls: Optional[str]
    name: str
    acquires: List[Tuple[str, int]] = field(default_factory=list)
    edges: List[Tuple[str, str, int]] = field(default_factory=list)
    calls: List[_Call] = field(default_factory=list)
    # transitive may-acquire: lock id -> witness (qualname, line) of the
    # acquisition this function can reach
    may_acquire: Dict[str, Tuple[str, int]] = field(default_factory=dict)


class _ModuleFacts:
    """Per-module lock/function/import facts feeding the program graph.
    ``known_paths`` is the set of relpaths IN the program, so import
    resolution works for fixture modules that exist only in memory."""

    def __init__(self, ctx: ModuleContext, known_paths: Optional[Set[str]] = None):
        self.ctx = ctx
        self.known_paths = known_paths or set()
        self.relpath = ctx.relpath
        self.threading_aliases: Set[str] = set()
        self.ctor_aliases: Dict[str, str] = {}  # bare name -> kind
        self.module_imports: Dict[str, str] = {}  # local alias -> relpath
        self.func_imports: Dict[str, Tuple[str, str]] = {}  # name -> (relpath, fn)
        self.locks: Dict[str, LockInfo] = {}  # lock_id -> info
        self.module_lock_names: Dict[str, str] = {}  # global name -> lock_id
        # class -> {self attr -> lock_id}
        self.class_lock_attrs: Dict[str, Dict[str, str]] = {}
        self.functions: Dict[str, _FuncInfo] = {}
        self._collect_imports()
        self._collect_locks()

    # -- imports -------------------------------------------------------

    def _collect_imports(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "threading":
                        self.threading_aliases.add(a.asname or "threading")
                    elif a.name.startswith("celestia_tpu."):
                        if a.asname is not None:
                            target = self._mod_relpath(a.name)
                            if target is not None:
                                self.module_imports[a.asname] = target
            elif isinstance(node, ast.ImportFrom):
                if node.module == "threading":
                    for a in node.names:
                        if a.name in _LOCK_CTORS:
                            self.ctor_aliases[a.asname or a.name] = (
                                _LOCK_CTORS[a.name]
                            )
                elif node.module and node.module.startswith("celestia_tpu"):
                    for a in node.names:
                        local = a.asname or a.name
                        sub = self._mod_relpath(f"{node.module}.{a.name}")
                        if sub is not None:
                            # "from celestia_tpu.utils import faults"
                            self.module_imports[local] = sub
                        else:
                            owner = self._mod_relpath(node.module)
                            if owner is not None:
                                self.func_imports[local] = (owner, a.name)

    def _mod_relpath(self, dotted: str) -> Optional[str]:
        """repo-relative path of a celestia_tpu dotted module — resolved
        against the program's own files FIRST (fixtures exist only in
        memory), the working tree second.  None when the dotted name is
        not a module (then it was a from-import of a function/class)."""
        if not dotted.startswith("celestia_tpu"):
            return None
        rel = dotted.replace(".", "/")
        if rel + ".py" in self.known_paths:
            return rel + ".py"
        if rel + "/__init__.py" in self.known_paths:
            return rel + "/__init__.py"
        if (REPO_ROOT / (rel + ".py")).is_file():
            return rel + ".py"
        if (REPO_ROOT / rel / "__init__.py").is_file():
            return rel + "/__init__.py"
        return None

    # -- lock discovery ------------------------------------------------

    def _lock_kind_of_call(self, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        f = node.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
            if f.value.id in self.threading_aliases and f.attr in _LOCK_CTORS:
                return _LOCK_CTORS[f.attr]
        elif isinstance(f, ast.Name) and f.id in self.ctor_aliases:
            return self.ctor_aliases[f.id]
        return None

    def _enclosing_class(self, node: ast.AST) -> Optional[str]:
        for anc in self.ctx.ancestors(node):
            if isinstance(anc, ast.ClassDef):
                return anc.name
            if isinstance(anc, ast.Module):
                break
        return None

    def _add_lock(
        self, name: str, cls: Optional[str], line: int, kind: str
    ) -> str:
        if cls is not None:
            lock_id = f"{self.relpath}::{cls}.self.{name}"
            self.class_lock_attrs.setdefault(cls, {})[name] = lock_id
        else:
            lock_id = f"{self.relpath}::{name}"
            self.module_lock_names[name] = lock_id
        if lock_id not in self.locks or self.locks[lock_id].kind == "annotation":
            self.locks[lock_id] = LockInfo(lock_id, self.relpath, line, kind)
        return lock_id

    def _collect_locks(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            value = node.value
            kind = self._lock_kind_of_call(value)
            if kind is None:
                continue
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Name):
                    cls = self._enclosing_class(node)
                    # a Lock() assigned to a plain name inside a class
                    # body is a class attribute; inside a function it is
                    # a local — both are scoped to best effort
                    self._add_lock(t.id, cls, node.lineno, kind)
                elif (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    cls = self._enclosing_class(node)
                    if cls is not None:
                        self._add_lock(t.attr, cls, node.lineno, kind)
        # annotation-only locks: guarded-by(<expr>) registers the lock
        # even when its construction is out of scope
        for g in self.ctx.guards:
            self._resolve_guard_lock(g.lock, g.target_line)

    def _resolve_guard_lock(self, expr: str, line: int) -> None:
        expr = normalize_expr(expr)
        if expr.startswith("self."):
            attr = expr[len("self."):]
            node = self._node_at_line(line)
            cls = self._enclosing_class(node) if node is not None else None
            if cls is not None and attr not in self.class_lock_attrs.get(cls, {}):
                self._add_lock(attr, cls, line, "annotation")
        elif "." not in expr and "(" not in expr:
            if expr not in self.module_lock_names:
                self._add_lock(expr, None, line, "annotation")

    def _node_at_line(self, line: int) -> Optional[ast.AST]:
        for node in ast.walk(self.ctx.tree):
            if getattr(node, "lineno", None) == line:
                return node
        return None

    # -- with-expression resolution -------------------------------------

    def resolve_lock_expr(self, expr: ast.AST, cls: Optional[str]) -> Optional[str]:
        """Lock id a ``with`` context expression refers to, or None."""
        if isinstance(expr, ast.Name):
            return self.module_lock_names.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and cls is not None:
                    return self.class_lock_attrs.get(cls, {}).get(expr.attr)
                target = self.module_imports.get(base.id)
                if target is not None:
                    # with faults._lock: — cross-module module-level lock
                    return f"{target}::{expr.attr}"
        return None


# ---------------------------------------------------------------------------
# function analysis
# ---------------------------------------------------------------------------


def _analyze_functions(facts: _ModuleFacts) -> None:
    ctx = facts.ctx

    def walk_nodes(
        fn: _FuncInfo,
        nodes: List[ast.AST],
        held: Tuple[str, ...],
        cls: Optional[str],
    ) -> None:
        for child in nodes:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs are separate (unresolved) scopes
            if isinstance(child, ast.With):
                new_held = list(held)
                for item in child.items:
                    # the context expressions themselves may contain calls
                    walk_nodes(fn, [item.context_expr], held, cls)
                    lock_id = facts.resolve_lock_expr(item.context_expr, cls)
                    if lock_id is None:
                        continue
                    for h in new_held:
                        # h == lock_id is a SELF-edge: re-acquisition
                        # while held (self-deadlock on a plain Lock)
                        fn.edges.append((h, lock_id, child.lineno))
                    fn.acquires.append((lock_id, child.lineno))
                    new_held.append(lock_id)
                walk_nodes(fn, child.body, tuple(new_held), cls)
                continue
            if isinstance(child, ast.Call):
                keys = _call_keys(facts, child, cls)
                if keys:
                    fn.calls.append(_Call(child.lineno, held, keys))
            walk_nodes(fn, list(ast.iter_child_nodes(child)), held, cls)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cls = facts._enclosing_class(node)
        qual = (
            f"{facts.relpath}::{cls}.{node.name}"
            if cls
            else f"{facts.relpath}::{node.name}"
        )
        fn = _FuncInfo(qual, facts.relpath, cls, node.name)
        held: Tuple[str, ...] = ()
        if node.name.endswith("_locked"):
            # caller-holds convention: analyze the body as if the owning
            # scope's locks are already held
            assumed: List[str] = []
            if cls is not None:
                assumed.extend(facts.class_lock_attrs.get(cls, {}).values())
            else:
                assumed.extend(facts.module_lock_names.values())
            held = tuple(assumed)
        walk_nodes(fn, node.body, held, cls)
        facts.functions[qual] = fn


# names that collide with builtin-container/threading methods: a call
# like `_armed.pop(k)` or `_threads.remove(t)` must NOT unique-resolve
# to some class that happens to define the same method name — the
# receiver is far more likely a dict/list/set/Lock than the one class
# the name matched.  Derived from the builtin types this tree actually
# passes around, plus the threading primitives.
_UNIQUE_DENYLIST: Set[str] = set()
for _t in (dict, list, set, frozenset, tuple, str, bytes, bytearray):
    _UNIQUE_DENYLIST.update(n for n in dir(_t) if not n.startswith("__"))
_UNIQUE_DENYLIST.update(
    ("acquire", "release", "locked", "join", "start", "close", "put",
     "get", "get_nowait", "put_nowait", "set", "wait", "notify",
     "notify_all", "cancel", "result", "submit", "shutdown")
)


def _call_keys(
    facts: _ModuleFacts, node: ast.Call, cls: Optional[str]
) -> Tuple[Tuple, ...]:
    f = node.func
    if isinstance(f, ast.Name):
        imported = facts.func_imports.get(f.id)
        if imported is not None:
            return (("func", imported[0], imported[1]),)
        return (("func", facts.relpath, f.id),)
    if isinstance(f, ast.Attribute):
        base = f.value
        if isinstance(base, ast.Name):
            if base.id == "self" and cls is not None:
                return (("method", facts.relpath, cls, f.attr),)
            target = facts.module_imports.get(base.id)
            if target is not None:
                return (("func", target, f.attr),)
        if f.attr in _UNIQUE_DENYLIST:
            return ()
        return (("unique", f.attr),)
    return ()


# ---------------------------------------------------------------------------
# the program graph
# ---------------------------------------------------------------------------


class LockGraph:
    def __init__(self):
        self.locks: Dict[str, LockInfo] = {}
        # a -> b -> (file, line, via) witness of the first edge seen
        self.edges: Dict[str, Dict[str, Tuple[str, int, str]]] = {}

    def add_edge(
        self, a: str, b: str, relpath: str, line: int, via: str
    ) -> None:
        self.edges.setdefault(a, {}).setdefault(b, (relpath, line, via))

    def cycles(self) -> List[List[str]]:
        """Elementary cycles, deduped by node set (one report per knot)."""
        seen_sets: Set[frozenset] = set()
        out: List[List[str]] = []
        # DFS from each node with an explicit stack; bounded by the small
        # size of the lock population
        for start in sorted(self.edges):
            stack = [(start, [start])]
            visited_paths = 0
            while stack:
                nodeid, path = stack.pop()
                visited_paths += 1
                if visited_paths > 20000:
                    break  # defensive bound; the real graph is tiny
                for nxt in sorted(self.edges.get(nodeid, ())):
                    if nxt == start and len(path) > 1:
                        key = frozenset(path)
                        if key not in seen_sets:
                            seen_sets.add(key)
                            out.append(path[:])
                    elif nxt not in path and nxt > start:
                        # only walk nodes after `start` so each cycle is
                        # found from its smallest node exactly once
                        stack.append((nxt, path + [nxt]))
        return out

    def self_deadlocks(self) -> List[Tuple[str, Tuple[str, int, str]]]:
        out = []
        for a, targets in self.edges.items():
            if a in targets:
                info = self.locks.get(a)
                if info is not None and info.kind == "lock":
                    out.append((a, targets[a]))
        return out


def build_lock_graph(program: Program) -> LockGraph:
    known_paths = set(program.by_path)
    facts_by_path: Dict[str, _ModuleFacts] = {}
    for ctx in program.contexts:
        facts = _ModuleFacts(ctx, known_paths)
        _analyze_functions(facts)
        facts_by_path[ctx.relpath] = facts

    graph = LockGraph()
    all_funcs: Dict[str, _FuncInfo] = {}
    by_name: Dict[Tuple[str, str], _FuncInfo] = {}  # (relpath, name) module fns
    by_method: Dict[Tuple[str, str, str], _FuncInfo] = {}
    method_name_count: Dict[str, List[_FuncInfo]] = {}
    for facts in facts_by_path.values():
        graph.locks.update(facts.locks)
        for fn in facts.functions.values():
            all_funcs[fn.qualname] = fn
            if fn.cls is None:
                by_name[(fn.relpath, fn.name)] = fn
            else:
                by_method[(fn.relpath, fn.cls, fn.name)] = fn
                method_name_count.setdefault(fn.name, []).append(fn)

    def resolve(call: _Call) -> Optional[_FuncInfo]:
        for key in call.keys:
            if key[0] == "func":
                fn = by_name.get((key[1], key[2]))
                if fn is not None:
                    return fn
            elif key[0] == "method":
                fn = by_method.get((key[1], key[2], key[3]))
                if fn is not None:
                    return fn
            elif key[0] == "unique":
                cands = method_name_count.get(key[1], ())
                if len(cands) == 1:
                    return cands[0]
        return None

    # seed may-acquire with direct acquisitions, then propagate through
    # resolved calls to a fixpoint
    for fn in all_funcs.values():
        for lock_id, line in fn.acquires:
            fn.may_acquire.setdefault(lock_id, (fn.qualname, line))
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for fn in all_funcs.values():
            for call in fn.calls:
                callee = resolve(call)
                if callee is None:
                    continue
                for lock_id, witness in callee.may_acquire.items():
                    if lock_id not in fn.may_acquire:
                        fn.may_acquire[lock_id] = witness
                        changed = True

    # edges: lexical nesting + call-mediated
    for fn in all_funcs.values():
        for a, b, line in fn.edges:
            graph.add_edge(a, b, fn.relpath, line, f"nested with in {fn.qualname}")
        for call in fn.calls:
            if not call.held:
                continue
            callee = resolve(call)
            if callee is None:
                continue
            for lock_id, (wq, wl) in callee.may_acquire.items():
                for h in call.held:
                    # h == lock_id included: a call that re-acquires a
                    # held non-reentrant lock is a self-deadlock
                    graph.add_edge(
                        h, lock_id, fn.relpath, call.line,
                        f"call to {callee.qualname} (acquires at "
                        f"{wq.split('::')[0]}:{wl})",
                    )
    return graph


# ---------------------------------------------------------------------------
# the rule
# ---------------------------------------------------------------------------


def _short(lock_id: str) -> str:
    relpath, name = lock_id.split("::", 1)
    return f"{relpath.replace('celestia_tpu/', '')}::{name}"


@register
class LockOrderRule(ProgramRule):
    id = "lock-order"
    summary = "the cross-module lock-acquisition graph must stay acyclic"
    doc = (
        "Builds one directed graph over every threading.Lock/RLock in "
        "the package (with-nesting, guarded-by annotations, the *_locked "
        "caller-holds convention, intra-package call resolution) and "
        "fails on any cycle — a potential AB/BA deadlock — printing the "
        "offending acquisition chain.  A non-reentrant Lock re-acquired "
        "while held is reported as a self-deadlock.  Full-tree runs also "
        "verify specs/lock_hierarchy.md matches the derived graph "
        f"(regenerate: {REGEN_CMD})."
    )

    def check_program(self, program: Program) -> Iterator[Finding]:
        graph = build_lock_graph(program)
        for lock_id, (relpath, line, via) in graph.self_deadlocks():
            yield Finding(
                self.id, relpath, line, 0,
                f"non-reentrant lock {_short(lock_id)} may be re-acquired "
                f"while already held ({via}) — an immediate self-deadlock; "
                "use the *_locked caller-holds convention or an RLock",
            )
        for cycle in graph.cycles():
            chain = []
            hops = cycle + [cycle[0]]
            first_site = None
            for a, b in zip(hops, hops[1:]):
                relpath, line, via = graph.edges[a][b]
                if first_site is None:
                    first_site = (relpath, line)
                chain.append(
                    f"{_short(a)} -> {_short(b)} ({relpath}:{line}, {via})"
                )
            relpath, line = first_site if first_site else ("", 0)
            yield Finding(
                self.id, relpath, line, 0,
                "lock-order cycle (potential deadlock): " + "; ".join(chain),
            )
        if program.full_tree:
            want = render_hierarchy(graph)
            path = REPO_ROOT / HIERARCHY_PATH
            have = path.read_text() if path.is_file() else ""
            if have != want:
                yield Finding(
                    self.id, HIERARCHY_PATH, 1, 0,
                    "specs/lock_hierarchy.md is out of date with the "
                    f"derived lock graph — regenerate with `{REGEN_CMD}`",
                )


# ---------------------------------------------------------------------------
# hierarchy document + lockwatch bridge
# ---------------------------------------------------------------------------


def _rank_locks(graph: LockGraph) -> Dict[str, int]:
    """Longest-path rank of each lock in the (acyclic) graph: rank 0
    locks are acquired first, higher ranks only while lower ones may be
    held.  Cyclic graphs fall back to rank 0 everywhere (the cycle is
    already a finding)."""
    ranks = {lock_id: 0 for lock_id in graph.locks}
    for _ in range(len(graph.locks) + 1):
        changed = False
        for a, targets in graph.edges.items():
            for b in targets:
                if a == b:
                    continue
                if a in ranks and b in ranks and ranks[b] < ranks[a] + 1:
                    ranks[b] = ranks[a] + 1
                    changed = True
        if not changed:
            return ranks
    return {lock_id: 0 for lock_id in graph.locks}  # cycle: no stable rank


def render_hierarchy(graph: LockGraph) -> str:
    """The generated specs/lock_hierarchy.md body: every lock with its
    declaration site and rank, every edge with its witness.  Fully
    deterministic so drift checking is an exact string compare."""
    ranks = _rank_locks(graph)
    lines = [
        "# Lock hierarchy (generated)",
        "",
        "<!-- GENERATED FILE — do not edit by hand. -->",
        f"<!-- Regenerate with: {REGEN_CMD} -->",
        "",
        "Derived by celint R6 (`lock-order`) from the package's lock-",
        "acquisition graph: `with` nesting, `guarded-by` annotations, the",
        "`*_locked` caller-holds convention, and intra-package call",
        "resolution.  A lock may only be acquired while holding locks of",
        "a strictly LOWER rank; celint fails the build on any cycle, and",
        "utils/lockwatch.py cross-checks the observed runtime order",
        "against this graph under `CELESTIA_TPU_LOCKWATCH=1`.",
        "",
        "## Locks by rank",
        "",
    ]
    by_rank: Dict[int, List[str]] = {}
    for lock_id in sorted(graph.locks):
        by_rank.setdefault(ranks.get(lock_id, 0), []).append(lock_id)
    for rank in sorted(by_rank):
        lines.append(f"### Rank {rank}")
        lines.append("")
        for lock_id in by_rank[rank]:
            info = graph.locks[lock_id]
            lines.append(
                f"- `{_short(lock_id)}` ({info.kind}, "
                f"{info.relpath}:{info.line})"
            )
        lines.append("")
    lines.append("## Acquisition edges")
    lines.append("")
    if not any(graph.edges.values()):
        lines.append("(none observed)")
    for a in sorted(graph.edges):
        for b in sorted(graph.edges[a]):
            relpath, line, via = graph.edges[a][b]
            lines.append(
                f"- `{_short(a)}` → `{_short(b)}` — {relpath}:{line} ({via})"
            )
    lines.append("")
    return "\n".join(lines)


def _full_tree_program() -> Program:
    from celestia_tpu.lint.engine import iter_py_files

    contexts = []
    for path in iter_py_files([REPO_ROOT / "celestia_tpu"]):
        rel = str(path.resolve().relative_to(REPO_ROOT)).replace("\\", "/")
        try:
            contexts.append(ModuleContext(rel, path.read_text()))
        except SyntaxError:
            continue
    return Program(contexts, full_tree=True)


def write_lock_hierarchy() -> Path:
    """Regenerate specs/lock_hierarchy.md from the current tree."""
    graph = build_lock_graph(_full_tree_program())
    path = REPO_ROOT / HIERARCHY_PATH
    path.write_text(render_hierarchy(graph))
    return path


def lock_decl_sites(graph: Optional[LockGraph] = None) -> Dict[Tuple[str, int], str]:
    """(relpath, line) of every lock declaration -> lock id.  The bridge
    utils/lockwatch.py's runtime observations are joined on: a watched
    lock knows only WHERE it was constructed."""
    if graph is None:
        graph = build_lock_graph(_full_tree_program())
    return {
        (info.relpath, info.line): lock_id
        for lock_id, info in graph.locks.items()
    }


def runtime_crosscheck(
    observed_pairs: Dict[Tuple[Tuple[str, int], Tuple[str, int]], str],
    graph: Optional[LockGraph] = None,
) -> List[str]:
    """Cross-check lockwatch's observed acquisition pairs against the
    static graph.  ``observed_pairs`` maps ((file, line), (file, line))
    construction-site pairs (A held while B acquired) to a stack
    summary.  Returns one message per contradiction: an observed order
    whose REVERSE is reachable in the static graph — execution proving
    the static cycle risk is real — or an observed A->B together with an
    observed B->A (a live inversion even if the static pass missed it)."""
    if graph is None:
        graph = build_lock_graph(_full_tree_program())
    decls = lock_decl_sites(graph)

    def reachable(a: str, b: str) -> bool:
        seen: Set[str] = set()
        stack = [a]
        while stack:
            cur = stack.pop()
            if cur == b:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(graph.edges.get(cur, ()))
        return False

    problems: List[str] = []
    mapped: Dict[Tuple[str, str], str] = {}
    for (site_a, site_b), stack_summary in sorted(observed_pairs.items()):
        a = decls.get(site_a)
        b = decls.get(site_b)
        if a is None or b is None or a == b:
            continue
        mapped[(a, b)] = stack_summary
    for (a, b), stack_summary in sorted(mapped.items()):
        if (b, a) in mapped:
            if a < b:  # report each inversion once
                problems.append(
                    f"runtime inversion: {_short(a)} -> {_short(b)} AND "
                    f"{_short(b)} -> {_short(a)} both observed\n"
                    f"  {stack_summary}\n  {mapped[(b, a)]}"
                )
        elif reachable(b, a):
            problems.append(
                f"observed {_short(a)} -> {_short(b)} contradicts the "
                f"static order ({_short(b)} precedes {_short(a)} in the "
                f"lock graph)\n  {stack_summary}"
            )
    return problems
