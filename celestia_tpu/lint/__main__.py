"""CLI: ``python -m celestia_tpu.lint [paths...] [options]``.

Exit status 0 when the tree is clean (every finding suppressed with a
reason), 1 when any finding fails, 2 on usage errors — so `make lint`
and CI can gate on it directly.  ``--format json|sarif`` emits the
machine-readable documents (stable rule ids, file/line/col, suppression
state) under the SAME exit-code contract.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from celestia_tpu.lint.engine import (
    LintStats,
    failing,
    render_human,
    render_json,
    render_sarif,
    resolve_rules,
    run_lint,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m celestia_tpu.lint",
        description="celint: concurrency & determinism static analysis",
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/directories to lint (default: the celestia_tpu package)",
    )
    parser.add_argument(
        "--rules", help="comma-separated rule ids or r1..r8 aliases "
        "(default: all)",
    )
    parser.add_argument(
        "--format", dest="fmt", choices=("human", "json", "sarif"),
        default="human", help="output format (default: human)",
    )
    parser.add_argument(
        "--json", action="store_true",
        help="alias for --format json (kept for existing callers)",
    )
    parser.add_argument(
        "--stats", action="store_true",
        help="append per-rule wall-time/finding stats (human prints a "
        "table; json embeds a stats object)",
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed findings with their reasons",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "--write-lock-hierarchy", action="store_true",
        help="regenerate specs/lock_hierarchy.md from the R6 lock graph "
        "and exit (0 on success)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in resolve_rules(None):
            print(f"{rule.id}: {rule.summary}")
            if rule.doc:
                print(f"    {rule.doc}")
        return 0

    if args.write_lock_hierarchy:
        from celestia_tpu.lint.lockorder import write_lock_hierarchy

        path = write_lock_hierarchy()
        print(f"wrote {path}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
    fmt = "json" if args.json else args.fmt
    stats = LintStats() if args.stats else None
    try:
        findings = run_lint(args.paths or None, rule_ids, stats=stats)
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 2
    if fmt == "json":
        print(render_json(findings, stats=stats))
    elif fmt == "sarif":
        print(render_sarif(findings))
        if stats is not None:
            # the SARIF document has no stats slot; keep stdout a clean
            # parseable document and put the table on stderr
            _print_stats(stats, sys.stderr)
    else:
        print(render_human(findings, show_suppressed=args.show_suppressed))
        if stats is not None:
            _print_stats(stats, sys.stdout)
    return 1 if failing(findings) else 0


def _print_stats(stats: LintStats, out) -> None:
    d = stats.to_dict()
    print(f"stats: {d['files']} file(s) in {d['total_wall_ms']:.0f} ms", file=out)
    for rid, rec in d["rules"].items():
        print(
            f"  {rid}: {rec['wall_ms']:.0f} ms, "
            f"{rec['findings']} finding(s), "
            f"{rec['suppressed']} suppressed",
            file=out,
        )


if __name__ == "__main__":
    sys.exit(main())
