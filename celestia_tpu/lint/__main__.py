"""CLI: ``python -m celestia_tpu.lint [paths...] [options]``.

Exit status 0 when the tree is clean (every finding suppressed with a
reason), 1 when any finding fails, 2 on usage errors — so `make lint`
and CI can gate on it directly.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from celestia_tpu.lint.engine import (
    failing,
    render_human,
    render_json,
    resolve_rules,
    run_lint,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m celestia_tpu.lint",
        description="celint: concurrency & determinism static analysis",
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/directories to lint (default: the celestia_tpu package)",
    )
    parser.add_argument(
        "--rules", help="comma-separated rule ids or r1..r4 aliases "
        "(default: all)",
    )
    parser.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    parser.add_argument(
        "--show-suppressed", action="store_true",
        help="also print suppressed findings with their reasons",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in resolve_rules(None):
            print(f"{rule.id}: {rule.summary}")
            if rule.doc:
                print(f"    {rule.doc}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = [r.strip() for r in args.rules.split(",") if r.strip()]
    try:
        findings = run_lint(args.paths or None, rule_ids)
    except KeyError as e:
        print(e.args[0], file=sys.stderr)
        return 2
    if args.json:
        print(render_json(findings))
    else:
        print(render_human(findings, show_suppressed=args.show_suppressed))
    return 1 if failing(findings) else 0


if __name__ == "__main__":
    sys.exit(main())
