"""celint: repo-specific concurrency & determinism static analysis.

``python -m celestia_tpu.lint`` runs the rule catalog over the package;
tests/test_lint.py runs it as a tier-1 gate.  See engine.py for the
machinery, rules.py for R1-R4, specs/static_analysis.md for the docs.
"""

from celestia_tpu.lint.engine import (  # noqa: F401
    ALIASES,
    Finding,
    LintStats,
    ModuleContext,
    Program,
    ProgramRule,
    REGISTRY,
    Rule,
    failing,
    lint_program,
    lint_source,
    register,
    render_human,
    render_json,
    render_sarif,
    resolve_rules,
    run_lint,
)
