"""Anomaly flight recorder: black-box incident capture on alert firing.

The alert engine (utils/timeseries.py) can SAY a node is degrading —
but when a human finally looks, the evidence has evaporated: the trace
ring rolled over, the timeseries window slid past the spike, the stacks
that were on-CPU are gone.  A production node serving millions of light
clients needs black-box incident capture, not a dashboard watcher.

This module is that recorder.  It subscribes to AlertEngine *firing
transitions* (a rule flipping not-firing -> firing; steady-state firing
never re-triggers) plus an optional slow-block threshold, and on each
trigger dumps ONE bounded on-disk **incident bundle**:

    <flight-dir>/inc-<seq>-<reason>/
        manifest.json     schema + trigger + per-file sha256 manifest
        trace.json        Chrome trace (spans + host-profiler samples,
                          utils/hostprof.merged_trace_dump — opens in
                          Perfetto as-is)
        timeseries.json   the telemetry ring window at trigger time
        metrics.prom      full Prometheus exposition text
        stacks.folded     folded host stacks (flamegraph-ready)
        faults.json       fault notes / degradations / armed points
        alerts.json       every rule verdict (firing and not)

Bundles live in a **size-capped ring of incident dirs**: at most
``max_incidents`` directories and ``max_total_bytes`` on disk, oldest
evicted first — a flapping node cannot fill the volume.  Triggers are
rate-limited (``min_interval_s``) so one bad minute produces one
bundle, not sixty.

Layering (celint R8): this is a utils/ module — it reads only other
utils surfaces (tracing, hostprof, faults, telemetry clock).  Node-side
context (height, exposition text, the timeseries window, alert
verdicts) is HANDED IN by node/server.py, which owns the recorder and
drives :meth:`FlightRecorder.on_alerts` from its sampler tick.

Served by the ``FlightList`` / ``FlightFetch`` RPCs (node/server.py),
``query incidents`` / ``query incident --out`` / ``query
cluster-incidents`` (cli.py) and the ``make incident-smoke`` gate.

Clock: :func:`telemetry.clock` — this module is on celint R3's
SANCTIONED_CHANNELS list (clock reads sanctioned, entropy still
banned: incident ids are sequence numbers, never random).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import threading
from typing import Dict, List, Optional, Tuple

from celestia_tpu.utils import tracing
from celestia_tpu.utils.telemetry import clock

MANIFEST_SCHEMA_VERSION = 1

DEFAULT_MAX_INCIDENTS = 8
DEFAULT_MAX_TOTAL_BYTES = 64 * 1024 * 1024
DEFAULT_MIN_INTERVAL_S = 10.0

ENV_SLOW_BLOCK_MS = "CELESTIA_TPU_FLIGHT_SLOW_BLOCK_MS"

# every bundle carries exactly these artifacts (manifest.json is the
# index, not a member); validate_manifest pins the set
BUNDLE_FILES = (
    "trace.json",
    "timeseries.json",
    "metrics.prom",
    "stacks.folded",
    "faults.json",
    "alerts.json",
)

_ID_RE = re.compile(r"^inc-(\d{6})(?:-[a-z0-9_.-]*)?$")


def _slug(reason: str) -> str:
    out = re.sub(r"[^a-z0-9_.-]+", "-", reason.lower()).strip("-")
    return out[:48] or "incident"


def validate_manifest(doc: dict) -> List[str]:
    """Schema check of a manifest.json document (the incident-smoke
    gate): a list of problems, empty when well-formed."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["manifest is not an object"]
    if doc.get("schema_version") != MANIFEST_SCHEMA_VERSION:
        problems.append(
            f"schema_version is {doc.get('schema_version')!r} "
            f"(expected {MANIFEST_SCHEMA_VERSION})"
        )
    for field, typ in (
        ("id", str), ("reason", str), ("node_id", str), ("ts", float),
        ("height", int), ("seq", int), ("rules", list), ("files", list),
    ):
        if not isinstance(doc.get(field), typ):
            problems.append(
                f"{field!r} missing or not {typ.__name__}"
            )
    files = doc.get("files")
    if isinstance(files, list):
        names = set()
        for i, f in enumerate(files):
            if not isinstance(f, dict):
                problems.append(f"files[{i}] is not an object")
                continue
            for field in ("name", "bytes", "sha256"):
                if field not in f:
                    problems.append(f"files[{i}] lacks {field!r}")
            names.add(f.get("name"))
        for want in BUNDLE_FILES:
            if want not in names:
                problems.append(f"bundle file {want!r} not in manifest")
    return problems


class FlightRecorder:
    """The incident ring: trigger detection + bundle dump + eviction."""

    def __init__(
        self,
        root_dir: str,
        max_incidents: int = DEFAULT_MAX_INCIDENTS,
        max_total_bytes: int = DEFAULT_MAX_TOTAL_BYTES,
        min_interval_s: float = DEFAULT_MIN_INTERVAL_S,
        slow_block_ms: Optional[float] = None,
    ):
        self.root = os.path.abspath(root_dir)
        os.makedirs(self.root, exist_ok=True)
        self.max_incidents = max(1, int(max_incidents))
        self.max_total_bytes = max(1, int(max_total_bytes))
        self.min_interval_s = max(0.0, float(min_interval_s))
        if slow_block_ms is None:
            raw = os.environ.get(ENV_SLOW_BLOCK_MS, "").strip()
            if raw:
                try:
                    slow_block_ms = float(raw)
                except ValueError:
                    slow_block_ms = None
        self.slow_block_ms = slow_block_ms
        self._lock = threading.Lock()
        # rules observed firing at the previous on_alerts tick (firing
        # TRANSITIONS trigger, steady state does not);
        # celint: guarded-by(self._lock)
        self._prev_firing: set = set()
        # last trigger timestamp (rate limit) + lifetime trigger count;
        # celint: guarded-by(self._lock)
        self._last_trigger_ts: Optional[float] = None
        self._triggered_total = 0
        # heights whose slow-block verdict was already judged;
        # celint: guarded-by(self._lock)
        self._last_slow_height = 0
        # next incident sequence number: resumes past existing dirs so a
        # restarted node never reuses an id; celint: guarded-by(self._lock)
        self._seq = self._max_existing_seq() + 1

    # -- trigger detection --------------------------------------------

    def on_alerts(
        self,
        verdicts: List[dict],
        *,
        height: int = 0,
        metrics_text: str = "",
        timeseries_snapshots: Optional[List[dict]] = None,
    ) -> Optional[str]:
        """Feed one alert-engine evaluation (the sampler tick).  A rule
        transitioning into ``firing`` triggers a bundle; returns the new
        incident id, or None."""
        firing = {v["name"] for v in verdicts if v.get("firing")}
        with self._lock:
            new = firing - self._prev_firing
            # cleared rules re-arm immediately; NEW rules are only
            # marked handled below once their bundle actually dumped —
            # a rate-limit suppression or a failed dump must retry on
            # the next tick, not silently spend the transition
            self._prev_firing &= firing
        if not new:
            return None
        inc = self.trigger(
            "alert:" + "+".join(sorted(new)),
            rules=sorted(new),
            verdicts=verdicts,
            height=height,
            metrics_text=metrics_text,
            timeseries_snapshots=timeseries_snapshots,
        )
        if inc is not None:
            with self._lock:
                self._prev_firing |= new
        return inc

    def on_block(
        self,
        height: int,
        total_ms: float,
        *,
        metrics_text: str = "",
        timeseries_snapshots: Optional[List[dict]] = None,
    ) -> Optional[str]:
        """Feed one completed block's wall time; a block over the
        slow-block threshold triggers (once per height)."""
        if self.slow_block_ms is None or total_ms <= self.slow_block_ms:
            return None
        with self._lock:
            if height <= self._last_slow_height:
                return None
        inc = self._trigger_slow_block(
            height, total_ms,
            metrics_text=metrics_text,
            timeseries_snapshots=timeseries_snapshots,
        )
        if inc is not None:
            with self._lock:
                # judged-once only after a SUCCESSFUL dump: a
                # rate-limited tick retries the same height next time
                self._last_slow_height = max(self._last_slow_height, height)
        return inc

    def _trigger_slow_block(
        self, height, total_ms, *, metrics_text, timeseries_snapshots
    ) -> Optional[str]:
        return self.trigger(
            "slow_block",
            rules=["slow_block"],
            verdicts=[
                {
                    "name": "slow_block",
                    "firing": True,
                    "value": round(total_ms, 3),
                    "threshold": self.slow_block_ms,
                }
            ],
            height=height,
            metrics_text=metrics_text,
            timeseries_snapshots=timeseries_snapshots,
        )

    # -- bundle dump ---------------------------------------------------

    def trigger(
        self,
        reason: str,
        *,
        rules: Optional[List[str]] = None,
        verdicts: Optional[List[dict]] = None,
        height: int = 0,
        metrics_text: str = "",
        timeseries_snapshots: Optional[List[dict]] = None,
    ) -> Optional[str]:
        """Dump one incident bundle NOW (rate-limited).  Returns the
        incident id, or None when suppressed by the rate limit.  A dump
        failure is reported through faults.note — the recorder must
        never take the node down with it."""
        from celestia_tpu.utils import faults

        now = clock()
        with self._lock:
            if (
                self._last_trigger_ts is not None
                and now - self._last_trigger_ts < self.min_interval_s
            ):
                return None
            prev_ts = self._last_trigger_ts
            self._last_trigger_ts = now
            seq = self._seq
            self._seq += 1
            self._triggered_total += 1
        incident_id = f"inc-{seq:06d}-{_slug(reason)}"
        try:
            artifacts = self._collect(
                reason, verdicts or [], metrics_text,
                timeseries_snapshots or [],
            )
            self._write_bundle(
                incident_id, seq, reason, rules or [], height, now,
                artifacts,
            )
            self._evict()
        except Exception as e:
            faults.note("flight.dump", e)
            with self._lock:
                # a FAILED dump must not burn the rate-limit window or
                # inflate the incident counter (the seq stays consumed:
                # a half-written tmp dir may exist under the old id)
                self._last_trigger_ts = prev_ts
                self._triggered_total -= 1
            return None
        if tracing.enabled():
            tracing.instant(
                "flight.incident", cat="fault", id=incident_id,
                reason=reason[:120],
            )
        return incident_id

    def _collect(
        self, reason, verdicts, metrics_text, snapshots
    ) -> Dict[str, bytes]:
        """Build every bundle artifact in memory (no recorder lock held:
        the collectors take their own module locks).  ``metrics_text``
        and ``snapshots`` may be CALLABLES — resolved only here, so the
        no-trigger tick never pays for an exposition build."""
        from celestia_tpu.utils import faults, hostprof

        if callable(metrics_text):
            metrics_text = metrics_text()
        if callable(snapshots):
            snapshots = snapshots()
        trace_doc = hostprof.merged_trace_dump()
        return {
            "trace.json": json.dumps(trace_doc).encode(),
            "timeseries.json": json.dumps(
                {"snapshots": snapshots}
            ).encode(),
            "metrics.prom": (metrics_text or "").encode(),
            "stacks.folded": hostprof.folded_text().encode(),
            "faults.json": json.dumps(
                faults.fault_stats(), default=str
            ).encode(),
            "alerts.json": json.dumps(
                {"reason": reason, "verdicts": verdicts}
            ).encode(),
        }

    def _write_bundle(
        self, incident_id, seq, reason, rules, height, ts, artifacts
    ) -> None:
        """Write tmp dir -> fsync-free rename: a torn dump (crash mid
        write) never shows up as a listable incident."""
        final = os.path.join(self.root, incident_id)
        tmp = final + ".tmp"
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        files = []
        for name in BUNDLE_FILES:
            data = artifacts.get(name, b"")
            with open(os.path.join(tmp, name), "wb") as f:
                f.write(data)
            files.append(
                {
                    "name": name,
                    "bytes": len(data),
                    "sha256": hashlib.sha256(data).hexdigest(),
                }
            )
        manifest = {
            "schema_version": MANIFEST_SCHEMA_VERSION,
            "id": incident_id,
            "seq": int(seq),
            "reason": str(reason)[:200],
            "rules": [str(r) for r in rules],
            "node_id": tracing.node_id(),
            "height": int(height),
            "ts": float(round(ts, 6)),
            "files": files,
            "total_bytes": sum(f["bytes"] for f in files),
        }
        with open(os.path.join(tmp, "manifest.json"), "w") as f:
            json.dump(manifest, f, indent=1, sort_keys=True)
        if os.path.exists(final):  # id collision cannot happen (seq is
            shutil.rmtree(final)   # monotone) except after a crash loop
        os.replace(tmp, final)

    def _evict(self) -> None:
        """Enforce the ring bounds: oldest incidents out first until both
        the count cap and the byte cap hold.  The NEWEST bundle is never
        evicted — a byte cap smaller than one bundle must not erase the
        very evidence the recorder exists to keep."""
        with self._lock:
            entries = self._scan()
            total = sum(size for _, _, size in entries)
            while len(entries) > 1 and (  # celint: allow(no-handrolled-cache) — an on-disk incident-dir ring, not an in-memory cache; LruCache cannot own directories
                len(entries) > self.max_incidents
                or total > self.max_total_bytes
            ):
                _seq, path, size = entries.pop(0)
                shutil.rmtree(path, ignore_errors=True)
                total -= size

    # -- listing / retrieval ------------------------------------------

    def _max_existing_seq(self) -> int:
        best = 0
        try:
            names = os.listdir(self.root)
        except OSError:
            return 0
        for name in names:
            if name.endswith(".tmp"):
                continue
            m = _ID_RE.match(name)
            if m:
                best = max(best, int(m.group(1)))
        return best

    def _scan(self) -> List[Tuple[int, str, int]]:
        """(seq, path, bytes) of every complete incident dir, oldest
        first.  *.tmp dirs (torn dumps) are ignored."""
        out: List[Tuple[int, str, int]] = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if name.endswith(".tmp"):
                continue  # torn dump mid-write: never listable
            m = _ID_RE.match(name)
            if not m:
                continue
            path = os.path.join(self.root, name)
            if not os.path.isdir(path):
                continue
            size = 0
            for fn in os.listdir(path):
                try:
                    size += os.path.getsize(os.path.join(path, fn))
                except OSError:
                    continue
            out.append((int(m.group(1)), path, size))
        out.sort()
        return out

    def list_incidents(self) -> List[dict]:
        """Manifest summaries of every kept incident, oldest first.  A
        dir whose manifest is unreadable is reported with its error, not
        silently dropped."""
        out: List[dict] = []
        for _seq, path, size in self._scan():
            mpath = os.path.join(path, "manifest.json")
            try:
                with open(mpath) as f:
                    doc = json.load(f)
                out.append(
                    {
                        "id": doc.get("id", os.path.basename(path)),
                        "seq": doc.get("seq", _seq),
                        "reason": doc.get("reason", ""),
                        "rules": doc.get("rules", []),
                        "height": doc.get("height", 0),
                        "ts": doc.get("ts", 0.0),
                        "node_id": doc.get("node_id", ""),
                        "total_bytes": size,
                    }
                )
            except (OSError, ValueError) as e:
                out.append(
                    {
                        "id": os.path.basename(path),
                        "seq": _seq,
                        "error": str(e)[:200],
                        "total_bytes": size,
                    }
                )
        return out

    def load_bundle(self, incident_id: str) -> Optional[dict]:
        """One full bundle: ``{"manifest": dict, "files": {name: text}}``
        or None when the id is unknown.  Files are returned as TEXT (the
        bundle members are all JSON/text by construction)."""
        if not _ID_RE.match(incident_id or "") or incident_id.endswith(".tmp"):
            return None
        path = os.path.join(self.root, incident_id)
        mpath = os.path.join(path, "manifest.json")
        if not os.path.isfile(mpath):
            return None
        with open(mpath) as f:
            manifest = json.load(f)
        files: Dict[str, str] = {}
        for entry in manifest.get("files", []):
            name = entry.get("name", "")
            if name not in BUNDLE_FILES:
                continue
            try:
                with open(os.path.join(path, name), "rb") as f:
                    files[name] = f.read().decode("utf-8", "replace")
            except OSError as e:
                files[name] = f"<unreadable: {e}>"
        return {"manifest": manifest, "files": files}

    def stats(self) -> dict:
        entries = self._scan()
        with self._lock:
            return {
                "dir": self.root,
                "incidents_kept": len(entries),
                "incidents_total": self._triggered_total,
                "next_seq": self._seq,
                "total_bytes": sum(s for _, _, s in entries),
                "max_incidents": self.max_incidents,
                "max_total_bytes": self.max_total_bytes,
                "min_interval_s": self.min_interval_s,
                "slow_block_ms": self.slow_block_ms,
            }
