"""Deterministic fault injection + the unified retry/degradation policy layer.

PRs 4-6 made the DA hot path aggressively concurrent (hostpool, the
3-phase native pipeline, six shared LRU caches) and each round hand-fixed
the failure modes the previous one shipped — but nothing could *provoke*
a native crash, a dead pool worker, a truncated snapshot chunk, or a
flaky peer on demand, so every recovery path was an untested guess.
This module makes degradation first-class, tested code:

* **Fault-injection registry.**  Named fault points (:data:`FAULT_POINTS`)
  armed via the ``CELESTIA_TPU_FAULTS`` environment variable or the
  ``chaos`` test fixture, with a SEEDED schedule — fail-once, fail-rate,
  latency, corrupt-bytes.  Same seed => same decision sequence, across
  processes (seeds are domain-separated through sha256, never Python's
  randomized ``hash()``).  When nothing is armed, :func:`fire` is one
  module-bool check — zero overhead on the hot path.
* **One retry policy.**  :class:`RetryPolicy` (decorrelated-jitter
  backoff from a seeded generator, hard deadline budgets) and
  :class:`CircuitBreaker`/:class:`BreakerRegistry` (per-peer failure
  gating) replace the ad-hoc sleep/backoff logic that had grown
  independently in node/gossip.py, node/coordinator.py, client/remote.py
  and client/signer.py.  celint rule R5 (``sanctioned-retry``) forbids
  hand-rolled ``time.sleep`` retry loops and silent exception swallows
  everywhere but here, so the consolidation cannot regress.
* **Degradation telemetry.**  :func:`note` records exceptions that
  background/pooled threads deliberately survive (named by fault point,
  never silently dropped — the audit-sweep contract), and
  :func:`fault_stats` exposes injected/recovered counts to bench.py's
  ``extras.fault_stats``.

Reproduction: every schedule derives from ``CELESTIA_TPU_CHAOS_SEED``
(or an explicit ``seed=``); ``CELESTIA_TPU_FAULTS`` takes
``point:mode[,key=value...][;point:mode...]``, e.g.
``gossip.fetch:fail_rate,rate=0.1,seed=7;snapshots.chunk:corrupt``.
See specs/robustness.md for the catalog and the degradation ladder.
"""

from __future__ import annotations

import hashlib
import itertools
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

# bounded length of each armed point's decision trace (see _ArmedFault)
_TRACE_CAP = 4096

# ---------------------------------------------------------------------------
# fault points
# ---------------------------------------------------------------------------

FAULT_POINTS = (
    "native.extend",    # native .so ExtendBlock pipeline entry
    "hostpool.worker",  # a pooled host worker dies mid-item
    "gossip.fetch",     # catch-up / status / decided-block pull RPCs
    "snapshots.chunk",  # state-sync chunk fetch (fail or corrupt bytes)
    "server.sample",    # DAS serving-plane handler
    "lru.put",          # a cache insert is dropped (lost write)
)

MODES = ("fail_once", "fail_rate", "latency", "corrupt")

_ENV = "CELESTIA_TPU_FAULTS"
_SEED_ENV = "CELESTIA_TPU_CHAOS_SEED"


class InjectedFault(RuntimeError):
    """An error raised by an armed fault point (never by real code)."""


class WorkerDeath(InjectedFault):
    """The hostpool.worker flavor: simulates a pool worker dying mid-item
    so utils/hostpool.py can prove it self-heals without losing tasks."""


class Overloaded(RuntimeError):
    """A serving plane shed this request; retry after ``retry_after_ms``.
    Raised client-side on a shed response so :meth:`RetryPolicy.run` can
    honor the server's pushback instead of hammering it."""

    def __init__(self, msg: str, retry_after_ms: float = 25.0):
        super().__init__(msg)
        self.retry_after_ms = float(retry_after_ms)


def chaos_seed() -> int:
    """The process-wide chaos seed (``CELESTIA_TPU_CHAOS_SEED``, default
    0) — every schedule and every seeded backoff derives from it unless
    given an explicit ``seed=``."""
    raw = os.environ.get(_SEED_ENV, "").strip()
    try:
        return int(raw) if raw else 0
    except ValueError:
        return 0


def derive_seed(*parts) -> int:
    """Deterministic 64-bit sub-seed from (seed, domain, ...) parts.

    sha256, NOT ``hash()``: Python string hashing is salted per process
    (PYTHONHASHSEED), and the whole point of a chaos seed is that the
    schedule reproduces across runs and machines."""
    h = hashlib.sha256("|".join(str(p) for p in parts).encode())
    return int.from_bytes(h.digest()[:8], "big")


class _ArmedFault:
    """One armed point's schedule state (mutated under ``_lock``)."""

    def __init__(
        self,
        point: str,
        mode: str,
        *,
        rate: float = 1.0,
        delay_ms: float = 0.0,
        count: Optional[int] = None,
        seed: Optional[int] = None,
    ):
        if point not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {point!r} (known: {', '.join(FAULT_POINTS)})"
            )
        if mode not in MODES:
            raise ValueError(
                f"unknown fault mode {mode!r} (known: {', '.join(MODES)})"
            )
        self.point = point
        self.mode = mode
        self.rate = float(rate)
        self.delay_ms = float(delay_ms)
        # fail_once defaults to exactly one injection; other modes are
        # unbounded unless count says otherwise
        self.count = (
            int(count)
            if count is not None
            else (1 if mode == "fail_once" else None)
        )
        self.seed = seed if seed is not None else chaos_seed()
        self._rng = random.Random(derive_seed(self.seed, point, mode))
        self.checks = 0
        self.injected = 0
        # per-check trace (determinism assertions); bounded so a point
        # left armed on a long-running chaos node cannot leak — the last
        # _TRACE_CAP decisions are plenty for any suite assertion
        self.decisions: "deque[bool]" = deque(maxlen=_TRACE_CAP)

    def decide_locked(self) -> bool:
        """One schedule decision; caller holds the registry lock."""
        self.checks += 1
        if self.count is not None and self.injected >= self.count:
            self.decisions.append(False)
            return False
        if self.mode == "fail_once":
            hit = True
        else:
            # one rng draw per check keeps the decision sequence a pure
            # function of (seed, point, mode, check index)
            hit = self._rng.random() < self.rate
        if hit:
            self.injected += 1
        self.decisions.append(hit)
        return hit

    def corrupt_locked(self, data: bytes) -> bytes:
        """Deterministically flip one byte of ``data`` (corrupt mode)."""
        if not data:
            return data
        idx = self._rng.randrange(len(data))
        flip = self._rng.randrange(1, 256)
        out = bytearray(data)
        out[idx] ^= flip
        return bytes(out)

    def spec(self) -> dict:
        return {
            "mode": self.mode,
            "rate": self.rate,
            "delay_ms": self.delay_ms,
            "count": self.count,
            "seed": self.seed,
            "checks": self.checks,
            "injected": self.injected,
        }


_lock = threading.Lock()
# point -> schedule; celint: guarded-by(_lock)
_armed: Dict[str, _ArmedFault] = {}
# fast-path gate: fire()/should_drop()/corrupt() return immediately when
# False, so a disarmed node pays one bool check per fault point
_active = False
# swallowed-exception telemetry: name -> [count, last repr];
# celint: guarded-by(_lock)
_notes: Dict[str, list] = {}
# degradations recorded by poison()/self-heal paths;
# celint: guarded-by(_lock)
_degradations: List[dict] = []


def arm(
    point: str,
    mode: str,
    *,
    rate: float = 1.0,
    delay_ms: float = 0.0,
    count: Optional[int] = None,
    seed: Optional[int] = None,
) -> None:
    """Arm one fault point with a seeded schedule (replaces any previous
    schedule for the point)."""
    global _active
    f = _ArmedFault(
        point, mode, rate=rate, delay_ms=delay_ms, count=count, seed=seed
    )
    with _lock:
        _armed[point] = f
        _active = True


def disarm(point: Optional[str] = None) -> None:
    """Disarm one point, or everything when ``point`` is None."""
    global _active
    with _lock:
        if point is None:
            _armed.clear()
        else:
            _armed.pop(point, None)
        _active = bool(_armed)


def armed_points() -> Dict[str, dict]:
    with _lock:
        return {p: f.spec() for p, f in _armed.items()}


def arm_from_spec(spec: str) -> None:
    """Arm from a ``CELESTIA_TPU_FAULTS``-style spec string:
    ``point:mode[,key=value...]`` entries separated by ``;``."""
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        head, _, tail = entry.partition(",")
        point, _, mode = head.partition(":")
        if not mode:
            raise ValueError(
                f"fault spec entry {entry!r} must be point:mode[,k=v...]"
            )
        kwargs: Dict[str, Any] = {}
        for kv in tail.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            if k == "rate":
                kwargs["rate"] = float(v)
            elif k == "delay_ms":
                kwargs["delay_ms"] = float(v)
            elif k == "count":
                kwargs["count"] = int(v)
            elif k == "seed":
                kwargs["seed"] = int(v)
            else:
                raise ValueError(f"unknown fault spec key {k!r} in {entry!r}")
        arm(point.strip(), mode.strip(), **kwargs)


def arm_from_env() -> None:
    """Arm from ``CELESTIA_TPU_FAULTS`` (no-op when unset).  Called once
    at import so a chaos-configured process needs no code changes; a
    malformed spec raises loudly — silently ignoring a typo'd chaos spec
    would fake a green chaos run."""
    spec = os.environ.get(_ENV, "").strip()
    if spec:
        arm_from_spec(spec)


def fire(point: str) -> None:
    """The injection hook: no-op when ``point`` is disarmed; raises
    :class:`InjectedFault` (``WorkerDeath`` for hostpool.worker) or
    sleeps per the armed schedule otherwise.  Call it at the top of the
    operation the point names."""
    if not _active:
        return
    with _lock:
        f = _armed.get(point)
        if f is None or f.mode == "corrupt":
            return  # corrupt mode only acts through corrupt()
        hit = f.decide_locked()
        mode = f.mode
        delay = f.delay_ms if (hit and mode == "latency") else 0.0
    if not hit:
        return
    if mode == "latency":
        time.sleep(delay / 1000.0)
        return
    if point == "hostpool.worker":
        raise WorkerDeath(f"injected worker death at {point}")
    raise InjectedFault(f"injected fault at {point}")


def should_drop(point: str) -> bool:
    """Non-raising schedule check for lost-write style faults (lru.put):
    True means the caller must silently drop the operation, exactly like
    a write that never landed."""
    if not _active:
        return False
    with _lock:
        f = _armed.get(point)
        if f is None:
            return False
        return f.decide_locked()


def corrupt(point: str, data: bytes) -> bytes:
    """Pass ``data`` through the point's corrupt schedule: identity when
    disarmed or when the schedule says no, one deterministic bit-flip
    otherwise."""
    if not _active:
        return data
    with _lock:
        f = _armed.get(point)
        if f is None or f.mode != "corrupt":
            return data
        if not f.decide_locked():
            return data
        return f.corrupt_locked(data)


# ---------------------------------------------------------------------------
# swallowed-exception / degradation telemetry
# ---------------------------------------------------------------------------


def note(point: str, exc: BaseException) -> None:
    """Record an exception a background/pooled thread deliberately
    survives.  The audit-sweep contract (celint R5): a worker may keep
    its loop alive, but the failure must land in telemetry under a named
    point — never vanish in ``except Exception: pass``."""
    r = repr(exc)[:200]
    with _lock:
        entry = _notes.get(point)
        if entry is None:
            _notes[point] = [1, r]
        else:
            entry[0] += 1
            entry[1] = r
    # the swallow also lands on the active trace as an instant event so
    # a trace reader sees WHERE in the block the failure was absorbed
    # (guarded: with tracing off this must stay one enabled() check,
    # and it runs outside the lock on purpose)
    from celestia_tpu.utils import tracing

    if tracing.enabled():
        tracing.instant("fault.note", cat="fault", point=point, error=r[:120])


def record_degradation(subsystem: str, reason: str) -> None:
    """Log a one-way degradation event (native poison, pool respawn) so
    operators see WHEN the node stepped down a rung, not just that it is
    slow now."""
    with _lock:
        _degradations.append({"subsystem": subsystem, "reason": reason[:300]})
    from celestia_tpu.utils import tracing

    if tracing.enabled():
        tracing.instant(
            "degradation", cat="fault", subsystem=subsystem,
            reason=reason[:160],
        )


def fault_stats() -> dict:
    """Aggregate injection/recovery view for bench.py and the chaos
    suite: per-point schedules + counters, swallow notes, degradations."""
    with _lock:
        return {
            "armed": {p: f.spec() for p, f in _armed.items()},
            "notes": {k: {"count": v[0], "last": v[1]} for k, v in _notes.items()},
            "degradations": list(_degradations),
        }


def decision_trace(point: str) -> List[bool]:
    """The armed point's per-check decision sequence so far (chaos suite
    determinism assertions: same seed => same trace)."""
    with _lock:
        f = _armed.get(point)
        return list(f.decisions) if f is not None else []


def reset_stats() -> None:
    with _lock:
        _notes.clear()
        _degradations.clear()


# ---------------------------------------------------------------------------
# RetryPolicy: the ONE retry/backoff implementation
# ---------------------------------------------------------------------------

# default-seed derivation for policies constructed without seed=: each
# instance must get a DISTINCT backoff sequence (N clients shed by one
# saturated server must not sleep identically and return as one stampede)
_policy_counter = itertools.count()
_proc_nonce: Optional[int] = None
_proc_nonce_lock = threading.Lock()


def _default_policy_seed() -> int:
    """Per-instance default seed.  Under an explicit chaos seed
    (CELESTIA_TPU_CHAOS_SEED) the sequence of constructed policies is
    fully reproducible (seed x construction index); without one —
    production — a per-process entropy nonce is mixed in so independent
    clients jitter independently instead of in lockstep."""
    global _proc_nonce
    n = next(_policy_counter)
    if os.environ.get(_SEED_ENV, "").strip():
        return derive_seed(chaos_seed(), "retry", n)
    with _proc_nonce_lock:
        if _proc_nonce is None:
            _proc_nonce = int.from_bytes(os.urandom(8), "big")
        return derive_seed(_proc_nonce, "retry", n)


class RetryPolicy:
    """Bounded retry with decorrelated-jitter backoff and a deadline
    budget, from a SEEDED generator.

    * backoff: ``sleep_n = min(cap_s, uniform(base_s, sleep_{n-1} * 3))``
      — decorrelated jitter spreads retry storms without synchronizing
      clients the way exponential-with-full-jitter resets do.
    * seeding: an explicit ``seed=`` (or a set CELESTIA_TPU_CHAOS_SEED)
      makes the sequence reproducible; otherwise each instance mixes a
      per-process entropy nonce so independent clients never jitter in
      lockstep (see :func:`_default_policy_seed`).
    * ``deadline_s`` is a hard budget over the whole run/poll, including
      sleeps: a retry that cannot finish before the deadline is not
      attempted.
    * ``sleep``/``clock`` are injectable for tests (virtual time).
    """

    def __init__(
        self,
        *,
        attempts: int = 4,
        base_s: float = 0.05,
        cap_s: float = 2.0,
        deadline_s: Optional[float] = None,
        seed: Optional[int] = None,
        sleep: Optional[Callable[[float], None]] = None,
        clock: Optional[Callable[[], float]] = None,
    ):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        self.attempts = int(attempts)
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.deadline_s = deadline_s
        self._rng = random.Random(
            derive_seed(seed, "retry")
            if seed is not None
            else _default_policy_seed()
        )
        self._sleep = sleep if sleep is not None else time.sleep
        self._clock = clock if clock is not None else time.monotonic

    def backoffs(self) -> Iterator[float]:
        """The (deterministic, seeded) backoff sequence."""
        prev = self.base_s
        while True:
            prev = min(self.cap_s, self._rng.uniform(self.base_s, prev * 3))
            yield prev

    def run(
        self,
        fn: Callable[[], Any],
        *,
        retry_on: Tuple[type, ...] = (Exception,),
        no_retry_on: Tuple[type, ...] = (),
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
    ) -> Any:
        """Call ``fn`` up to ``attempts`` times within the deadline.

        Retries only on ``retry_on`` (``no_retry_on`` carves exceptions
        back out — e.g. a resource-bound violation subclassing a
        retriable base is hostile, not transient); an :class:`Overloaded`
        failure's ``retry_after_ms`` floors the next sleep (server
        pushback wins over local jitter).  The last failure re-raises
        unchanged."""
        start = self._clock()
        backoff = self.backoffs()
        for attempt in range(1, self.attempts + 1):
            try:
                return fn()
            except no_retry_on:
                raise
            except retry_on as e:
                delay = next(backoff)
                floor = getattr(e, "retry_after_ms", None)
                if floor is not None:
                    delay = max(delay, float(floor) / 1000.0)
                out_of_time = self.deadline_s is not None and (
                    self._clock() - start + delay >= self.deadline_s
                )
                if attempt >= self.attempts or out_of_time:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                self._sleep(delay)
        raise AssertionError("unreachable")  # pragma: no cover

    def poll(
        self,
        predicate: Callable[[], Any],
        *,
        what: str = "condition",
    ) -> Any:
        """Sleep-poll ``predicate`` until it returns a truthy value and
        return that value; :class:`TimeoutError` at the deadline (which
        is REQUIRED here — an unbounded poll is exactly the hand-rolled
        loop this class exists to retire).  Attempts are not counted:
        polling is bounded by time, not tries."""
        if self.deadline_s is None:
            raise ValueError("poll() requires deadline_s")
        start = self._clock()
        while True:
            value = predicate()
            if value:
                return value
            elapsed = self._clock() - start
            if elapsed >= self.deadline_s:
                raise TimeoutError(
                    f"{what} not reached within {self.deadline_s:.1f}s"
                )
            # jittered base-interval sleeps, clipped to the budget
            delay = min(
                self._rng.uniform(self.base_s, self.base_s * 2),
                self.cap_s,
                max(0.0, self.deadline_s - elapsed),
            )
            self._sleep(delay)


# ---------------------------------------------------------------------------
# circuit breakers (per-peer failure gating)
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """closed -> open after N consecutive failures -> half-open probe
    after the cooldown.  One success closes; a failed probe re-opens."""

    def __init__(
        self,
        *,
        failures_to_open: int = 3,
        cooldown_s: float = 10.0,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.failures_to_open = int(failures_to_open)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._failures = 0  # celint: guarded-by(self._lock)
        self._open_until = 0.0  # celint: guarded-by(self._lock)
        self._probing = False  # celint: guarded-by(self._lock)

    def allow(self) -> bool:
        """True when a call may proceed (closed, or the one half-open
        probe after cooldown)."""
        with self._lock:
            if self._failures < self.failures_to_open:
                return True
            if self._clock() < self._open_until:
                return False
            if self._probing:
                return False  # one probe at a time
            self._probing = True
            return True

    def record_ok(self) -> None:
        with self._lock:
            self._failures = 0
            self._open_until = 0.0
            self._probing = False

    def record_failure(self, cooldown_s: Optional[float] = None) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._failures >= self.failures_to_open:
                self._open_until = self._clock() + (
                    self.cooldown_s if cooldown_s is None else float(cooldown_s)
                )

    def trip(self, cooldown_s: Optional[float] = None) -> None:
        """Open immediately (resource-bound violations: no honest peer
        trips these, so don't wait for the failure budget)."""
        with self._lock:
            self._failures = max(self._failures + 1, self.failures_to_open)
            self._probing = False
            self._open_until = self._clock() + (
                self.cooldown_s if cooldown_s is None else float(cooldown_s)
            )

    @property
    def state(self) -> str:
        with self._lock:
            if self._failures < self.failures_to_open:
                return "closed"
            return "open" if self._clock() < self._open_until else "half-open"

    def cooldown_remaining(self) -> float:
        with self._lock:
            return max(0.0, self._open_until - self._clock())


class BreakerRegistry:
    """Keyed circuit breakers (one per peer address) behind one lock —
    the per-peer gating layer node/gossip.py's catch-up/state-sync pulls
    route through instead of hand-rolled cooldown dicts."""

    def __init__(self, **breaker_kwargs):
        self._kwargs = breaker_kwargs
        self._lock = threading.Lock()
        # key -> breaker; celint: guarded-by(self._lock)
        self._breakers: Dict[Any, CircuitBreaker] = {}

    def _get(self, key) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(key)
            if b is None:
                b = CircuitBreaker(**self._kwargs)
                self._breakers[key] = b
            return b

    def allow(self, key) -> bool:
        return self._get(key).allow()

    def available(self, key) -> bool:
        """Side-effect-free view: True unless the breaker is open.  Use
        for building candidate lists; ``allow`` (which claims the single
        half-open probe) gates the actual call."""
        return self._get(key).state != "open"

    def record_ok(self, key) -> None:
        self._get(key).record_ok()

    def record_failure(self, key, cooldown_s: Optional[float] = None) -> None:
        self._get(key).record_failure(cooldown_s)

    def trip(self, key, cooldown_s: Optional[float] = None) -> None:
        self._get(key).trip(cooldown_s)

    def cooldown_remaining(self, key) -> float:
        return self._get(key).cooldown_remaining()

    def drop(self, key) -> None:
        with self._lock:
            self._breakers.pop(key, None)

    def stats(self) -> dict:
        with self._lock:
            items = list(self._breakers.items())
        return {str(k): b.state for k, b in items}


# ---------------------------------------------------------------------------
# load shedding (bounded-concurrency admission for serving planes)
# ---------------------------------------------------------------------------


class LoadShedGate:
    """Admit up to ``max_inflight`` concurrent units of work; shed the
    rest with a retry-after hint instead of queueing unboundedly.
    Shedding keeps the served requests fast (bounded queue => bounded
    latency) and gives honest clients an explicit, retriable signal —
    the serving plane degrades, it does not collapse.

    Admission is WEIGHTED: a batch request passes its work size (the
    DAS batch plane weighs a chunk by the distinct rows it proves), so
    batching cannot launder n requests' load past a gate sized for
    single-cell traffic.  An oversize weight (> ``max_inflight``) is
    admitted only when the gate is fully idle — bounded overshoot beats
    a request class that can never be served.

    QoS LANES (opt-in): pass ``lanes`` as a sequence of
    ``(name, reserved)`` pairs (or a mapping name -> reserved) to split
    ``max_inflight`` into per-lane reserved capacity plus one shared
    pool (``max_inflight - sum(reserved)``).  A lane's inflight up to
    its reservation never touches the shared pool, so a flood on a
    zero-reserved lane (``bulk``/``hostile``) can saturate only the
    shared pool and can never starve a reserved lane's admissions.
    Per-lane admitted/shed/inflight are tracked alongside the global
    counters.  With ``lanes=None`` (the default) the gate runs the
    original single-lane code path unchanged — the weighted single-gate
    behavior IS the degenerate one-lane case."""

    def __init__(
        self,
        max_inflight: int = 8,
        retry_after_ms: float = 25.0,
        lanes: Optional[Any] = None,
    ):
        self.max_inflight = max(1, int(max_inflight))
        self.retry_after_ms = float(retry_after_ms)
        self._lock = threading.Lock()
        self._inflight = 0  # celint: guarded-by(self._lock)
        self.admitted = 0  # celint: guarded-by(self._lock)
        self.shed = 0  # celint: guarded-by(self._lock)
        self._lanes: Optional[Dict[str, Dict[str, int]]] = None
        self._shared_capacity = 0
        self._shared_used = 0  # celint: guarded-by(self._lock)
        self._default_lane: Optional[str] = None
        if lanes is not None:
            pairs = list(lanes.items()) if hasattr(lanes, "items") else list(lanes)
            if not pairs:
                raise ValueError("lanes must name at least one lane")
            table: Dict[str, Dict[str, int]] = {}
            for name, reserved in pairs:
                name = str(name)
                if name in table:
                    raise ValueError(f"duplicate lane {name!r}")
                # inflight/admitted/shed counters are mutated only
                # under self._lock (same discipline as the gate totals)
                table[name] = {
                    "reserved": max(0, int(reserved)),
                    "inflight": 0,
                    "admitted": 0,
                    "shed": 0,
                }
            total_reserved = sum(st["reserved"] for st in table.values())
            if total_reserved > self.max_inflight:
                raise ValueError(
                    f"reserved capacity {total_reserved} exceeds "
                    f"max_inflight {self.max_inflight}"
                )
            self._lanes = table
            self._shared_capacity = self.max_inflight - total_reserved
            self._default_lane = next(iter(table))

    def _lane_state(self, lane: Optional[str]) -> Dict[str, int]:
        # caller holds self._lock; unknown lane names fall back to the
        # first-declared lane so a stale client label cannot crash serving
        assert self._lanes is not None
        st = self._lanes.get(lane) if lane is not None else None
        if st is None:
            st = self._lanes[self._default_lane]
        return st

    def try_acquire(self, weight: int = 1, lane: Optional[str] = None) -> bool:
        weight = max(1, int(weight))
        with self._lock:
            if self._lanes is None:
                if self._inflight > 0 and (
                    self._inflight + weight > self.max_inflight
                ):
                    self.shed += 1
                    return False
                self._inflight += weight
                self.admitted += 1
                return True
            st = self._lane_state(lane)
            cur = st["inflight"]
            old_excess = max(0, cur - st["reserved"])
            new_excess = max(0, cur + weight - st["reserved"])
            over_shared = (
                self._shared_used - old_excess + new_excess
                > self._shared_capacity
            )
            # global-idle oversize admission is preserved lane-wise: a
            # weight larger than the whole gate is admitted only when
            # NOTHING is inflight anywhere (bounded overshoot, as above)
            if self._inflight > 0 and over_shared:
                self.shed += 1
                st["shed"] += 1
                return False
            st["inflight"] = cur + weight
            st["admitted"] += 1
            self._shared_used += new_excess - old_excess
            self._inflight += weight
            self.admitted += 1
            return True

    def release(self, weight: int = 1, lane: Optional[str] = None) -> None:
        weight = max(1, int(weight))
        with self._lock:
            if self._lanes is None:
                self._inflight = max(0, self._inflight - weight)
                return
            st = self._lane_state(lane)
            cur = st["inflight"]
            take = min(cur, weight)
            old_excess = max(0, cur - st["reserved"])
            new_excess = max(0, cur - take - st["reserved"])
            st["inflight"] = cur - take
            self._shared_used = max(
                0, self._shared_used - (old_excess - new_excess)
            )
            self._inflight = max(0, self._inflight - take)

    def stats(self) -> dict:
        with self._lock:
            out = {
                "max_inflight": self.max_inflight,
                "inflight": self._inflight,
                "admitted": self.admitted,
                "shed": self.shed,
            }
            if self._lanes is not None:
                out["shared_capacity"] = self._shared_capacity
                out["shared_inflight"] = self._shared_used
                out["lanes"] = {
                    name: dict(st) for name, st in self._lanes.items()
                }
            return out


# ---------------------------------------------------------------------------
# QoS tier assignment (deterministic peer -> lane policy)
# ---------------------------------------------------------------------------


class TierPolicy:
    """Deterministic peer -> QoS lane assignment for a laned
    :class:`LoadShedGate`.

    Default policy is RECENT-USAGE DEMOTION: each peer's asked rows are
    counted in a two-bucket sliding window (current + previous epoch of
    ``window_s`` seconds, rotated on an injectable clock, so the signal
    is deterministic under a virtual clock and needs no timers).  A peer
    whose recent asked-rows reach ``demote_rows`` slides from ``light``
    to ``bulk``; reaching ``hostile_rows`` auto-pins it to ``hostile``
    for ``pin_cooldown_s`` (and :meth:`pin` applies the same
    :meth:`CircuitBreaker.trip`-style pinning manually).  Per-peer state
    lives on a bounded :class:`~celestia_tpu.utils.lru.LruCache`, so an
    open swarm cannot grow server memory without bound — an evicted
    peer simply restarts as ``light``.
    """

    LIGHT = "light"
    BULK = "bulk"
    HOSTILE = "hostile"
    LANES = (LIGHT, BULK, HOSTILE)

    def __init__(
        self,
        demote_rows: int = 64,
        hostile_rows: int = 256,
        window_s: float = 2.0,
        pin_cooldown_s: float = 30.0,
        max_peers: int = 1024,
        clock: Optional[Callable[[], float]] = None,
    ):
        from celestia_tpu.utils.lru import LruCache

        self.demote_rows = max(1, int(demote_rows))
        self.hostile_rows = max(self.demote_rows, int(hostile_rows))
        self.window_s = max(1e-6, float(window_s))
        self.pin_cooldown_s = max(0.0, float(pin_cooldown_s))
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        # entries are mutable dicts mutated only under self._lock
        self._usage = LruCache("qos_peer_usage", max_entries=max(1, int(max_peers)))
        self.pins = 0  # celint: guarded-by(self._lock)

    def _entry(self, peer: str) -> Dict[str, float]:
        # caller holds self._lock
        st = self._usage.get(peer, count=False)
        if st is None:
            st = {"epoch": -1, "cur": 0.0, "prev": 0.0, "pin_until": 0.0}
            self._usage.put(peer, st)
        return st

    def _rotate(self, st: Dict[str, float], epoch: int) -> None:
        # caller holds self._lock
        if epoch == st["epoch"]:
            return
        if epoch == st["epoch"] + 1:
            st["prev"] = st["cur"]
        else:
            st["prev"] = 0.0
        st["cur"] = 0.0
        st["epoch"] = epoch

    def note(self, peer: str, rows: int = 1) -> None:
        """Record ``rows`` of asked work for ``peer`` (asked, not served
        — demotion must see the load a shed over-asker keeps offering)."""
        if not peer:
            return
        with self._lock:
            now = self._clock()
            st = self._entry(peer)
            self._rotate(st, int(now / self.window_s))
            st["cur"] += max(0, int(rows))
            if (
                st["cur"] + st["prev"] >= self.hostile_rows
                and now >= st["pin_until"]
            ):
                st["pin_until"] = now + self.pin_cooldown_s
                self.pins += 1

    def pin(self, peer: str, cooldown_s: Optional[float] = None) -> None:
        """Pin ``peer`` to the hostile lane for ``cooldown_s`` (default
        ``pin_cooldown_s``) — the trip()-style manual override."""
        if not peer:
            return
        with self._lock:
            st = self._entry(peer)
            hold = self.pin_cooldown_s if cooldown_s is None else float(cooldown_s)
            st["pin_until"] = self._clock() + max(0.0, hold)
            self.pins += 1

    def lane_for(self, peer: str) -> str:
        """Deterministic lane for ``peer`` right now.  Unknown / empty
        peers are ``light`` — anonymity costs nothing until usage does."""
        if not peer:
            return self.LIGHT
        with self._lock:
            st = self._usage.get(peer, count=False)
            if st is None:
                return self.LIGHT
            now = self._clock()
            if now < st["pin_until"]:
                return self.HOSTILE
            self._rotate(st, int(now / self.window_s))
            recent = st["cur"] + st["prev"]
            if recent >= self.hostile_rows:
                return self.HOSTILE
            if recent >= self.demote_rows:
                return self.BULK
            return self.LIGHT

    def stats(self) -> dict:
        with self._lock:
            return {
                "peers": self._usage.stats()["entries"],
                "pins": self.pins,
                "demote_rows": self.demote_rows,
                "hostile_rows": self.hostile_rows,
                "window_s": self.window_s,
            }


# arm from the environment at import: a chaos-configured process needs no
# code changes, and a bad spec fails the process loudly at startup
arm_from_env()
