"""Telemetry: counters + BOUNDED latency histograms around the hot path.

Parity role: cosmos-sdk telemetry as used by the reference
(telemetry.MeasureSince in Prepare/Process at app/prepare_proposal.go:24 and
app/process_proposal.go:25, invalid-tx counters validate_txs.go:58,88,
panic counter process_proposal.go:31, mint gauges x/mint/abci.go:15,72).

Timings are fixed log2-bucket histograms (:class:`Log2Histogram`) — a
node that stays up for a million blocks holds the same few hundred bytes
per metric it held after ten, while still answering p50/p90/p95/p99/max.
The Prometheus surface exports them as proper ``histogram`` metrics
(cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``), with metric and
label names escaped so a cache named ``row_memo.v2-beta`` cannot emit a
malformed exposition line.

The per-span trace aggregation (utils/tracing.py) reuses
:class:`Log2Histogram` and lands in :meth:`Telemetry.summary` under
``"spans"`` whenever the tracer is enabled.
"""

from __future__ import annotations

import bisect
import re
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional


def clock() -> float:
    """Wall-clock read for DURATION measurement only.  celint rule R3
    (consensus-determinism) bans direct time.* reads in state/ and da/;
    this function (and Telemetry.clock) is the sanctioned channel — a
    value obtained here feeds telemetry/bench/tracing, never consensus
    bytes."""
    return time.time()


# ---------------------------------------------------------------------------
# bounded histograms
# ---------------------------------------------------------------------------

# log2 bucket upper bounds in SECONDS: 2^-20 (~1 µs) .. 2^6 (64 s).
# 27 finite buckets + one overflow bucket; anything a block pipeline or
# an RPC does lands inside this range with <2x relative quantile error.
BUCKET_BOUNDS: tuple = tuple(2.0**e for e in range(-20, 7))


class Log2Histogram:
    """Fixed-size latency histogram (seconds): 27 log2 buckets + overflow.

    Replaces the unbounded per-metric ``List[float]`` the Telemetry
    class accumulated before PR 8 — O(1) memory, O(log B) observe, and
    p50/p90/p95/p99 answered by linear interpolation inside the owning
    bucket (exact min/max/sum/count are tracked separately)."""

    __slots__ = ("counts", "count", "total", "vmax", "vmin", "_lock")

    def __init__(self):
        self.counts = [0] * (len(BUCKET_BOUNDS) + 1)
        self.count = 0
        self.total = 0.0
        self.vmax = 0.0
        self.vmin = float("inf")
        self._lock = threading.Lock()

    def observe(self, seconds: float) -> None:
        v = max(0.0, float(seconds))
        idx = bisect.bisect_left(BUCKET_BOUNDS, v)
        with self._lock:
            self.counts[idx] += 1
            self.count += 1
            self.total += v
            if v > self.vmax:
                self.vmax = v
            if v < self.vmin:
                self.vmin = v

    def quantile(self, q: float) -> float:
        """Approximate q-quantile in seconds (linear interpolation within
        the owning log2 bucket, clamped to the observed min/max)."""
        with self._lock:
            if self.count == 0:
                return 0.0
            target = q * self.count
            cum = 0
            for i, c in enumerate(self.counts):
                if c == 0:
                    continue
                if cum + c >= target:
                    lo = BUCKET_BOUNDS[i - 1] if i > 0 else 0.0
                    hi = (
                        BUCKET_BOUNDS[i]
                        if i < len(BUCKET_BOUNDS)
                        else max(self.vmax, lo)
                    )
                    frac = (target - cum) / c
                    est = lo + (hi - lo) * frac
                    return min(max(est, self.vmin), self.vmax)
                cum += c
            return self.vmax

    def summary(self) -> dict:
        with self._lock:
            count, vmax = self.count, self.vmax
        if count == 0:
            return {
                "count": 0, "p50_ms": 0.0, "p90_ms": 0.0, "p95_ms": 0.0,
                "p99_ms": 0.0, "max_ms": 0.0,
            }
        return {
            "count": count,
            "p50_ms": self.quantile(0.50) * 1000.0,
            "p90_ms": self.quantile(0.90) * 1000.0,
            "p95_ms": self.quantile(0.95) * 1000.0,
            "p99_ms": self.quantile(0.99) * 1000.0,
            "max_ms": vmax * 1000.0,
        }

    def prometheus_lines(self, metric: str) -> List[str]:
        """Proper histogram exposition: cumulative buckets + sum + count."""
        with self._lock:
            counts = list(self.counts)
            total, count = self.total, self.count
        lines = [f"# TYPE {metric} histogram"]
        cum = 0
        for bound, c in zip(BUCKET_BOUNDS, counts):
            cum += c
            lines.append(
                f'{metric}_bucket{{le="{format(bound, ".9g")}"}} {cum}'
            )
        lines.append(f'{metric}_bucket{{le="+Inf"}} {count}')
        lines.append(f"{metric}_sum {total:.9g}")
        lines.append(f"{metric}_count {count}")
        return lines


# ---------------------------------------------------------------------------
# exposition hygiene
# ---------------------------------------------------------------------------

_METRIC_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_METRIC_BAD_CHARS = re.compile(r"[^a-zA-Z0-9_:]")


# one validator for the whole tree (tests + make trace-smoke share it):
# every exposition line must be blank, a TYPE/HELP comment, or a sample
# `name{label="value",...} value`
_EXPO_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r" [+-]?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?$"
)
_EXPO_COMMENT_RE = re.compile(r"^# (TYPE|HELP) [a-zA-Z_:][a-zA-Z0-9_:]* .+$")


def validate_exposition(text: str) -> List[str]:
    """Parse every line of a Prometheus text exposition; returns the
    malformed lines (empty list = valid).  The format-validity gate for
    the Metrics RPC — escaped label values and sanitized metric names
    must survive any cache/metric naming."""
    bad: List[str] = []
    for ln in text.splitlines():
        if not ln:
            continue
        if not (_EXPO_SAMPLE_RE.match(ln) or _EXPO_COMMENT_RE.match(ln)):
            bad.append(ln)
    return bad


def snake_case(name: str) -> str:
    """CamelCase RPC method name -> metric-safe snake case (Broadcast ->
    broadcast, DasSample -> das_sample).  The ONE fold shared by the
    server-side ``rpc_{method}_*`` and client-side
    ``rpc_client_{method}_*`` counter families — per-method names must
    line up for the cluster-health rollup to join them."""
    out = []
    for i, c in enumerate(name):
        if c.isupper() and i and not name[i - 1].isupper():
            out.append("_")
        out.append(c.lower())
    return "".join(out)


def sanitize_metric_name(name: str) -> str:
    """Fold an internal metric name (dots, dashes, anything) into a
    valid Prometheus metric name; idempotent for already-valid names."""
    out = _METRIC_BAD_CHARS.sub("_", name)
    if not out or not _METRIC_OK.match(out):
        out = "_" + out
    return out


def escape_label_value(value: str) -> str:
    """Prometheus label-value escaping (backslash, quote, newline)."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def jain_fairness_index(values) -> Optional[float]:
    """Jain's fairness index over per-peer allocation counts:
    ``(sum x)^2 / (n * sum x^2)``.  1.0 = perfectly even, 1/n = one peer
    took everything.  Zero-allocation peers COUNT (a starved peer is the
    unfairness being measured); returns ``None`` when there is no signal
    at all (no peers, or nothing served yet) so callers can honor the
    skip-absent contract instead of reporting a fake 0."""
    xs = [max(0.0, float(v)) for v in values]
    if not xs:
        return None
    sq = sum(x * x for x in xs)
    if sq <= 0.0:
        return None
    total = sum(xs)
    return (total * total) / (len(xs) * sq)


class Telemetry:
    def __init__(self):
        # one lock over the metric MAPS (first-insert + snapshot): the
        # Metrics RPC made export/summary a concurrently-invoked remote
        # surface, and iterating a dict a producer thread is growing
        # raises mid-scrape.  Histogram counts have their own lock.
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        self.timings: Dict[str, Log2Histogram] = defaultdict(Log2Histogram)

    def incr(self, name: str, by: int = 1) -> None:
        with self._lock:
            self.counters[name] += by

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def _hist(self, name: str) -> Log2Histogram:
        # defaultdict __missing__ under the lock: two threads racing the
        # first observation of one name must share ONE histogram
        with self._lock:
            return self.timings[name]

    def measure_since(self, name: str, t0: float) -> None:
        # the sanctioned clock() channel, NOT a direct time.time() read:
        # both ends of every duration go through the same auditable door
        self._hist(name).observe(clock() - t0)

    def observe(self, name: str, value_ms: float) -> None:
        """Record an externally-measured duration (milliseconds)."""
        self._hist(name).observe(value_ms / 1000.0)

    def _snapshot(self):
        with self._lock:
            return dict(self.counters), dict(self.gauges), dict(self.timings)

    def clock(self) -> float:
        """Wall-clock read for DURATION measurement only.  state/ and da/
        code must take timestamps through here (or carry a celint allow):
        celint rule R3 (consensus-determinism) bans direct time.* reads
        there, and this indirection is the auditable sanctioned channel —
        a value obtained from clock() feeds telemetry, never consensus
        bytes."""
        return clock()

    def summary(self, include_caches: bool = False) -> dict:
        counters, gauges, timings = self._snapshot()
        out: dict = {"counters": counters, "gauges": gauges}
        if include_caches:
            out["caches"] = cache_stats()
        for name, hist in timings.items():
            out[name] = hist.summary()
        # per-span aggregation from the block-lifecycle tracer: one
        # summary document answers both "how long" (timings) and "which
        # phase" (spans).  Imported lazily — tracing builds on this
        # module's clock/histograms.
        from celestia_tpu.utils import tracing

        if tracing.enabled():
            spans = tracing.span_summary()
            if spans:
                out["spans"] = spans
        return out

    def export_prometheus(self) -> str:
        """Prometheus text exposition (the node-level metrics endpoint role
        — comet's DefaultMetricsProvider, test/util/testnode/full_node.go:44).
        Served over gRPC by node/server.py's ``Metrics`` RPC."""
        counters, gauges, timings = self._snapshot()
        lines: List[str] = []
        for name, val in sorted(counters.items()):
            metric = sanitize_metric_name(f"celestia_tpu_{name}_total")
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {val}")
        for name, val in sorted(gauges.items()):
            metric = sanitize_metric_name(f"celestia_tpu_{name}")
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {val}")
        for name, hist in sorted(timings.items()):
            metric = sanitize_metric_name(f"celestia_tpu_{name}_seconds")
            lines.extend(hist.prometheus_lines(metric))
        # per-span duration histograms from the tracer (same bounded
        # buckets), labeled by span name
        from celestia_tpu.utils import tracing

        if tracing.enabled():
            for name, hist in sorted(tracing.TRACER._agg_snapshot().items()):
                metric = sanitize_metric_name(
                    f"celestia_tpu_span_{name}_seconds"
                )
                lines.extend(hist.prometheus_lines(metric))
        # process-wide unified cache stats (utils/lru.py registry) — the
        # one-dashboard view of every bounded cache in the node
        cs = cache_stats()
        for name, agg in sorted(cs.get("caches", {}).items()):
            label = escape_label_value(name)
            for field in ("hits", "misses", "puts", "evictions"):
                metric = f"celestia_tpu_cache_{field}_total"
                lines.append(f'{metric}{{cache="{label}"}} {agg[field]}')
            for field in ("entries", "approx_bytes"):
                metric = f"celestia_tpu_cache_{field}"
                lines.append(f'{metric}{{cache="{label}"}} {agg[field]}')
        lines.append(
            f"celestia_tpu_cache_total_approx_bytes {cs['total_approx_bytes']}"
        )
        return "\n".join(lines) + "\n"


def cache_stats() -> dict:
    """Aggregated stats of every live bounded cache (utils/lru.py
    registry): per-cache hits/misses/evictions/entries/approx bytes plus
    the process-wide total against the CELESTIA_TPU_CACHE_BUDGET_MB
    advisory budget."""
    from celestia_tpu.utils import lru

    return lru.registry_stats()
