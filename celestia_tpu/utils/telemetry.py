"""Telemetry: counters + latency histograms around the hot path.

Parity role: cosmos-sdk telemetry as used by the reference
(telemetry.MeasureSince in Prepare/Process at app/prepare_proposal.go:24 and
app/process_proposal.go:25, invalid-tx counters validate_txs.go:58,88,
panic counter process_proposal.go:31, mint gauges x/mint/abci.go:15,72).
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List


def clock() -> float:
    """Wall-clock read for DURATION measurement only.  celint rule R3
    (consensus-determinism) bans direct time.* reads in state/ and da/;
    this function (and Telemetry.clock) is the sanctioned channel — a
    value obtained here feeds telemetry/bench, never consensus bytes."""
    return time.time()


class Telemetry:
    def __init__(self):
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        self.timings: Dict[str, List[float]] = defaultdict(list)

    def incr(self, name: str, by: int = 1) -> None:
        self.counters[name] += by

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def measure_since(self, name: str, t0: float) -> None:
        self.timings[name].append(time.time() - t0)

    def observe(self, name: str, value_ms: float) -> None:
        """Record an externally-measured duration (milliseconds)."""
        self.timings[name].append(value_ms / 1000.0)

    def clock(self) -> float:
        """Wall-clock read for DURATION measurement only.  state/ and da/
        code must take timestamps through here (or carry a celint allow):
        celint rule R3 (consensus-determinism) bans direct time.* reads
        there, and this indirection is the auditable sanctioned channel —
        a value obtained from clock() feeds telemetry, never consensus
        bytes."""
        return clock()

    def summary(self, include_caches: bool = False) -> dict:
        out: dict = {"counters": dict(self.counters), "gauges": dict(self.gauges)}
        if include_caches:
            out["caches"] = cache_stats()
        for name, vals in self.timings.items():
            s = sorted(vals)
            out[name] = {
                "count": len(s),
                "p50_ms": s[len(s) // 2] * 1000 if s else 0.0,
                "p95_ms": s[int(len(s) * 0.95)] * 1000 if s else 0.0,
                "max_ms": s[-1] * 1000 if s else 0.0,
            }
        return out

    def export_prometheus(self) -> str:
        """Prometheus text exposition (the node-level metrics endpoint role
        — comet's DefaultMetricsProvider, test/util/testnode/full_node.go:44)."""
        lines: List[str] = []
        for name, val in sorted(self.counters.items()):
            metric = f"celestia_tpu_{name}_total"
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {val}")
        for name, val in sorted(self.gauges.items()):
            metric = f"celestia_tpu_{name}"
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {val}")
        for name, vals in sorted(self.timings.items()):
            metric = f"celestia_tpu_{name}_seconds"
            s = sorted(vals)
            lines.append(f"# TYPE {metric} summary")
            for q in (0.5, 0.95, 0.99):
                idx = min(len(s) - 1, int(len(s) * q))
                lines.append(
                    f'{metric}{{quantile="{q}"}} {s[idx] if s else 0.0:.6f}'
                )
            lines.append(f"{metric}_count {len(s)}")
            lines.append(f"{metric}_sum {sum(s):.6f}")
        # process-wide unified cache stats (utils/lru.py registry) — the
        # one-dashboard view of every bounded cache in the node
        cs = cache_stats()
        for name, agg in sorted(cs.get("caches", {}).items()):
            for field in ("hits", "misses", "puts", "evictions"):
                metric = f"celestia_tpu_cache_{field}_total"
                lines.append(f'{metric}{{cache="{name}"}} {agg[field]}')
            for field in ("entries", "approx_bytes"):
                metric = f"celestia_tpu_cache_{field}"
                lines.append(f'{metric}{{cache="{name}"}} {agg[field]}')
        lines.append(
            f"celestia_tpu_cache_total_approx_bytes {cs['total_approx_bytes']}"
        )
        return "\n".join(lines) + "\n"


def cache_stats() -> dict:
    """Aggregated stats of every live bounded cache (utils/lru.py
    registry): per-cache hits/misses/evictions/entries/approx bytes plus
    the process-wide total against the CELESTIA_TPU_CACHE_BUDGET_MB
    advisory budget."""
    from celestia_tpu.utils import lru

    return lru.registry_stats()
