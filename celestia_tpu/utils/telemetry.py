"""Telemetry: counters + latency histograms around the hot path.

Parity role: cosmos-sdk telemetry as used by the reference
(telemetry.MeasureSince in Prepare/Process at app/prepare_proposal.go:24 and
app/process_proposal.go:25, invalid-tx counters validate_txs.go:58,88,
panic counter process_proposal.go:31, mint gauges x/mint/abci.go:15,72).
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Dict, List


class Telemetry:
    def __init__(self):
        self.counters: Dict[str, int] = defaultdict(int)
        self.gauges: Dict[str, float] = {}
        self.timings: Dict[str, List[float]] = defaultdict(list)

    def incr(self, name: str, by: int = 1) -> None:
        self.counters[name] += by

    def gauge(self, name: str, value: float) -> None:
        self.gauges[name] = value

    def measure_since(self, name: str, t0: float) -> None:
        self.timings[name].append(time.time() - t0)

    def observe(self, name: str, value_ms: float) -> None:
        """Record an externally-measured duration (milliseconds)."""
        self.timings[name].append(value_ms / 1000.0)

    def summary(self) -> dict:
        out: dict = {"counters": dict(self.counters), "gauges": dict(self.gauges)}
        for name, vals in self.timings.items():
            s = sorted(vals)
            out[name] = {
                "count": len(s),
                "p50_ms": s[len(s) // 2] * 1000 if s else 0.0,
                "p95_ms": s[int(len(s) * 0.95)] * 1000 if s else 0.0,
                "max_ms": s[-1] * 1000 if s else 0.0,
            }
        return out

    def export_prometheus(self) -> str:
        """Prometheus text exposition (the node-level metrics endpoint role
        — comet's DefaultMetricsProvider, test/util/testnode/full_node.go:44)."""
        lines: List[str] = []
        for name, val in sorted(self.counters.items()):
            metric = f"celestia_tpu_{name}_total"
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {val}")
        for name, val in sorted(self.gauges.items()):
            metric = f"celestia_tpu_{name}"
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {val}")
        for name, vals in sorted(self.timings.items()):
            metric = f"celestia_tpu_{name}_seconds"
            s = sorted(vals)
            lines.append(f"# TYPE {metric} summary")
            for q in (0.5, 0.95, 0.99):
                idx = min(len(s) - 1, int(len(s) * q))
                lines.append(
                    f'{metric}{{quantile="{q}"}} {s[idx] if s else 0.0:.6f}'
                )
            lines.append(f"{metric}_count {len(s)}")
            lines.append(f"{metric}_sum {sum(s):.6f}")
        return "\n".join(lines) + "\n"
