"""Block-lifecycle critical-path analysis over recorded traces.

Consumes either a live :class:`~celestia_tpu.utils.tracing.BlockTrace`
or an already-exported Chrome trace document (a single-node
``trace_dump`` or the ``merge_node_dumps`` multi-node doc from
``node/cluster.py``) and extracts the **critical path** of one block:
the longest blocking chain from the block root to commit, with every
millisecond of the analyzed window attributed to exactly one of four
categories:

* ``self``        — a leaf span actually executing
* ``queue_wait``  — ``hostpool.queue_wait`` spans (the async b/e pairs):
                    work submitted but not yet picked up
* ``flow``        — cross-node edges: the gap between a ``_tc`` send
                    timestamp (shifted onto the collector's clock axis
                    by the estimated clock offset) and the receiving
                    span's start — i.e. per-hop propagation delay
* ``gap``         — unattributed time inside a span that HAS children
                    but none of them covers the moment (decomposed
                    per phase with the same ``{phase}_untraced_ms`` /
                    ``untraced_ms`` names ``Tracer.phase_breakdown``
                    uses), plus inter-span handoff gaps

The walk is a backward sweep: start at the end of the terminal span and
repeatedly descend into the last-finishing child that ends before the
cursor.  By construction the emitted segments PARTITION the analyzed
window — their durations sum to the window wall exactly (float
rounding aside), which is the invariant the smoke gate pins at 1%.

This module is deliberately **clock-free**: it only does arithmetic on
timestamps already recorded by the tracing plane, so it is safe to run
anywhere (celint R3 does not apply) and results are reproducible from
a trace file alone.  It lives in ``utils/`` and therefore must not
import ``node/`` (celint R8); ``node/cluster.py`` imports *us* for the
mesh waterfall rollup.

Negative cross-node deltas (``recv < send_ts`` after the offset shift,
i.e. clock-offset noise) are NEVER reported as negative seconds: the
hop's delay clamps to 0 and the report counts it in
``clock_skew_clamped`` so serving-plane consumers can increment
``celestia_tpu_clock_skew_clamped_total`` instead of poisoning
histograms.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "PathSpan",
    "extract_spans",
    "critical_path",
    "propagation_delays",
    "hop_delay_ms",
    "BLOCK_ROOT_NAMES",
    "COMMIT_SPAN_NAMES",
]

# Block lifecycle anchors: block_span roots (carry args.height) and the
# commit-side rpc span that ends the lifecycle on a validator.
BLOCK_ROOT_NAMES = ("prepare_proposal", "process_proposal")
COMMIT_SPAN_NAMES = ("rpc.cons_commit",)

_QUEUE_WAIT_NAME = "hostpool.queue_wait"
_EPS = 1e-9  # seconds; float-noise guard for the cursor arithmetic


class PathSpan:
    """One normalized span on a single merged clock axis (seconds)."""

    __slots__ = ("node", "span_id", "parent_id", "name", "cat", "t0", "t1", "args")

    def __init__(self, node, span_id, parent_id, name, cat, t0, t1, args):
        self.node = node
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.cat = cat
        self.t0 = t0
        self.t1 = t1
        self.args = args

    @property
    def wall_ms(self) -> float:
        return max(0.0, self.t1 - self.t0) * 1000.0


def _spans_from_blocktrace(trace) -> Tuple[List[PathSpan], Dict[str, float]]:
    """BlockTrace -> spans on the local clock axis; no offsets."""
    out = []
    for s in trace.spans:
        out.append(
            PathSpan("", s.span_id, s.parent_id, s.name, s.cat, s.t0, s.t1, dict(s.args))
        )
    return out, {}


def _spans_from_doc(doc: dict) -> Tuple[List[PathSpan], Dict[str, float]]:
    """Chrome doc (single-node dump or merged) -> spans + clock offsets.

    Merged docs carry ``otherData.nodes`` with per-part ``pid`` and
    ``clock_offset_s`` (peer minus collector — event timestamps were
    already shifted onto the collector axis at merge time; the offsets
    are still needed to shift the RAW ``remote_send_ts`` args, which
    ride untouched on the origin's clock).  Single-node dumps fall back
    to ``otherData.node_id`` / per-event ``args.node_id``.
    """
    other = doc.get("otherData", {}) or {}
    pid_node: Dict[int, str] = {}
    offsets: Dict[str, float] = {}
    for n in other.get("nodes", []) or []:
        nid = str(n.get("node_id", ""))
        try:
            pid_node[int(n.get("pid", 0))] = nid
        except (TypeError, ValueError):
            pass
        try:
            offsets[nid] = float(n.get("clock_offset_s") or 0.0)
        except (TypeError, ValueError):
            offsets[nid] = 0.0
    default_node = str(other.get("node_id", ""))

    spans: List[PathSpan] = []
    pending: Dict[Tuple[int, str], dict] = {}  # (pid, id) -> b event
    for ev in doc.get("traceEvents", []) or []:
        ph = ev.get("ph")
        args = ev.get("args", {}) or {}
        if ph == "X" and "span_id" in args:
            pid = int(ev.get("pid", 1) or 1)
            node = str(args.get("node_id") or pid_node.get(pid, default_node))
            ts = float(ev.get("ts", 0.0)) / 1e6
            dur = max(0.0, float(ev.get("dur", 0.0))) / 1e6
            spans.append(
                PathSpan(
                    node,
                    int(args["span_id"]),
                    int(args.get("parent_id", 0) or 0),
                    str(ev.get("name", "")),
                    str(ev.get("cat", "")),
                    ts,
                    ts + dur,
                    dict(args),
                )
            )
        elif ph == "b" and "span_id" in args:
            pending[(int(ev.get("pid", 1) or 1), str(ev.get("id", "")))] = ev
        elif ph == "e":
            key = (int(ev.get("pid", 1) or 1), str(ev.get("id", "")))
            b = pending.pop(key, None)
            if b is None:
                continue
            bargs = b.get("args", {}) or {}
            pid = key[0]
            node = str(bargs.get("node_id") or pid_node.get(pid, default_node))
            t0 = float(b.get("ts", 0.0)) / 1e6
            t1 = float(ev.get("ts", t0 * 1e6)) / 1e6
            spans.append(
                PathSpan(
                    node,
                    int(bargs.get("span_id", 0) or 0),
                    int(bargs.get("parent_id", 0) or 0),
                    str(b.get("name", "")),
                    str(b.get("cat", "")),
                    t0,
                    max(t0, t1),
                    dict(bargs),
                )
            )
    return spans, offsets


def extract_spans(source) -> Tuple[List[PathSpan], Dict[str, float]]:
    """Normalize a BlockTrace or Chrome doc into ``(spans, offsets)``.

    ``offsets`` maps node id -> clock_offset_s (peer minus collector);
    empty for BlockTrace input (one process, one clock).
    """
    if isinstance(source, dict):
        return _spans_from_doc(source)
    if hasattr(source, "spans"):
        return _spans_from_blocktrace(source)
    raise TypeError(f"unsupported trace source: {type(source).__name__}")


def _is_queue_wait(span: PathSpan) -> bool:
    return span.name == _QUEUE_WAIT_NAME or (
        span.cat == "hostpool" and "queue_wait" in span.name
    )


def _send_ts_local(span: PathSpan, offsets: Dict[str, float]) -> Optional[float]:
    """The span's ``remote_send_ts`` shifted onto the collector axis.

    ``remote_send_ts`` rides RAW on the origin node's clock; subtracting
    the origin's ``clock_offset_s`` (peer minus collector) lands it on
    the same axis as the (already shifted) event timestamps.
    """
    ts = span.args.get("remote_send_ts")
    if ts is None:
        return None
    try:
        ts = float(ts)
    except (TypeError, ValueError):
        return None
    origin = str(span.args.get("remote_node", ""))
    return ts - float(offsets.get(origin, 0.0))


def hop_delay_ms(span: PathSpan, offsets: Dict[str, float]):
    """One receiving span's propagation delay: ``(delay_ms, clamped)``,
    or None when the span carries no cross-node send timestamp.  The
    delay clamps at 0 (``clamped=True`` marks clock-offset noise)."""
    send_local = _send_ts_local(span, offsets)
    if send_local is None:
        return None
    raw = (span.t0 - send_local) * 1000.0
    return (round(max(0.0, raw), 3), raw < 0.0)


class _Walker:
    """Backward sweep emitting partition segments for one window."""

    def __init__(self, kids_of, root_key):
        self.kids_of = kids_of
        self.root_key = root_key
        self.segments: List[dict] = []

    def _emit(self, span: PathSpan, lo: float, hi: float, scope: str) -> None:
        if hi - lo <= _EPS:
            return
        has_kids = bool(self.kids_of.get((span.node, span.span_id)))
        if _is_queue_wait(span):
            kind, phase = "queue_wait", ""
        elif has_kids:
            kind = "gap"
            phase = (
                "untraced_ms"
                if (span.node, span.span_id) == self.root_key
                else f"{span.name}_untraced_ms"
            )
        else:
            kind, phase = "self", ""
        self.segments.append(
            {
                "node": span.node,
                "name": span.name,
                "span_id": span.span_id,
                "kind": kind,
                "phase": phase,
                "scope": scope,
                "t0": lo,
                "t1": hi,
            }
        )

    def walk(self, span: PathSpan, lo: float, hi: float, scope: str) -> None:
        """Attribute ``[lo, hi]`` (clipped to the span's own interval).

        Invariant: the segments emitted for this call sum exactly to
        ``hi - lo`` — children chosen on the path recurse over disjoint
        sub-windows and the cursor arithmetic covers every remainder.
        """
        lo = max(lo, span.t0)
        hi = min(hi, span.t1)
        if hi - lo <= _EPS:
            return
        kids = self.kids_of.get((span.node, span.span_id), ())
        cursor = hi
        for c in sorted(kids, key=lambda c: c.t1, reverse=True):
            c_hi = min(c.t1, cursor)
            c_lo = max(lo, c.t0)
            if c_hi - c_lo <= _EPS:
                continue
            if cursor - c_hi > _EPS:
                self._emit(span, c_hi, cursor, scope)
            self.walk(c, c_lo, c_hi, scope)
            cursor = c_lo
            if cursor - lo <= _EPS:
                break
        if cursor - lo > _EPS:
            self._emit(span, lo, cursor, scope)


def _pick_anchor(
    spans: Sequence[PathSpan], height: Optional[int], root_id: Optional[int]
) -> Optional[PathSpan]:
    if root_id is not None:
        for s in spans:
            if s.span_id == root_id:
                return s
    best = None
    for s in spans:
        if s.name not in BLOCK_ROOT_NAMES:
            continue
        if height is not None and s.args.get("height") not in (height, str(height)):
            continue
        if best is None or s.t1 > best.t1:
            best = s
    return best


def propagation_delays(source, offsets: Optional[Dict[str, float]] = None) -> List[dict]:
    """Every cross-node hop recorded in the source, one entry per hop.

    delay = receiving span's start − (``remote_send_ts`` − origin clock
    offset), clamped at 0 (``clamped: True`` marks hops where the raw
    delta went negative — clock-offset noise, never a real negative
    flight time).  Hops are deduped on (origin, remote_span, send_ts):
    the rpc envelope and the block root it contains carry the same
    context; the EARLIEST receiving span (the true receipt) wins.
    """
    if offsets is None:
        spans, offsets = extract_spans(source)
    else:
        spans, _ = extract_spans(source)
    hops: Dict[tuple, dict] = {}
    for s in spans:
        send_local = _send_ts_local(s, offsets)
        if send_local is None:
            continue
        key = (
            str(s.args.get("remote_node", "")),
            s.args.get("remote_span"),
            s.args.get("remote_send_ts"),
        )
        prev = hops.get(key)
        if prev is not None and prev["_t0"] <= s.t0:
            continue
        raw_ms = (s.t0 - send_local) * 1000.0
        hops[key] = {
            "from_node": key[0],
            "to_node": s.node,
            "name": s.name,
            "delay_ms": round(max(0.0, raw_ms), 3),
            "clamped": raw_ms < 0.0,
            "_t0": s.t0,
        }
    out = sorted(hops.values(), key=lambda h: h["_t0"])
    for h in out:
        del h["_t0"]
    return out


def critical_path(source, height: Optional[int] = None) -> dict:
    """Extract the critical path of one block lifecycle.

    The chain is assembled backward from the terminal span:

    1. **anchor** — the latest-ending block root (``prepare_proposal``
       / ``process_proposal``) for ``height`` (BlockTrace input: its
       own root); its subtree is swept over its full wall.
    2. **commit extension** — the first ``rpc.cons_commit`` span on the
       anchor's node starting at/after the anchor's end extends the
       chain through commit; the handoff gap is attributed as ``gap``
       (phase ``commit_lag``) and surfaced as ``commit_lag_ms``.
    3. **upstream** — if the anchor carries cross-node origin args, a
       ``flow`` edge covers [send, anchor start] (the propagation hop,
       clamped at 0 on skew) and, when the origin span is resolvable
       in a merged doc, the origin's subtree is swept up to the send
       timestamp with the origin→send handoff as ``gap``.

    Returns a report dict; ``attribution_ms`` sums the whole chain and
    ``root_attribution_ms`` sums only the anchor-wall segments (the
    partition identity the acceptance gate checks against
    ``root_wall_ms``).
    """
    spans, offsets = extract_spans(source)
    root_id = getattr(source, "root_id", None) if not isinstance(source, dict) else None
    if height is None and not isinstance(source, dict):
        height = getattr(source, "height", None)

    anchor = _pick_anchor(spans, height, root_id)
    if anchor is None:
        return {
            "height": height,
            "root": None,
            "steps": [],
            "total_ms": 0.0,
            "root_wall_ms": 0.0,
            "attribution_ms": {"self": 0.0, "queue_wait": 0.0, "flow": 0.0, "gap": 0.0},
            "root_attribution_ms": {
                "self": 0.0,
                "queue_wait": 0.0,
                "flow": 0.0,
                "gap": 0.0,
            },
            "gap_by_phase_ms": {},
            "top_contributors": [],
            "propagation": [],
            "clock_skew_clamped": 0,
            "unresolved_links": 0,
            "commit_lag_ms": None,
        }

    kids_of: Dict[Tuple[str, int], List[PathSpan]] = {}
    index: Dict[Tuple[str, int], PathSpan] = {}
    for s in spans:
        index[(s.node, s.span_id)] = s
        if s.parent_id:
            kids_of.setdefault((s.node, s.parent_id), []).append(s)

    walker = _Walker(kids_of, (anchor.node, anchor.span_id))
    unresolved = 0

    # --- upstream: flow edge + origin subtree (merged docs) -----------
    send_local = _send_ts_local(anchor, offsets)
    origin_key = (
        str(anchor.args.get("remote_node", "")),
        int(anchor.args.get("remote_span", 0) or 0),
    )
    origin = index.get(origin_key) if origin_key[1] else None
    if origin is None and origin_key[1]:
        unresolved += 1
    if send_local is not None:
        raw_ms = (anchor.t0 - send_local) * 1000.0
        flow_lo = min(send_local, anchor.t0)
        if origin is not None:
            walker.walk(origin, origin.t0, min(origin.t1, flow_lo), "upstream")
            if flow_lo - origin.t1 > _EPS:
                walker.segments.append(
                    {
                        "node": origin.node,
                        "name": f"{origin.name}→send",
                        "span_id": origin.span_id,
                        "kind": "gap",
                        "phase": "handoff",
                        "scope": "upstream",
                        "t0": origin.t1,
                        "t1": flow_lo,
                    }
                )
        walker.segments.append(
            {
                "node": anchor.node,
                "name": "propagation",
                "span_id": 0,
                "kind": "flow",
                "phase": "",
                "scope": "flow",
                "t0": flow_lo,
                "t1": anchor.t0,
                "clamped": raw_ms < 0.0,
            }
        )

    # --- the anchor root itself --------------------------------------
    walker.walk(anchor, anchor.t0, anchor.t1, "root")

    # --- commit extension --------------------------------------------
    commit = None
    for s in spans:
        if s.name not in COMMIT_SPAN_NAMES or s.node != anchor.node:
            continue
        if s.t0 < anchor.t1 - _EPS:
            continue
        if commit is None or s.t0 < commit.t0:
            commit = s
    commit_lag_ms = None
    if commit is not None:
        commit_lag_ms = round(max(0.0, commit.t0 - anchor.t1) * 1000.0, 3)
        if commit.t0 - anchor.t1 > _EPS:
            walker.segments.append(
                {
                    "node": anchor.node,
                    "name": "commit_handoff",
                    "span_id": 0,
                    "kind": "gap",
                    "phase": "commit_lag",
                    "scope": "commit",
                    "t0": anchor.t1,
                    "t1": commit.t0,
                }
            )
        walker.walk(commit, commit.t0, commit.t1, "commit")

    # --- assemble the report -----------------------------------------
    segments = sorted(walker.segments, key=lambda g: g["t0"])
    chain_t0 = segments[0]["t0"] if segments else anchor.t0
    attribution = {"self": 0.0, "queue_wait": 0.0, "flow": 0.0, "gap": 0.0}
    root_attribution = {"self": 0.0, "queue_wait": 0.0, "flow": 0.0, "gap": 0.0}
    gap_by_phase: Dict[str, float] = {}
    contrib: Dict[Tuple[str, str, str], float] = {}
    steps = []
    for g in segments:
        ms = (g["t1"] - g["t0"]) * 1000.0
        attribution[g["kind"]] += ms
        if g["scope"] == "root":
            root_attribution[g["kind"]] += ms
        if g["kind"] == "gap" and g["phase"]:
            gap_by_phase[g["phase"]] = gap_by_phase.get(g["phase"], 0.0) + ms
        contrib_key = (g["node"], g["name"], g["kind"])
        contrib[contrib_key] = contrib.get(contrib_key, 0.0) + ms
        steps.append(
            {
                "node": g["node"],
                "name": g["name"],
                "span_id": g["span_id"],
                "kind": g["kind"],
                "scope": g["scope"],
                "ms": round(ms, 3),
                "t0_ms": round((g["t0"] - chain_t0) * 1000.0, 3),
                "t1_ms": round((g["t1"] - chain_t0) * 1000.0, 3),
            }
        )

    top = sorted(
        (
            {"node": k[0], "name": k[1], "kind": k[2], "ms": round(v, 3)}
            for k, v in contrib.items()
        ),
        key=lambda c: c["ms"],
        reverse=True,
    )[:3]

    prop = propagation_delays(source)
    clamped = sum(1 for h in prop if h["clamped"])

    return {
        "height": anchor.args.get("height", height),
        "node": anchor.node,
        "root": {"name": anchor.name, "node": anchor.node, "span_id": anchor.span_id},
        "end": {
            "name": commit.name if commit is not None else anchor.name,
            "node": anchor.node,
            "span_id": commit.span_id if commit is not None else anchor.span_id,
        },
        "root_wall_ms": round(anchor.wall_ms, 3),
        "total_ms": round(sum(attribution.values()), 3),
        "steps": steps,
        "attribution_ms": {k: round(v, 3) for k, v in attribution.items()},
        "root_attribution_ms": {k: round(v, 3) for k, v in root_attribution.items()},
        "gap_by_phase_ms": {k: round(v, 3) for k, v in sorted(gap_by_phase.items())},
        "top_contributors": top,
        "propagation": prop,
        "propagation_delay_ms": prop[0]["delay_ms"] if prop else None,
        "clock_skew_clamped": clamped,
        "unresolved_links": unresolved,
        "commit_lag_ms": commit_lag_ms,
    }
