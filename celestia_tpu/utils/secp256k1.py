"""secp256k1 ECDSA (pure Python) — tx signing/verification primitives.

Equivalent role to the reference's decred secp256k1 dependency
(SURVEY.md §2.2 "BLS / secp256k1 / SHA"): account-key signatures over
SIGN_MODE_DIRECT-style sign bytes.  Deterministic nonces per RFC 6979 so
signing is reproducible.  Pure Python is adequate for the host-side tx path
(the device does the DA compute); a native C++ path can slot in behind the
same interface later.
"""

from __future__ import annotations

import hashlib
import hmac
from dataclasses import dataclass
from functools import lru_cache
from typing import Optional, Tuple

# Curve parameters (SEC2 secp256k1)
P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
Gx = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
Gy = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


def _point_add(p1: Optional[Tuple[int, int]], p2: Optional[Tuple[int, int]]):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2 and (y1 + y2) % P == 0:
        return None
    if p1 == p2:
        lam = (3 * x1 * x1) * _inv(2 * y1, P) % P
    else:
        lam = (y2 - y1) * _inv(x2 - x1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    y3 = (lam * (x1 - x3) - y1) % P
    return (x3, y3)


def _point_mul(k: int, point: Tuple[int, int]):
    result = None
    addend = point
    while k:
        if k & 1:
            result = _point_add(result, addend)
        addend = _point_add(addend, addend)
        k >>= 1
    return result


@dataclass(frozen=True)
class PrivateKey:
    d: int

    @classmethod
    def from_seed(cls, seed: bytes) -> "PrivateKey":
        """Derive a valid key deterministically from arbitrary seed bytes."""
        d = 0
        counter = 0
        while not 1 <= d < N:
            d = int.from_bytes(
                hashlib.sha256(seed + counter.to_bytes(4, "big")).digest(), "big"
            )
            counter += 1
        return cls(d)

    def public_key(self) -> "PublicKey":
        x, y = _point_mul(self.d, (Gx, Gy))
        return PublicKey(x, y)

    def sign(self, msg: bytes) -> bytes:
        """Deterministic ECDSA (RFC 6979, SHA-256); 64-byte r||s, low-s."""
        z = int.from_bytes(hashlib.sha256(msg).digest(), "big") % N
        k = _rfc6979_k(self.d, hashlib.sha256(msg).digest())
        while True:
            R = _point_mul(k, (Gx, Gy))
            r = R[0] % N
            if r == 0:
                k = (k + 1) % N
                continue
            s = _inv(k, N) * (z + r * self.d) % N
            if s == 0:
                k = (k + 1) % N
                continue
            if s > N // 2:  # canonical low-s
                s = N - s
            return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def _rfc6979_k(d: int, h1: bytes) -> int:
    x = d.to_bytes(32, "big")
    V = b"\x01" * 32
    K = b"\x00" * 32
    K = hmac.new(K, V + b"\x00" + x + h1, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    K = hmac.new(K, V + b"\x01" + x + h1, hashlib.sha256).digest()
    V = hmac.new(K, V, hashlib.sha256).digest()
    while True:
        V = hmac.new(K, V, hashlib.sha256).digest()
        k = int.from_bytes(V, "big")
        if 1 <= k < N:
            return k
        K = hmac.new(K, V + b"\x00", hashlib.sha256).digest()
        V = hmac.new(K, V, hashlib.sha256).digest()


@dataclass(frozen=True)
class PublicKey:
    x: int
    y: int

    def compressed(self) -> bytes:
        return bytes([2 + (self.y & 1)]) + self.x.to_bytes(32, "big")

    @classmethod
    def from_compressed(cls, raw: bytes) -> "PublicKey":
        # decompression costs a modular sqrt; the same few pubkeys repeat
        # across a block's txs, so memoize (instances are frozen).  The
        # cached helper raises for invalid encodings like the inline path.
        return _decompress_cached(bytes(raw))

    def verify(self, msg: bytes, sig: bytes) -> bool:
        pre = _verify_scalars(msg, sig)
        if pre is None:
            return False
        r, u1, u2 = pre
        x = _ecmul_double_x(u1, u2, self)
        if x is None:
            return False
        return x % N == r

    def address(self) -> bytes:
        """20-byte account address: sha256(compressed pubkey)[:20]."""
        return hashlib.sha256(self.compressed()).digest()[:20]


@lru_cache(maxsize=4096)
def _decompress_cached(raw: bytes) -> PublicKey:
    if len(raw) != 33 or raw[0] not in (2, 3):
        raise ValueError("invalid compressed pubkey")
    x = int.from_bytes(raw[1:], "big")
    if x >= P:
        raise ValueError("pubkey x out of range")
    y2 = (pow(x, 3, P) + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("point not on curve")
    if (y & 1) != (raw[0] & 1):
        y = P - y
    return PublicKey(x, y)


MULTISIG_PREFIX = 0xF0


@dataclass(frozen=True)
class MultisigPubKey:
    """k-of-n threshold key over compressed secp256k1 keys.

    Parity role: the SDK's LegacyAminoPubKey multisig accepted by the
    reference's ante chain (SURVEY §2.1 ante 'multisig pubkeys').  Wire
    form: 0xF0 | threshold | n | 33-byte keys...; the signature blob is a
    concatenation of (key index byte | 64-byte r||s) entries.
    """

    threshold: int
    keys: Tuple[bytes, ...]  # compressed pubkeys, order-significant

    def __post_init__(self):
        if not 1 <= self.threshold <= len(self.keys):
            raise ValueError(
                f"threshold {self.threshold} out of range for "
                f"{len(self.keys)} keys"
            )
        if len(self.keys) > 255:
            raise ValueError("at most 255 keys in a multisig")
        for k in self.keys:
            if len(k) != 33 or k[0] not in (2, 3):
                raise ValueError("multisig member must be a compressed pubkey")

    def marshal(self) -> bytes:
        out = bytearray([MULTISIG_PREFIX, self.threshold, len(self.keys)])
        for k in self.keys:
            out += k
        return bytes(out)

    @classmethod
    def unmarshal(cls, raw: bytes) -> "MultisigPubKey":
        if len(raw) < 3 or raw[0] != MULTISIG_PREFIX:
            raise ValueError("not a multisig pubkey")
        threshold, n = raw[1], raw[2]
        if len(raw) != 3 + 33 * n:
            raise ValueError("truncated multisig pubkey")
        keys = tuple(raw[3 + 33 * i : 3 + 33 * (i + 1)] for i in range(n))
        return cls(threshold, keys)

    def address(self) -> bytes:
        return hashlib.sha256(self.marshal()).digest()[:20]

    def verify(self, msg: bytes, sig_blob: bytes) -> bool:
        """Canonical threshold verification: >= threshold entries, EVERY
        entry must be a valid signature by a distinct member, and entries
        must appear in strictly increasing index order.  Tolerating any
        invalid or reordered entry would make the signature blob — and
        therefore the tx hash — third-party malleable (the SDK's
        LegacyAminoPubKey verification rejects such blobs the same way)."""
        entry = 1 + 64
        if not sig_blob or len(sig_blob) % entry:
            return False
        n_entries = len(sig_blob) // entry
        if not self.threshold <= n_entries <= len(self.keys):
            return False
        last_idx = -1
        for off in range(0, len(sig_blob), entry):
            idx = sig_blob[off]
            if idx >= len(self.keys) or idx <= last_idx:
                return False  # unknown signer or non-canonical order
            last_idx = idx
            sig = sig_blob[off + 1 : off + entry]
            try:
                pk = PublicKey.from_compressed(self.keys[idx])
            except ValueError:
                return False
            if not pk.verify(msg, sig):
                return False  # any bad entry invalidates the whole blob
        return True


def combine_multisig_signatures(entries) -> bytes:
    """[(key_index, 64-byte sig), ...] -> the tx signature blob."""
    out = bytearray()
    for idx, sig in sorted(entries):
        if len(sig) != 64:
            raise ValueError("each partial signature must be 64 bytes")
        out += bytes([idx]) + sig
    return bytes(out)


def _verify_scalars(msg: bytes, sig: bytes):
    """Shared ECDSA pre-checks + scalar math; (r, u1, u2) or None.

    Rejects non-canonical high-s signatures: accepting (r, N-s) alongside
    (r, s) lets any third party malleate an in-flight tx into a different tx
    hash that still executes — breaking confirm-by-hash lookup and mempool
    dedup.  Mirrors the low-s rule sign() enforces and the reference's
    secp256k1 behavior (SURVEY.md §2.2).
    """
    if len(sig) != 64:
        return None
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    if not (1 <= r < N and 1 <= s < N):
        return None
    if s > N // 2:
        return None
    z = int.from_bytes(hashlib.sha256(msg).digest(), "big") % N
    w = _inv(s, N)
    return r, z * w % N, r * w % N


# --- GLV endomorphism (verification speedup) -------------------------------
# secp256k1 has an efficient endomorphism phi(x, y) = (beta*x, y) with
# phi(P) = lambda*P (beta, lambda the matching cube roots of unity mod p
# and mod N).  Splitting a 256-bit scalar k into k1 + k2*lambda with
# |k1|, |k2| ~ 2^128 (lattice rounding below, the standard GLV basis)
# halves the doubling count of the native wNAF loop.  The split runs here
# in Python (CPython bigints), the point math in native C++.
GLV_LAMBDA = 0x5363AD4CC05C30E0A5261C028812645A122E22EA20816678DF02967C1B23BD72
_GLV_A1 = 0x3086D221A7D46BCDE86C90E49284EB15
_GLV_B1 = -0xE4437ED6010E88286F547FA90ABFE4C3
_GLV_A2 = 0x114CA50F7A8E2F3F657C1108D9D44CFD8
_GLV_B2 = _GLV_A1


def _glv_split(k: int):
    """k -> (k1, k2) with k1 + k2*GLV_LAMBDA ≡ k (mod N), both ~128-bit."""
    c1 = (_GLV_B2 * k + N // 2) // N
    c2 = (-_GLV_B1 * k + N // 2) // N
    k1 = k - c1 * _GLV_A1 - c2 * _GLV_A2
    k2 = -c1 * _GLV_B1 - c2 * _GLV_B2
    return k1, k2


def _batch_inv(vals, mod):
    """Montgomery's trick: invert every element with ONE modular
    inversion plus 3(n-1) multiplications.  All vals must be non-zero
    mod ``mod`` (signature s-values are range-checked before this)."""
    n = len(vals)
    prefix = [1] * (n + 1)
    for i, v in enumerate(vals):
        prefix[i + 1] = prefix[i] * v % mod
    acc = _inv(prefix[n], mod)
    out = [0] * n
    for i in range(n - 1, -1, -1):
        out[i] = prefix[i] * acc % mod
        acc = acc * vals[i] % mod
    return out


def _ecmul_double(u1: int, u2: int, pub: "PublicKey"):
    """u1*G + u2*pub — native C when available, pure Python otherwise."""
    from celestia_tpu.utils import native

    if native.available():
        got = native.ecmul_double(
            u1.to_bytes(32, "big"), u2.to_bytes(32, "big"), pub.compressed()
        )
        if got is None:
            return None
        return int.from_bytes(got[0], "big"), int.from_bytes(got[1], "big")
    return _point_add(_point_mul(u1, (Gx, Gy)), _point_mul(u2, (pub.x, pub.y)))


def _glv_pack(u1: int, u2: int):
    """(ks_row, signs_row) for the native GLV ABI: the four 32-byte
    big-endian magnitudes |k1_G| ‖ |k2_G| ‖ |k1_Q| ‖ |k2_Q| and their
    sign bytes.  The ONE place the component order lives Python-side —
    verify_batch and the single-verify path both marshal through here
    (native/celestia_native.cpp secp256k1_ecmul_double_glv)."""
    parts = _glv_split(u1) + _glv_split(u2)
    ks = b"".join(abs(k).to_bytes(32, "big") for k in parts)
    signs = bytes(1 if k < 0 else 0 for k in parts)
    return ks, signs


def _ecmul_double_x(u1: int, u2: int, pub: "PublicKey"):
    """x(u1*G + u2*pub) or None — ECDSA verification only needs x.
    Prefers the GLV kernel (half the doublings; the single-sig CheckTx
    path gets the same speedup the batch path does) as a batch of one."""
    from celestia_tpu.utils import native

    if native.has_glv():
        import numpy as np

        ks, signs = _glv_pack(u1, u2)
        pubs = np.frombuffer(
            pub.x.to_bytes(32, "big") + pub.y.to_bytes(32, "big"),
            dtype=np.uint8,
        ).reshape(1, 64)
        ok, xs = native.ecmul_double_glv_batch(
            np.frombuffer(ks, dtype=np.uint8).reshape(1, 128),
            np.frombuffer(signs, dtype=np.uint8).reshape(1, 4),
            pubs,
            # celint: allow(hostpool-discipline) — single-signature path:
            # a batch of one has nothing to fan out, and this runs inside
            # ante handlers that may already sit on pool workers
            nthreads=1,
        )
        if not ok[0]:
            return None
        return int.from_bytes(xs[0].tobytes(), "big")
    pt = _ecmul_double(u1, u2, pub)
    return None if pt is None else pt[0]


@lru_cache(maxsize=4096)
def _uncompressed64(raw: bytes):
    """compressed(33B) -> uncompressed(64B x||y) for the native GLV path;
    memoized on top of the memoized sqrt decompression.  Raises
    ValueError for invalid encodings (like from_compressed)."""
    pk = _decompress_cached(raw)
    return pk.x.to_bytes(32, "big") + pk.y.to_bytes(32, "big")


def verify_batch(msgs, sigs, pubkeys, precomp=None) -> list:
    """Verify many (msg, sig, compressed-pubkey) triples at once.

    Uses the threaded native batch path when available (the reference's
    analogue is per-tx C secp256k1 verification inside FilterTxs /
    ProcessProposal — app/validate_txs.go:39-97); falls back to sequential
    verify otherwise.  Returns a list of bools.

    precomp routes the GLV leg's table strategy (see
    native.ecmul_double_glv_batch): None = auto, True/False force the
    precomputed-affine-table / legacy symbol.  Ignored off the GLV path.
    """
    import numpy as np

    from celestia_tpu.utils import native

    n = len(msgs)
    if not (len(sigs) == len(pubkeys) == n):
        raise ValueError("msgs/sigs/pubkeys length mismatch")
    if not native.available():
        out = []
        for msg, sig, raw in zip(msgs, sigs, pubkeys):
            try:
                pk = PublicKey.from_compressed(raw)
            except ValueError:
                out.append(False)
                continue
            out.append(pk.verify(msg, sig))
        return out

    results = [False] * n
    rs = [0] * n
    live = np.zeros(n, dtype=bool)
    # Montgomery batch inversion: ONE modular inversion for the whole
    # batch instead of one per signature (the per-sig s^-1 was a visible
    # slice of FilterTxs host time at proposal scale)
    pre_rsz: list = [None] * n
    s_vals: list = []
    for i, (msg, sig, raw) in enumerate(zip(msgs, sigs, pubkeys)):
        if len(sig) != 64 or len(raw) != 33 or raw[0] not in (2, 3):
            continue
        r = int.from_bytes(sig[:32], "big")
        s = int.from_bytes(sig[32:], "big")
        if not (1 <= r < N and 1 <= s < N) or s > N // 2:
            continue  # low-s rule: see _verify_scalars
        z = int.from_bytes(hashlib.sha256(msg).digest(), "big") % N
        pre_rsz[i] = (r, s, z, len(s_vals))
        s_vals.append(s)
    if s_vals:
        ws = _batch_inv(s_vals, N)
    use_glv = native.has_glv()
    if use_glv:
        # GLV path wants UNCOMPRESSED keys (decompression costs a field
        # sqrt; senders repeat, so the cache amortizes it to ~zero)
        pubs = np.zeros((n, 64), dtype=np.uint8)
        ks = np.zeros((n, 128), dtype=np.uint8)
        sgn = np.zeros((n, 4), dtype=np.uint8)
    else:
        pubs = np.zeros((n, 33), dtype=np.uint8)
        u1s = np.zeros((n, 32), dtype=np.uint8)
        u2s = np.zeros((n, 32), dtype=np.uint8)
    for i in range(n):
        pre = pre_rsz[i]
        if pre is None:
            continue
        r, s, z, j = pre
        w = ws[j]
        u1 = z * w % N
        u2 = r * w % N
        if use_glv:
            try:
                raw_pub = _uncompressed64(bytes(pubkeys[i]))
            except ValueError:
                continue  # invalid pubkey: signature cannot verify
            k_row, s_row = _glv_pack(u1, u2)
            ks[i] = np.frombuffer(k_row, dtype=np.uint8)
            sgn[i] = np.frombuffer(s_row, dtype=np.uint8)
            pubs[i] = np.frombuffer(raw_pub, dtype=np.uint8)
        else:
            u1s[i] = np.frombuffer(u1.to_bytes(32, "big"), dtype=np.uint8)
            u2s[i] = np.frombuffer(u2.to_bytes(32, "big"), dtype=np.uint8)
            pubs[i] = np.frombuffer(pubkeys[i], dtype=np.uint8)
        rs[i] = r
        live[i] = True
    if not live.any():
        return results
    # ship only live rows: dead rows (scalar pre-check / decompression
    # failures) would each pay the kernel's on-curve validation work
    idx = np.flatnonzero(live)
    if use_glv:
        ok, xs = native.ecmul_double_glv_batch(
            ks[idx], sgn[idx], pubs[idx], precomp=precomp
        )
    else:
        ok, xs = native.ecmul_double_batch(u1s[idx], u2s[idx], pubs[idx])
    for j, i in enumerate(idx):
        if ok[j]:
            results[i] = int.from_bytes(xs[j].tobytes(), "big") % N == rs[i]
    return results
