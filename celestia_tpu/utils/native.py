"""ctypes bindings for the native C++ host library (native/celestia_native.cpp).

Builds the shared object on demand with g++ (cached by source mtime) and
exposes the same operations as the device kernels — used as the CPU
comparison leg in bench.py and as a host fallback.  If no compiler is
available the module degrades gracefully (``available()`` returns False).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_REPO_ROOT = Path(__file__).resolve().parents[2]
_SRC = _REPO_ROOT / "native" / "celestia_native.cpp"
# CELESTIA_TPU_NATIVE_SO points the loader at an alternative build of the
# same source — the sanitizer harness (make native-sanitize) rebuilds the
# library under TSan/ASan at a side path and re-runs the thread-scaling
# byte-identity tests against it without disturbing the pristine .so.
# An overridden .so is never rebuilt here: the override owns its build.
_SO_OVERRIDE = os.environ.get("CELESTIA_TPU_NATIVE_SO", "")
_SO = (
    Path(_SO_OVERRIDE)
    if _SO_OVERRIDE
    else _REPO_ROOT / "native" / "celestia_native.so"
)

_lib: Optional[ctypes.CDLL] = None
_tried = False
_has_glv = False
_has_glv_pre = False

# One-way degradation pin (specs/robustness.md "degradation ladder"): a
# native fault mid-run poisons the library for the REST OF THE PROCESS,
# so every caller falls back to the byte-identical table-GF/jax legs.
# The pin is deliberately one-way — a library that faulted once under
# load cannot be trusted to silently come back, and a mid-chain flap
# between legs would make perf numbers and telemetry unreadable.  Only
# clear_poison(force=True) (tests, operator intervention) clears it.
_poison_lock = threading.Lock()
_poison_reason: Optional[str] = None  # celint: guarded-by(_poison_lock)


def poison(reason: str) -> None:
    """Pin the native library OFF after a fault (loud, one-way)."""
    global _poison_reason
    from celestia_tpu.utils import faults
    from celestia_tpu.utils.logging import Logger

    with _poison_lock:
        if _poison_reason is not None:
            return  # already degraded; first reason wins
        _poison_reason = reason
    faults.record_degradation("native", reason)
    Logger(level="warn").warn(
        "native DA pipeline poisoned: falling back to the pure table-GF "
        "path for the rest of the process (byte-identical, slower)",
        reason=reason[:200],
    )


def poisoned() -> Optional[str]:
    """The poison reason, or None when the native leg is trusted."""
    with _poison_lock:
        return _poison_reason


def clear_poison(force: bool = False) -> None:
    """Un-pin the degradation.  Refuses without ``force=True``: the pin
    exists precisely so nothing switches back silently."""
    global _poison_reason
    with _poison_lock:
        if _poison_reason is None:
            return
        if not force:
            raise RuntimeError(
                "the native pipeline was poisoned "
                f"({_poison_reason!r}) and the degradation pin is one-way; "
                "pass force=True only if you KNOW the fault is resolved"
            )
        _poison_reason = None


def _build() -> bool:
    try:
        subprocess.run(
            [
                "g++", "-O3", "-march=native", "-shared", "-fPIC", "-pthread",
                str(_SRC), "-o", str(_SO),
            ],
            check=True,
            capture_output=True,
            timeout=300,
        )
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not _SRC.exists():
        return None
    if _SO_OVERRIDE:
        if not _SO.exists():
            return None  # sanitizer harness must have built it already
    elif not _SO.exists() or _SO.stat().st_mtime < _SRC.stat().st_mtime:
        if not _build():
            return None
    try:
        lib = ctypes.CDLL(str(_SO))
    except OSError:
        return None
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.rs_extend_square.argtypes = [u8p, u8p, u8p, ctypes.c_int, ctypes.c_int]
    lib.sha256_batch.argtypes = [u8p, ctypes.c_int, ctypes.c_int, u8p]
    lib.nmt_root.argtypes = [u8p, ctypes.c_int, ctypes.c_int, u8p]
    lib.create_commitment.argtypes = [
        u8p, ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int, u8p,
    ]
    i32p = ctypes.POINTER(ctypes.c_int32)
    try:
        lib.create_commitments_batch.argtypes = [
            u8p, ctypes.c_int, i32p, i32p, i32p, ctypes.c_int, u8p,
            ctypes.c_int,
        ]
    except AttributeError:
        return None  # stale .so predating this round: see codec guard below
    lib.eds_nmt_roots.argtypes = [u8p, ctypes.c_int, ctypes.c_int, u8p]
    try:
        lib.eds_nmt_roots_mt.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, u8p, ctypes.c_int,
        ]
        lib.sha256_batch_mt.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, u8p, ctypes.c_int,
        ]
    except AttributeError:
        return None  # stale .so predating the threaded hashing entry points
    lib.gf_matmul_axes.argtypes = [
        u8p, u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
    ]
    lib.extend_block_cpu.argtypes = [
        u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, u8p, u8p, u8p,
    ]
    try:
        lib.gf_load_mul.argtypes = [u8p]
        lib.leo_encode.argtypes = [u8p, ctypes.c_int, ctypes.c_int, u8p]
        lib.leo_extend_square_cpu.argtypes = [
            u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ]
        lib.extend_block_leopard_cpu.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, u8p, u8p, u8p,
        ]
        lib.leo_decode_axes.argtypes = [
            u8p, u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int, u8p,
            ctypes.c_int,
        ]
    except AttributeError:
        # stale .so without the codec symbols: the GF legs would compute
        # in the WRONG field for the leopard codec (gf_load_mul missing),
        # so the lib is unusable as a coherent unit — degrade to the
        # pure-Python/device paths entirely rather than risk wrong parity
        return None
    lib.secp256k1_ecmul_double.argtypes = [u8p, u8p, u8p, u8p, u8p]
    lib.secp256k1_ecmul_double.restype = ctypes.c_int
    lib.secp256k1_ecmul_double_batch.argtypes = [
        u8p, u8p, u8p, ctypes.c_int, u8p, u8p, ctypes.c_int,
    ]
    global _has_glv, _has_glv_pre
    try:
        lib.secp256k1_ecmul_double_glv_batch.argtypes = [
            u8p, u8p, u8p, ctypes.c_int, u8p, u8p, ctypes.c_int,
        ]
        _has_glv = True
    except AttributeError:
        # stale .so without the GLV symbol: degrade to the plain path
        _has_glv = False
    try:
        lib.secp256k1_ecmul_double_glv_batch_pre.argtypes = [
            u8p, u8p, u8p, ctypes.c_int, u8p, u8p, ctypes.c_int,
        ]
        _has_glv_pre = True
    except AttributeError:
        # stale .so without the precomputed-table symbol: the legacy GLV
        # batch still works, ingress just loses the per-batch amortization
        _has_glv_pre = False
    _lib = lib
    return _lib


def available() -> bool:
    with _poison_lock:
        if _poison_reason is not None:
            return False
    return _load() is not None


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


_loaded_codec: Optional[str] = None

# Serializes gf_load_mul against every in-flight table-method call
# (ADVICE r5): the native MUL table is process-global, so a codec switch
# racing an rs_extend_square / extend_block_cpu / gf_matmul_axes call on
# another thread would compute in a mixed field and return silently
# wrong parity.  Each table-method wrapper holds this lock across BOTH
# _ensure_field and the native call; re-entrant so nested helpers work.
_field_lock = threading.RLock()


def _ensure_field(lib) -> None:
    """Keep the native MUL table in the active codec's representation so
    table-method GF legs here stay bit-identical to the device path.
    Callers must hold ``_field_lock`` across this AND the native call."""
    global _loaded_codec
    # celint: allow(layering) — native is the C twin of ops/gf256: both sides must share ONE codec pin and ONE mul table or the byte-identity contract breaks; the import is lazy and utils/ has no module-level dependency on ops/
    from celestia_tpu.ops import gf256

    codec = gf256.active_codec()
    if codec == _loaded_codec:
        return
    table = np.ascontiguousarray(gf256.mul_table(codec))
    lib.gf_load_mul(_ptr(table))
    _loaded_codec = codec
    # first native use of the codec's field: from here on set_active_codec
    # refuses to SWITCH codecs outside tests (pin-once-at-genesis)
    gf256.mark_codec_used()


def _resolve_threads(nthreads: Optional[int]) -> int:
    """None -> the process-wide pool size (``--cpu-threads`` /
    CELESTIA_TPU_CPU_THREADS / os.cpu_count); ints pass through (0 keeps
    the C side's hardware_concurrency fallback)."""
    if nthreads is None:
        from celestia_tpu.utils import hostpool

        return hostpool.cpu_threads()
    return nthreads


def rs_extend_square(square: np.ndarray) -> np.ndarray:
    """uint8[k, k, B] -> uint8[2k, 2k, B] (bit-identical to the device)."""
    # celint: allow(layering) — byte-identity twin: the native leg must use the SAME encode matrix as the device path (ops/gf256 owns it); lazy import, no module-level edge
    from celestia_tpu.ops.gf256 import encode_matrix

    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    square = np.ascontiguousarray(square, dtype=np.uint8)
    k, B = square.shape[0], square.shape[2]
    E = np.ascontiguousarray(encode_matrix(k))
    out = np.zeros((2 * k, 2 * k, B), dtype=np.uint8)
    with _field_lock:
        _ensure_field(lib)
        lib.rs_extend_square(_ptr(square), _ptr(E), _ptr(out), k, B)
    return out


def sha256_batch(msgs: np.ndarray, nthreads: Optional[int] = None) -> np.ndarray:
    """SHA-256 over n equal-length rows, striped across the host pool."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    msgs = np.ascontiguousarray(msgs, dtype=np.uint8)
    n, length = msgs.shape
    out = np.zeros((n, 32), dtype=np.uint8)
    lib.sha256_batch_mt(_ptr(msgs), n, length, _ptr(out),
                        _resolve_threads(nthreads))
    return out


def eds_nmt_roots(eds: np.ndarray, nthreads: Optional[int] = None) -> np.ndarray:
    """uint8[2k, 2k, B] -> uint8[4k, 90] (rows then columns), the 4k
    independent trees sharded across the host pool."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    eds = np.ascontiguousarray(eds, dtype=np.uint8)
    n = eds.shape[0]
    k = n // 2
    out = np.zeros((2 * n, 90), dtype=np.uint8)
    lib.eds_nmt_roots_mt(_ptr(eds), k, eds.shape[2], _ptr(out),
                         _resolve_threads(nthreads))
    return out


def extend_block_cpu(square: np.ndarray, nthreads: Optional[int] = None):
    """Full CPU ExtendBlock: square -> (eds, axis roots, data root).

    Threaded native pipeline with the extend->roots overlap — the honest
    CPU comparison leg for bench.py (role of Leopard-RS + crypto/sha256
    in the reference, SURVEY.md §2.2).
    """
    from celestia_tpu.utils import faults

    faults.fire("native.extend")
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    # celint: allow(layering) — byte-identity twin: same encode matrix as the device path (see rs_extend_square)
    from celestia_tpu.ops.gf256 import encode_matrix

    square = np.ascontiguousarray(square, dtype=np.uint8)
    k, B = square.shape[0], square.shape[2]
    E = np.ascontiguousarray(encode_matrix(k))
    eds = np.zeros((2 * k, 2 * k, B), dtype=np.uint8)
    roots = np.zeros((4 * k, 90), dtype=np.uint8)
    data_root = np.zeros(32, dtype=np.uint8)
    with _field_lock:
        _ensure_field(lib)
        lib.extend_block_cpu(
            _ptr(square), _ptr(E), k, B, _resolve_threads(nthreads),
            _ptr(eds), _ptr(roots), _ptr(data_root),
        )
    return eds, roots, data_root


def leo_encode(data: np.ndarray) -> np.ndarray:
    """Leopard FFT encode of one axis: data uint8[k, B] -> parity
    uint8[k, B] (O(k log k); codec-independent — always the leopard
    code, used for cross-validation and the bench leg)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    data = np.ascontiguousarray(data, dtype=np.uint8)
    k, B = data.shape
    parity = np.zeros((k, B), dtype=np.uint8)
    lib.leo_encode(_ptr(data), k, B, _ptr(parity))
    return parity


def leo_extend_square(
    square: np.ndarray, nthreads: Optional[int] = None
) -> np.ndarray:
    """Leopard-codec square extension (FFT per axis): uint8[k, k, B] ->
    uint8[2k, 2k, B], quadrant layout as rs_extend_square."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    square = np.ascontiguousarray(square, dtype=np.uint8)
    k, B = square.shape[0], square.shape[2]
    eds = np.zeros((2 * k, 2 * k, B), dtype=np.uint8)
    lib.leo_extend_square_cpu(
        _ptr(square), _ptr(eds), k, B, _resolve_threads(nthreads)
    )
    return eds


def leo_decode_axes(
    data: np.ndarray, present: np.ndarray, nthreads: Optional[int] = None
) -> np.ndarray:
    """Leopard O(n log n) erasure decode, IN PLACE, threaded across axes.

    data uint8[n_axes, 2k, B]: axis rows in EDS position order with
    erased rows zeroed; present uint8[n_axes, 2k] marks received rows.
    Returns ok uint8[n_axes] (0 = fewer than k rows present).  Leopard
    codec only — the caller must hold the leopard-ff8 codec active."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    if not data.flags.c_contiguous or data.dtype != np.uint8:
        raise ValueError("data must be C-contiguous uint8 (decoded in place)")
    present = np.ascontiguousarray(present, dtype=np.uint8)
    n_axes, n, B = data.shape
    if present.shape != (n_axes, n):
        raise ValueError(f"present must be ({n_axes}, {n})")
    # the C side uses fixed 256-entry domain buffers (the field has 256
    # points); an oversized axis must fail HERE, not smash the stack
    if not (1 <= n <= 256) or n & (n - 1):
        raise ValueError(f"axis length must be a power of two <= 256, got {n}")
    ok = np.zeros(n_axes, dtype=np.uint8)
    lib.leo_decode_axes(
        _ptr(data), _ptr(present), n_axes, n, B, _ptr(ok),
        _resolve_threads(nthreads),
    )
    return ok


def extend_block_leopard_cpu(
    square: np.ndarray, nthreads: Optional[int] = None
):
    """Full CPU ExtendBlock via the Leopard O(n log n) FFT codec:
    square -> (eds, axis roots, data root).  The honest vs_leopard_cpu
    comparison leg for bench.py (the reference's codec class at full
    size, same SHA/NMT stage as extend_block_cpu)."""
    from celestia_tpu.utils import faults

    faults.fire("native.extend")
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    square = np.ascontiguousarray(square, dtype=np.uint8)
    k, B = square.shape[0], square.shape[2]
    eds = np.zeros((2 * k, 2 * k, B), dtype=np.uint8)
    roots = np.zeros((4 * k, 90), dtype=np.uint8)
    data_root = np.zeros(32, dtype=np.uint8)
    lib.extend_block_leopard_cpu(
        _ptr(square), k, B, _resolve_threads(nthreads), _ptr(eds),
        _ptr(roots), _ptr(data_root),
    )
    return eds, roots, data_root


def nmt_root(leaves: np.ndarray) -> np.ndarray:
    """Root of one NMT whose leaves are ns-prefixed payloads.

    leaves: uint8[n, leaf_len] with n a power of two -> uint8[90].
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    leaves = np.ascontiguousarray(leaves, dtype=np.uint8)
    n, leaf_len = leaves.shape
    out = np.zeros(90, dtype=np.uint8)
    lib.nmt_root(_ptr(leaves), n, leaf_len, _ptr(out))
    return out


def create_commitment(leaves: np.ndarray, sizes) -> bytes:
    """Blob share commitment in ONE native call: NMT roots of the
    mountain-range subtrees + the RFC-6962 root over them.

    leaves: uint8[n, leaf_len] ns-prefixed shares; sizes: mountain widths
    summing to n.  Replaces ~one ctypes crossing per subtree."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    leaves = np.ascontiguousarray(leaves, dtype=np.uint8)
    n, leaf_len = leaves.shape
    sizes_arr = np.ascontiguousarray(sizes, dtype=np.int32)
    out = np.zeros(32, dtype=np.uint8)
    lib.create_commitment(
        _ptr(leaves), n, leaf_len,
        sizes_arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(sizes_arr), _ptr(out),
    )
    return out.tobytes()


def create_commitments_batch(
    leaves: np.ndarray, blob_off: np.ndarray, sizes: np.ndarray,
    size_off: np.ndarray, nthreads: Optional[int] = None,
) -> np.ndarray:
    """Commitments for MANY blobs in one call: leaves uint8[total, leaf_len]
    (all blobs' ns-prefixed shares concatenated), blob_off int32[n+1] row
    offsets, sizes int32[...] mountain widths (concatenated), size_off
    int32[n+1] offsets into sizes.  Returns uint8[n, 32]."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    leaves = np.ascontiguousarray(leaves, dtype=np.uint8)
    blob_off = np.ascontiguousarray(blob_off, dtype=np.int32)
    sizes = np.ascontiguousarray(sizes, dtype=np.int32)
    size_off = np.ascontiguousarray(size_off, dtype=np.int32)
    n = len(blob_off) - 1
    out = np.zeros((n, 32), dtype=np.uint8)
    i32 = ctypes.POINTER(ctypes.c_int32)
    lib.create_commitments_batch(
        _ptr(leaves), leaves.shape[1],
        blob_off.ctypes.data_as(i32), sizes.ctypes.data_as(i32),
        size_off.ctypes.data_as(i32), n, _ptr(out),
        _resolve_threads(nthreads),
    )
    return out


def gf_matmul_axes(
    D: np.ndarray, X: np.ndarray, nthreads: Optional[int] = None
) -> np.ndarray:
    """Per-axis GF(256) matmul: D uint8[n, R, k] x X uint8[n, k, B] ->
    uint8[n, R, B] (the repair decode step, threaded)."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    D = np.ascontiguousarray(D, dtype=np.uint8)
    X = np.ascontiguousarray(X, dtype=np.uint8)
    n, R, k = D.shape
    B = X.shape[2]
    if X.shape != (n, k, B):
        raise ValueError(f"X must be ({n}, {k}, B), got {X.shape}")
    out = np.zeros((n, R, B), dtype=np.uint8)
    with _field_lock:
        _ensure_field(lib)
        lib.gf_matmul_axes(
            _ptr(D), _ptr(X), _ptr(out), n, R, k, B,
            _resolve_threads(nthreads),
        )
    return out


def ecmul_double(u1_be: bytes, u2_be: bytes, pub33: bytes):
    """(u1*G + u2*Q) affine coords, or None on infinity/invalid pubkey.

    The expensive inner op of ECDSA verification (reference relies on the
    decred C secp256k1 for this — SURVEY.md §2.2); scalar math mod the group
    order stays in Python where CPython's pow() is already C.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    u1 = np.frombuffer(u1_be, dtype=np.uint8)
    u2 = np.frombuffer(u2_be, dtype=np.uint8)
    pub = np.frombuffer(pub33, dtype=np.uint8)
    out_x = np.zeros(32, dtype=np.uint8)
    out_y = np.zeros(32, dtype=np.uint8)
    ok = lib.secp256k1_ecmul_double(
        _ptr(u1), _ptr(u2), _ptr(pub), _ptr(out_x), _ptr(out_y)
    )
    if not ok:
        return None
    return out_x.tobytes(), out_y.tobytes()


def ecmul_double_batch(
    u1s: np.ndarray, u2s: np.ndarray, pubs: np.ndarray,
    nthreads: Optional[int] = None,
):
    """Threaded batch of ecmul_double.

    u1s/u2s: uint8[n, 32] big-endian scalars; pubs: uint8[n, 33] compressed
    keys. Returns (ok uint8[n], x uint8[n, 32]).
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    u1s = np.ascontiguousarray(u1s, dtype=np.uint8)
    u2s = np.ascontiguousarray(u2s, dtype=np.uint8)
    pubs = np.ascontiguousarray(pubs, dtype=np.uint8)
    n = u1s.shape[0]
    out_x = np.zeros((n, 32), dtype=np.uint8)
    ok = np.zeros(n, dtype=np.uint8)
    lib.secp256k1_ecmul_double_batch(
        _ptr(u1s), _ptr(u2s), _ptr(pubs), n, _ptr(out_x), _ptr(ok),
        _resolve_threads(nthreads),
    )
    return ok, out_x


def has_glv() -> bool:
    return _load() is not None and _has_glv


def has_glv_pre() -> bool:
    return _load() is not None and _has_glv_pre


# below this many live verifies the _pre symbol's per-stripe table
# normalization costs more than the mixed-affine digit loop saves
_GLV_PRE_MIN_BATCH = 4


def ecmul_double_glv_batch(
    ks: np.ndarray, signs: np.ndarray, pubs: np.ndarray,
    nthreads: Optional[int] = None,
    precomp: Optional[bool] = None,
):
    """Threaded batch of GLV-split double multiplications.

    ks: uint8[n, 128] — four 32-byte big-endian scalar magnitudes per
    verify (|k1_G|, |k2_G|, |k1_Q|, |k2_Q| from utils.secp256k1._glv_split);
    signs: uint8[n, 4] (1 = negative component); pubs: uint8[n, 64]
    UNCOMPRESSED affine keys (x||y big-endian).
    Returns (ok uint8[n], x uint8[n, 32]).

    precomp — route to secp256k1_ecmul_double_glv_batch_pre, which
    normalizes every verify's Q-tables to affine with one shared
    Montgomery inversion per stripe so the digit loops run all-mixed-
    affine.  None = auto (use it when available and the batch is big
    enough to amortize the table normalization); True = force when the
    symbol exists; False = legacy Jacobian-table symbol.
    """
    lib = _load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    ks = np.ascontiguousarray(ks, dtype=np.uint8)
    signs = np.ascontiguousarray(signs, dtype=np.uint8)
    pubs = np.ascontiguousarray(pubs, dtype=np.uint8)
    n = ks.shape[0]
    out_x = np.zeros((n, 32), dtype=np.uint8)
    ok = np.zeros(n, dtype=np.uint8)
    if precomp is None:
        precomp = _has_glv_pre and n >= _GLV_PRE_MIN_BATCH
    fn = (
        lib.secp256k1_ecmul_double_glv_batch_pre
        if (precomp and _has_glv_pre)
        else lib.secp256k1_ecmul_double_glv_batch
    )
    fn(
        _ptr(ks), _ptr(signs), _ptr(pubs), n, _ptr(out_x), _ptr(ok),
        _resolve_threads(nthreads),
    )
    return ok, out_x
