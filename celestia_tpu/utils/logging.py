"""Structured logging: leveled key-value logger with plain/JSON output.

Parity role: the reference's structured loggers — comet's logger through
app.Logger(), zerolog in txsim (test/txsim/run.go:49), the --log-to-file
flag (cmd/celestia-appd/cmd/root.go:48-106), and structured
rejected-proposal logs with proposer context (app/process_proposal.go:168-188).
"""

from __future__ import annotations

import json
import sys
import threading
import time
from typing import Any, IO, Optional

LEVELS = {"debug": 10, "info": 20, "warn": 30, "error": 40}


class Logger:
    def __init__(
        self,
        level: str = "info",
        fmt: str = "plain",
        stream: Optional[IO[str]] = None,
        to_file: str = "",
        **bound: Any,
    ):
        self.level = LEVELS.get(level, 20)
        self.fmt = fmt
        self._bound = bound
        self._lock = threading.Lock()
        if to_file:
            self._stream: IO[str] = open(to_file, "a", buffering=1)
        else:
            self._stream = stream if stream is not None else sys.stderr

    def with_fields(self, **fields: Any) -> "Logger":
        child = Logger.__new__(Logger)
        child.level = self.level
        child.fmt = self.fmt
        child._bound = {**self._bound, **fields}
        child._lock = self._lock
        child._stream = self._stream
        return child

    def _log(self, level: str, msg: str, fields: dict) -> None:
        if LEVELS[level] < self.level:
            return
        record = {
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "level": level,
            "msg": msg,
            **self._bound,
            **fields,
        }
        if self.fmt == "json":
            line = json.dumps(record, default=str)
        else:
            extras = " ".join(
                f"{k}={v}" for k, v in record.items()
                if k not in ("ts", "level", "msg")
            )
            line = f"{record['ts']} {level.upper():5s} {msg}"
            if extras:
                line += f" | {extras}"
        with self._lock:
            self._stream.write(line + "\n")

    def debug(self, msg: str, **fields: Any) -> None:
        self._log("debug", msg, fields)

    def info(self, msg: str, **fields: Any) -> None:
        self._log("info", msg, fields)

    def warn(self, msg: str, **fields: Any) -> None:
        self._log("warn", msg, fields)

    def error(self, msg: str, **fields: Any) -> None:
        self._log("error", msg, fields)


_null = Logger(level="error", stream=open("/dev/null", "w"))


def null_logger() -> Logger:
    """A silenced logger for tests / library defaults."""
    return _null
