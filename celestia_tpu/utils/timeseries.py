"""Continuous telemetry: a bounded snapshot ring + declarative alerts.

The metrics plane so far is point-in-time — a scrape answers "what is
the hit rate NOW", never "has it been collapsing for five minutes" or
"when did the height stop moving".  This module adds the time axis with
the same bounded-structure discipline as the rest of the plane:

* :class:`TimeSeries` — a ring (``deque(maxlen=N)``) of periodic
  telemetry snapshots ``{"ts", "values": {name: float}}`` with
  rate/derivative queries (``rate``, ``delta``, ``rates``).  A node
  that stays up for a month holds the same few KB it held after an
  hour.
* :class:`AlertRule` / :class:`AlertEngine` — a small declarative rule
  engine over the ring.  Three kinds: ``value`` (threshold with a
  *sustained-burn* window — the predicate must hold over ``for_s``
  seconds of consecutive samples, not one noisy scrape), ``rate``
  (threshold on the per-second derivative) and ``stall`` (the metric
  has not changed for ``for_s`` — the height-stall detector).  Rules
  skip metrics a snapshot does not carry, so a CPU-only node never
  false-fires a device-memory rule and a fresh cache (no lookups yet)
  never false-fires the hit-rate floor.
* :func:`collect_node_sample` — the one snapshot builder: height,
  eds-cache hit rate, gossip breaker states, fault/degradation totals,
  trace-ring drops, device busy/occupancy + memory watermark
  (utils/devprof.py), DAS serving health (shed + samples-served
  counters, das_rows proof-cache hit rate).

Operators extend the rule set declaratively via the
``CELESTIA_TPU_ALERT_RULES`` environment variable (a JSON list of rule
objects — the schema is the :class:`AlertRule` constructor), which is
how the profile-smoke gate trips a synthetic rule without code changes.

Served by node/server.py (``TimeSeries`` RPC + sampler thread +
``celestia_tpu_alert_firing`` exposition lines), consumed by
``query timeseries`` / ``query alerts`` (cli.py) and folded into
``cluster_health`` so a degrading node is flagged across the mesh.

Clock: :func:`telemetry.clock` — the sanctioned channel.
"""

from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Dict, List, Optional

from celestia_tpu.utils.telemetry import clock

ENV_RULES = "CELESTIA_TPU_ALERT_RULES"

DEFAULT_MAX_SAMPLES = 720  # 1 h at a 5 s cadence, ~few KB resident

# default-rule thresholds (module constants so tests/docs can cite them)
EDS_HIT_RATE_FLOOR = 0.05
EDS_HIT_RATE_FOR_S = 120.0
BREAKERS_OPEN_FOR_S = 30.0
DEVICE_MEM_FRAC_CEIL = 0.9
DEVICE_MEM_FOR_S = 30.0
HEIGHT_STALL_FOR_S = 60.0
# Jain fairness over per-peer served DAS samples: below this the crowd
# is being served unfairly (hostile over-askers crowding light clients).
# for_s=0 — fairness is computed over cumulative counts, so one bad
# sample already summarizes sustained skew; the metric is skip-absent
# (only exists once an identified peer has been served), so anonymous
# traffic can never fire it.
DAS_FAIRNESS_FLOOR = 0.8


class TimeSeries:
    """Bounded ring of telemetry snapshots with derivative queries."""

    def __init__(self, max_samples: int = DEFAULT_MAX_SAMPLES):
        self._lock = threading.Lock()
        # snapshot dicts, oldest evicted first; celint: guarded-by(self._lock)
        self._samples: "deque[dict]" = deque(maxlen=max(2, int(max_samples)))

    def record(self, values: Dict[str, float], ts: Optional[float] = None) -> None:
        """Append one snapshot (``ts`` defaults to the sanctioned clock).
        Values must be a flat name -> number map; non-numeric entries
        are dropped so a buggy collector cannot poison the ring."""
        clean = {
            k: float(v)
            for k, v in values.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }
        snap = {"ts": float(ts if ts is not None else clock()), "values": clean}
        with self._lock:
            self._samples.append(snap)

    def samples(self, last: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._samples)
        if last is not None:
            out = out[-max(0, int(last)):]
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._samples)

    @property
    def max_samples(self) -> int:
        return self._samples.maxlen or DEFAULT_MAX_SAMPLES

    def clear(self) -> None:
        with self._lock:
            self._samples.clear()

    # -- queries -------------------------------------------------------

    def _points(self, name: str, window_s: Optional[float]) -> List[tuple]:
        pts = [
            (s["ts"], s["values"][name])
            for s in self.samples()
            if name in s["values"]
        ]
        if window_s is not None and pts:
            cutoff = pts[-1][0] - float(window_s)
            pts = [p for p in pts if p[0] >= cutoff]
        return pts

    def latest(self, name: str):
        pts = self._points(name, None)
        return pts[-1][1] if pts else None

    def delta(self, name: str, window_s: Optional[float] = None):
        """last - first over the window; None with <2 points."""
        pts = self._points(name, window_s)
        if len(pts) < 2:
            return None
        return pts[-1][1] - pts[0][1]

    def rate(self, name: str, window_s: Optional[float] = None):
        """Per-second derivative (last-first)/dt over the window; None
        with <2 points or a zero time span."""
        pts = self._points(name, window_s)
        if len(pts) < 2:
            return None
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0:
            return None
        return (pts[-1][1] - pts[0][1]) / dt

    def rates(self, window_s: Optional[float] = None) -> Dict[str, float]:
        """Per-second derivative of EVERY metric with >=2 points — the
        ``query timeseries`` "computed rates" section."""
        names: Dict[str, None] = {}
        for s in self.samples():
            for k in s["values"]:
                names.setdefault(k)
        out: Dict[str, float] = {}
        for name in names:
            r = self.rate(name, window_s)
            if r is not None:
                out[name] = round(r, 6)
        return out


# ---------------------------------------------------------------------------
# alert rules
# ---------------------------------------------------------------------------

_OPS = {
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
    "==": lambda v, t: v == t,
}

_KINDS = ("value", "rate", "stall")


class AlertRule:
    """One declarative rule.  ``kind``:

    * ``value`` — fires when the trailing run of consecutive samples
      satisfying ``<metric> <op> <threshold>`` spans >= ``for_s``
      seconds (``for_s=0``: the latest sample alone decides) —
      sustained-burn, not single-scrape noise.
    * ``rate`` — fires when the per-second derivative over the last
      ``for_s`` seconds (whole ring when 0) satisfies the predicate.
    * ``stall`` — fires when the metric has not CHANGED for >= ``for_s``
      seconds (>= 2 samples required); ``op``/``threshold`` unused.
    """

    __slots__ = ("name", "metric", "op", "threshold", "kind", "for_s", "severity")

    def __init__(
        self,
        name: str,
        metric: str,
        op: str = ">",
        threshold: float = 0.0,
        kind: str = "value",
        for_s: float = 0.0,
        severity: str = "warning",
    ):
        if kind not in _KINDS:
            raise ValueError(f"unknown alert kind {kind!r} (expected {_KINDS})")
        if op not in _OPS:
            raise ValueError(f"unknown alert op {op!r} (expected {tuple(_OPS)})")
        self.name = str(name)
        self.metric = str(metric)
        self.op = op
        self.threshold = float(threshold)
        self.kind = kind
        self.for_s = max(0.0, float(for_s))
        self.severity = str(severity)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "metric": self.metric,
            "op": self.op,
            "threshold": self.threshold,
            "kind": self.kind,
            "for_s": self.for_s,
            "severity": self.severity,
        }

    def _pred(self, v: float) -> bool:
        return _OPS[self.op](v, self.threshold)

    def evaluate(self, series: TimeSeries) -> dict:
        out = dict(self.to_dict())
        out.update({"firing": False, "value": None, "held_s": 0.0})
        pts = series._points(self.metric, None)
        if not pts:
            return out  # metric absent from every snapshot: never fires
        out["value"] = pts[-1][1]
        if self.kind == "rate":
            r = series.rate(self.metric, self.for_s or None)
            out["value"] = r
            out["firing"] = r is not None and self._pred(r)
            return out
        if self.kind == "stall":
            if len(pts) < 2:
                return out
            latest = pts[-1][1]
            # the stall clock starts at the FIRST sample of the trailing
            # flat run (the ring's start when every sample is flat)
            since = pts[-1][0]
            for ts, v in reversed(pts[:-1]):
                if v != latest:
                    break
                since = ts
            held = pts[-1][0] - since
            out["held_s"] = round(held, 3)
            out["firing"] = held >= self.for_s
            return out
        # value: trailing consecutive run satisfying the predicate
        run_start = None
        for ts, v in reversed(pts):
            if self._pred(v):
                run_start = ts
            else:
                break
        if run_start is None:
            return out
        held = pts[-1][0] - run_start
        out["held_s"] = round(held, 3)
        out["firing"] = held >= self.for_s
        return out


class AlertEngine:
    """An ordered rule set evaluated against one TimeSeries."""

    def __init__(self, rules: Optional[List[AlertRule]] = None):
        self._lock = threading.Lock()
        # celint: guarded-by(self._lock)
        self._rules: List[AlertRule] = list(rules or [])

    def add_rule(self, rule: AlertRule) -> None:
        with self._lock:
            self._rules.append(rule)

    def rules(self) -> List[AlertRule]:
        with self._lock:
            return list(self._rules)

    def evaluate(self, series: TimeSeries) -> List[dict]:
        return [r.evaluate(series) for r in self.rules()]

    def firing(self, series: TimeSeries) -> List[dict]:
        return [a for a in self.evaluate(series) if a["firing"]]


def default_rules() -> List[AlertRule]:
    """The stock rule set every node serves (thresholds are the module
    constants above; each rule self-disables on platforms whose
    snapshots lack its metric)."""
    return [
        AlertRule(
            "eds_cache_hit_rate_floor",
            metric="eds_cache_hit_rate",
            op="<",
            threshold=EDS_HIT_RATE_FLOOR,
            for_s=EDS_HIT_RATE_FOR_S,
            severity="warning",
        ),
        AlertRule(
            "breakers_open",
            metric="breakers_open",
            op=">",
            threshold=0,
            for_s=BREAKERS_OPEN_FOR_S,
            severity="warning",
        ),
        AlertRule(
            # keyed on CURRENT usage (device_mem_frac), sustained: the
            # lifetime peak_frac never falls, so a rule on it would
            # latch critical forever off one transient spike
            "device_mem_watermark",
            metric="device_mem_frac",
            op=">",
            threshold=DEVICE_MEM_FRAC_CEIL,
            for_s=DEVICE_MEM_FOR_S,
            severity="critical",
        ),
        AlertRule(
            "height_stall",
            metric="height",
            kind="stall",
            for_s=HEIGHT_STALL_FOR_S,
            severity="critical",
        ),
        AlertRule(
            "degradations",
            metric="degradations",
            op=">",
            threshold=0,
            for_s=0.0,
            severity="warning",
        ),
        AlertRule(
            # swarm fairness collapse (hostile over-askers starving the
            # light tier): trips the flight recorder into an incident
            # bundle — see specs/da_serving.md "QoS lanes & per-peer
            # accounting" for the fairness definition
            "das_fairness_floor",
            metric="das_fairness_index",
            op="<",
            threshold=DAS_FAIRNESS_FLOOR,
            for_s=0.0,
            severity="warning",
        ),
    ]


def rules_from_json(text: str) -> List[AlertRule]:
    """Parse a JSON list of rule objects (the AlertRule constructor
    schema).  Raises ValueError on malformed input — rule configuration
    errors must be loud at boot, not silent at the first incident."""
    try:
        docs = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(f"alert rules are not valid JSON: {e}")
    if not isinstance(docs, list):
        raise ValueError("alert rules must be a JSON LIST of rule objects")
    out = []
    for i, doc in enumerate(docs):
        if not isinstance(doc, dict) or "name" not in doc or "metric" not in doc:
            raise ValueError(f"alert rule [{i}] needs at least name+metric")
        allowed = {
            "name", "metric", "op", "threshold", "kind", "for_s", "severity",
        }
        unknown = set(doc) - allowed
        if unknown:
            raise ValueError(f"alert rule [{i}] has unknown keys {sorted(unknown)}")
        out.append(AlertRule(**doc))
    return out


def rules_from_env() -> List[AlertRule]:
    """Operator-declared extra rules (CELESTIA_TPU_ALERT_RULES)."""
    raw = os.environ.get(ENV_RULES, "").strip()
    if not raw:
        return []
    return rules_from_json(raw)


# ---------------------------------------------------------------------------
# SLO plane: per-phase latency budgets + dual-window burn-rate alerts
# ---------------------------------------------------------------------------

ENV_SLO = "CELESTIA_TPU_SLO"

# Stock budgets for the block lifecycle (scorecard observations recorded
# by node/server.py): generous for the tiny-k dev path, meaningful for a
# production square.  The objective is the fraction of observations that
# must land under budget; burn rate = breach fraction / error budget.
BLOCK_E2E_BUDGET_MS = 2000.0
PROPAGATION_BUDGET_MS = 250.0
SLO_OBJECTIVE = 0.99
# Dual windows (classic multiwindow burn-rate alerting): the FAST window
# at a high burn threshold catches spikes within a couple of samples;
# the SLOW window at a low threshold catches budgets bleeding out over
# minutes.  Either tripping fires the SLO.
SLO_FAST_WINDOW_S = 60.0
SLO_SLOW_WINDOW_S = 600.0
SLO_FAST_BURN = 14.0
SLO_SLOW_BURN = 2.0


class SLO:
    """One latency budget evaluated by dual-window burn rate.

    Observations are latency samples (ms) in the node TimeSeries (e.g.
    ``block_e2e_ms`` recorded per committed height).  A sample over
    ``budget_ms`` is a breach; breach fraction over a trailing window
    divided by the error budget (1 - objective) is the burn rate.
    Firing when EITHER window exceeds its threshold; the verdict dict is
    AlertRule-shaped (``name``/``firing``/``severity``/``value``) so
    firing transitions ride the existing flight-recorder path
    unchanged.  Skip-absent contract: a metric with no points in the
    slow window never fires.
    """

    __slots__ = (
        "name",
        "metric",
        "budget_ms",
        "objective",
        "fast_window_s",
        "slow_window_s",
        "fast_burn",
        "slow_burn",
        "severity",
    )

    def __init__(
        self,
        name: str,
        *,
        metric: str,
        budget_ms: float,
        objective: float = SLO_OBJECTIVE,
        fast_window_s: float = SLO_FAST_WINDOW_S,
        slow_window_s: float = SLO_SLOW_WINDOW_S,
        fast_burn: float = SLO_FAST_BURN,
        slow_burn: float = SLO_SLOW_BURN,
        severity: str = "critical",
    ):
        if not name or not metric:
            raise ValueError("SLO needs a name and a metric")
        if budget_ms <= 0:
            raise ValueError(f"SLO {name}: budget_ms must be positive")
        if not (0.0 < objective < 1.0):
            raise ValueError(f"SLO {name}: objective must be in (0, 1)")
        if fast_window_s <= 0 or slow_window_s <= 0:
            raise ValueError(f"SLO {name}: windows must be positive")
        self.name = name
        self.metric = metric
        self.budget_ms = float(budget_ms)
        self.objective = float(objective)
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_burn = float(slow_burn)
        self.severity = severity

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "metric": self.metric,
            "kind": "slo",
            "budget_ms": self.budget_ms,
            "objective": self.objective,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "fast_burn": self.fast_burn,
            "slow_burn": self.slow_burn,
            "severity": self.severity,
        }

    def _burn(self, pts: List[tuple]):
        if not pts:
            return None
        breach = sum(1 for _, v in pts if v > self.budget_ms)
        return (breach / len(pts)) / max(1e-9, 1.0 - self.objective)

    def evaluate(self, series: TimeSeries) -> dict:
        out = dict(self.to_dict())
        out.update(
            {"firing": False, "value": None, "burn_fast": None, "burn_slow": None,
             "window": ""}
        )
        slow_pts = series._points(self.metric, self.slow_window_s)
        if not slow_pts:
            return out  # metric absent: never fires
        fast_pts = series._points(self.metric, self.fast_window_s)
        bf = self._burn(fast_pts)
        bs = self._burn(slow_pts)
        out["burn_fast"] = None if bf is None else round(bf, 3)
        out["burn_slow"] = None if bs is None else round(bs, 3)
        out["value"] = out["burn_fast"] if bf is not None else out["burn_slow"]
        fast_hit = bf is not None and bf >= self.fast_burn
        slow_hit = bs is not None and bs >= self.slow_burn
        out["firing"] = fast_hit or slow_hit
        out["window"] = "fast" if fast_hit else ("slow" if slow_hit else "")
        return out


def default_slos() -> List[SLO]:
    """The stock block-lifecycle SLOs (scorecard-fed metrics)."""
    return [
        SLO(
            "block_e2e_slo",
            metric="block_e2e_ms",
            budget_ms=BLOCK_E2E_BUDGET_MS,
            severity="critical",
        ),
        SLO(
            "propagation_slo",
            metric="block_propagation_ms",
            budget_ms=PROPAGATION_BUDGET_MS,
            severity="warning",
        ),
    ]


def slos_from_json(text: str) -> List[SLO]:
    """Parse a JSON list of SLO objects (the SLO constructor schema).
    Raises ValueError on malformed input — budget configuration errors
    must be loud at boot, not silent at the first breach."""
    try:
        docs = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(f"SLO config is not valid JSON: {e}")
    if not isinstance(docs, list):
        raise ValueError("SLO config must be a JSON LIST of SLO objects")
    allowed = {
        "name", "metric", "budget_ms", "objective", "fast_window_s",
        "slow_window_s", "fast_burn", "slow_burn", "severity",
    }
    out = []
    for i, doc in enumerate(docs):
        if not isinstance(doc, dict) or "name" not in doc or "metric" not in doc:
            raise ValueError(f"SLO [{i}] needs at least name+metric")
        unknown = set(doc) - allowed
        if unknown:
            raise ValueError(f"SLO [{i}] has unknown keys {sorted(unknown)}")
        if "budget_ms" not in doc:
            raise ValueError(f"SLO [{i}] needs budget_ms")
        kw = dict(doc)
        out.append(SLO(kw.pop("name"), **kw))
    return out


def effective_slos() -> List[SLO]:
    """Stock SLOs with operator overrides applied (CELESTIA_TPU_SLO).

    An env SLO whose name matches a stock one REPLACES it (that is the
    override path); unmatched names append.  Malformed JSON raises —
    same loud-at-boot contract as ``rules_from_json``.
    """
    slos = default_slos()
    raw = os.environ.get(ENV_SLO, "").strip()
    if not raw:
        return slos
    by_name = {s.name: i for i, s in enumerate(slos)}
    for s in slos_from_json(raw):
        if s.name in by_name:
            slos[by_name[s.name]] = s
        else:
            slos.append(s)
    return slos


# ---------------------------------------------------------------------------
# the node snapshot collector
# ---------------------------------------------------------------------------


def collect_node_sample(node) -> Dict[str, float]:
    """One flat snapshot of a node's operational signals.  Metrics a
    platform cannot answer are OMITTED (not zeroed): the alert engine's
    skip-absent contract depends on it."""
    from celestia_tpu.utils import devprof, faults, lru, tracing

    values: Dict[str, float] = {}
    values["height"] = float(getattr(node, "height", 0) or 0)
    # unified cache registry: the eds hit rate is the flagship signal;
    # it is omitted until the cache has seen a counted lookup
    reg = lru.registry_stats()
    eds = reg["caches"].get("eds")
    if eds is not None and (eds["hits"] + eds["misses"]) > 0:
        values["eds_cache_hit_rate"] = float(eds["hit_rate"])
    values["cache_total_bytes"] = float(reg["total_approx_bytes"])
    # robustness ladder totals
    fs = faults.fault_stats()
    values["degradations"] = float(len(fs["degradations"]))
    values["fault_notes"] = float(
        sum(v["count"] for v in fs["notes"].values())
    )
    # gossip breakers (meshed nodes only)
    eng = getattr(node, "gossip_engine", None)
    if eng is not None:
        try:
            breakers = eng.stats().get("pull_breakers", {})
            values["breakers_open"] = float(
                sum(1 for s in breakers.values() if s != "closed")
            )
        except Exception as e:
            faults.note("timeseries.breakers", e)
    # trace-ring truncation (satellite: remote detectability)
    rs = tracing.ring_stats()
    values["trace_span_drops"] = float(rs["span_drops_total"])
    values["trace_background_depth"] = float(rs["background_depth"])
    # device plane — ONLY when dispatch bracketing is armed (tracing on
    # or a collect window open): with the bracket off nothing measures
    # busy time, and recording a hard 0.0 would read as "device idle"
    # to every occupancy alert while the chip is fully loaded.  Absent
    # means unknown; zero means measured-idle (skip-absent contract).
    # Occupancy is the INTER-PROBE delta (devprof.occupancy_probe) — the
    # since-reset aggregate decays toward zero on a long-lived node and
    # would make every alert on it meaningless; the first armed sample
    # omits it (no previous probe), like every platform-absent metric.
    if devprof.active():
        prof = devprof.device_profile()
        values["device_busy_ms_total"] = float(prof["device_busy_ms_total"])
        occ = devprof.occupancy_probe()
        if occ is not None:
            values["device_occupancy_pct"] = float(occ)
        mem = prof["mem"]
        if isinstance(mem, dict) and mem.get("bytes_in_use") is not None:
            values["device_mem_bytes_in_use"] = float(mem["bytes_in_use"])
            values["device_mem_peak_bytes"] = float(mem["peak_bytes_in_use"])
            # frac (CURRENT usage / limit) is the alertable signal —
            # peak_frac is a monotone lifetime high-water mark jax never
            # lowers, so a rule on it could fire forever off one spike
            if "frac" in mem:
                values["device_mem_frac"] = float(mem["frac"])
            if "peak_frac" in mem:
                values["device_mem_peak_frac"] = float(mem["peak_frac"])
    # serving-plane pressure + throughput: shed and served counters so
    # the stock rate rules can watch serving health, plus the das_rows
    # hit rate (omitted until the cache has seen a counted lookup —
    # same skip-absent contract as the eds rate)
    app = getattr(node, "app", None)
    telemetry = getattr(app, "telemetry", None)
    if telemetry is not None:
        counters, _g, _t = telemetry._snapshot()
        values["das_shed"] = float(
            counters.get("das_sample_shed", 0)
            + counters.get("das_batch_shed", 0)
        )
        values["das_samples_served"] = float(
            counters.get("das_samples_served", 0)
        )
        values["blocks_prepared"] = float(
            counters.get("eds_cache_hit_prepare", 0)
            + counters.get("eds_cache_miss_prepare", 0)
        )
    das_rows = reg["caches"].get("das_rows")
    if das_rows is not None and (das_rows["hits"] + das_rows["misses"]) > 0:
        values["das_rows_hit_rate"] = float(das_rows["hit_rate"])
    # per-peer QoS plane (node/server.py NodeService backref): gate +
    # per-lane pressure and the Jain fairness index.  Fairness is
    # skip-absent — it only exists once an identified peer has been
    # served, so the stock das_fairness_floor rule self-disables on
    # nodes serving purely anonymous traffic
    svc = getattr(node, "_das_service", None)
    if svc is not None:
        gate = svc.das_gate.stats()
        values["das_gate_inflight"] = float(gate["inflight"])
        values["das_gate_shed"] = float(gate["shed"])
        for lane, lst in (gate.get("lanes") or {}).items():
            values[f"das_lane_inflight_{lane}"] = float(lst["inflight"])
            values[f"das_lane_shed_{lane}"] = float(lst["shed"])
        fairness = svc.das_peers.fairness_index()
        if fairness is not None:
            values["das_fairness_index"] = float(fairness)
    return values
