"""Runtime lock-order shadow checker: the dynamic half of celint R6.

The static pass (celestia_tpu/lint/lockorder.py) derives a lock-
acquisition graph from source; this module records the orders the
process ACTUALLY acquires locks in, so the tier-1 concurrency hammers
(tests/test_race.py, tests/test_lru.py — `make lockwatch`) validate the
static graph with execution instead of trusting it:

* an **inversion** — some thread acquired A then B while some thread
  acquired B then A — is detected the moment the second order is
  observed and reported through ``faults.note("lockwatch.inversion")``
  with BOTH acquisition stacks (the two sides of the would-be deadlock);
* the observed pair set is exportable (:func:`observed_pairs`, keyed by
  lock CONSTRUCTION site ``(repo-relative file, line)``) so
  ``lint.lockorder.runtime_crosscheck`` can join it against the static
  graph — an execution order contradicting the derived hierarchy fails
  even when no second thread happened to race the opposite order.

**Arming.**  ``CELESTIA_TPU_LOCKWATCH=1`` in the environment installs
the watcher at ``celestia_tpu`` import time (before any module-level
lock is constructed) and arms it; the chaos fixture arms an
already-installed watcher per-test.  Installation replaces
``threading.Lock``/``threading.RLock`` with factories that wrap ONLY
locks constructed from files inside the package (the construction site
is how observations join back to static identities — a stdlib or jax
lock has none); everything else receives the real primitive untouched.
Disarmed and uninstalled — every production run — the module costs
nothing: no factory is installed, no import-time work happens beyond
one environment check.

**Self-instrumentation hazard.**  The reporter itself uses locks
(its own bookkeeping lock, and ``faults._lock`` inside ``note``).  The
bookkeeping lock is created from the saved REAL constructor so it is
never watched, and a thread-local re-entrancy guard keeps the
``faults.note`` call from recursing into pair recording while a report
is being filed.
"""

from __future__ import annotations

import os
import sys
import threading
import traceback
from typing import Dict, List, Optional, Tuple

ENV = "CELESTIA_TPU_LOCKWATCH"

# saved BEFORE install() ever swaps the module attributes
_real_lock_ctor = threading.Lock
_real_rlock_ctor = threading.RLock

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_ROOT)

_installed = False
_armed = False
_state_lock = _real_lock_ctor()  # deliberately unwatched (see docstring)
# (site_a, site_b) -> acquisition stack of the B-acquire that created the
# pair; sites are (repo-relative path, line) construction sites
_pairs: Dict[Tuple[Tuple[str, int], Tuple[str, int]], str] = {}  # celint: guarded-by(_state_lock)
_inversions: List[dict] = []  # celint: guarded-by(_state_lock)
# lock-free fast-path dedup: a pair already seen skips the stack capture
# entirely (benign race: a duplicate capture is re-deduped under the lock)
_seen_fast: set = set()

_tls = threading.local()

Site = Tuple[str, int]


class WatchedLock:
    """A wrapped threading.Lock/RLock that records acquisition order
    while the watcher is armed.  ``site`` is the construction site the
    static analysis knows this lock by."""

    __slots__ = ("_real", "site", "reentrant")

    def __init__(self, real, site: Site, reentrant: bool):
        self._real = real
        self.site = site
        self.reentrant = reentrant

    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._real.acquire(blocking, timeout)
        if ok and _armed:
            _on_acquired(self)
        return ok

    def release(self) -> None:
        # balance the held list even while DISARMED: a lock acquired
        # armed and released across a disarm window would otherwise
        # linger in _tls.held and fabricate pairs after re-arming
        _on_released(self)
        self._real.release()

    def __enter__(self) -> "WatchedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        probe = getattr(self._real, "locked", None)
        return bool(probe()) if callable(probe) else False

    def __repr__(self) -> str:
        return f"<WatchedLock {self.site[0]}:{self.site[1]}>"


def _held() -> List[WatchedLock]:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
    return h


def _on_acquired(lock: WatchedLock) -> None:
    held = _held()
    if any(h is lock for h in held):
        held.append(lock)  # reentrant reacquire: balance releases only
        return
    fresh = [
        (h.site, lock.site)
        for h in held
        if h.site != lock.site and (h.site, lock.site) not in _seen_fast
    ]
    held.append(lock)
    if not fresh or getattr(_tls, "in_hook", False):
        return
    _tls.in_hook = True
    try:
        stack = "".join(traceback.format_stack(limit=16)[:-1])
        new_inversions: List[dict] = []
        with _state_lock:
            for pair in fresh:
                _seen_fast.add(pair)
                if pair in _pairs:
                    continue
                _pairs[pair] = stack
                rev = (pair[1], pair[0])
                if rev in _pairs:
                    new_inversions.append(
                        {
                            "first": pair[0],
                            "second": pair[1],
                            "stack_ab": stack,
                            "stack_ba": _pairs[rev],
                        }
                    )
                    _inversions.append(new_inversions[-1])
        for inv in new_inversions:
            _report_inversion(inv)
    finally:
        _tls.in_hook = False


def _report_inversion(inv: dict) -> None:
    from celestia_tpu.utils import faults

    a = "%s:%d" % inv["first"]
    b = "%s:%d" % inv["second"]
    faults.note(
        "lockwatch.inversion",
        RuntimeError(
            f"lock-order inversion: {a} -> {b} and {b} -> {a} both "
            "observed (full stacks in lockwatch.inversions())"
        ),
    )


def _on_released(lock: WatchedLock) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] is lock:
            del held[i]
            return
    # acquired before arming (or across a disarm): nothing to balance


# ---------------------------------------------------------------------------
# construction-site wrapping
# ---------------------------------------------------------------------------


def _caller_site() -> Optional[Site]:
    """(repo-relative path, line) of the first frame outside this module
    — None when the construction is not from inside the package (that
    lock has no static identity and stays unwatched)."""
    f = sys._getframe(2)
    while f is not None and f.f_code.co_filename == __file__:
        f = f.f_back
    if f is None:
        return None
    path = f.f_code.co_filename
    if not path.startswith(_PKG_ROOT + os.sep):
        return None
    rel = os.path.relpath(path, _REPO_ROOT).replace(os.sep, "/")
    return (rel, f.f_lineno)


def _make_lock():
    real = _real_lock_ctor()
    site = _caller_site()
    if site is None:
        return real
    return WatchedLock(real, site, reentrant=False)


def _make_rlock():
    real = _real_rlock_ctor()
    site = _caller_site()
    if site is None:
        return real
    return WatchedLock(real, site, reentrant=True)


def watched(reentrant: bool = False, site: Optional[Site] = None) -> WatchedLock:
    """Explicitly construct a watched lock (unit tests inject deliberate
    inversions without installing the global factories)."""
    real = _real_rlock_ctor() if reentrant else _real_lock_ctor()
    if site is None:
        f = sys._getframe(1)
        site = (
            os.path.relpath(f.f_code.co_filename, _REPO_ROOT).replace(os.sep, "/"),
            f.f_lineno,
        )
    return WatchedLock(real, site, reentrant)


# ---------------------------------------------------------------------------
# lifecycle
# ---------------------------------------------------------------------------


def install() -> None:
    """Swap threading.Lock/RLock for the site-filtered factories.  Call
    BEFORE package modules construct their module-level locks (the
    package __init__ does, when the environment arms it)."""
    global _installed
    if _installed:
        return
    threading.Lock = _make_lock
    threading.RLock = _make_rlock
    _installed = True


def installed() -> bool:
    return _installed


def arm() -> None:
    global _armed
    _armed = True


def disarm() -> None:
    global _armed
    _armed = False


def armed() -> bool:
    return _armed


def install_from_env() -> None:
    if os.environ.get(ENV, "").strip():
        install()
        arm()


def reset() -> None:
    with _state_lock:
        _pairs.clear()
        _inversions.clear()
    _seen_fast.clear()


def observed_pairs() -> Dict[Tuple[Site, Site], str]:
    with _state_lock:
        return dict(_pairs)


def inversions() -> List[dict]:
    with _state_lock:
        return [dict(i) for i in _inversions]


def report() -> str:
    """Human-readable summary: every inversion with its two stacks."""
    invs = inversions()
    if not invs:
        with _state_lock:
            n = len(_pairs)
        return f"lockwatch: no inversions ({n} ordered pair(s) observed)"
    lines = [f"lockwatch: {len(invs)} lock-order inversion(s)"]
    for inv in invs:
        a = "%s:%d" % inv["first"]
        b = "%s:%d" % inv["second"]
        lines.append(f"--- {a} -> {b} observed here:")
        lines.append(inv["stack_ab"].rstrip())
        lines.append(f"--- and {b} -> {a} observed here:")
        lines.append(inv["stack_ba"].rstrip())
    return "\n".join(lines)
