"""Process-wide host worker pool for the CPU DA pipeline.

Every host-side leg of the DA path (native NMT/SHA hashing, the Leopard
erasure decode, the pure-Python fallbacks) fans out over ONE shared pool
so the node never oversubscribes the machine: N subsystems each spawning
``os.cpu_count()`` threads would thrash; one pool sized once does not.

Thread-count resolution order (first match wins):

1. an explicit :func:`set_cpu_threads` call (the ``--cpu-threads`` CLI
   flag routes here);
2. the ``CELESTIA_TPU_CPU_THREADS`` environment variable;
3. ``os.cpu_count()``.

The native C++ entry points take the resolved count as an ``nthreads``
argument (they spawn their own short-lived ``std::thread`` teams — cheap
relative to the multi-ms work items); the :class:`ThreadPoolExecutor`
from :func:`get_pool` serves the pure-Python legs, where hashlib/numpy
release the GIL.
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, List, Optional

_lock = threading.Lock()
_pool: Optional[ThreadPoolExecutor] = None
_pool_size = 0
_override: Optional[int] = None
_respawns = 0  # pools rebuilt after an observed worker death


def set_cpu_threads(n: Optional[int]) -> None:
    """Pin the pool size (``--cpu-threads``); ``None`` clears the pin.

    Takes effect for every subsequent :func:`cpu_threads` /
    :func:`get_pool` call; an existing pool is rebuilt lazily."""
    global _override
    if n is not None and n < 1:
        raise ValueError(f"cpu threads must be >= 1, got {n}")
    with _lock:
        _override = n


def cpu_threads() -> int:
    """The host worker count every CPU DA leg should use."""
    with _lock:
        if _override is not None:
            return _override
    env = os.environ.get("CELESTIA_TPU_CPU_THREADS", "").strip()
    if env:
        try:
            n = int(env)
            if n >= 1:
                return n
        except ValueError:
            pass  # malformed env var: fall through to the default
    return os.cpu_count() or 1


def get_pool() -> ThreadPoolExecutor:
    """The shared executor, (re)built to the current cpu_threads()."""
    global _pool, _pool_size
    n = cpu_threads()
    with _lock:
        if _pool is None or _pool_size != n:
            # the replaced executor is NOT shut down: a concurrent caller
            # may hold it between its get_pool() and .map(), and
            # scheduling on a shut-down executor raises.  It simply
            # drains and idles — a resize is a rare config-time event,
            # and parked workers cost nothing.
            _pool = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="celestia-host"
            )
            _pool_size = n
        return _pool


def heal_pool() -> None:
    """Drop the executor after an observed worker death so the next
    :func:`get_pool` builds a fresh one.  The broken executor is not
    shut down (other callers may hold it mid-map; its surviving workers
    drain and idle) — the point is that NEW work lands on healthy
    threads."""
    global _pool, _pool_size, _respawns
    with _lock:
        _pool = None
        _pool_size = 0
        _respawns += 1


def stats() -> dict:
    with _lock:
        return {"pool_size": _pool_size, "respawns": _respawns}


def run_sharded(fn: Callable, items: Iterable) -> List:
    """Map ``fn`` over ``items`` on the shared pool, preserving order.

    Runs inline for a single worker or a single item (no pool overhead,
    and results stay deterministic either way — callers rely on the
    threaded path being byte-identical to the serial one).  The first
    worker exception propagates to the submitter (celint R5: pooled work
    never fails silently).

    Worker-death recovery: a :class:`faults.WorkerDeath` (the
    hostpool.worker fault point — the observable stand-in for a worker
    thread dying) marks the pool for rebuild and the lost items re-run
    inline, so a dead worker costs latency, never results.

    Tracing: when the block-lifecycle tracer is enabled AND the caller
    sits inside a span, every item gets a ``hostpool.queue_wait`` span
    (submit -> pick-up: the time the item sat behind other work — the
    visible form of a pipeline tail) and a ``hostpool.task`` run span,
    both parented to the SUBMITTING thread's span (contextvars do not
    cross pool threads; the parent is captured here explicitly)."""
    from celestia_tpu.utils import faults, tracing

    items = list(items)
    if cpu_threads() <= 1 or len(items) <= 1:
        return [fn(x) for x in items]

    parent = tracing.current()  # None when disabled or outside any span
    if parent is not None:
        from celestia_tpu.utils.telemetry import clock as _clock

        # queue-wait spans live on the SUBMITTER's track: they start at
        # submit time, and stamping the worker's tid would overlap that
        # worker's own run spans from earlier items
        submitter = threading.current_thread()
        sub_tid, sub_name = submitter.ident or 0, submitter.name

        def _submit(i, x):
            t_submit = _clock()

            def _traced():
                tracing.record_span(
                    "hostpool.queue_wait", t_submit, _clock(),
                    parent=parent, cat="hostpool", index=i,
                    tid=sub_tid, thread_name=sub_name,
                    # waits overlap each other on the submitter's track
                    # (shared submit instant, staggered pick-ups):
                    # async b/e export is the format's overlap mechanism
                    render_async=True,
                )
                with tracing.span(
                    "hostpool.task", parent=parent, cat="hostpool", index=i
                ):
                    faults.fire("hostpool.worker")
                    return fn(x)

            return get_pool().submit(_traced)

        futures = [_submit(i, x) for i, x in enumerate(items)]
    else:
        def _guarded(x):
            faults.fire("hostpool.worker")
            return fn(x)

        futures = [get_pool().submit(_guarded, x) for x in items]
    out: List = []
    lost: List[int] = []
    for i, fut in enumerate(futures):
        try:
            out.append(fut.result())
        except faults.WorkerDeath as e:
            faults.note("hostpool.worker", e)
            out.append(None)
            lost.append(i)
    if lost:
        heal_pool()  # queued work on the old pool still drains
        for i in lost:
            out[i] = fn(items[i])  # the item is never lost
    return out
