"""Block-lifecycle span tracing: where inside a block does the time go.

The telemetry layer (utils/telemetry.py) answers "how long do prepares
take on average"; it cannot answer "why was THIS prepare slow" — the
question every ROADMAP perf item (streaming proposer, the phase-3
column-root tail, the DAS serving plane) stalls on.  This module is the
per-height, per-phase structure: a thread-aware span tracer whose output
opens directly in Perfetto (chrome://tracing JSON), ring-buffered over
the last N blocks and exported over gRPC (node/server.py ``TraceDump``).

Design constraints, in order:

* **Near-zero overhead disabled.**  Every public entry checks one module
  bool first; ``span()`` returns a shared no-op context manager, and no
  clock is read, no object allocated, no lock taken.  The <50 ms
  PrepareProposal gate must not notice a disabled tracer.
* **Deterministic ids.**  Span ids come from one process-wide
  ``itertools.count`` — never ``random`` or wall-clock bits — so the
  tracer passes celint R3 (consensus-determinism: the sanctioned-channel
  list names this module) and two runs of the same block sequence
  produce structurally identical trees (tests/test_tracing.py pins it).
* **Thread-aware.**  Parent linkage rides a :mod:`contextvars` variable,
  which follows the logical call stack per thread; work fanned to the
  hostpool carries its parent EXPLICITLY (the submitting thread's
  current span), so per-task queue-wait + run spans nest under the phase
  that scheduled them and the phase-3 tail becomes a visible gap.
* **Bounded memory.**  Completed block traces live in a
  ``deque(maxlen=N)``; each block keeps at most ``MAX_SPANS_PER_BLOCK``
  spans (overflow is counted, never silently ignored); background spans
  (gossip rounds, DAS samples, snapshot chunk fetches — work that
  belongs to no block) live in their own bounded ring.

Clock: durations are measured through :func:`telemetry.clock` — the one
sanctioned wall-clock channel (celint R3) — and only ever feed
telemetry/trace output, never consensus bytes.

Cross-node tracing (PR 9): the workload is inherently multi-node — one
block's causal chain is the proposer's prepare, every validator's
process, gossip dissemination and DAS serving, split across machines.
This module therefore also carries:

* a stable **node id** (:func:`set_node_id`; ``CELESTIA_TPU_NODE_ID`` or
  the gRPC bind address) stamped onto every exported event, so merged
  timelines attribute spans/faults to the right machine;
* a compact **wire trace context** (:func:`wire_context` — origin node
  id, parent span id, height, send timestamp) that rides cross-node RPC
  envelopes as an optional ``"_tc"`` field old peers silently ignore;
  the receiving side opens an :func:`rpc_span` that records the remote
  parent EXPLICITLY (``remote_node``/``remote_span`` args — local span
  ids are per-process, so cross-node parentage is by (node, span) pair,
  resolved into Chrome flow events by ``tools/trace_merge.py``);
* a **clock-offset probe** (:func:`estimate_clock_offset` — RPC midpoint
  method over this module's sanctioned clock) so N nodes' dumps merge
  onto one aligned timeline.
"""

from __future__ import annotations

import contextvars
import itertools
import json
import threading
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from celestia_tpu.utils.telemetry import Log2Histogram, clock

ENV_FLAG = "CELESTIA_TPU_TRACE"
ENV_BLOCKS = "CELESTIA_TPU_TRACE_BLOCKS"
ENV_NODE_ID = "CELESTIA_TPU_NODE_ID"

DEFAULT_MAX_BLOCKS = 8
MAX_SPANS_PER_BLOCK = 8192
MAX_BACKGROUND_SPANS = 2048

# ---------------------------------------------------------------------------
# node identity (cross-node attribution)
# ---------------------------------------------------------------------------

# the stable identity of THIS process in a mesh: stamped onto every
# exported trace event and carried as the origin of outbound trace
# contexts.  Set once (env wins over code); empty = single-node.
_node_id = ""


def set_node_id(node_id: str, force: bool = False) -> None:
    """Set this process's node id (first write wins unless ``force``):
    the NodeServer sets its bind address at start, the env var overrides
    at import, tests force their own."""
    global _node_id
    if _node_id and not force:
        return
    _node_id = str(node_id)[:128]


def node_id() -> str:
    return _node_id

# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

# one process-wide monotonic id stream: deterministic (no random/time
# bits) and unique across threads (itertools.count.__next__ is atomic
# under the GIL)
_span_ids = itertools.count(1)

# the active span of the current logical context (per-thread via
# contextvars; explicitly captured + passed for pool-fanned work)
_current: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "celestia_tpu_trace_span", default=None
)

# live span per OS thread id — the CROSS-thread view the host sampling
# profiler (utils/hostprof.py) joins wall-clock samples against
# (contextvars are only readable from their own thread; a sampler
# walking sys._current_frames() needs tid -> span).  Written only on
# the enabled span enter/exit path; single dict item ops are atomic
# under the GIL, so readers never need the tracer lock.
_active_by_thread: Dict[int, Span] = {}


class Span:
    """One timed operation.  ``t0``/``t1`` are telemetry-clock seconds."""

    __slots__ = (
        "span_id", "parent_id", "name", "cat", "t0", "t1", "tid",
        "thread_name", "args", "_sink", "_token",
    )

    def __init__(self, name, cat, parent_id, sink, args, t0=None, t1=0.0):
        """``t0=None`` stamps the span open NOW (the context-manager
        form); explicit t0/t1 build an already-measured span (the
        queue-wait form used by :meth:`Tracer.record_span`)."""
        self.span_id = next(_span_ids)
        self.parent_id = parent_id
        self.name = name
        self.cat = cat
        self.args = args
        self._sink = sink
        self._token = None
        t = threading.current_thread()
        self.tid = t.ident or 0
        self.thread_name = t.name
        self.t0 = clock() if t0 is None else t0
        self.t1 = t1

    @property
    def duration_ms(self) -> float:
        return (self.t1 - self.t0) * 1000.0

    def annotate(self, **kv) -> None:
        """Attach key/value args to a live span (e.g. cache hit/miss)."""
        self.args.update(kv)

    def to_event(self) -> dict:
        """Chrome trace-event 'X' (complete) form, ts/dur in µs."""
        return {
            "ph": "X",
            "name": self.name,
            "cat": self.cat,
            "ts": round(self.t0 * 1e6, 3),
            "dur": round(max(0.0, self.t1 - self.t0) * 1e6, 3),
            "pid": 1,
            "tid": self.tid,
            "args": {
                "span_id": self.span_id,
                "parent_id": self.parent_id,
                **{k: v for k, v in self.args.items() if k != "_render"},
            },
        }

    def to_async_events(self) -> List[dict]:
        """Chrome ASYNC ('b'/'e' + id) form: the export for spans that
        legitimately overlap others on one track (queue waits all start
        at submit time and end at staggered pick-ups — complete 'X'
        events would mis-stack in Perfetto, async tracks render them)."""
        base = {
            "name": self.name,
            "cat": self.cat,
            "id": str(self.span_id),
            "pid": 1,
            "tid": self.tid,
            "args": {
                "span_id": self.span_id,
                "parent_id": self.parent_id,
                **{k: v for k, v in self.args.items() if k != "_render"},
            },
        }
        return [
            dict(base, ph="b", ts=round(self.t0 * 1e6, 3)),
            dict(base, ph="e", ts=round(self.t1 * 1e6, 3)),
        ]

    def export_events(self) -> List[dict]:
        if self.args.get("_render") == "async":
            return self.to_async_events()
        return [self.to_event()]


class _NullSpan:
    """The disabled-path span: one shared instance, every operation a
    no-op.  Returned by ``span()``/``block_span()`` when tracing is off
    so call sites never branch."""

    __slots__ = ()
    span_id = 0
    parent_id = 0

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **kv) -> None:
        pass


NULL_SPAN = _NullSpan()


class BlockTrace:
    """All spans + instant events of one per-height root span (one
    prepare, one process, ...).  Span/instant appends are serialized by
    the tracer lock (pool workers finish spans concurrently)."""

    __slots__ = (
        "name", "height", "root_id", "spans", "instants", "dropped",
        "complete",
    )

    def __init__(self, name: str, height: int, root_id: int):
        self.name = name
        self.height = height
        self.root_id = root_id
        self.spans: List[Span] = []
        self.instants: List[dict] = []
        self.dropped = 0
        self.complete = False

    def span_names(self) -> List[str]:
        return [s.name for s in self.spans]

    def to_events(self) -> List[dict]:
        events: List[dict] = []
        for s in self.spans:
            events.extend(s.export_events())
        events.extend(self.instants)
        return events

    def tree(self) -> dict:
        """Structural form (no durations, no ids): {name, children}
        nested by parent links — what the determinism tests compare.
        Children are sorted by name: pool workers finish in arbitrary
        order, and completion order is timing, not structure."""
        by_id = {s.span_id: {"name": s.name, "children": []} for s in self.spans}
        root = None
        for s in self.spans:
            node = by_id[s.span_id]
            parent = by_id.get(s.parent_id)
            if parent is not None:
                parent["children"].append(node)
            elif s.span_id == self.root_id:
                root = node
        for node in by_id.values():
            node["children"].sort(key=lambda n: n["name"])
        return root or {"name": self.name, "children": []}


class Tracer:
    """The process tracer: a ring of recent block traces + a background
    ring for spans that belong to no block."""

    def __init__(self, max_blocks: int = DEFAULT_MAX_BLOCKS):
        self._lock = threading.Lock()
        # completed block traces, oldest evicted first;
        # celint: guarded-by(self._lock)
        self._blocks: "deque[BlockTrace]" = deque(maxlen=max_blocks)
        # spans/instants outside any block (gossip, DAS serving, ...);
        # celint: guarded-by(self._lock)
        self._background: "deque[dict]" = deque(maxlen=MAX_BACKGROUND_SPANS)
        # per-name duration aggregation (bounded histograms) feeding the
        # telemetry summary; celint: guarded-by(self._lock)
        self._agg: Dict[str, Log2Histogram] = {}
        # cumulative span/instant drops across ALL traces (per-trace
        # ``dropped`` dies with its ring slot; a busy node silently
        # truncating must be detectable remotely long after);
        # celint: guarded-by(self._lock)
        self._span_drops_total = 0
        self.enabled = False

    # -- lifecycle -----------------------------------------------------

    def enable(self, max_blocks: Optional[int] = None) -> None:
        global _enabled
        with self._lock:
            if max_blocks is not None and max_blocks != self._blocks.maxlen:
                self._blocks = deque(self._blocks, maxlen=max(1, max_blocks))
            self.enabled = True
        _enabled = True

    def disable(self) -> None:
        global _enabled
        with self._lock:
            self.enabled = False
        _enabled = False

    def clear(self) -> None:
        with self._lock:
            self._blocks.clear()
            self._background.clear()
            self._agg.clear()
            self._span_drops_total = 0
        _active_by_thread.clear()

    @property
    def max_blocks(self) -> int:
        return self._blocks.maxlen or DEFAULT_MAX_BLOCKS

    # -- span API ------------------------------------------------------

    def span(
        self,
        name: str,
        cat: str = "block",
        parent: Optional[Span] = None,
        **args,
    ):
        """Context manager for one timed operation, parented to the
        current contextvar span (or an explicit ``parent`` — the
        cross-thread form pool workers use)."""
        if not self.enabled:
            return NULL_SPAN
        if parent is None:
            parent = _current.get()
        sink = parent._sink if isinstance(parent, Span) else None
        s = Span(name, cat, parent.span_id if parent else 0, sink, args)
        return _SpanCtx(self, s)

    def block_span(self, name: str, height: int, **args):
        """A per-height ROOT span: opens a fresh :class:`BlockTrace`
        that collects every descendant span; the trace enters the ring
        when this span ends.

        The root's parent_id stays 0 (a block trace is its own tree),
        but when an enclosing span is active — e.g. the server-side
        ``rpc.*`` span a cross-node RPC opened — its id is recorded as
        ``link_span_id`` and any remote-origin args it carries
        (``remote_node``/``remote_span``/``remote_send_ts``) are
        inherited, so the proposer's prepare on node A links explicitly
        to the validator's process root on node B."""
        if not self.enabled:
            return NULL_SPAN
        s = Span(name, "block", 0, None, {"height": height, **args})
        enc = _current.get()
        if enc is not None:
            s.args.setdefault("link_span_id", enc.span_id)
            for k in ("remote_node", "remote_span", "remote_send_ts"):
                if k in enc.args:
                    s.args.setdefault(k, enc.args[k])
        s._sink = BlockTrace(name, height, s.span_id)
        return _SpanCtx(self, s)

    def current(self) -> Optional[Span]:
        """The active span of this thread's context (capture it before
        handing work to a pool; None when disabled or outside spans)."""
        if not self.enabled:
            return None
        return _current.get()

    def record_span(
        self,
        name: str,
        t0: float,
        t1: float,
        parent: Optional[Span] = None,
        cat: str = "block",
        tid: Optional[int] = None,
        thread_name: Optional[str] = None,
        render_async: bool = False,
        **args,
    ) -> None:
        """Record an already-measured span (explicit timestamps) — the
        queue-wait form: the submitting thread stamps t0, the worker
        stamps t1, nobody holds a context over the gap.  ``tid`` /
        ``thread_name`` re-home the span onto the thread it conceptually
        belongs to (a queue-wait starts on the SUBMITTER's track; the
        worker that eventually picks the item up merely records it —
        stamping the worker's tid would overlap its own run spans).
        ``render_async=True`` exports the span as a Chrome async
        ('b'/'e') pair instead of a complete 'X' event — required when
        same-track spans legitimately overlap (N queue waits share one
        submit instant but end at staggered pick-ups)."""
        if not self.enabled:
            return
        if render_async:
            args["_render"] = "async"
        sink = parent._sink if isinstance(parent, Span) else None
        s = Span(
            name, cat, parent.span_id if parent else 0, sink, args,
            t0=t0, t1=t1,
        )
        if tid is not None:
            s.tid = tid
        if thread_name is not None:
            s.thread_name = thread_name
        self._finish(s)

    def instant(self, name: str, cat: str = "event", **args) -> None:
        """An instant event ('i') on the active trace — fault notes,
        degradations, cache hit/miss marks."""
        if not self.enabled:
            return
        parent = _current.get()
        t = threading.current_thread()
        ev = {
            "ph": "i",
            "name": name,
            "cat": cat,
            "ts": round(clock() * 1e6, 3),
            "pid": 1,
            "tid": t.ident or 0,
            "s": "t",
            "args": {
                "parent_id": parent.span_id if parent else 0,
                **args,
            },
        }
        sink = parent._sink if parent is not None else None
        with self._lock:
            if sink is not None:
                if len(sink.instants) + len(sink.spans) < MAX_SPANS_PER_BLOCK:
                    sink.instants.append(ev)
                else:
                    sink.dropped += 1
                    self._span_drops_total += 1
            else:
                self._background.append(ev)

    # -- internals -----------------------------------------------------

    def _finish(self, s: Span) -> None:
        with self._lock:
            hist = self._agg.get(s.name)
            if hist is None:
                hist = self._agg[s.name] = Log2Histogram()
            hist.observe(max(0.0, s.t1 - s.t0))
            sink = s._sink
            if sink is not None:
                is_root = s.span_id == sink.root_id
                # the ROOT is exempt from the cap: it finishes last, and
                # dropping it would turn an over-full trace into an
                # empty tree (no parent for anything) instead of a
                # truncated-but-readable one
                if is_root or (
                    len(sink.spans) + len(sink.instants) < MAX_SPANS_PER_BLOCK
                ):
                    sink.spans.append(s)
                else:
                    sink.dropped += 1
                    self._span_drops_total += 1
                if is_root:
                    sink.complete = True
                    self._blocks.append(sink)
            else:
                self._background.extend(s.export_events())

    # -- export --------------------------------------------------------

    def block_traces(self, last: Optional[int] = None) -> List[BlockTrace]:
        with self._lock:
            traces = list(self._blocks)
        if last is not None:
            traces = traces[-max(0, int(last)):]
        return traces

    def ring_stats(self) -> dict:
        """Ring-health counters for the metrics plane (satellite: silent
        trace truncation on a busy node must be detectable REMOTELY, not
        only in a local dump): cumulative span/instant drops, the
        background-ring depth, and the block-ring fill."""
        with self._lock:
            return {
                "span_drops_total": self._span_drops_total,
                "background_depth": len(self._background),
                "blocks_kept": len(self._blocks),
                "max_blocks": self._blocks.maxlen or DEFAULT_MAX_BLOCKS,
            }

    def span_summary(self) -> Dict[str, dict]:
        """Per-span-name duration aggregates (count/p50/p95/p99/max) for
        the telemetry summary."""
        with self._lock:
            return {name: h.summary() for name, h in sorted(self._agg.items())}

    def _agg_snapshot(self) -> Dict[str, Log2Histogram]:
        """Stable view of the per-name histograms for the Prometheus
        export (histograms are internally locked; the dict copy is what
        needs the tracer lock)."""
        with self._lock:
            return dict(self._agg)

    def trace_dump(self, last: Optional[int] = None) -> dict:
        """Chrome trace-event JSON of the last N block traces plus the
        background ring — open it in Perfetto (ui.perfetto.dev) or
        chrome://tracing as-is."""
        traces = self.block_traces(last)
        with self._lock:
            background = list(self._background)
        events: List[dict] = []
        seen_threads: Dict[int, str] = {}
        for tr in traces:
            events.extend(tr.to_events())
            for s in tr.spans:
                seen_threads.setdefault(s.tid, s.thread_name)
        events.extend(background)
        nid = _node_id
        if nid:
            # tag every span with the stable node id (cross-node merge
            # attribution).  Background events are the live ring's dicts;
            # copy before stamping so the export never mutates the ring.
            events = [
                dict(ev, args=dict(ev.get("args", {}), node_id=nid))
                for ev in events
            ]
        meta = [
            {
                "ph": "M",
                "name": "thread_name",
                "pid": 1,
                "tid": tid,
                "args": {"name": tname},
            }
            for tid, tname in sorted(seen_threads.items())
        ]
        if nid:
            meta.insert(
                0,
                {
                    "ph": "M",
                    "name": "process_name",
                    "pid": 1,
                    "args": {"name": nid},
                },
            )
        return {
            "displayTimeUnit": "ms",
            "traceEvents": meta + events,
            "otherData": {
                "tracer": "celestia-tpu",
                "node_id": nid,
                "blocks": [
                    {
                        "name": tr.name,
                        "height": tr.height,
                        "spans": len(tr.spans),
                        "instants": len(tr.instants),
                        "dropped": tr.dropped,
                    }
                    for tr in traces
                ],
            },
        }

    def phase_breakdown(self, trace: BlockTrace) -> Dict[str, float]:
        """Per-phase ms of one block trace: each DIRECT child of the
        root contributes its duration under its name (duplicate names
        sum); ``total_ms`` is the root span itself and ``untraced_ms``
        the root time no direct child covers.

        For every phase that has sub-spans of its own (e.g. ``extend``
        containing ``extend.native``/``roots``/hostpool tasks) the
        breakdown also reports ``<phase>_untraced_ms`` — the phase time
        its own children do not cover.  THAT is the intra-phase
        pipeline-tail figure (the root-level ``untraced_ms`` only sees
        glue between top-level phases).  Parallel children can sum past
        their parent's wall time, so the remainder clamps at zero —
        a fully-overlapped phase has no serial tail to report."""
        out: Dict[str, float] = {}
        root_dur = 0.0
        direct_sum = 0.0
        # parent span id -> summed child wall time
        child_sum: Dict[int, float] = {}
        for s in trace.spans:
            if s.span_id != trace.root_id:
                child_sum[s.parent_id] = (
                    child_sum.get(s.parent_id, 0.0) + s.duration_ms
                )
        for s in trace.spans:
            if s.span_id == trace.root_id:
                root_dur = s.duration_ms
            elif s.parent_id == trace.root_id:
                key = f"{s.name}_ms"
                out[key] = out.get(key, 0.0) + s.duration_ms
                direct_sum += s.duration_ms
                if s.span_id in child_sum:
                    ukey = f"{s.name}_untraced_ms"
                    out[ukey] = out.get(ukey, 0.0) + max(
                        0.0, s.duration_ms - child_sum[s.span_id]
                    )
        out["total_ms"] = root_dur
        out["untraced_ms"] = max(0.0, root_dur - direct_sum)
        return {k: round(v, 3) for k, v in out.items()}


class _SpanCtx:
    """Context manager that ends one live span (restores the contextvar
    even when the body raises; the error is annotated, never swallowed).

    The contextvar is set in ``__enter__``, NOT at span construction: a
    span object that is created but never entered (held in a variable,
    discarded on a branch) must not corrupt the thread's parent chain."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: Tracer, span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        self._span._token = _current.set(self._span)
        _active_by_thread[self._span.tid] = self._span
        return self._span

    def __exit__(self, exc_type, exc, tb) -> bool:
        s = self._span
        if exc is not None:
            s.args["error"] = repr(exc)[:200]
        s.t1 = clock()
        if s._token is not None:
            # restore the thread's sampler-visible span to whatever was
            # active before this one (the token records the old value)
            old = s._token.old_value
            if old is contextvars.Token.MISSING:
                old = None
            if old is None:
                _active_by_thread.pop(s.tid, None)
            else:
                _active_by_thread[s.tid] = old
            _current.reset(s._token)
        self._tracer._finish(s)
        return False


# ---------------------------------------------------------------------------
# module-level surface (one process tracer, like the faults registry)
# ---------------------------------------------------------------------------

TRACER = Tracer()

# fast-path gate mirrored at module level: the disabled hot path is one
# global load + truth test, no attribute chase
_enabled = False


def enable(max_blocks: Optional[int] = None) -> None:
    TRACER.enable(max_blocks)


def disable() -> None:
    TRACER.disable()


def enabled() -> bool:
    return _enabled


def clear() -> None:
    TRACER.clear()


def span(name: str, cat: str = "block", parent: Optional[Span] = None, **args):
    if not _enabled:
        return NULL_SPAN
    return TRACER.span(name, cat=cat, parent=parent, **args)


def block_span(name: str, height: int, **args):
    if not _enabled:
        return NULL_SPAN
    return TRACER.block_span(name, height, **args)


def current() -> Optional[Span]:
    if not _enabled:
        return None
    return TRACER.current()


def thread_span(tid: int) -> Optional[Span]:
    """The span currently active on the thread with OS id ``tid`` —
    the cross-thread join point for the host sampling profiler
    (utils/hostprof.py): a wall-clock sample of a pool worker lands
    under that worker's live ``hostpool.task`` span, so ``untraced_ms``
    decomposes into named frames.  None when tracing is disabled or the
    thread is between spans."""
    if not _enabled:
        return None
    return _active_by_thread.get(tid)


def record_span(
    name, t0, t1, parent=None, cat="block", tid=None, thread_name=None,
    render_async=False, **args,
) -> None:
    if not _enabled:
        return
    TRACER.record_span(
        name, t0, t1, parent=parent, cat=cat, tid=tid,
        thread_name=thread_name, render_async=render_async, **args,
    )


def instant(name: str, cat: str = "event", **args) -> None:
    if not _enabled:
        return
    TRACER.instant(name, cat=cat, **args)


def trace_dump(last: Optional[int] = None) -> dict:
    return TRACER.trace_dump(last)


def span_summary() -> Dict[str, dict]:
    return TRACER.span_summary()


def ring_stats() -> dict:
    return TRACER.ring_stats()


def block_traces(last: Optional[int] = None) -> List[BlockTrace]:
    return TRACER.block_traces(last)


# ---------------------------------------------------------------------------
# cross-node trace context (the "_tc" wire field)
# ---------------------------------------------------------------------------
#
# Local span ids are a per-process monotonic count, so cross-node
# parentage can never be a bare id: the wire context names the ORIGIN
# (node id) + the parent span id within that origin, and the merge tool
# resolves (node, span) pairs into Chrome flow events.  The context is
# a plain JSON-safe dict with compact keys:
#
#   {"n": origin node id, "s": parent span id (0 = none),
#    "h": height (0 = n/a), "t": send timestamp (telemetry clock)}
#
# It rides cross-node RPC envelopes as an OPTIONAL "_tc" field that
# un-upgraded peers ignore (their handlers read named keys); a missing,
# truncated or malformed context degrades to "no remote parent" — never
# an error, never a leaked span.


def wire_context(height: int = 0) -> Optional[dict]:
    """The compact trace context of the CURRENT logical call site, for
    attaching to an outbound cross-node RPC.  None when tracing is off
    (the envelope then carries no ``_tc`` at all — zero bytes, zero
    cost on the gossip hot path)."""
    if not _enabled:
        return None
    cur = _current.get()
    return {
        "n": _node_id,
        "s": cur.span_id if cur is not None else 0,
        "h": int(height or 0),
        "t": round(clock(), 6),
    }


def last_block_context(name: Optional[str] = None) -> Optional[dict]:
    """Wire context anchored to the newest completed block trace
    (optionally of a given root name): how a proposer hands the span id
    of its *prepare* root to the coordinator, which forwards it to every
    validator's *process* leg."""
    if not _enabled:
        return None
    for tr in reversed(TRACER.block_traces()):
        if name is None or tr.name == name:
            return {
                "n": _node_id,
                "s": tr.root_id,
                "h": tr.height,
                "t": round(clock(), 6),
            }
    return None


def _context_args(tc) -> dict:
    """Remote-origin span args from a received wire context.  Malformed
    or version-mismatched contexts (old peers, hostile bytes) fold to
    {} — mixed-version meshes must keep working.  A context with no
    parent span (``s`` 0 — e.g. a gossip flood drained from the outbox
    outside any span) still attributes the ORIGIN node; only a valid
    span id adds the flow-linkable ``remote_span``."""
    if not isinstance(tc, dict) or not isinstance(tc.get("n"), str):
        return {}
    try:
        origin = tc["n"][:128]
        span_id = int(tc.get("s", 0) or 0)
        send_ts = float(tc.get("t", 0.0) or 0.0)
    except (TypeError, ValueError):
        return {}
    if not origin:
        return {}
    out = {"remote_node": origin}
    if span_id > 0:
        out["remote_span"] = span_id
    if send_ts > 0.0:
        out["remote_send_ts"] = round(send_ts, 6)
    return out


def rpc_span(name: str, tc=None, cat: str = "rpc", **args):
    """Server-side span for a cross-node RPC: like :func:`span`, but
    records the caller's context as explicit ``remote_node``/
    ``remote_span`` args (local parentage still rides the contextvar).
    A block trace opened inside it inherits the remote link onto its
    root (see :meth:`Tracer.block_span`)."""
    if not _enabled:
        return NULL_SPAN
    return TRACER.span(name, cat=cat, **{**_context_args(tc), **args})


# ---------------------------------------------------------------------------
# clock alignment (RPC midpoint offset probe)
# ---------------------------------------------------------------------------


def estimate_clock_offset(probe_fn, samples: int = 5) -> dict:
    """Estimate a peer's clock offset by the RPC midpoint method.

    ``probe_fn()`` performs one round trip and returns the PEER's
    telemetry-clock timestamp (seconds).  For each sample the peer time
    is compared against the midpoint of the local send/receive stamps —
    the standard symmetric-delay estimator — and the sample with the
    smallest RTT wins (least queueing noise).  All local stamps come
    from the sanctioned telemetry ``clock()`` (celint R3: this module is
    a sanctioned channel).

    Returns ``{"offset_s", "rtt_s", "samples"}`` where ``offset_s`` is
    *peer clock minus local clock*: subtract it from the peer's
    timestamps to land them on the local timeline."""
    best_rtt = float("inf")
    best_offset = 0.0
    n = 0
    for _ in range(max(1, int(samples))):
        t0 = clock()
        peer_ts = float(probe_fn())
        t1 = clock()
        rtt = max(0.0, t1 - t0)
        n += 1
        if rtt < best_rtt:
            best_rtt = rtt
            best_offset = peer_ts - (t0 + t1) / 2.0
    return {
        "offset_s": round(best_offset, 6),
        "rtt_s": round(best_rtt, 6),
        "samples": n,
    }


def validate_chrome_trace(dump: dict) -> List[str]:
    """Schema check of a trace_dump() document (the trace-smoke gate):
    returns a list of problems, empty when the JSON is a well-formed
    Chrome trace-event document Perfetto will open."""
    problems: List[str] = []
    if not isinstance(dump, dict):
        return ["dump is not an object"]
    events = dump.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    if not events:
        problems.append("traceEvents is empty")
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i} is not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "i", "M", "B", "E", "b", "e", "s", "t", "f"):
            problems.append(f"event {i} has unknown phase {ph!r}")
            continue
        if ph == "M":
            if "name" not in ev or "args" not in ev:
                problems.append(f"metadata event {i} lacks name/args")
            continue
        for field in ("name", "ts", "pid", "tid"):
            if field not in ev:
                problems.append(f"event {i} ({ev.get('name')}) lacks {field!r}")
        if ph == "X" and "dur" not in ev:
            problems.append(f"complete event {i} ({ev.get('name')}) lacks dur")
        if ph in ("b", "e") and "id" not in ev:
            problems.append(f"async event {i} ({ev.get('name')}) lacks id")
        if ph in ("s", "t", "f") and "id" not in ev:
            problems.append(f"flow event {i} ({ev.get('name')}) lacks id")
        if not isinstance(ev.get("ts", 0), (int, float)):
            problems.append(f"event {i} ts is not numeric")
    try:
        json.dumps(dump)
    except (TypeError, ValueError) as e:
        problems.append(f"dump is not JSON-serializable: {e}")
    return problems


# ---------------------------------------------------------------------------
# store-write bridge: ONE tracing surface for block execution
# ---------------------------------------------------------------------------


class trace_store_writes:
    """Route a MultiStore's write tracer into the span tracer: every
    store write/delete becomes an instant event on the active trace
    (SetCommitMultiStoreTracer parity, app/app.go:243 — but through the
    one tracing surface instead of an ad-hoc callback list).

    Context manager; restores the previous store tracer on exit.  The
    captured events are also kept on ``self.events`` so callers (tests,
    debuggers) can assert without digging through the trace dump."""

    def __init__(self, multistore, include_values: bool = False):
        self._store = multistore
        self._include_values = include_values
        self._prev = None
        self._installed = False
        self.events: List[Tuple[str, str, bytes]] = []

    def _on_write(self, op, store, key, value) -> None:
        # only the INSTALLED (innermost) bridge emits the trace instant:
        # chained outer bridges record the event but must not duplicate
        # it on the trace (one write = one store.write instant)
        kv = {"op": op, "store": store, "key": key.hex()}
        if self._include_values and value is not None:
            kv["value"] = value.hex()[:128]
        instant("store.write", cat="store", **kv)
        self._record(op, store, key, value)

    def _record(self, op, store, key, value) -> None:
        """Append to this bridge's event list and chain onward: nested
        bridges record without re-emitting instants; a non-bridge
        previous tracer (operator callback) is invoked as installed."""
        self.events.append((op, store, key))
        prev = self._prev
        if prev is None:
            return
        outer = getattr(prev, "__self__", None)
        if isinstance(outer, trace_store_writes):
            outer._record(op, store, key, value)
        else:
            prev(op, store, key, value)

    def __enter__(self) -> "trace_store_writes":
        self._prev = self._store._tracer_ref[0]
        self._store.set_tracer(self._on_write)
        self._installed = True
        return self

    def __exit__(self, *exc) -> bool:
        if self._installed:
            self._store.set_tracer(self._prev)
            self._installed = False
        return False


def _arm_from_env() -> None:
    """Enable at import when CELESTIA_TPU_TRACE is truthy — a traced
    node needs no code changes, same contract as the faults registry.
    CELESTIA_TPU_TRACE_BLOCKS alone also enables (mirroring the CLI,
    where --trace-blocks implies --trace: sizing a ring you did not
    turn on must not be a silent no-op).  CELESTIA_TPU_NODE_ID pins the
    node identity regardless of tracing state (the metrics plane tags
    by it too)."""
    import os

    nid = os.environ.get(ENV_NODE_ID, "").strip()
    if nid:
        set_node_id(nid, force=True)
    flag = os.environ.get(ENV_FLAG, "").strip().lower()
    blocks = os.environ.get(ENV_BLOCKS, "").strip()
    try:
        n = int(blocks) if blocks else None
    except ValueError:
        n = None
    if flag in ("1", "true", "yes", "on") or n is not None:
        enable(n)


_arm_from_env()
