"""Continuous host profiling: wall-clock stack sampling over all threads.

The observability plane sees every *instrumented* span (utils/tracing.py),
every device dispatch (utils/devprof.py) and every periodic snapshot
(utils/timeseries.py) — but the host CPU between spans is a black box:
bench's ``trace_summary`` pins real ``untraced_ms`` tails (the phase-3
column-root tail, inter-phase glue, the ingress filter leg) that no span
names.  This module closes that gap with a sampling profiler in the same
bounded-structure, zero-overhead-disarmed idiom as the rest of the plane:

* **Wall-clock sampling.**  A single daemon thread wakes at a
  configurable rate (``--host-profile [HZ]`` / ``CELESTIA_TPU_HOST_PROFILE``,
  default :data:`DEFAULT_HZ`), snapshots ``sys._current_frames()`` and
  records one bounded stack per live thread.  No signals, no tracing
  hooks — the profiled code pays nothing per call; the only cost is the
  sampler's own tick, which is measured and reported as
  ``overhead_pct`` (bench + ``tools/bench_check.py`` alarm at >2%).
* **Span attribution.**  Each sample is joined to the sampled thread's
  ACTIVE span via :func:`tracing.thread_span` (the tid -> span registry
  the span tracer maintains), so a busy hostpool worker's frames land
  under its ``hostpool.task`` span and an ``untraced_ms`` figure
  decomposes into named frames.  Thread NAMES ride along too — hostpool
  workers (``celestia-host-*``), gossip/BFT pumps, the timeseries
  sampler and the block producer are attributed by name, not by bare
  tid.
* **Two exports.**  (1) *Folded stacks* — ``thread;[span:name;]f1;f2 N``
  lines, directly consumable by any flamegraph tool — aggregated into a
  bounded map (:data:`MAX_FOLDED` distinct stacks + an overflow
  counter).  (2) *Chrome-trace sample events* — ``ph:"i"``/``cat:"sample"``
  instants on the SAME per-thread Perfetto tracks the span tracer uses
  (:func:`merged_trace_dump`), so frames line up with spans on ONE
  timeline.
* **Bounded, zero overhead disarmed.**  Raw samples live in a
  ``deque(maxlen=MAX_SAMPLES)``; disarmed, every public entry is one
  module-bool check and the sampler thread does not exist
  (tests/test_hostprof.py pins the disarmed cost, same style as
  tracing's).

celint R3: this module is on the SANCTIONED_CHANNELS list — its clock
reads go through :func:`telemetry.clock` and the entropy bans still
apply inside it (a sampler seeded from ``random`` would launder
nondeterminism through the one open door).
"""

from __future__ import annotations

import sys
import threading
from collections import deque
from typing import Dict, List, Optional

from celestia_tpu.utils import tracing
from celestia_tpu.utils.telemetry import clock

ENV_FLAG = "CELESTIA_TPU_HOST_PROFILE"

# default sampling rate: high enough to catch a multi-ms tail inside one
# block, low enough that the measured tick cost stays well under the 2%
# overhead alarm.  A non-round number avoids lockstep with 10 ms timer
# beats (a sampler phase-locked to the work it measures sees aliases,
# not a profile).
DEFAULT_HZ = 67.0
MAX_HZ = 1000.0

MAX_SAMPLES = 4096   # raw sample ring (Chrome-event export window)
MAX_FOLDED = 8192    # distinct folded stacks kept (overflow counted)
MAX_STACK_DEPTH = 48

_lock = threading.Lock()
_enabled = False
_hz = DEFAULT_HZ
# raw recent samples (dicts; see sample_once); celint: guarded-by(_lock)
_samples: "deque[dict]" = deque(maxlen=MAX_SAMPLES)
# folded stack -> count, bounded with an overflow counter (same
# bounded-accumulator shape as devprof's kernel table);
# celint: guarded-by(_lock)
_folded: Dict[str, int] = {}
_folded_dropped = 0  # celint: guarded-by(_lock)
_samples_total = 0   # lifetime per-thread samples; celint: guarded-by(_lock)
_ticks_total = 0     # sampler wake-ups; celint: guarded-by(_lock)
_sampling_s = 0.0    # cumulative time spent INSIDE ticks; celint: guarded-by(_lock)
_window_t0 = 0.0     # armed-window start; celint: guarded-by(_lock)
_window_t1: Optional[float] = None  # window end (stop()); celint: guarded-by(_lock)
_thread: Optional[threading.Thread] = None
_sampler_tid: Optional[int] = None  # the loop thread's own ident
_stop = threading.Event()


def enabled() -> bool:
    return _enabled


def hz() -> float:
    return _hz


def _frame_stack(frame) -> List[str]:
    """Root-first ``module.func`` frames of one thread, bounded depth.
    Module is the file's basename (no .py): short enough to fold, unique
    enough to read.  A deeper-than-cap stack keeps its LEAF end (the
    code actually on-CPU) and drops the root."""
    out: List[str] = []
    f = frame
    while f is not None and len(out) < MAX_STACK_DEPTH:
        code = f.f_code
        mod = code.co_filename.rsplit("/", 1)[-1]
        if mod.endswith(".py"):
            mod = mod[:-3]
        out.append(f"{mod}.{code.co_name}")
        f = f.f_back
    out.reverse()
    return out


def sample_once() -> int:
    """Take ONE sample of every live thread (the sampler tick; public so
    tests and bench drive it deterministically).  Returns the number of
    per-thread samples recorded.  No-op disarmed."""
    global _samples_total, _ticks_total, _sampling_s, _folded_dropped
    if not _enabled:
        return 0
    t0 = clock()
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    recorded = 0
    new: List[dict] = []
    folds: List[str] = []
    for tid, frame in frames.items():
        if tid == _sampler_tid:
            continue  # the sampler thread never profiles itself (a
            # DIRECT sample_once() caller — tests, bench — is real work
            # and IS profiled)
        stack = _frame_stack(frame)
        if not stack:
            continue
        tname = names.get(tid, f"thread-{tid}")
        sp = tracing.thread_span(tid)
        entry = {
            "ts": t0,
            "tid": tid,
            "thread": tname,
            "stack": stack,
            "span_id": sp.span_id if sp is not None else 0,
            "span": sp.name if sp is not None else "",
        }
        # folded key: thread name, then the active span (so untraced
        # time decomposes UNDER the span that owns it), then frames
        parts = [tname]
        if sp is not None:
            parts.append(f"span:{sp.name}")
        parts.extend(stack)
        folds.append(";".join(parts))
        new.append(entry)
        recorded += 1
    dt = clock() - t0
    with _lock:
        _samples.extend(new)
        for key in folds:
            if key in _folded:
                _folded[key] += 1
            elif len(_folded) < MAX_FOLDED:
                _folded[key] = 1
            else:
                _folded_dropped += 1
        _samples_total += recorded
        _ticks_total += 1
        _sampling_s += max(0.0, dt)
    return recorded


def _loop() -> None:
    # Event.wait paces the cadence (no sleep-in-loop, celint R5); a
    # sampler tick can never raise — sys._current_frames returns plain
    # frames and the fold path is pure dict work — but the loop still
    # guards via faults.note so a future collector bug degrades the
    # profile, never kills the thread.
    global _sampler_tid

    from celestia_tpu.utils import faults

    _sampler_tid = threading.get_ident()
    interval = 1.0 / max(0.001, _hz)
    while not _stop.wait(interval):
        try:
            sample_once()
        except Exception as e:  # pragma: no cover - defensive
            faults.note("hostprof.tick", e)


def start(hz: Optional[float] = None) -> None:
    """Arm the sampler (idempotent; a new rate restarts the thread).
    ``hz`` is clamped to (0, MAX_HZ]."""
    global _enabled, _hz, _thread, _window_t0, _window_t1
    with _lock:
        rate = float(hz) if hz else DEFAULT_HZ
        rate = min(MAX_HZ, max(0.1, rate))
        if _enabled and _thread is not None and rate == _hz:
            return
        _hz = rate
    stop()
    with _lock:
        _enabled = True
        _window_t0 = clock()
        _window_t1 = None
    _stop.clear()
    t = threading.Thread(target=_loop, name="hostprof-sampler", daemon=True)
    _thread = t
    t.start()


def stop() -> None:
    """Disarm the sampler and join its thread.  Recorded samples stay
    readable (a flight bundle dumps them after the incident)."""
    global _enabled, _thread, _window_t1
    was_enabled = _enabled
    _enabled = False
    _stop.set()
    t = _thread
    _thread = None
    if t is not None and t.is_alive():
        t.join(timeout=5)
    _stop.clear()
    if was_enabled:
        with _lock:
            # freeze the overhead window: stats() read after stop must
            # report sampling cost over the ARMED wall, not dilute as
            # idle time accrues
            _window_t1 = clock()


def clear() -> None:
    """Drop all recorded samples + accounting (tests, bench legs)."""
    global _folded_dropped, _samples_total, _ticks_total, _sampling_s
    global _window_t0, _window_t1
    with _lock:
        _samples.clear()
        _folded.clear()
        _folded_dropped = 0
        _samples_total = 0
        _ticks_total = 0
        _sampling_s = 0.0
        _window_t0 = clock()
        _window_t1 = None


def stats() -> dict:
    """Sampler accounting: rates and the measured self-overhead (the
    figure bench records and tools/bench_check.py alarms on >2%)."""
    with _lock:
        end = _window_t1 if _window_t1 is not None else clock()
        window_s = max(0.0, end - _window_t0) if _window_t0 else 0.0
        return {
            "enabled": _enabled,
            "hz": _hz,
            "samples_total": _samples_total,
            "samples_kept": len(_samples),
            "ticks": _ticks_total,
            "folded_unique": len(_folded),
            "folded_dropped": _folded_dropped,
            "sampling_ms_total": round(_sampling_s * 1000.0, 3),
            "window_s": round(window_s, 3),
            "samples_per_s": (
                round(_samples_total / window_s, 1) if window_s > 0 else 0.0
            ),
            "overhead_pct": (
                round(100.0 * _sampling_s / window_s, 3)
                if window_s > 0
                else 0.0
            ),
        }


def samples(last: Optional[int] = None) -> List[dict]:
    with _lock:
        out = list(_samples)
    if last is not None:
        out = out[-max(0, int(last)):]
    return out


def folded_stacks() -> Dict[str, int]:
    """folded-stack -> sample count (flamegraph input as a dict)."""
    with _lock:
        return dict(_folded)


def folded_text(top: Optional[int] = None) -> str:
    """The classic folded format — one ``stack count`` line per distinct
    stack, count-descending — ``flamegraph.pl``/speedscope-ready and the
    ``stacks.folded`` artifact of a flight bundle."""
    items = sorted(
        folded_stacks().items(), key=lambda kv: (-kv[1], kv[0])
    )
    if top is not None:
        items = items[: max(0, int(top))]
    return "\n".join(f"{stack} {count}" for stack, count in items) + (
        "\n" if items else ""
    )


def top_frames(n: int = 10) -> List[dict]:
    """Self-time ranking: the LEAF frame of each sample is where the CPU
    actually was; counts aggregate per leaf across threads."""
    leaf: Dict[str, int] = {}
    total = 0
    with _lock:
        for key, count in _folded.items():
            leaf_frame = key.rsplit(";", 1)[-1]
            leaf[leaf_frame] = leaf.get(leaf_frame, 0) + count
            total += count
    ranked = sorted(leaf.items(), key=lambda kv: (-kv[1], kv[0]))
    return [
        {
            "frame": frame,
            "samples": count,
            "pct": round(100.0 * count / total, 2) if total else 0.0,
        }
        for frame, count in ranked[: max(0, int(n))]
    ]


def chrome_events(last: Optional[int] = None) -> List[dict]:
    """The raw sample ring as Chrome trace instants (``cat="sample"``)
    on the sampled threads' OWN tracks — merged next to the span
    tracer's events they land on the same Perfetto timeline rows."""
    out: List[dict] = []
    for s in samples(last):
        args = {"stack": ";".join(s["stack"])}
        if s["span_id"]:
            args["span_id_sampled"] = s["span_id"]
            args["span"] = s["span"]
        out.append(
            {
                "ph": "i",
                "name": s["stack"][-1],
                "cat": "sample",
                "ts": round(s["ts"] * 1e6, 3),
                "pid": 1,
                "tid": s["tid"],
                "s": "t",
                "args": args,
            }
        )
    return out


def merged_trace_dump(last: Optional[int] = None) -> dict:
    """One Chrome trace document: the span tracer's dump PLUS this
    module's sample instants, with thread_name metadata for sampled
    threads the tracer never saw (gossip pumps, grpc workers) — open in
    Perfetto and frames line up with spans on one timeline."""
    dump = tracing.trace_dump(last)
    events = dump.get("traceEvents", [])
    named = {
        ev.get("tid")
        for ev in events
        if ev.get("ph") == "M" and ev.get("name") == "thread_name"
    }
    nid = tracing.node_id()
    sample_events = chrome_events()
    if nid:
        sample_events = [
            dict(ev, args=dict(ev["args"], node_id=nid))
            for ev in sample_events
        ]
    meta: List[dict] = []
    seen: Dict[int, str] = {}
    for s in samples():
        seen.setdefault(s["tid"], s["thread"])
    for tid, tname in sorted(seen.items()):
        if tid not in named:
            meta.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": 1,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
    dump["traceEvents"] = meta + events + sample_events
    dump.setdefault("otherData", {})["host_samples"] = len(sample_events)
    return dump


def exposition_lines() -> List[str]:
    """Prometheus lines for the metrics plane (zero lines disarmed with
    nothing recorded — absent means unknown, same contract as devprof)."""
    st = stats()
    if not st["enabled"] and st["samples_total"] == 0:
        return []
    return [
        "# TYPE celestia_tpu_hostprof_samples_total counter",
        f"celestia_tpu_hostprof_samples_total {st['samples_total']}",
        f"celestia_tpu_hostprof_enabled {1 if st['enabled'] else 0}",
        f"celestia_tpu_hostprof_hz {st['hz']}",
        f"celestia_tpu_hostprof_overhead_pct {st['overhead_pct']}",
    ]


def _arm_from_env() -> None:
    """CELESTIA_TPU_HOST_PROFILE: truthy arms at the default rate, a
    number arms at that Hz, falsy/absent stays off — same contract as
    CELESTIA_TPU_TRACE / CELESTIA_TPU_DEVICE_PROFILE."""
    import os

    raw = os.environ.get(ENV_FLAG, "").strip().lower()
    if not raw or raw in ("0", "false", "no", "off"):
        return
    if raw in ("1", "true", "yes", "on"):
        start()
        return
    try:
        start(float(raw))
    except ValueError:
        start()


_arm_from_env()
