"""The ONE bounded LRU every cache in the node is built on.

Five hand-rolled lock+OrderedDict caches grew up independently on the
hot path (da/eds_cache, da/dah row memo, App sig/decoded caches,
gossip's seen-set) and a sixth (da/inclusion's commitment cache) shipped
with NO lock at all while being mutated from pooled threads.  Each copy
re-implemented the same four responsibilities — recency, bounding,
thread-safety, stats — and each copy was one review away from drifting
(the commitment cache DID drift).  This module centralises them:

* **Thread-safe by construction.**  Every read and mutation happens
  under one internal lock; callers never see a torn OrderedDict.  The
  compound operations concurrent callers actually need
  (:meth:`add_if_absent`, :meth:`get_or_put`) are atomic methods here,
  not check-then-act sequences at call sites.
* **Bounded two ways.**  ``max_entries`` is the hard entry cap;
  ``max_bytes`` (optional, needs a ``weigher``) additionally evicts by
  approximate resident size, so one cache of huge values (a 128x128 EDS
  is ~32 MiB) and one of tiny digests can share a uniform policy.
* **Unified stats.**  hits/misses/puts/replacements/evictions plus
  approximate resident bytes, per cache and aggregated process-wide via
  :func:`registry_stats`, surfaced through utils/telemetry.py and
  bench.py — production nodes get one knob and one dashboard, not five.

celint rule R2 (no-handrolled-cache) forbids the OrderedDict+eviction
pattern everywhere else in the tree, so the next cache MUST be built on
this class — the rule is what keeps this consolidation from regressing.

The registry holds weak references: short-lived caches (each test App
owns a sig cache) vanish from the process view when their owner dies.
"""

from __future__ import annotations

import os
import threading
import weakref
from collections import OrderedDict  # R2-exempt: the sanctioned implementation
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

# process-wide soft budget over the summed approx_bytes of every live
# cache; purely advisory (reported + flagged, never cross-cache
# enforced — each cache's own caps do the evicting)
_BUDGET_ENV = "CELESTIA_TPU_CACHE_BUDGET_MB"

_registry_lock = threading.Lock()
# id(cache) -> weakref; celint: guarded-by(_registry_lock)
_registry: Dict[int, "weakref.ref[LruCache]"] = {}


def _register(cache: "LruCache") -> None:
    with _registry_lock:
        _registry[id(cache)] = weakref.ref(cache)


class LruCache:
    """Bounded, thread-safe LRU mapping with unified stats.

    ``weigher(key, value) -> int`` estimates an entry's resident bytes;
    it is consulted once per insert (weights are stored, so eviction
    never re-weighs a value that may have been mutated).
    """

    def __init__(
        self,
        name: str,
        max_entries: int,
        *,
        weigher: Optional[Callable[[Any, Any], int]] = None,
        max_bytes: Optional[int] = None,
        register: bool = True,
    ):
        self.name = name
        self._max_entries = max(1, int(max_entries))
        self._max_bytes = int(max_bytes) if max_bytes else None
        self._weigher = weigher
        self._lock = threading.Lock()
        # value + stored weight; celint: guarded-by(self._lock)
        self._entries: "OrderedDict[Any, Tuple[Any, int]]" = OrderedDict()
        self._bytes = 0  # celint: guarded-by(self._lock)
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.replacements = 0
        self.evictions = 0
        if register:
            _register(self)

    # -- reads ---------------------------------------------------------

    def get(self, key, default=None, *, count: bool = True, touch: bool = True):
        """Value for ``key`` (refreshing recency) or ``default``.

        ``count=False`` skips the hit/miss counters — for high-frequency
        bookkeeping lookups that would drown the workload hit rate (the
        min-DAH reads in da/eds_cache) — but still refreshes recency so
        the entry does not sit perpetually first in the eviction line.

        ``touch=False`` additionally leaves recency alone.  With it, a
        cache whose puts arrive in a meaningful order (the decided log's
        monotonically increasing heights) keeps FIFO eviction no matter
        how often old entries are read — reads cannot fragment the
        retained window.
        """
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                if count:
                    self.misses += 1
                return default
            if touch:
                self._entries.move_to_end(key)
            if count:
                self.hits += 1
            return entry[0]

    def get_many(self, keys: Iterable[Any], default=None, *, count: bool = True) -> List[Any]:
        """Batch :meth:`get` under ONE lock acquisition (hot batch paths
        like the row memo: one lock round-trip per square, not per row)."""
        with self._lock:
            out = []
            for key in keys:
                entry = self._entries.get(key)
                if entry is None:
                    if count:
                        self.misses += 1
                    out.append(default)
                    continue
                self._entries.move_to_end(key)
                if count:
                    self.hits += 1
                out.append(entry[0])
            return out

    def peek(self, key, default=None):
        """:meth:`get` without touching the hit/miss counters."""
        return self.get(key, default, count=False)

    def __contains__(self, key) -> bool:
        """Membership only: no counters, no recency refresh."""
        with self._lock:
            return key in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __iter__(self):
        """Iterate a SNAPSHOT of the keys (LRU-first): safe under
        concurrent mutation, no recency/counter effects."""
        with self._lock:
            return iter(list(self._entries))

    def keys(self) -> List[Any]:
        """Key snapshot, LRU-first (same contract as ``__iter__``)."""
        with self._lock:
            return list(self._entries)

    # -- writes --------------------------------------------------------

    def _weigh(self, key, value) -> int:
        if self._weigher is None:
            return 0
        try:
            return max(0, int(self._weigher(key, value)))
        except Exception:
            return 0  # a broken weigher must never break the cache

    def _insert_locked(self, key, value) -> bool:
        """Insert/replace + evict; caller holds the lock.  True if new."""
        w = self._weigh(key, value)
        prev = self._entries.get(key)
        if prev is not None:
            self._bytes -= prev[1]
            self.replacements += 1
            new = False
        else:
            self.puts += 1
            new = True
        self._entries[key] = (value, w)
        self._entries.move_to_end(key)
        self._bytes += w
        while len(self._entries) > self._max_entries or (
            self._max_bytes is not None
            and self._bytes > self._max_bytes
            and len(self._entries) > 1
        ):
            _, (_, ew) = self._entries.popitem(last=False)
            self._bytes -= ew
            self.evictions += 1
        return new

    def put(self, key, value) -> bool:
        """Insert or replace.  Returns True when ``key`` was new.

        Under an armed ``lru.put`` fault the write is silently DROPPED
        (a lost write, not an error): callers must already tolerate a
        later miss by recomputing, and the chaos suite proves the
        EDS/DAH cache and row memo do — an entry is either absent or
        complete, never partial."""
        from celestia_tpu.utils import faults

        if faults.should_drop("lru.put"):
            return False
        with self._lock:
            return self._insert_locked(key, value)

    def put_many(self, pairs: Iterable[Tuple[Any, Any]]) -> None:
        """Batch :meth:`put` under ONE lock acquisition — the batch is
        atomic: no interleaved reader observes a half-inserted batch.
        An armed ``lru.put`` fault drops the WHOLE batch (atomicity is
        part of the contract; a half-landed batch would be exactly the
        partial state the fault exists to rule out)."""
        from celestia_tpu.utils import faults

        if faults.should_drop("lru.put"):
            return
        with self._lock:
            for key, value in pairs:
                self._insert_locked(key, value)

    def add_if_absent(self, key, value=True) -> bool:
        """Atomic membership-add (dedup-set use).  True if newly added;
        an existing entry counts as a hit, a fresh one as a miss."""
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                self.hits += 1
                return False
            self.misses += 1
            self._insert_locked(key, value)
            return True

    def get_or_put(self, key, factory: Callable[[], Any]):
        """Atomic lookup-or-compute.  ``factory`` runs under the lock —
        keep it cheap (for expensive values compute outside and race on
        :meth:`put`; last writer wins with identical bytes)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                return entry[0]
            self.misses += 1
            value = factory()
            self._insert_locked(key, value)
            return value

    def pop(self, key, default=None):
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return default
            self._bytes -= entry[1]
            return entry[0]

    def clear(self) -> None:
        """Drop all entries AND reset counters (bench epoch boundary)."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self.hits = self.misses = self.puts = 0
            self.replacements = self.evictions = 0

    # -- sizing --------------------------------------------------------

    @property
    def max_entries(self) -> int:
        return self._max_entries

    def set_max_entries(self, n: int) -> None:
        """Re-cap; an over-full cache is trimmed immediately."""
        with self._lock:
            self._max_entries = max(1, int(n))
            while len(self._entries) > self._max_entries:
                _, (_, ew) = self._entries.popitem(last=False)
                self._bytes -= ew
                self.evictions += 1

    def approx_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "name": self.name,
                "entries": len(self._entries),
                "max_entries": self._max_entries,
                "hits": self.hits,
                "misses": self.misses,
                "puts": self.puts,
                "replacements": self.replacements,
                "evictions": self.evictions,
                "hit_rate": (self.hits / total) if total else 0.0,
                "approx_bytes": self._bytes,
            }


# ---------------------------------------------------------------------------
# process-wide registry + budget reporting
# ---------------------------------------------------------------------------


def live_caches() -> List[LruCache]:
    """Snapshot of registered caches still alive (dead refs pruned)."""
    with _registry_lock:
        out: List[LruCache] = []
        dead: List[int] = []
        for cid, ref in _registry.items():
            cache = ref()
            if cache is None:
                dead.append(cid)
            else:
                out.append(cache)
        for cid in dead:
            del _registry[cid]
        return out


def cache_budget_bytes() -> Optional[int]:
    """The advisory process-wide budget (None = unset)."""
    raw = os.environ.get(_BUDGET_ENV, "").strip()
    if not raw:
        return None
    try:
        mb = float(raw)
    except ValueError:
        return None
    return int(mb * 1024 * 1024) if mb > 0 else None


def registry_stats() -> dict:
    """Aggregated view of every live cache, grouped by name (several App
    instances each own a ``sig`` cache; the process view sums them)."""
    by_name: Dict[str, dict] = {}
    for cache in live_caches():
        s = cache.stats()
        agg = by_name.get(s["name"])
        if agg is None:
            agg = dict(s)
            agg["instances"] = 1
            del agg["name"]
            by_name[s["name"]] = agg
        else:
            agg["instances"] += 1
            for k in (
                "entries", "hits", "misses", "puts", "replacements",
                "evictions", "approx_bytes",
            ):
                agg[k] += s[k]
            agg["max_entries"] = max(agg["max_entries"], s["max_entries"])
    for agg in by_name.values():
        total = agg["hits"] + agg["misses"]
        agg["hit_rate"] = round(agg["hits"] / total, 4) if total else 0.0
    total_bytes = sum(a["approx_bytes"] for a in by_name.values())
    budget = cache_budget_bytes()
    return {
        "caches": by_name,
        "total_approx_bytes": total_bytes,
        "budget_bytes": budget,
        "over_budget": bool(budget is not None and total_bytes > budget),
    }


# shared weighers ------------------------------------------------------------


def bytes_len_weigher(key, value) -> int:
    """Weigher for bytes-like keys/values (digest caches)."""
    kw = len(key) if isinstance(key, (bytes, bytearray, str)) else 16
    vw = len(value) if isinstance(value, (bytes, bytearray)) else 16
    return kw + vw


def nbytes_weigher(key, value) -> int:
    """Weigher for values exposing numpy-style ``.nbytes`` (possibly
    nested one level in a tuple) — the EDS/DAH pair case."""
    def one(v) -> int:
        # ExtendedDataSquare: size from the share tensor's SHAPE so a
        # device-resident EDS is never pulled to the host just to weigh it
        inner = getattr(v, "_shares", None)
        shape = getattr(inner, "shape", None)
        if shape is not None:
            n = 1
            for d in shape:
                n *= int(d)
            return n
        nb = getattr(v, "nbytes", None)
        if nb is not None:
            return int(nb)
        if isinstance(v, (bytes, bytearray)):
            return len(v)
        return 64
    if isinstance(value, tuple):
        return sum(one(v) for v in value) + 32
    return one(value) + 32
