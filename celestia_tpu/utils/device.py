"""Device-backend probing.

A dead accelerator tunnel can make JAX backend init HANG for minutes
rather than raise (observed live in round 5), so anything that would
touch the backend at a time-sensitive moment probes it in a CHILD
process with a timeout first.  Used by bench.py (which rejects a silent
CPU fallback — its numbers must be device numbers) and the node CLI's
boot-time program warming (which accepts CPU: a CPU-backed node is a
legitimate deployment, e.g. the test meshes).
"""

from __future__ import annotations

import subprocess
import sys
from typing import Optional

_host_regime: Optional[bool] = None


def host_regime() -> bool:
    """True when this process's default jax backend is the host CPU —
    the regime every node lives in while the device tunnel is down.

    The host-regime fast paths (da/dah.py) route the DA pipeline through
    the pooled native C++ legs instead of compiling XLA CPU programs
    (minutes at k=128).  Cached: the default backend cannot change within
    a process.  Only call from code that already initializes jax — the
    first call touches the backend."""
    global _host_regime
    if _host_regime is None:
        try:
            import jax

            _host_regime = jax.default_backend() == "cpu"
        except Exception:
            # no usable jax backend at all: host-only by definition
            _host_regime = True
    return _host_regime


def force_host_devices_env(env: dict, n: int) -> dict:
    """Prepare ``env`` (in place; also returned) so a CHILD process sees
    an n-device virtual CPU mesh: pins JAX_PLATFORMS=cpu and sets or
    REPLACES ``--xla_force_host_platform_device_count`` in XLA_FLAGS —
    the flag only takes effect before jax initialises, which is why
    every user of it re-execs (dryrun_multichip, the mesh smoke, the
    bench multichip leg; this is the one shared copy of that dance)."""
    import re

    env["JAX_PLATFORMS"] = "cpu"
    flag = f"--xla_force_host_platform_device_count={int(n)}"
    xf = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in xf:
        xf = re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, xf
        )
    else:
        xf = (xf + " " + flag).strip()
    env["XLA_FLAGS"] = xf
    return env


def backend_available(
    timeout_s: float = 120.0, accept_cpu: bool = True
) -> bool:
    """True when `jax.devices()` initializes within the timeout (in a
    subprocess — a hang or crash there cannot take the caller down).
    With accept_cpu=False a CPU-only backend counts as unavailable."""
    code = (
        "import jax\n"
        "ds = jax.devices()\n"
        "assert ds\n"
        "print('PROBE_OK', ds[0].platform)\n"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False
    if proc.returncode != 0:
        return False
    for line in proc.stdout.decode("utf-8", "replace").splitlines():
        if line.startswith("PROBE_OK"):
            platform = line.split()[-1].lower()
            if platform in ("cpu", "probe_ok") and not accept_cpu:
                return False
            return True
    return False
