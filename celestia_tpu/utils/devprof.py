"""Device-side observability: the DEVICE half of the tracing plane.

The span tracer (utils/tracing.py) sees host wall-clock only: with JAX's
async dispatch the ``extend.jax`` span measures ENQUEUE time, not where
the ~8 ms of device work at k=128 actually goes.  This module closes the
gap with three mechanisms, all built on the same sanctioned telemetry
clock and the same bounded-structure idioms as the rest of the plane:

* **Per-dispatch device timing** (:func:`dispatch`).  A dispatch bracket
  stamps t0 (before the jitted call), t1 (the call returned — enqueue
  complete) and, after ``jax.block_until_ready``, t2 (device drained).
  The t1→t2 interval is recorded as a span on a synthetic per-chip
  **"device" Chrome-trace track** (``thread_name="device:<platform>:<id>"``,
  one track per chip) parented under the host span that issued the
  dispatch — Perfetto shows host spans, enqueue time and device
  occupancy on one timeline, and dispatch gaps become visible pixels.
  The interval is queue-wait PLUS execution (an upper bound on
  occupancy): splitting the two needs the XLA profiler, which is the
  optional :func:`start_profiler` capture below.
* **XLA cost/memory accounting** (:func:`note_compile`).  Once per
  (kernel, arg-shapes) — deduped through a bounded :class:`LruCache` —
  the jitted function is AOT-lowered and compiled, and the measured
  compile time plus ``cost_analysis()`` FLOPs / bytes-accessed land in
  the kernel table (``celestia_tpu_xla_*`` on the exposition).  The
  2108.02692 roofline numbers become mechanical telemetry.
* **Device-memory watermarks** (:func:`sample_memory`).  Each completed
  dispatch (and every time-series snapshot) samples
  ``device.memory_stats()``; ``bytes_in_use`` / ``peak_bytes_in_use``
  (+ the fraction of ``bytes_limit`` when the platform reports one)
  become gauges and device-span args.

**CPU degradation contract** (tests/test_devprof.py): every one of
these degrades to a telemetry *note*, never an exception —
``memory_stats()`` returning None (CPU), ``cost_analysis()``
absent/raising on the platform, the profiler flag set without a TPU.
A CPU backend still gets a device track (``device:cpu:0``): the XLA CPU
stream has the same async-dispatch blind spot.

Activation: device-track spans ride the ONE tracing switch
(``tracing.enabled()``) — a traced node gets the device track with no
extra flag.  Bench legs that want occupancy/cost stats without the
trace ring arm the module directly via :func:`collect`.  Disabled, the
hot path pays one function call returning a shared no-op.

The optional ``jax.profiler`` capture (``--device-profile DIR`` /
``CELESTIA_TPU_DEVICE_PROFILE``) wraps :func:`start_profiler` /
:func:`stop_profiler` around the node's lifetime and writes a
TensorBoard/XPlane trace next to (not instead of) this module's
Chrome-track accounting.

celint R3: this module is on the SANCTIONED_CHANNELS list — its clock
reads go through :func:`telemetry.clock` and the entropy bans still
apply inside it.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Dict, List, Optional, Tuple

from celestia_tpu.utils import tracing
from celestia_tpu.utils.lru import LruCache
from celestia_tpu.utils.telemetry import (
    Log2Histogram,
    clock,
    escape_label_value,
    sanitize_metric_name,
)

ENV_PROFILE = "CELESTIA_TPU_DEVICE_PROFILE"

# Synthetic Chrome tid base for the per-chip device tracks: far above
# any OS thread id so device tracks never collide with host threads in
# the merged timeline (tid = base + device ordinal).
DEVICE_TID_BASE = 1 << 40

# bounded caps on the accounting maps: a kernel table can only hold as
# many rows as there are distinct jitted programs, but a hostile/buggy
# caller must not grow it without bound
_MAX_KERNELS = 128
_MAX_NOTES = 64

_lock = threading.Lock()
_force = False  # bench-style collection armed without the tracer
_window_t0: float = clock()  # occupancy window start (reset())
# per-device busy seconds + dispatch counts; celint: guarded-by(_lock)
_busy_s: Dict[str, float] = {}
_dispatch_counts: Dict[str, int] = {}
# per-kernel cost/compile accounting; celint: guarded-by(_lock)
_kernels: Dict[str, dict] = {}
# degradation notes (CPU fallbacks, platform gaps): kind -> {count, last};
# celint: guarded-by(_lock)
_notes: Dict[str, dict] = {}
# per-dispatch-name duration histograms; celint: guarded-by(_lock)
_dispatch_hist: Dict[str, Log2Histogram] = {}
# per-leg H2D/D2H transfer accounting (bytes + ms + event counts);
# celint: guarded-by(_lock)
_transfers: Dict[str, dict] = {}
_MAX_TRANSFER_LEGS = 64
# last sampled memory watermark; celint: guarded-by(_lock)
_mem: Optional[dict] = None
# previous occupancy probe (ts, summed busy seconds) for the
# inter-sample gauge; celint: guarded-by(_lock)
_probe_prev: Optional[Tuple[float, float]] = None
# one compile note per (kernel, shapes): bounded, R2-compliant
_seen_compiles = LruCache("devprof_compiles", 256, register=False)
# outstanding background cost-compile threads; celint: guarded-by(_lock)
_compile_threads: List[threading.Thread] = []
_MAX_OUTSTANDING_COMPILES = 8
_profiler_dir: Optional[str] = None


def active() -> bool:
    """True when dispatch bracketing is armed: the tracer is on (the
    device track rides the one tracing switch) or a :func:`collect`
    window is open (bench stats without the trace ring)."""
    return _force or tracing.enabled()


def note(kind: str, exc: BaseException) -> None:
    """Record a degradation note (bounded): the CPU-only contract is
    that every platform gap lands HERE, never as an exception on the
    block path."""
    with _lock:
        rec = _notes.get(kind)
        if rec is None:
            if len(_notes) >= _MAX_NOTES:
                return
            rec = _notes[kind] = {"count": 0, "last": ""}
        rec["count"] += 1
        rec["last"] = repr(exc)[:200]


def reset() -> None:
    """Drop all accounting and restart the occupancy window (bench leg
    boundary / tests).  Outstanding background cost-compiles are joined
    FIRST so a late-landing kernel row can never leak into the next
    epoch's table."""
    global _window_t0, _mem, _probe_prev
    flush_compiles()
    with _lock:
        _busy_s.clear()
        _dispatch_counts.clear()
        _kernels.clear()
        _notes.clear()
        _dispatch_hist.clear()
        _transfers.clear()
        _mem = None
        _probe_prev = None
        _window_t0 = clock()
    _seen_compiles.clear()


def restart_window() -> None:
    """Restart ONLY the occupancy window (busy counters + t0), keeping
    the kernel/cost table and notes.  The bench leg uses it to exclude
    the one-time AOT compile from the dispatch-occupancy measurement."""
    global _window_t0
    with _lock:
        _busy_s.clear()
        _dispatch_counts.clear()
        _window_t0 = clock()


def occupancy_probe() -> Optional[float]:
    """Occupancy percent over the interval since the PREVIOUS probe
    call — the CONTINUOUS sampler's gauge.  ``device_profile()``'s
    window figure is the since-reset aggregate, which on a long-lived
    node decays toward zero regardless of current load; per-interval
    deltas are what an operator alert can act on.  None on the first
    probe or an empty interval (the time-series collector then simply
    omits the metric — skip-absent, like every platform gap)."""
    global _probe_prev
    now = clock()
    with _lock:
        busy = sum(_busy_s.values())
        prev = _probe_prev
        _probe_prev = (now, busy)
    if prev is None:
        return None
    dt = now - prev[0]
    if dt <= 0:
        return None
    return round(max(0.0, min(100.0, 100.0 * (busy - prev[1]) / dt)), 2)


@contextlib.contextmanager
def collect():
    """Arm dispatch/cost collection for a scoped window without the
    tracer (the bench ``extras.device_profile`` leg): stats are reset on
    entry and the occupancy window spans exactly the ``with`` body."""
    global _force
    reset()
    _force = True
    try:
        yield
    finally:
        _force = False


# ---------------------------------------------------------------------------
# dispatch bracketing (the device track)
# ---------------------------------------------------------------------------


class _NullDispatch:
    """Disabled-path dispatch: one shared instance, ``done`` is identity."""

    __slots__ = ()

    def done(self, out):
        return out


NULL_DISPATCH = _NullDispatch()


def _device_of(out):
    """(platform, ordinal) of the device holding ``out`` (first array
    leaf); falls back to the default backend.  Never raises."""
    try:
        import jax

        for leaf in jax.tree_util.tree_leaves(out):
            devs = getattr(leaf, "devices", None)
            if callable(devs):
                got = devs()
                if got:
                    d = next(iter(got))
                    return str(d.platform), int(d.id), d
            d = getattr(leaf, "device", None)
            if d is not None and not callable(d):
                return str(d.platform), int(d.id), d
        d = jax.devices()[0]
        return str(d.platform), int(d.id), d
    except Exception as e:
        note("device_of", e)
        return "unknown", 0, None


def _devices_of_sharded(out):
    """Every (platform, ordinal, dev) a sharded output spans, ordinal-
    sorted, or None when no leaf exposes a sharding (single-device
    arrays, host fallbacks).  Never raises — a platform that cannot
    answer degrades to the single-device accounting."""
    try:
        import jax

        for leaf in jax.tree_util.tree_leaves(out):
            sh = getattr(leaf, "sharding", None)
            ds = getattr(sh, "device_set", None)
            if ds and len(ds) > 1:
                return sorted(
                    ((str(d.platform), int(d.id), d) for d in ds),
                    key=lambda t: t[1],
                )
    except Exception as e:
        note("devices_of_sharded", e)
    return None


def _sample_memory_of(dev) -> Optional[dict]:
    """memory_stats() of one device folded to the watermark dict, or
    None (CPU backends return None / raise — both degrade to a note).
    Caller holds no lock; only the shared-state write takes it."""
    global _mem
    if dev is None:
        return None
    try:
        stats = dev.memory_stats()
    except Exception as e:
        note("memory_stats", e)
        return None
    if not isinstance(stats, dict):
        note("memory_stats", ValueError(f"memory_stats() -> {type(stats).__name__}"))
        return None
    out = {
        "bytes_in_use": int(stats.get("bytes_in_use", 0) or 0),
        "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0) or 0),
    }
    limit = stats.get("bytes_limit")
    if isinstance(limit, (int, float)) and limit > 0:
        out["bytes_limit"] = int(limit)
        # frac is CURRENT usage (alertable: it falls when pressure
        # clears); peak_frac is the monotone lifetime high-water mark
        # (informational: jax never lowers it)
        out["frac"] = round(out["bytes_in_use"] / float(limit), 4)
        out["peak_frac"] = round(out["peak_bytes_in_use"] / float(limit), 4)
    with _lock:
        _mem = dict(out)
    return out


def sample_memory() -> Optional[dict]:
    """One watermark sample of the default device (the time-series
    collector's entry): ``{"bytes_in_use", "peak_bytes_in_use"
    [, "bytes_limit", "peak_frac"]}`` or None on a platform without
    memory stats (noted, never raised)."""
    try:
        import jax

        dev = jax.devices()[0]
    except Exception as e:
        note("devices", e)
        return None
    return _sample_memory_of(dev)


class Dispatch:
    """One device dispatch bracket.  Construct BEFORE the jitted call
    (stamps enqueue start), call :meth:`done` with the call's result —
    it blocks until the device drains, records the device-track span and
    the occupancy stats, and returns the result unchanged.

    With ``multi=True`` (sharded dispatches — parallel/sharded.py) the
    t1→t2 interval is recorded on EVERY chip the output is sharded
    across: one device-track span and one busy contribution per chip.
    All chips execute the collective program concurrently, so charging
    the full interval to each is the same queue-wait-plus-execution
    upper bound the single-device bracket records."""

    __slots__ = ("name", "args", "_t0", "_parent", "_multi")

    def __init__(self, name: str, args: dict, multi: bool = False):
        self.name = name
        self.args = args
        self._multi = multi
        self._parent = tracing.current()
        self._t0 = clock()

    def done(self, out):
        import jax

        t1 = clock()  # enqueue returned; device may still be running
        try:
            jax.block_until_ready(out)
        except Exception as e:
            # a dead tunnel mid-dispatch: the caller sees ITS error from
            # its own consumption of `out`; profiling must not preempt it
            note("block_until_ready", e)
            return out
        t2 = clock()
        devices = _devices_of_sharded(out) if self._multi else None
        if not devices:
            devices = [_device_of(out)]
        busy = max(0.0, t2 - t1)
        with _lock:
            for platform, ordinal, _dev in devices:
                key = f"{platform}:{ordinal}"
                _busy_s[key] = _busy_s.get(key, 0.0) + busy
            _dispatch_counts[self.name] = _dispatch_counts.get(self.name, 0) + 1
            hist = _dispatch_hist.get(self.name)
            if hist is None:
                hist = _dispatch_hist[self.name] = Log2Histogram()
        hist.observe(busy)
        # per-CHIP watermarks: each device track carries its OWN memory
        # numbers (an HBM imbalance across a sharded dispatch is exactly
        # what per-track spans exist to show); the module-level _mem
        # keeps the last sample, same as the single-device bracket
        mems = {
            ordinal: _sample_memory_of(dev)
            for _platform, ordinal, dev in devices
        }
        if tracing.enabled():
            for platform, ordinal, _dev in devices:
                key = f"{platform}:{ordinal}"
                span_args = dict(self.args)
                span_args["enqueue_ms"] = round((t1 - self._t0) * 1000.0, 3)
                span_args["device"] = key
                mem = mems.get(ordinal)
                if mem is not None:
                    span_args["mem_bytes_in_use"] = mem["bytes_in_use"]
                    span_args["mem_peak_bytes"] = mem["peak_bytes_in_use"]
                tracing.record_span(
                    f"device.{self.name}",
                    t1,
                    t2,
                    parent=self._parent,
                    cat="device",
                    tid=DEVICE_TID_BASE + ordinal,
                    thread_name=f"device:{key}",
                    **span_args,
                )
        return out


def dispatch(name: str, multi_device: bool = False, **args) -> Any:
    """Open a dispatch bracket (no-op shared instance when inactive).
    ``multi_device=True`` records the bracket on every chip a sharded
    output spans (one span per device track)."""
    if not active():
        return NULL_DISPATCH
    return Dispatch(name, args, multi=multi_device)


# ---------------------------------------------------------------------------
# H2D/D2H transfer accounting (the device-resident plane's ledger)
# ---------------------------------------------------------------------------


def record_transfer(
    leg: str, direction: str, nbytes: int, ms: float = 0.0
) -> None:
    """Charge one host<->device crossing to a named leg (``direction`` is
    ``"h2d"`` or ``"d2h"``).  Bytes are computed by the caller from array
    SHAPES — recording a transfer must never itself force one.  Inactive
    (no tracer, no :func:`collect` window), this is a no-op: the hot path
    pays one call + a bool."""
    if not active():
        return
    if direction not in ("h2d", "d2h"):
        raise ValueError(f"direction must be h2d/d2h, got {direction!r}")
    with _lock:
        rec = _transfers.get(leg)
        if rec is None:
            if len(_transfers) >= _MAX_TRANSFER_LEGS:
                return
            rec = _transfers[leg] = {
                "h2d_bytes": 0, "h2d_ms": 0.0, "h2d_events": 0,
                "d2h_bytes": 0, "d2h_ms": 0.0, "d2h_events": 0,
            }
        rec[f"{direction}_bytes"] += int(nbytes)
        rec[f"{direction}_ms"] += float(ms)
        rec[f"{direction}_events"] += 1


def fetch(leg: str, values):
    """``jax.device_get`` with transfer accounting: ONE batched D2H fetch
    of the whole pytree, charged to ``leg`` with its measured wall ms and
    the fetched byte count.  The sanctioned bulk-fetch primitive of the
    device-resident plane — per-array ``np.asarray`` pays a round trip
    each AND is invisible to the transfer ledger."""
    import jax

    if not active():
        return jax.device_get(values)
    t0 = clock()
    out = jax.device_get(values)
    ms = (clock() - t0) * 1000.0
    nbytes = 0
    try:
        for leaf in jax.tree_util.tree_leaves(out):
            nbytes += int(getattr(leaf, "nbytes", 0) or 0)
    except Exception as e:
        note("transfer_nbytes", e)
    record_transfer(leg, "d2h", nbytes, ms)
    return out


def transfer_accounting() -> Dict[str, dict]:
    """Per-leg transfer ledger snapshot:
    ``{leg: {h2d_bytes, h2d_ms, h2d_events, d2h_bytes, d2h_ms,
    d2h_events}}`` (bench ``extras.transfer_accounting`` + the
    device-resident smoke's only-sanctioned-D2H assertion)."""
    with _lock:
        return {
            leg: {
                k: (round(v, 3) if isinstance(v, float) else v)
                for k, v in rec.items()
            }
            for leg, rec in sorted(_transfers.items())
        }


# ---------------------------------------------------------------------------
# XLA cost / compile accounting
# ---------------------------------------------------------------------------


def _shape_key(args: Tuple[Any, ...]) -> tuple:
    return tuple(
        (tuple(getattr(a, "shape", ()) or ()), str(getattr(a, "dtype", "")))
        for a in args
    )


def _cost_fields(compiled) -> dict:
    """flops / bytes_accessed out of ``cost_analysis()`` across the
    jax-version shapes it has taken (dict, or list-of-dicts per
    partition); platform gaps fold to notes."""
    out: dict = {}
    try:
        ca = compiled.cost_analysis()
    except Exception as e:
        note("cost_analysis", e)
        return out
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        note("cost_analysis", ValueError(f"cost_analysis() -> {type(ca).__name__}"))
        return out
    for field, keys in (
        ("flops", ("flops",)),
        ("bytes_accessed", ("bytes accessed", "bytes_accessed")),
    ):
        for k in keys:
            v = ca.get(k)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[field] = float(v)
                break
    return out


def _run_compile(name: str, fn, args: Tuple[Any, ...]) -> None:
    """The background cost-compile body (one daemon thread per first
    sighting): measure the AOT lower+compile, harvest cost/memory
    analysis, land the kernel row.  Every failure is a note."""
    try:
        t0 = clock()
        try:
            compiled = fn.lower(*args).compile()
        except Exception as e:
            note(f"compile.{name}", e)
            return
        compile_ms = (clock() - t0) * 1000.0
        rec = {"compile_ms": round(compile_ms, 3)}
        rec.update(_cost_fields(compiled))
        try:
            mem = compiled.memory_analysis()
            for attr in ("temp_size_in_bytes", "output_size_in_bytes"):
                v = getattr(mem, attr, None)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    rec[attr.replace("_size_in_bytes", "_bytes")] = int(v)
        except Exception as e:
            note("memory_analysis", e)
        with _lock:
            if name in _kernels or len(_kernels) < _MAX_KERNELS:
                _kernels[name] = rec
    finally:
        me = threading.current_thread()
        with _lock:
            if me in _compile_threads:
                _compile_threads.remove(me)


def note_compile(name: str, fn, args: Tuple[Any, ...]) -> None:
    """Record compile time + XLA cost analysis for a jitted kernel, once
    per (name, arg shapes/dtypes).  The AOT lower+compile runs on a
    BACKGROUND daemon thread — a traced validator's block path must
    never stall to measure itself (the jitted call already compiled the
    program; this build exists only for the cost/compile figures).  The
    measured wall time of that build IS the recorded compile figure.
    Outstanding builds are bounded (excess first-sightings are dropped
    with a note) and joinable via :func:`flush_compiles` (bench/tests/
    gates read the table deterministically).  Every platform gap
    (``lower`` unsupported, ``cost_analysis`` absent) degrades to a
    note; the kernel row still lands with whatever fields resolved."""
    if not active():
        return
    if not _seen_compiles.add_if_absent((name, _shape_key(args))):
        return
    t = threading.Thread(
        target=_run_compile, args=(name, fn, args),
        name=f"devprof-compile-{name}", daemon=True,
    )
    with _lock:
        # note() ALSO takes _lock — it must be called after release (it
        # was not, once: celint R6's founding self-deadlock, hit exactly
        # when the outstanding-compile cap fired under armed profiling)
        dropped = len(_compile_threads) >= _MAX_OUTSTANDING_COMPILES
        if not dropped:
            _compile_threads.append(t)
    if dropped:
        note(
            "compile_queue",
            RuntimeError(f"outstanding-compile cap hit; dropped {name}"),
        )
        return
    t.start()


def flush_compiles(timeout_s: float = 60.0) -> None:
    """Join every outstanding background cost-compile (bench legs and
    the smoke gates call this before reading the kernel table)."""
    deadline = clock() + timeout_s
    while True:
        with _lock:
            threads = list(_compile_threads)
        if not threads:
            return
        for t in threads:
            t.join(timeout=max(0.0, deadline - clock()))
        if clock() >= deadline:
            return


# ---------------------------------------------------------------------------
# aggregate views (bench extras, time series, exposition)
# ---------------------------------------------------------------------------


def device_profile() -> dict:
    """The one-document device profile: per-kernel FLOPs/bytes/compile
    ms, per-dispatch counts + busy ms, occupancy over the current window
    (busy / wall, summed across chips), the last memory watermark, the
    degradation notes, and the backend identity.  Safe on any platform —
    a CPU-only process reports its CPU "chip" and folds the gaps to
    notes (the bench host-only leg records exactly this)."""
    try:
        import jax

        platform = str(jax.default_backend())
        num_devices = int(jax.local_device_count())
    except Exception as e:
        note("backend", e)
        platform, num_devices = "unavailable", 0
    with _lock:
        busy = dict(_busy_s)
        counts = dict(_dispatch_counts)
        kernels = {k: dict(v) for k, v in _kernels.items()}
        notes = {k: dict(v) for k, v in _notes.items()}
        mem = dict(_mem) if _mem is not None else None
        t0 = _window_t0
    wall_s = max(1e-9, clock() - t0)
    busy_ms_total = sum(busy.values()) * 1000.0
    return {
        "platform": platform,
        "num_devices": num_devices,
        "kernels": kernels,
        "dispatches": counts,
        "device_busy_ms": {k: round(v * 1000.0, 3) for k, v in busy.items()},
        "device_busy_ms_total": round(busy_ms_total, 3),
        "window_s": round(wall_s, 3),
        # mean occupancy ACROSS chips: multi-device brackets charge the
        # interval to every chip they span, so the wall denominator must
        # scale with the chips that reported busy time — a single-wall
        # denominator would inflate by the chip count and pin a mesh
        # node at the 100% cap, killing the falling-occupancy regression
        # signal exactly where it matters
        "device_occupancy_pct": round(
            min(
                100.0,
                100.0
                * busy_ms_total
                / (wall_s * 1000.0 * max(1, len(busy))),
            ),
            2,
        ),
        "mem": mem if mem is not None else {"available": False},
        "notes": notes,
    }


def dispatch_summary() -> Dict[str, dict]:
    """Per-dispatch-name duration aggregates (count/p50/p95/p99/max)."""
    with _lock:
        hists = dict(_dispatch_hist)
    return {name: h.summary() for name, h in sorted(hists.items())}


def exposition_lines() -> List[str]:
    """Prometheus lines for the device plane (``celestia_tpu_xla_*`` +
    ``celestia_tpu_device_*``), appended to the node's Metrics
    exposition by node/server.py.  Every line passes the shared
    format-validity gate."""
    with _lock:
        kernels = {k: dict(v) for k, v in _kernels.items()}
        busy = dict(_busy_s)
        notes_total = sum(v["count"] for v in _notes.values())
        mem = dict(_mem) if _mem is not None else None
    lines: List[str] = []
    for name, rec in sorted(kernels.items()):
        label = escape_label_value(sanitize_metric_name(name))
        for field in ("flops", "bytes_accessed", "compile_ms"):
            v = rec.get(field)
            if isinstance(v, (int, float)):
                lines.append(
                    f'celestia_tpu_xla_{field}{{kernel="{label}"}} {v}'
                )
    for key, sec in sorted(busy.items()):
        label = escape_label_value(key)
        lines.append(
            f'celestia_tpu_device_busy_ms{{device="{label}"}} '
            f"{round(sec * 1000.0, 3)}"
        )
    if mem is not None:
        lines.append(
            f"celestia_tpu_device_mem_bytes_in_use {mem['bytes_in_use']}"
        )
        lines.append(
            f"celestia_tpu_device_mem_peak_bytes {mem['peak_bytes_in_use']}"
        )
        if "peak_frac" in mem:
            lines.append(
                f"celestia_tpu_device_mem_peak_frac {mem['peak_frac']}"
            )
    lines.append(f"celestia_tpu_devprof_notes_total {notes_total}")
    return lines


# ---------------------------------------------------------------------------
# optional jax.profiler capture (--device-profile)
# ---------------------------------------------------------------------------


def start_profiler(log_dir: str) -> bool:
    """Start a ``jax.profiler`` trace capture into ``log_dir`` (the
    TensorBoard/XPlane format — per-op device timelines the Chrome
    track cannot see).  Returns False and records a note when the
    platform cannot capture (the flag set without a TPU must never
    raise)."""
    global _profiler_dir
    if _profiler_dir is not None:
        return True  # already capturing; one session per process
    try:
        import jax

        jax.profiler.start_trace(log_dir)
    except Exception as e:
        note("profiler.start", e)
        return False
    _profiler_dir = str(log_dir)
    return True


def stop_profiler() -> Optional[str]:
    """Stop the capture; returns the log dir when one was running (and
    stopped cleanly), None otherwise."""
    global _profiler_dir
    if _profiler_dir is None:
        return None
    out, _profiler_dir = _profiler_dir, None
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception as e:
        note("profiler.stop", e)
        return None
    return out


def profiler_dir() -> Optional[str]:
    return _profiler_dir
