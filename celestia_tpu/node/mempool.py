"""Prioritized mempool with TTL eviction.

Parity with the reference's consensus-side mempool config: v1 prioritized
mempool ordered by gas price, TTL of 5 blocks, MaxTxBytes bounded by the max
square (app/default_overrides.go:258-284; CAT pool spec
specs/src/specs/cat_pool.md is the gossip layer above this).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional

TTL_NUM_BLOCKS = 5


@dataclass
class MempoolTx:
    raw: bytes
    gas_price: float
    added_height: int
    tx_hash: bytes


class Mempool:
    def __init__(self, max_tx_bytes: int, ttl_blocks: int = TTL_NUM_BLOCKS):
        self.max_tx_bytes = max_tx_bytes
        self.ttl_blocks = ttl_blocks
        self._txs: Dict[bytes, MempoolTx] = {}
        self._order: Dict[bytes, int] = {}  # insertion sequence (FIFO ties)
        self._counter = 0

    def __len__(self) -> int:
        return len(self._txs)

    def add(self, raw: bytes, gas_price: float, height: int) -> bytes:
        if len(raw) > self.max_tx_bytes:
            raise ValueError(
                f"tx size {len(raw)} exceeds mempool max {self.max_tx_bytes}"
            )
        h = hashlib.sha256(raw).digest()
        if h not in self._txs:
            self._txs[h] = MempoolTx(raw, gas_price, height, h)
            self._order[h] = self._counter
            self._counter += 1
        return h

    def remove(self, tx_hash: bytes) -> None:
        self._txs.pop(tx_hash, None)
        self._order.pop(tx_hash, None)

    def reap(self, max_txs: Optional[int] = None) -> List[MempoolTx]:
        """Highest gas price first; strict FIFO within equal price (comet's
        prioritized mempool v1 ordering — a same-account sequence chain at
        one gas price must come out in submission order or FilterTxs drops
        the later nonces; data_square_layout.md 'Ordering')."""
        ordered = sorted(
            self._txs.values(),
            key=lambda t: (-t.gas_price, self._order[t.tx_hash]),
        )
        return ordered if max_txs is None else ordered[:max_txs]

    def recheck(self, still_valid) -> int:
        """Comet recheck parity: after a block commits, every pooled tx
        re-runs CheckTx against the fresh state; invalidated txs (spent
        balance, consumed sequence, expired timeout) leave the pool
        immediately instead of lingering until TTL.  Iterates in
        ADMISSION order — not reap order — because a same-account
        sequence chain was admitted oldest-nonce-first regardless of gas
        price, and rechecking a later nonce before an earlier one would
        wrongly evict a still-valid chain.
        still_valid(raw) -> bool; returns the eviction count."""
        evicted = 0
        for t in sorted(
            list(self._txs.values()), key=lambda t: self._order[t.tx_hash]
        ):
            if not still_valid(t.raw):
                self.remove(t.tx_hash)
                evicted += 1
        return evicted

    def evict_expired(self, current_height: int) -> int:
        expired = [
            h
            for h, t in self._txs.items()
            if current_height - t.added_height >= self.ttl_blocks
        ]
        for h in expired:
            del self._txs[h]
            self._order.pop(h, None)
        return len(expired)
