"""Cluster observability: fold N nodes' planes into one operator view.

Two rollups over the per-node RPC surface (node/server.py):

* **Cluster trace** — fan ``TraceDump`` out to every peer, probe each
  peer's clock offset (RPC midpoint method, ClockProbe), and merge the
  dumps into ONE Chrome trace-event document: one Chrome "process" per
  node (named by its node id), every timestamp shifted onto the
  collector's timeline, and every span that recorded an explicit
  cross-node parent (``remote_node``/``remote_span`` args — see
  utils/tracing.py) resolved into a flow arrow from the sender's span
  to the receiver's.  Open the result in Perfetto and the proposer's
  prepare, the validators' process legs and the gossip hops line up on
  adjacent tracks.

* **Cluster health** — fan ``Status`` + ``Metrics`` out and aggregate
  the operational signals one page answers: per-peer height/app-hash,
  gossip breaker states (PR 7), cache hit rates (PR 6), fault-note/
  degradation/shed totals and the per-RPC byte/call counters (PR 9).

Consumed by ``celestia-tpu query cluster-trace`` / ``cluster-health``
(cli.py) and the file-driven ``tools/trace_merge.py``.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from celestia_tpu.utils import faults, tracing

# ---------------------------------------------------------------------------
# collection (per peer)
# ---------------------------------------------------------------------------


def collect_trace(client, last: Optional[int] = None, probes: int = 5) -> dict:
    """One peer's TraceDump + clock offset, in the merge input shape:
    ``{"node_id", "clock_offset_s", "rtt_s", "enabled", "trace"}``.
    An un-upgraded peer without the ClockProbe RPC merges at offset 0
    (its track still renders; only alignment degrades), and a peer that
    dies between dial and fan-out contributes an empty track annotated
    with its error — the other N-1 nodes still merge."""
    try:
        out = client.trace_dump(last=last)
    except Exception as e:
        faults.note("cluster.trace_dump", e)
        return {
            "node_id": str(getattr(client, "address", "")),
            "clock_offset_s": 0.0,
            "rtt_s": 0.0,
            "enabled": False,
            "error": str(e)[:200],
            "trace": {"traceEvents": [], "otherData": {}},
        }
    trace = out.get("trace", {}) or {}
    node_id = str(
        trace.get("otherData", {}).get("node_id", "")
        or getattr(client, "address", "")
    )
    offset_s, rtt_s = 0.0, 0.0
    try:
        probe = client.clock_offset(samples=probes)
        offset_s, rtt_s = probe["offset_s"], probe["rtt_s"]
    except Exception as e:
        faults.note("cluster.clock_probe", e)
    return {
        "node_id": node_id,
        "clock_offset_s": offset_s,
        "rtt_s": rtt_s,
        "enabled": bool(out.get("enabled")),
        "trace": trace,
    }


# ---------------------------------------------------------------------------
# merge
# ---------------------------------------------------------------------------


def merge_node_dumps(parts: List[dict]) -> dict:
    """Fold N per-node trace parts (:func:`collect_trace` shape, or a
    bare Chrome doc under ``"trace"``) into one Perfetto timeline.

    Per part: a distinct Chrome pid with a ``process_name`` metadata
    event carrying the node id; every event's ``ts`` shifted by that
    node's ``clock_offset_s`` (peer minus collector, so subtracting
    lands on the collector's axis).  Then every event whose args name a
    cross-node parent is resolved against a (node, span) index of ALL
    parts and emitted as a Chrome flow ``s``/``f`` pair — the explicit
    cross-node link between the sender's span and the receiver's."""
    events_out: List[dict] = []
    span_index: Dict[Tuple[str, int], dict] = {}
    linked: List[Tuple[dict, dict]] = []  # (event, its remote args)
    nodes: List[dict] = []
    for i, part in enumerate(parts):
        pid = i + 1
        trace = part.get("trace", part) or {}
        node_id = str(
            part.get("node_id", "")
            or trace.get("otherData", {}).get("node_id", "")
            or f"node-{pid}"
        )
        offset_us = float(part.get("clock_offset_s", 0.0) or 0.0) * 1e6
        node_entry = {
            "node_id": node_id,
            "pid": pid,
            "clock_offset_s": part.get("clock_offset_s", 0.0),
            "rtt_s": part.get("rtt_s", 0.0),
        }
        if part.get("error"):
            # a peer that failed collection still gets its (empty) track,
            # but the merged doc must say WHY it is empty — "unreachable"
            # and "tracing off" are different operator problems
            node_entry["error"] = part["error"]
        nodes.append(node_entry)
        events_out.append(
            {
                "ph": "M",
                "name": "process_name",
                "pid": pid,
                "args": {"name": node_id},
            }
        )
        events_out.append(
            {
                "ph": "M",
                "name": "process_sort_index",
                "pid": pid,
                "args": {"sort_index": pid},
            }
        )
        for ev in trace.get("traceEvents", []):
            if not isinstance(ev, dict):
                continue
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                continue  # replaced by the per-node entry above
            ev = dict(ev, pid=pid)
            if "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) - offset_us, 3)
            args = ev.get("args")
            if isinstance(args, dict):
                sid = args.get("span_id")
                if isinstance(sid, int) and sid > 0 and ev.get("ph") in (
                    "X", "b"
                ):
                    span_index.setdefault((node_id, sid), ev)
                if args.get("remote_node") and args.get("remote_span"):
                    linked.append((ev, args))
            events_out.append(ev)
    flows = _flow_events(span_index, linked)
    events_out.extend(flows)
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events_out,
        "otherData": {
            "tracer": "celestia-tpu-cluster",
            "nodes": nodes,
            "cross_node_flows": len(flows) // 2,
        },
    }


def _flow_events(
    span_index: Dict[Tuple[str, int], dict],
    linked: List[Tuple[dict, dict]],
) -> List[dict]:
    """Chrome flow ``s``/``f`` pairs for every resolvable cross-node
    link.  The ``s`` event binds inside the SOURCE span's interval (its
    end, minus an epsilon: the send happens after the work) and the
    ``f`` event (``bp: "e"``) inside the destination's start — the
    binding rule Perfetto uses to attach arrows to slices.  Links whose
    source span lives in a dump we did not collect (ring rolled over,
    peer missing) are skipped — attribution degrades, never errors."""
    out: List[dict] = []
    flow_id = 0
    for ev, args in linked:
        src = span_index.get((args["remote_node"], args["remote_span"]))
        if src is None or src is ev:
            continue
        flow_id += 1
        src_ts = float(src.get("ts", 0.0))
        src_end = src_ts + max(0.0, float(src.get("dur", 0.0)) - 1.0)
        base = {
            "name": "xnode",
            "cat": "xnode",
            "id": str(flow_id),
        }
        out.append(
            dict(
                base,
                ph="s",
                pid=src["pid"],
                tid=src.get("tid", 0),
                ts=round(src_end, 3),
            )
        )
        out.append(
            dict(
                base,
                ph="f",
                bp="e",
                pid=ev["pid"],
                tid=ev.get("tid", 0),
                ts=round(float(ev.get("ts", 0.0)) + 1.0, 3),
            )
        )
    return out


def cluster_trace(
    clients, last: Optional[int] = None, probes: int = 5
) -> dict:
    """Fan TraceDump+ClockProbe out to every client and merge: the
    ``query cluster-trace`` backend.  Returns the merged Chrome doc."""
    return merge_node_dumps(
        [collect_trace(c, last=last, probes=probes) for c in clients]
    )


# ---------------------------------------------------------------------------
# per-height mesh waterfall
# ---------------------------------------------------------------------------


def mesh_waterfall(doc: dict, height: Optional[int] = None) -> dict:
    """Per-height latency waterfall across a merged mesh trace.

    For every height with a block root in ``doc`` (a
    :func:`merge_node_dumps` product — all timestamps on the
    collector's clock axis): the proposer's prepare wall, each
    validator's process wall with its propagation hop (``_tc`` send ts
    shifted by the node's clock offset, clamped at 0 on skew), start /
    end offsets relative to the proposer's prepare start, the
    propagation SPREAD (max - min hop delay: how unevenly gossip
    reached the mesh) and the slowest validator NAMED (latest
    wall-clock finisher — the node actually holding up the round).
    ``height`` filters to one height; default rolls up every height in
    the doc.
    """
    from celestia_tpu.utils import critpath

    spans, offsets = critpath.extract_spans(doc)
    by_height: Dict[int, list] = {}
    for s in spans:
        if s.name not in critpath.BLOCK_ROOT_NAMES:
            continue
        try:
            h = int(s.args.get("height"))
        except (TypeError, ValueError):
            continue
        if height is not None and h != int(height):
            continue
        by_height.setdefault(h, []).append(s)

    heights = []
    for h in sorted(by_height):
        roots = by_height[h]
        proposer = None
        for s in roots:
            if s.name == "prepare_proposal" and (
                proposer is None or s.t0 < proposer.t0
            ):
                proposer = s
        t_zero = proposer.t0 if proposer is not None else min(s.t0 for s in roots)
        validators = []
        for s in sorted(
            (x for x in roots if x.name == "process_proposal"),
            key=lambda x: x.t0,
        ):
            entry = {
                "node": s.node,
                "process_ms": round(s.wall_ms, 3),
                "start_ms": round((s.t0 - t_zero) * 1000.0, 3),
                "end_ms": round((s.t1 - t_zero) * 1000.0, 3),
            }
            hop = critpath.hop_delay_ms(s, offsets)
            if hop is not None:
                entry["propagation_ms"], entry["clamped"] = hop
            validators.append(entry)
        delays = [
            v["propagation_ms"] for v in validators if "propagation_ms" in v
        ]
        slowest = max(validators, key=lambda v: v["end_ms"], default=None)
        row = {
            "height": h,
            "proposer": (
                {
                    "node": proposer.node,
                    "prepare_ms": round(proposer.wall_ms, 3),
                }
                if proposer is not None
                else None
            ),
            "validators": validators,
            "propagation_spread_ms": (
                round(max(delays) - min(delays), 3) if delays else None
            ),
            "slowest_validator": slowest["node"] if slowest else None,
        }
        heights.append(row)
    return {
        "heights": heights,
        "nodes": sorted({s.node for s in spans if s.node}),
    }


# ---------------------------------------------------------------------------
# cluster health
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?'
    r' (?P<value>[+-]?[0-9.eE+-]+)$'
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Minimal Prometheus text parse: (metric, labels, value) triples.
    Comment/TYPE lines are skipped; unparseable lines are ignored (the
    exposition's own validity gate lives in telemetry tests)."""
    out = []
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        m = _SAMPLE_RE.match(ln)
        if m is None:
            continue
        labels = dict(_LABEL_RE.findall(m.group("labels") or ""))
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        out.append((m.group("name"), labels, value))
    return out


def _peer_health(client) -> dict:
    status = client.status()
    samples = parse_exposition(client.metrics())
    by_name: Dict[str, float] = {}
    cache_hits: Dict[str, float] = {}
    cache_misses: Dict[str, float] = {}
    rpc: Dict[str, dict] = {}
    alerts: Dict[str, bool] = {}
    peer_served: Dict[str, int] = {}
    peer_shed: Dict[str, int] = {}
    lanes: Dict[str, dict] = {}
    node_info = ""
    for name, labels, value in samples:
        if name == "celestia_tpu_node_info":
            node_info = labels.get("node_id", "")
        elif name == "celestia_tpu_cache_hits_total":
            cache_hits[labels.get("cache", "?")] = value
        elif name == "celestia_tpu_cache_misses_total":
            cache_misses[labels.get("cache", "?")] = value
        elif name == "celestia_tpu_alert_firing":
            alerts[labels.get("rule", "?")] = bool(value)
        elif name == "celestia_tpu_das_peer_served_total":
            peer_served[labels.get("peer", "?")] = int(value)
        elif name == "celestia_tpu_das_peer_shed_total":
            peer_shed[labels.get("peer", "?")] = int(value)
        elif name.startswith("celestia_tpu_das_lane_"):
            lane = labels.get("lane", "?")
            key = name[len("celestia_tpu_das_lane_"):].replace("_total", "")
            lanes.setdefault(lane, {})[key] = int(value)
        elif name.startswith("celestia_tpu_rpc_"):
            m = re.match(
                r"celestia_tpu_rpc_(client_)?(\w+?)_"
                r"(calls|bytes_in|bytes_out|errors)_total$",
                name,
            )
            if m:
                side = "client" if m.group(1) else "server"
                method = m.group(2)
                rpc.setdefault(side, {}).setdefault(method, {})[
                    m.group(3)
                ] = int(value)
        elif not labels:
            by_name[name] = value
    caches = {
        name: {
            "hits": int(hits),
            "misses": int(cache_misses.get(name, 0)),
            "hit_rate": round(
                hits / (hits + cache_misses.get(name, 0)), 4
            )
            if (hits + cache_misses.get(name, 0)) > 0
            else 0.0,
        }
        for name, hits in sorted(cache_hits.items())
    }
    gossip = status.get("gossip", {})
    # DAS serving plane rollup (batch prover + das_rows cache): read
    # straight off the scrape, so the page needs no extra RPC.  Single-
    # cell + batch sheds combined — a mesh shedding only on the batch
    # plane must not read as a healthy serving plane — and computed
    # ONCE: the legacy top-level das_shed references the same figure.
    das = {
        "samples_served": int(
            by_name.get("celestia_tpu_das_samples_served_total", 0)
        ),
        "batch_calls": int(
            by_name.get("celestia_tpu_das_batch_calls_total", 0)
        ),
        "shed": int(
            by_name.get("celestia_tpu_das_sample_shed_total", 0)
        )
        + int(by_name.get("celestia_tpu_das_batch_shed_total", 0)),
        "rows_hit_rate": float(
            by_name.get("celestia_tpu_das_rows_hit_rate", 0.0)
        ),
        # per-peer QoS accounting (bounded labels — the serving node's
        # LRU-backed registry caps cardinality): identified clients'
        # served/shed counts, per-lane gate pressure, and this node's
        # own fairness index (None until a peer has been served —
        # skip-absent survives the scrape)
        "clients": len(peer_served),
        "peer_served": peer_served,
        "peer_shed": peer_shed,
        "lanes": lanes,
        "fairness_index": (
            float(by_name["celestia_tpu_das_fairness_index"])
            if "celestia_tpu_das_fairness_index" in by_name
            else None
        ),
    }
    return {
        "node_id": node_info
        or str(getattr(client, "address", "") or status.get("chain_id", "")),
        "address": str(getattr(client, "address", "")),
        "chain_id": status.get("chain_id", ""),
        "height": int(status.get("height", 0)),
        "app_hash": status.get("app_hash", ""),
        "data_root": status.get("data_root", ""),
        "gossip": {
            "peers": gossip.get("peers", 0),
            "dropped_total": gossip.get("dropped_total", 0),
            "pull_breakers": gossip.get("pull_breakers", {}),
        },
        "fault_notes": int(by_name.get("celestia_tpu_fault_notes_total", 0)),
        "degradations": int(
            by_name.get("celestia_tpu_degradations_total", 0)
        ),
        "das_shed": das["shed"],
        "das": das,
        "caches": caches,
        "rpc": rpc,
        # trace-ring health (PR 11 satellite): silent span truncation
        # and a ballooning background ring on a busy node are now
        # visible from the scrape, not only in a local dump
        "trace": {
            "span_drops": int(
                by_name.get("celestia_tpu_trace_span_drops_total", 0)
            ),
            "background_depth": int(
                by_name.get("celestia_tpu_trace_background_depth", 0)
            ),
        },
        # declarative alert states (utils/timeseries.py): rule -> firing
        "alerts": alerts,
        "alerts_firing": sum(1 for v in alerts.values() if v),
        # flight-recorder incident count (lifetime, from the scrape) —
        # a node that has been black-boxing incidents is visible
        # mesh-wide without a second RPC
        "incidents": int(
            by_name.get("celestia_tpu_flight_incidents_total", 0)
        ),
    }


def _aggregate_clients(healthy: List[dict]) -> Dict[str, Dict[str, int]]:
    """Per-CLIENT served/shed summed across every serving node (one
    light client may sample from many nodes — fairness is judged on
    what the mesh as a whole gave it)."""
    agg: Dict[str, Dict[str, int]] = {}
    for p in healthy:
        das = p.get("das", {})
        for cid, served in das.get("peer_served", {}).items():
            agg.setdefault(cid, {"served": 0, "shed": 0})["served"] += served
        for cid, shed in das.get("peer_shed", {}).items():
            agg.setdefault(cid, {"served": 0, "shed": 0})["shed"] += shed
    return agg


def _mesh_fairness(healthy: List[dict]):
    from celestia_tpu.utils.telemetry import jain_fairness_index

    agg = _aggregate_clients(healthy)
    return jain_fairness_index(st["served"] for st in agg.values())


def _top_over_askers(healthy: List[dict], k: int = 5) -> List[dict]:
    agg = _aggregate_clients(healthy)
    ranked = sorted(
        agg.items(),
        key=lambda it: (-(it[1]["served"] + it[1]["shed"]), it[0]),
    )
    return [
        {"peer": cid, "served": st["served"], "shed": st["shed"]}
        for cid, st in ranked[:k]
    ]


def cluster_health(clients, probes: int = 3) -> dict:
    """The coordinator-side aggregated health page: per-peer status +
    metrics rollup plus cluster-level agreement/spread summary.  An
    unreachable peer is reported with its error, never dropped
    silently."""
    peers: List[dict] = []
    for client in clients:
        addr = str(getattr(client, "address", ""))
        try:
            h = _peer_health(client)
            try:
                h["clock_offset_s"] = client.clock_offset(samples=probes)[
                    "offset_s"
                ]
            except Exception as e:  # un-upgraded peer: offset unknown
                faults.note("cluster.clock_probe", e)
                h["clock_offset_s"] = None
            peers.append(h)
        except Exception as e:
            peers.append({"node_id": addr, "error": str(e)[:200]})
    healthy = [p for p in peers if "error" not in p]
    heights = [p["height"] for p in healthy]
    # app-hash agreement is judged among the peers AT the max height;
    # laggards are a spread problem, not (yet) a fork
    top = [p for p in healthy if heights and p["height"] == max(heights)]
    return {
        "peers": peers,
        "reachable": len(healthy),
        "unreachable": len(peers) - len(healthy),
        "min_height": min(heights) if heights else 0,
        "max_height": max(heights) if heights else 0,
        "height_spread": (max(heights) - min(heights)) if heights else 0,
        # None (unknown) when nobody answered: a fully-dark cluster must
        # not read as healthy consensus to automation keying off this
        "app_hash_agree": (
            len({p["app_hash"] for p in top}) <= 1 if top else None
        ),
        "breakers_open": sum(
            1
            for p in healthy
            for state in p["gossip"]["pull_breakers"].values()
            if state != "closed"
        ),
        "degradations": sum(p["degradations"] for p in healthy),
        "das_shed": sum(p["das_shed"] for p in healthy),
        # serving-plane rollup: total cells served across the mesh and
        # the peers shedding batch load (the ones to scale out first)
        "das_samples_served": sum(
            p.get("das", {}).get("samples_served", 0) for p in healthy
        ),
        "das_shedding_peers": sorted(
            p["node_id"]
            for p in healthy
            if p.get("das", {}).get("shed", 0) > 0
        ),
        # swarm fairness rollup: Jain index over per-CLIENT served
        # counts aggregated across every serving node (None until any
        # node reports identified peers), and the top over-askers NAMED
        # — the clients to demote/pin first
        "das_fairness_index": _mesh_fairness(healthy),
        "das_top_over_askers": _top_over_askers(healthy),
        "fault_notes": sum(p["fault_notes"] for p in healthy),
        # mesh-wide degradation flags (PR 11): summed trace truncation
        # and every peer with at least one firing alert rule — the
        # degrading node is NAMED across the mesh, not observed post-hoc
        "trace_span_drops": sum(
            p.get("trace", {}).get("span_drops", 0) for p in healthy
        ),
        "alerts_firing": sum(p.get("alerts_firing", 0) for p in healthy),
        "degraded_peers": sorted(
            p["node_id"] for p in healthy if p.get("alerts_firing", 0) > 0
        ),
        # flight-recorder rollup: total incidents across the mesh plus
        # every peer that captured at least one (named, like
        # degraded_peers — the operator pulls those bundles first)
        "incidents": sum(p.get("incidents", 0) for p in healthy),
        "incident_peers": sorted(
            p["node_id"] for p in healthy if p.get("incidents", 0) > 0
        ),
        "collector_node_id": tracing.node_id(),
    }


def discover_peers(client, max_peers: int = 64) -> List[str]:
    """Peer addresses learned from one node's PEX surface (the CLI's
    fan-out discovery when --nodes is not given).  Returns dialable
    addresses, the seed's own excluded."""
    try:
        peers = client.peer_exchange("", [])
    except Exception as e:
        faults.note("cluster.discover", e)
        return []
    out: List[str] = []
    for addr in peers:
        if isinstance(addr, str) and addr and addr not in out:
            out.append(addr)
        if len(out) >= max_peers:
            break
    return out
