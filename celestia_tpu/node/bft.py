"""Two-phase BFT consensus engine: per-validator Tendermint state machine.

VERDICT r2 next-round #5: prevote/precommit with 2/3 quorums, proposer
locking, timeout-driven rounds — each validator decides commit from the
votes IT has verified, with any coordinator acting as dumb transport
only.  This replaces the round-1/2 single-phase centrally-sequenced
commit (node/network.py keeps the legacy driver for replication tests).

Role parity: celestia-core's consensus state machine (SURVEY §2.2; the
algorithm is Tendermint consensus, Buchman-Kwon-Milosevic
arXiv:1807.04938).  The implementation is message-driven and clock-free:
the engine never reads a wall clock — transports deliver messages via
``receive`` and fire ``on_timeout_*`` when their timers lapse, which is
what makes safety properties unit-testable (partitions, conflicting
proposals, dropped messages) without real time.

Safety intuition, enforced by the vote rules below:
- a validator PREVOTES a proposal only if it validates on its own state
  AND does not conflict with a block it locked earlier;
- it LOCKS (and precommits) only after seeing a 2/3-power polka of
  prevotes for that exact block in the current round;
- once locked, it prevotes against competing proposals unless a LATER
  polka (proof-of-lock round >= its lock round) justifies unlocking;
- it DECIDES only on 2/3-power precommits for one block in one round.
Two conflicting blocks can thus both commit at a height only if >= 1/3
of the power signed conflicting votes — the standard BFT bound, and the
engine reports every such double-sign it observes via on_equivocation.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set, Tuple

from celestia_tpu.utils.secp256k1 import PrivateKey, PublicKey

# The wire/crypto primitives (NIL, PREVOTE/PRECOMMIT, _varint,
# block_id_of, vote_sign_bytes, proposal_sign_bytes, Vote) moved to
# state/consensus.py so the IBC light client and the persistence layer
# can use them WITHOUT importing node/ (celint R8); re-exported here so
# engine-side callers are unchanged.
from celestia_tpu.state.consensus import (  # noqa: F401
    NIL,
    PRECOMMIT,
    PREVOTE,
    Vote,
    _varint,
    block_id_of,
    proposal_sign_bytes,
    vote_sign_bytes,
)

STEP_PROPOSE = "propose"
STEP_PREVOTE = "prevote"
STEP_PRECOMMIT = "precommit"


@dataclass(frozen=True)
class BlockPayload:
    """What a proposal carries: everything needed to validate + finalize.

    ``last_commit`` is the precommit certificate for height-1 as observed
    by THIS block's proposer.  Replicas verify it (>= 2/3 power of valid
    signatures over the previous block id) and feed it to finalization as
    LastCommitInfo — the Tendermint pattern of carrying block H-1's
    commit inside block H so all replicas apply identical reward/slash
    inputs regardless of which certificate their own engine assembled.
    """

    height: int
    time_ns: int
    square_size: int
    data_root: bytes
    txs: Tuple[bytes, ...]
    proposer: bytes = b""
    last_commit: Tuple["Vote", ...] = ()
    # the app hash committed by block height-1 (Tendermint header.AppHash);
    # replicas reject a payload whose value differs from their own commit,
    # so a 2/3 certificate over this block id PROVES the state root to
    # IBC light clients
    prev_app_hash: bytes = b""

    def last_commit_digest(self) -> bytes:
        h = hashlib.sha256(b"last-commit")
        for v in self.last_commit:
            h.update(v.validator)
            h.update(_varint(v.round))
            h.update(v.block_id)
            h.update(v.signature)
        return h.digest()

    @property
    def block_id(self) -> bytes:
        return block_id_of(
            self.height, self.time_ns, self.square_size, self.data_root,
            self.proposer, self.last_commit_digest(), self.prev_app_hash,
        )

    def header_fields(self) -> dict:
        """The block-id preimage WITHOUT txs — what an IBC light client
        needs to recompute the id a commit certificate signs."""
        return {
            "height": self.height,
            "time_ns": self.time_ns,
            "square_size": self.square_size,
            "data_root": self.data_root.hex(),
            "proposer": self.proposer.hex(),
            "last_commit_digest": self.last_commit_digest().hex(),
            "prev_app_hash": self.prev_app_hash.hex(),
        }

    def commit_signers(self) -> Set[bytes]:
        return {v.validator for v in self.last_commit}

    def to_wire(self) -> dict:
        return {
            "height": self.height,
            "time_ns": self.time_ns,
            "square_size": self.square_size,
            "data_root": self.data_root.hex(),
            "txs": [t.hex() for t in self.txs],
            "proposer": self.proposer.hex(),
            "last_commit": [v.to_wire() for v in self.last_commit],
            "prev_app_hash": self.prev_app_hash.hex(),
        }

    @classmethod
    def from_wire(cls, d: dict) -> "BlockPayload":
        height = int(d["height"])
        time_ns = int(d["time_ns"])
        square_size = int(d["square_size"])
        if height <= 0 or time_ns < 0 or square_size < 0:
            # negative ints would spin _varint forever in block_id_of
            raise ValueError("payload fields out of range")
        return cls(
            height=height,
            time_ns=time_ns,
            square_size=square_size,
            data_root=bytes.fromhex(d["data_root"]),
            txs=tuple(bytes.fromhex(t) for t in d["txs"]),
            proposer=bytes.fromhex(d.get("proposer", "")),
            last_commit=tuple(
                Vote.from_wire(v) for v in d.get("last_commit", [])
            ),
            prev_app_hash=bytes.fromhex(d.get("prev_app_hash", "")),
        )


@dataclass(frozen=True)
class Proposal:
    height: int
    round: int
    pol_round: int  # proof-of-lock round; -1 = fresh proposal
    payload: BlockPayload
    proposer: bytes  # validator operator address
    signature: bytes = b""

    def to_wire(self) -> dict:
        return {
            "kind": "proposal",
            "height": self.height,
            "round": self.round,
            "pol_round": self.pol_round,
            "payload": self.payload.to_wire(),
            "proposer": self.proposer.hex(),
            "signature": self.signature.hex(),
        }

    @classmethod
    def from_wire(cls, d: dict) -> "Proposal":
        height = int(d["height"])
        round_ = int(d["round"])
        pol_round = int(d["pol_round"])
        if height <= 0 or round_ < 0 or pol_round < -1:
            raise ValueError("proposal fields out of range")
        return cls(
            height=height,
            round=round_,
            pol_round=pol_round,
            payload=BlockPayload.from_wire(d["payload"]),
            proposer=bytes.fromhex(d["proposer"]),
            signature=bytes.fromhex(d["signature"]),
        )


def msg_from_wire(d: dict):
    return Proposal.from_wire(d) if d["kind"] == "proposal" else Vote.from_wire(d)


@dataclass
class DecidedBlock:
    payload: BlockPayload
    round: int
    # the precommits that justify the decision (>= 2/3 power): the commit
    # certificate a late joiner can verify, and LastCommitInfo's source
    precommits: List[Vote] = field(default_factory=list)


# Ceiling on how far ahead of the validator's own clock a proposed block
# timestamp may sit.  Tendermint derives BFT-time from commit votes; here
# the proposer names the time and every replica enforces the same two
# rules (strict monotonicity + bounded drift), which keeps _now_ns, block
# headers and time-based mint inflation out of a Byzantine proposer's
# control (reference: celestia-core header validation / BFT-time).
DEFAULT_MAX_TIME_DRIFT_NS = 60_000_000_000  # 60 s


def validate_payload_against_chain(
    engine: "BFTNode",
    payload: BlockPayload,
    prev_block_id: Optional[bytes],
    first_bft_height: int = 2,
    expected_prev_app_hash: Optional[bytes] = None,
    prev_time_ns: Optional[int] = None,
    now_ns: Optional[int] = None,
    max_drift_ns: int = DEFAULT_MAX_TIME_DRIFT_NS,
) -> Tuple[bool, str]:
    """Shared certificate-validation glue for every transport tier.

    - At the first BFT height there is no previous certificate, so the
      payload's last_commit must be EMPTY — a proposer cannot smuggle
      fabricated (unverified) votes into LastCommitInfo.
    - Past it, the previous block id must be known and the certificate
      must verify at >= 2/3 power (verify_commit_certificate).
    - When the validator knows its own committed app hash for height-1,
      the payload's prev_app_hash must equal it — this is what turns a
      commit certificate into a light-client-verifiable state-root proof
      (Tendermint header.AppHash semantics).
    - When the caller supplies prev_time_ns (its last committed block
      time), the payload's time must be strictly after it; when it
      supplies now_ns (its own clock), the payload's time must be within
      max_drift_ns of it.  Every tier inherits the timestamp rules by
      validating through this one path.
    """
    if prev_time_ns is not None and payload.time_ns <= prev_time_ns:
        return False, "proposal time is not after the previous block"
    if now_ns is not None and payload.time_ns > now_ns + max_drift_ns:
        return False, "proposal time is beyond the allowed clock drift"
    if expected_prev_app_hash is not None and payload.prev_app_hash != (
        expected_prev_app_hash
    ):
        return False, "prev_app_hash does not match the committed state"
    if payload.height <= first_bft_height:
        if payload.last_commit:
            return False, "first BFT height must carry an empty last_commit"
        return True, ""
    if prev_block_id is None:
        return False, "unknown previous block"
    return engine.verify_commit_certificate(
        payload, prev_block_id, payload.height - 1
    )


def verify_commit_certificate(
    chain_id: str,
    validators: Dict[bytes, int],
    pubkeys: Dict[bytes, bytes],
    total_power: int,
    payload: "BlockPayload",
    precommits: List["Vote"],
) -> Tuple[bool, str]:
    """Standalone 2/3 commit-certificate check over one block id: the
    verification core of adopt_decision, callable WITHOUT an engine and
    with no side effects — state-sync verifies a snapshot's anchoring
    certificate with this before swapping any state in."""
    h = payload.height
    bid = payload.block_id
    rounds = {v.round for v in precommits}
    if len(rounds) != 1:
        return False, "certificate mixes rounds"
    seen: Set[bytes] = set()
    power = 0
    for v in precommits:
        if v.round < 0:
            return False, "negative round in certificate"
        if v.vtype != PRECOMMIT or v.height != h or v.block_id != bid:
            return False, "certificate vote does not match the block"
        if v.validator in seen:
            return False, "duplicate validator in certificate"
        seen.add(v.validator)
        vp = validators.get(v.validator)
        pk_raw = pubkeys.get(v.validator)
        if not vp or pk_raw is None:
            return False, "unknown validator in certificate"
        digest = vote_sign_bytes(chain_id, v.height, v.round, v.vtype, v.block_id)
        if not PublicKey.from_compressed(pk_raw).verify(digest, v.signature):
            return False, "certificate signature invalid"
        power += vp
    if power * 3 < total_power * 2:
        return False, "certificate below 2/3 power"
    return True, ""


def last_commit_vote_pairs(
    validators: Dict[bytes, int], payload: BlockPayload
) -> List[Tuple[bytes, bool]]:
    """LastCommitInfo derivation shared by every tier: (address, signed)
    over the SORTED valset, driven only by the payload's certificate —
    identical on every replica by construction."""
    if payload.last_commit:
        signers = payload.commit_signers()
        return [(addr, addr in signers) for addr in sorted(validators)]
    return [(addr, True) for addr in sorted(validators)]


class BFTNode:
    """One validator's consensus state machine.

    Inputs: ``receive(msg)`` (from the transport), ``on_timeout_*``
    (from the transport's timers), ``start_height()``.
    Outputs: ``outbox`` (messages to gossip — the transport drains it),
    ``decided`` (height -> DecidedBlock), ``on_decide`` callback.
    The engine never calls the network and never sleeps.
    """

    def __init__(
        self,
        chain_id: str,
        key: PrivateKey,
        validators: Dict[bytes, int],  # operator address -> power
        validate_fn: Callable[[BlockPayload], Tuple[bool, str]],
        propose_fn: Callable[[int, int], Optional[BlockPayload]],
        on_decide: Optional[Callable[[DecidedBlock], None]] = None,
        on_equivocation: Optional[Callable[[Vote, Vote], None]] = None,
        pubkeys: Optional[Dict[bytes, bytes]] = None,
    ):
        """validate_fn runs ProcessProposal on the validator's own app;
        propose_fn(height, round) builds a fresh payload from its own
        mempool (returns None if this validator cannot propose — e.g.
        crashed app — which forfeits the round).
        pubkeys: operator address -> 33-byte compressed secp256k1 key;
        defaults to addresses derived from nothing — supply it unless all
        peers share this process (then keys are registered via
        register_pubkey)."""
        self.chain_id = chain_id
        self.key = key
        self.address = key.public_key().address()
        self.validators = dict(validators)
        self.total_power = sum(validators.values())
        self.pubkeys: Dict[bytes, bytes] = dict(pubkeys or {})
        self.pubkeys[self.address] = key.public_key().compressed()
        self.validate_fn = validate_fn
        self.propose_fn = propose_fn
        self.on_decide = on_decide
        self.on_equivocation = on_equivocation

        self.height = 0
        self.round = 0
        self.step = STEP_PROPOSE
        self.locked_payload: Optional[BlockPayload] = None
        self.locked_round = -1
        self.valid_payload: Optional[BlockPayload] = None
        self.valid_round = -1

        # (height, round) -> proposal received; block_id -> payload
        self._proposals: Dict[Tuple[int, int], Proposal] = {}
        self._payloads: Dict[bytes, BlockPayload] = {}
        # votes[(height, round, vtype)][validator] = Vote
        self._votes: Dict[Tuple[int, int, str], Dict[bytes, Vote]] = {}
        # validation cache: block_id -> (ok, reason)
        self._valid_cache: Dict[bytes, Tuple[bool, str]] = {}
        # once-only triggers per (height, round): polka lock, timeouts
        self._fired: Set[Tuple] = set()

        self.decided: Dict[int, DecidedBlock] = {}
        self.outbox: List[dict] = []
        # timeout requests for the transport: (step, height, round)
        self.timeout_requests: List[Tuple[str, int, int]] = []

    # -- identity helpers ------------------------------------------------

    def register_pubkey(self, address: bytes, compressed: bytes) -> None:
        self.pubkeys[address] = compressed

    def proposer_for(self, height: int, round_: int) -> bytes:
        """Deterministic rotation over the sorted validator set — every
        correct node computes the same proposer for (height, round)."""
        order = sorted(self.validators)
        return order[(height + round_) % len(order)]

    # -- lifecycle -------------------------------------------------------

    def start_height(self, height: int) -> None:
        if height <= self.height:
            return
        self.height = height
        self.locked_payload = None
        self.locked_round = -1
        self.valid_payload = None
        self.valid_round = -1
        self._prune_below(height)
        self._start_round(0)

    def _prune_below(self, height: int) -> None:
        """Drop per-height consensus state no longer reachable: a
        run-forever validator must not grow with chain length.  The
        previous height's decision is kept (its certificate becomes the
        next proposal's last_commit); older decisions are dropped."""
        self._proposals = {
            k: v for k, v in self._proposals.items() if k[0] >= height
        }
        live_payloads = {
            d.payload.block_id for d in self.decided.values()
        } | {p.payload.block_id for p in self._proposals.values()}
        self._votes = {
            k: v for k, v in self._votes.items() if k[0] >= height
        }
        self._fired = {k for k in self._fired if k[1] >= height}
        # keep a window of recent decisions: height-1 feeds the next
        # proposal's last_commit, the rest serve laggard catch-up
        for h in [h for h in self.decided if h < height - 8]:
            live_payloads.discard(self.decided[h].payload.block_id)
            del self.decided[h]
        self._payloads = {
            bid: p
            for bid, p in self._payloads.items()
            if bid in live_payloads or p.height >= height
        }
        self._valid_cache = {
            bid: v
            for bid, v in self._valid_cache.items()
            if bid in self._payloads
        }

    def adopt_decision(
        self, payload: BlockPayload, precommits: List[Vote]
    ) -> Tuple[bool, str]:
        """Catch-up: accept an externally-replayed decided block IF its
        commit certificate proves it — >= 2/3 power of valid precommit
        signatures over this exact block id, all from one round.  The
        replayer (relay or peer) is untrusted; the signatures are the
        authority.  On success the engine records the decision and fires
        on_decide (the app finalizes), exactly as if it had assembled
        the quorum itself."""
        h = payload.height
        if h in self.decided:
            return True, "already decided"
        ok, why = verify_commit_certificate(
            self.chain_id, self.validators, self.pubkeys,
            self.total_power, payload, precommits,
        )
        if not ok:
            return False, why
        self.height = max(self.height, h)
        self._payloads[payload.block_id] = payload
        # the helper guaranteed a non-empty single-round certificate
        decided = DecidedBlock(payload, precommits[0].round, list(precommits))
        self.decided[h] = decided
        if self.on_decide:
            self.on_decide(decided)
        return True, ""

    # (verify_commit_certificate lives at module level so state-sync can
    # verify a snapshot's anchoring certificate BEFORE any state swap)

    def _start_round(self, round_: int) -> None:
        if self.height in self.decided:
            return  # decided: the machine halts until start_height
        self.round = round_
        self.step = STEP_PROPOSE
        if self.proposer_for(self.height, round_) == self.address:
            payload = (
                self.valid_payload
                if self.valid_payload is not None
                else self.propose_fn(self.height, round_)
            )
            if payload is not None:
                prop = Proposal(
                    height=self.height,
                    round=round_,
                    pol_round=self.valid_round,
                    payload=payload,
                    proposer=self.address,
                    signature=self.key.sign(
                        proposal_sign_bytes(
                            self.chain_id, self.height, round_,
                            self.valid_round, payload.block_id,
                        )
                    ),
                )
                self._broadcast(prop.to_wire())
                self.receive(prop)  # deliver to self
                return
        # non-proposer (or a proposer with nothing to propose) arms the
        # propose timeout: no (valid) proposal in time -> prevote nil
        self.timeout_requests.append((STEP_PROPOSE, self.height, round_))

    # -- inbound ---------------------------------------------------------

    def receive(self, msg) -> None:
        if isinstance(msg, dict):
            msg = msg_from_wire(msg)
        if isinstance(msg, Proposal):
            self._on_proposal(msg)
        elif isinstance(msg, Vote):
            self._on_vote(msg)

    def _on_proposal(self, prop: Proposal) -> None:
        if prop.height != self.height:
            return
        if prop.proposer != self.proposer_for(prop.height, prop.round):
            return  # not this round's proposer: ignore
        pk_raw = self.pubkeys.get(prop.proposer)
        if pk_raw is None:
            return
        digest = proposal_sign_bytes(
            self.chain_id, prop.height, prop.round, prop.pol_round,
            prop.payload.block_id,
        )
        if not PublicKey.from_compressed(pk_raw).verify(digest, prop.signature):
            return
        if prop.payload.height != prop.height:
            return
        # a FRESH proposal's payload must name its builder as proposer —
        # rewards follow payload.proposer, so letting it point elsewhere
        # would let a proposer redirect or forfeit another's rewards.  A
        # re-proposal (pol_round >= 0) legitimately keeps the ORIGINAL
        # builder's name; its payload is pinned by the polka's block id.
        if prop.pol_round == -1 and prop.payload.proposer != prop.proposer:
            return
        if prop.payload.proposer not in self.validators:
            return
        key = (prop.height, prop.round)
        if key in self._proposals:
            return  # first proposal per round wins; a second is ignored
        self._proposals[key] = prop
        self._payloads[prop.payload.block_id] = prop.payload
        self._try_transitions(prop.round)

    def _on_vote(self, vote: Vote) -> None:
        if vote.height != self.height:
            # precommits for an already-decided height still matter to
            # laggards; the transport replays decided blocks instead
            return
        if vote.vtype not in (PREVOTE, PRECOMMIT):
            return
        power = self.validators.get(vote.validator)
        if not power:
            return  # not a validator: no voting power
        pk_raw = self.pubkeys.get(vote.validator)
        if pk_raw is None:
            return
        digest = vote_sign_bytes(
            self.chain_id, vote.height, vote.round, vote.vtype, vote.block_id
        )
        if not PublicKey.from_compressed(pk_raw).verify(digest, vote.signature):
            return  # forged or tampered vote
        slot = self._votes.setdefault(
            (vote.height, vote.round, vote.vtype), {}
        )
        prev = slot.get(vote.validator)
        if prev is not None:
            if prev.block_id != vote.block_id and self.on_equivocation:
                self.on_equivocation(prev, vote)
            return  # first vote per (h, r, type) counts
        slot[vote.validator] = vote
        self._try_transitions(vote.round)

    # -- timeouts (fired by the transport's timers) ----------------------

    def on_timeout_propose(self, height: int, round_: int) -> None:
        if (height, round_) == (self.height, self.round) and self.step == STEP_PROPOSE:
            self._cast_vote(PREVOTE, NIL)
            self.step = STEP_PREVOTE
            self._try_transitions(round_)

    def on_timeout_prevote(self, height: int, round_: int) -> None:
        if (height, round_) == (self.height, self.round) and self.step == STEP_PREVOTE:
            self._cast_vote(PRECOMMIT, NIL)
            self.step = STEP_PRECOMMIT
            self._try_transitions(round_)

    def on_timeout_precommit(self, height: int, round_: int) -> None:
        if (
            height == self.height
            and round_ == self.round
            and height not in self.decided
        ):
            self._start_round(round_ + 1)

    # -- internals -------------------------------------------------------

    def _broadcast(self, wire: dict) -> None:
        self.outbox.append(wire)

    def _cast_vote(self, vtype: str, block_id: bytes) -> None:
        vote = Vote(
            vtype=vtype,
            height=self.height,
            round=self.round,
            block_id=block_id,
            validator=self.address,
            signature=self.key.sign(
                vote_sign_bytes(
                    self.chain_id, self.height, self.round, vtype, block_id
                )
            ),
        )
        self._broadcast(vote.to_wire())
        self._on_vote(vote)  # count own vote

    def _validate(self, payload: BlockPayload) -> bool:
        bid = payload.block_id
        if bid not in self._valid_cache:
            try:
                self._valid_cache[bid] = self.validate_fn(payload)
            except Exception as e:  # validation panic = invalid
                self._valid_cache[bid] = (False, f"validation panic: {e}")
        return self._valid_cache[bid][0]

    def _power_for(
        self, round_: int, vtype: str, block_id: Optional[bytes]
    ) -> int:
        """Voting power at (height, round, vtype); block_id None = any."""
        slot = self._votes.get((self.height, round_, vtype), {})
        return sum(
            self.validators[v.validator]
            for v in slot.values()
            if block_id is None or v.block_id == block_id
        )

    def _quorum(self, power: int) -> bool:
        return power * 3 >= self.total_power * 2

    def _polka_block(self, round_: int) -> Optional[bytes]:
        """The non-nil block id with a 2/3 prevote quorum at round_, if any."""
        slot = self._votes.get((self.height, round_, PREVOTE), {})
        by_block: Dict[bytes, int] = {}
        for v in slot.values():
            by_block[v.block_id] = (
                by_block.get(v.block_id, 0) + self.validators[v.validator]
            )
        for bid, power in by_block.items():
            if bid != NIL and self._quorum(power):
                return bid
        return None

    def verify_commit_certificate(
        self, payload: BlockPayload, prev_block_id: bytes, prev_height: int
    ) -> Tuple[bool, str]:
        """Check a payload's last_commit: every vote must be a valid
        precommit signature by a known validator over prev_block_id, one
        per validator, totalling >= 2/3 power, all from ONE round — a
        commit is the set of precommits that co-existed in the round that
        decided, so mixing genuine votes from different rounds would
        fabricate a certificate that never existed (same rule as
        adopt_decision and LightClient.update).  Used by harness
        validate_fns so a proposer cannot forge reward/slash inputs."""
        if len({v.round for v in payload.last_commit}) > 1:
            return False, "commit certificate mixes rounds"
        if any(v.round < 0 for v in payload.last_commit):
            return False, "negative round in commit certificate"
        seen: Set[bytes] = set()
        power = 0
        for v in payload.last_commit:
            if v.validator in seen:
                return False, "duplicate validator in commit certificate"
            seen.add(v.validator)
            vp = self.validators.get(v.validator)
            pk_raw = self.pubkeys.get(v.validator)
            if not vp or pk_raw is None:
                return False, "unknown validator in commit certificate"
            if v.vtype != PRECOMMIT or v.height != prev_height:
                return False, "certificate vote is not a precommit for h-1"
            if v.block_id != prev_block_id:
                return False, "certificate vote is for a different block"
            digest = vote_sign_bytes(
                self.chain_id, v.height, v.round, v.vtype, v.block_id
            )
            if not PublicKey.from_compressed(pk_raw).verify(
                digest, v.signature
            ):
                return False, "certificate signature invalid"
            power += vp
        if not self._quorum(power):
            return False, "commit certificate below 2/3 power"
        return True, ""

    def _round_skip_check(self) -> None:
        """Liveness: > 1/3 power sending votes at a round AHEAD of ours
        proves the network moved on (at least one correct validator is
        there) — jump to that round instead of waiting out our timeouts."""
        by_round: Dict[int, Set[bytes]] = {}
        for (vh, vr, _), slot in self._votes.items():
            if vh == self.height and vr > self.round:
                by_round.setdefault(vr, set()).update(slot.keys())
        for vr in sorted(by_round):
            power = sum(self.validators[a] for a in by_round[vr])
            if power * 3 > self.total_power:
                self._start_round(vr)
                return

    def _try_transitions(self, round_: int) -> None:
        """Run every Tendermint 'upon' rule that newly applies."""
        h = self.height
        if h in self.decided:
            return  # decided: only start_height re-activates the machine
        self._round_skip_check()

        # -- upon Proposal at (h, current round) while step == propose
        prop = self._proposals.get((h, self.round))
        if prop is not None and self.step == STEP_PROPOSE:
            payload = prop.payload
            if prop.pol_round == -1:
                ok = self._validate(payload) and (
                    self.locked_round == -1
                    or self.locked_payload.block_id == payload.block_id
                )
                self._cast_vote(PREVOTE, payload.block_id if ok else NIL)
                self.step = STEP_PREVOTE
            elif 0 <= prop.pol_round < self.round:
                # re-proposal with a proof-of-lock: needs the polka at
                # pol_round before we can judge it
                if self._polka_block(prop.pol_round) == payload.block_id:
                    ok = self._validate(payload) and (
                        self.locked_round <= prop.pol_round
                        or self.locked_payload.block_id == payload.block_id
                    )
                    self._cast_vote(PREVOTE, payload.block_id if ok else NIL)
                    self.step = STEP_PREVOTE

        # -- upon 2/3 ANY prevotes at (h, current round) while prevoting:
        # arm the prevote timeout (votes are split; give the polka a
        # moment to form before precommitting nil)
        if self.step == STEP_PREVOTE and self._quorum(
            self._power_for(self.round, PREVOTE, None)
        ):
            fkey = ("timeout-prevote", h, self.round)
            if fkey not in self._fired:
                self._fired.add(fkey)
                self.timeout_requests.append((STEP_PREVOTE, h, self.round))

        # -- upon polka for a block at (h, current round) while step >=
        # prevote, first time: lock + precommit (if prevoting), mark valid
        polka = self._polka_block(self.round)
        if polka is not None and polka in self._payloads:
            payload = self._payloads[polka]
            if self._validate(payload):
                fkey = ("polka", h, self.round, polka)
                if fkey not in self._fired and self.step != STEP_PROPOSE:
                    self._fired.add(fkey)
                    if self.step == STEP_PREVOTE:
                        self.locked_payload = payload
                        self.locked_round = self.round
                        self._cast_vote(PRECOMMIT, polka)
                        self.step = STEP_PRECOMMIT
                    self.valid_payload = payload
                    self.valid_round = self.round

        # -- upon 2/3 prevotes NIL at (h, current round) while prevoting:
        # precommit nil
        if self.step == STEP_PREVOTE and self._quorum(
            self._power_for(self.round, PREVOTE, NIL)
        ):
            self._cast_vote(PRECOMMIT, NIL)
            self.step = STEP_PRECOMMIT

        # -- upon 2/3 ANY precommits at (h, current round): arm precommit
        # timeout (round change if no decision lands)
        if self._quorum(self._power_for(self.round, PRECOMMIT, None)):
            fkey = ("timeout-precommit", h, self.round)
            if fkey not in self._fired:
                self._fired.add(fkey)
                self.timeout_requests.append((STEP_PRECOMMIT, h, self.round))

        # -- upon 2/3 precommits for a block at (h, ANY round): decide
        for (vh, vr, vtype), slot in list(self._votes.items()):
            if vh != h or vtype != PRECOMMIT:
                continue
            by_block: Dict[bytes, int] = {}
            for v in slot.values():
                if v.block_id != NIL:
                    by_block[v.block_id] = (
                        by_block.get(v.block_id, 0)
                        + self.validators[v.validator]
                    )
            for bid, power in by_block.items():
                if not self._quorum(power):
                    continue
                payload = self._payloads.get(bid)
                if payload is None:
                    continue  # commit certificate seen, payload not yet
                if h not in self.decided and self._validate(payload):
                    cert = [
                        v for v in slot.values() if v.block_id == bid
                    ]
                    decided = DecidedBlock(payload, vr, cert)
                    self.decided[h] = decided
                    if self.on_decide:
                        self.on_decide(decided)
                return
