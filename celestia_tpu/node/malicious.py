"""Malicious app fixtures: byzantine proposal handlers for adversarial tests.

Parity with /root/reference/test/util/malicious/: a wrapper around the real
App with pluggable bad PrepareProposal handlers (registry at app.go:38-42) —
an out-of-order square builder (out_of_order_builder.go:24-63) and a
data-root liar — plus an auto-accept ProcessProposal (app.go:92-96).  Used
to prove honest validators reject malicious blocks.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from celestia_tpu.da import dah as dah_mod
from celestia_tpu.da.square import Square, build as build_square
from celestia_tpu.state.app import App, PreparedProposal

# handler name -> fn(app, txs) -> PreparedProposal
HANDLER_REGISTRY: Dict[str, Callable] = {}


def register_handler(name: str):
    def deco(fn):
        HANDLER_REGISTRY[name] = fn
        return fn

    return deco


@register_handler("out_of_order")
def out_of_order_prepare(app: App, txs: List[bytes]) -> PreparedProposal:
    """Build a square whose blob shares are NOT namespace-ordered (swap the
    first two blob sequences), then honestly commit to the malicious square.

    An honest validator reconstructs the canonical (sorted) square from the
    same txs and computes a different data root -> REJECT.
    """
    kept = app._filter_txs(txs)
    square, block_txs, _ = build_square(kept, app.max_effective_square_size())
    shares = list(square.shares)
    # find the first two distinct user-blob sequences and swap them
    starts = [
        i
        for i, s in enumerate(shares)
        if s.namespace.is_usable_by_users() and s.is_sequence_start
    ]
    if len(starts) < 2:
        raise ValueError(
            "out_of_order handler needs >= 2 user-blob sequences to reorder; "
            "drive it with at least two blob txs"
        )
    a, b = starts[0], starts[1]

    def seq_end(i):
        j = i + 1
        while j < len(shares) and (
            shares[j].namespace.raw == shares[i].namespace.raw
            and not shares[j].is_sequence_start
        ):
            j += 1
        return j

    ea, eb = seq_end(a), seq_end(b)
    shares = shares[:a] + shares[b:eb] + shares[ea:b] + shares[a:ea] + shares[eb:]
    bad_square = Square(tuple(shares), square.size)
    eds, dah = dah_mod.extend_block(bad_square)
    return PreparedProposal(block_txs, bad_square.size, dah.hash, eds, dah)


@register_handler("lying_data_root")
def lying_data_root_prepare(app: App, txs: List[bytes]) -> PreparedProposal:
    """Honest square, but the proposal lies about the data root."""
    proposal = App.prepare_proposal(app, txs)
    fake = bytes(32 - len(b"liar")) + b"liar"
    return PreparedProposal(
        proposal.block_txs, proposal.square_size, fake, proposal.eds, proposal.dah
    )


class MaliciousApp(App):
    """App with a pluggable byzantine PrepareProposal and an auto-accepting
    ProcessProposal (so the byzantine node votes for its own garbage)."""

    def __init__(self, *args, handler: str = "out_of_order", **kwargs):
        super().__init__(*args, **kwargs)
        if handler not in HANDLER_REGISTRY:
            raise KeyError(
                f"unknown malicious handler {handler!r}; "
                f"choose from {sorted(HANDLER_REGISTRY)}"
            )
        self._handler = HANDLER_REGISTRY[handler]

    def prepare_proposal(self, txs: List[bytes]) -> PreparedProposal:
        return self._handler(self, txs)

    def process_proposal(
        self, block_txs: List[bytes], square_size: int, data_root: bytes
    ) -> Tuple[bool, str]:
        return True, "malicious auto-accept"
