"""State-sync snapshots: chunked export, restore, pruning.

Parity role: the reference snapshots app state every 1500 blocks into a
chunk store, keeps the 2 most recent, and restores joining nodes from them
(cmd/celestia-appd/cmd/root.go:227-243 snapshot store wiring,
app/default_overrides.go:296-297 interval/keep-recent defaults,
``celestia-appd snapshot`` command root.go:158-160).

Format: one directory per snapshot (``<height>-<format>``) holding
``metadata.json`` (height, app hash, chain id, app version, chunk count +
per-chunk sha256) and zlib-compressed chunk files of the JSON store dump.
Every chunk is integrity-checked on restore; the restored state must
reproduce the snapshot's recorded app hash or the restore is rejected.
"""

from __future__ import annotations

import hashlib
import json
import shutil
import zlib
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional

SNAPSHOT_FORMAT = 1
CHUNK_BYTES = 1 << 20

# DoS bounds on PEER-SUPPLIED snapshot data (ADVICE r5): the writer
# never produces a chunk above CHUNK_BYTES, so anything larger on the
# wire is hostile; the decompressed state payload is capped so a zlib
# bomb cannot exhaust memory before the app-hash check would fail.
MAX_WIRE_CHUNK_BYTES = CHUNK_BYTES
MAX_STATE_BYTES = 1 << 30  # 1 GiB decompressed, far above any real state


class SnapshotLimitError(ValueError):
    """A peer-supplied snapshot exceeded a resource bound (oversized
    chunk or decompression blow-up) — abort the sync and back off the
    peer; no honest snapshot trips these."""


@dataclass(frozen=True)
class SnapshotInfo:
    height: int
    format: int
    chunks: int
    app_hash: bytes
    chain_id: str
    app_version: int

    @property
    def dirname(self) -> str:
        return f"{self.height}-{self.format}"


class SnapshotStore:
    """File-backed snapshot store under one directory."""

    def __init__(self, root: str):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    # -- creation ------------------------------------------------------

    def create(self, app) -> SnapshotInfo:
        """Snapshot the app's latest committed state."""
        height = app.store.last_height
        app_hash = app.store.committed_hash(height)
        payload = zlib.compress(
            json.dumps(
                {"state": app.store.export(), "genesis_time_ns": app.genesis_time_ns}
            ).encode(),
            level=6,
        )
        chunks = [
            payload[i : i + CHUNK_BYTES]
            for i in range(0, max(len(payload), 1), CHUNK_BYTES)
        ]
        info = SnapshotInfo(
            height=height,
            format=SNAPSHOT_FORMAT,
            chunks=len(chunks),
            app_hash=app_hash,
            chain_id=app.chain_id,
            app_version=app.app_version,
        )
        tmp = self.root / (info.dirname + ".tmp")
        if tmp.exists():
            shutil.rmtree(tmp)
        tmp.mkdir()
        chunk_hashes = []
        for i, chunk in enumerate(chunks):
            (tmp / f"chunk-{i:04d}").write_bytes(chunk)
            chunk_hashes.append(hashlib.sha256(chunk).hexdigest())
        (tmp / "metadata.json").write_text(
            json.dumps(
                {
                    "height": info.height,
                    "format": info.format,
                    "chunks": info.chunks,
                    "chunk_hashes": chunk_hashes,
                    "app_hash": app_hash.hex(),
                    "chain_id": info.chain_id,
                    "app_version": info.app_version,
                }
            )
        )
        final = self.root / info.dirname
        if final.exists():
            shutil.rmtree(final)
        tmp.rename(final)
        return info

    # -- listing / pruning ---------------------------------------------

    def _iter_metas(self) -> List[dict]:
        """All snapshot metadata dicts on disk, sorted by height — the
        single directory walk behind list() and list_wire().

        Only COMMITTED snapshots are listed: a ``*.tmp`` directory is a
        write in progress (save() publishes it atomically via rename) and
        must never surface as restorable — and racing its rename here is
        what made list() throw FileNotFoundError mid-state-sync.  A
        committed dir can still vanish between iterdir() and the read
        (concurrent prune()), so missing files are skipped, not fatal.
        """
        out = []
        for d in sorted(self.root.iterdir()):
            if d.suffix == ".tmp" or not d.is_dir():
                continue
            try:
                out.append(json.loads((d / "metadata.json").read_text()))
            except (FileNotFoundError, NotADirectoryError):
                continue  # pruned or re-staged between listing and read
        return sorted(out, key=lambda m: m["height"])

    def list(self) -> List[SnapshotInfo]:
        return [
            SnapshotInfo(
                height=m["height"],
                format=m["format"],
                chunks=m["chunks"],
                app_hash=bytes.fromhex(m["app_hash"]),
                chain_id=m["chain_id"],
                app_version=m["app_version"],
            )
            for m in self._iter_metas()
        ]

    def prune(self, keep_recent: int) -> int:
        snaps = self.list()
        dropped = 0
        for info in snaps[:-keep_recent] if keep_recent > 0 else []:
            shutil.rmtree(self.root / info.dirname, ignore_errors=True)
            dropped += 1
        return dropped

    # -- restore -------------------------------------------------------

    def latest(self) -> Optional[SnapshotInfo]:
        snaps = self.list()
        return snaps[-1] if snaps else None

    def load_state(self, info: SnapshotInfo) -> dict:
        """Read + verify chunks; returns {"state":…, "genesis_time_ns":…}."""
        d = self.root / info.dirname
        meta = json.loads((d / "metadata.json").read_text())
        chunks = [
            (d / f"chunk-{i:04d}").read_bytes() for i in range(info.chunks)
        ]
        return self.assemble(meta, chunks)

    # -- network serving (state-sync over gRPC) ------------------------

    def list_wire(self) -> List[dict]:
        """Snapshot metadata as JSON-safe dicts (incl. chunk hashes) for
        the SnapshotList RPC."""
        return self._iter_metas()

    def chunk_bytes(self, height: int, fmt: int, idx: int) -> Optional[bytes]:
        """One verified-on-write chunk, or None when absent."""
        d = self.root / f"{height}-{fmt}"
        path = d / f"chunk-{idx:04d}"
        if not path.exists():
            return None
        return path.read_bytes()

    @staticmethod
    def assemble(meta: dict, chunks: List[bytes]) -> dict:
        """Verify fetched chunks against the metadata hashes and decode
        the state payload — the restore half of the wire protocol.  The
        hashes only catch transfer corruption; TRUST comes from the app
        hash + commit-certificate checks done by the caller.  Resource
        bounds (chunk size, decompressed total) are enforced HERE so a
        malicious snapshot raises :class:`SnapshotLimitError` before it
        can exhaust memory."""
        if len(chunks) != meta["chunks"]:
            raise ValueError("chunk count mismatch")
        for i, chunk in enumerate(chunks):
            if len(chunk) > MAX_WIRE_CHUNK_BYTES:
                raise SnapshotLimitError(
                    f"snapshot chunk {i} is {len(chunk)} bytes "
                    f"(cap {MAX_WIRE_CHUNK_BYTES})"
                )
            got = hashlib.sha256(chunk).hexdigest()
            if got != meta["chunk_hashes"][i]:
                raise ValueError(f"snapshot chunk {i} corrupt in transfer")
        # capped streaming decompression: never materialize more than
        # MAX_STATE_BYTES of output no matter what the stream claims
        d = zlib.decompressobj()
        raw = d.decompress(b"".join(chunks), MAX_STATE_BYTES + 1)
        if len(raw) > MAX_STATE_BYTES:
            raise SnapshotLimitError(
                f"snapshot state exceeds the {MAX_STATE_BYTES}-byte "
                "decompression cap"
            )
        if d.unconsumed_tail or d.unused_data or not d.eof:
            raise ValueError("snapshot payload is not one zlib stream")
        return json.loads(raw)

    def restore_app(self, info: SnapshotInfo, **app_kwargs):
        """Build a fresh App from a snapshot; verifies the app hash."""
        from celestia_tpu.state.app import App

        data = self.load_state(info)
        app = App.restore_from_snapshot(
            chain_id=info.chain_id,
            state=data["state"],
            height=info.height,
            expected_app_hash=info.app_hash,
            genesis_time_ns=data.get("genesis_time_ns", 0),
            **app_kwargs,
        )
        return app
