"""In-process N-validator network driven by the two-phase BFT engine.

Each validator runs its OWN BFTNode state machine over its OWN App; the
harness is a dumb message transport with controllable faults — it
shuttles outbox messages between nodes (honoring partitions and drop
rules), fires timeouts only when the network is quiescent, and NEVER
counts votes or sequences commits itself.  Every validator decides from
the votes it verified; the harness merely checks afterwards that the
decisions and app hashes agree (a divergence raises ConsensusFailure —
that's an assertion about the protocol, not part of it).

Deterministic timeout model: real transports fire timeouts when wall
clocks lapse; here a timeout becomes DUE when the message queue drains
without a decision — same observable semantics (timeouts only matter
when progress stalls), fully reproducible.

Relation to the chaos harness (specs/robustness.md): this harness
injects faults at the TRANSPORT level (partitions, drop rules, crashed
validators) with determinism coming from the quiescence-driven pump; the
utils/faults.py registry injects at the SUBSYSTEM level (native codec,
hostpool, state-sync chunks, serving plane) with determinism coming from
seeded schedules.  The two compose: a BFTNetwork scenario can run with
fault points armed, and neither layer sleeps or draws ambient entropy.

Reference role: celestia-core consensus + p2p gossip driving N nodes
(SURVEY §2.2/§2.3); replaces the central sequencing of
node/network.py's legacy driver.
"""

from __future__ import annotations

import hashlib
from collections import deque
from typing import Callable, Dict, List, Optional, Set, Tuple

from celestia_tpu.appconsts import GOAL_BLOCK_TIME_SECONDS
from celestia_tpu.node.bft import (
    NIL,
    STEP_PRECOMMIT,
    STEP_PREVOTE,
    STEP_PROPOSE,
    BFTNode,
    BlockPayload,
    DecidedBlock,
    Vote,
)
from celestia_tpu.node.mempool import Mempool
from celestia_tpu.node.network import ConsensusFailure
from celestia_tpu.node.testnode import Block, BlockHeader
from celestia_tpu.state.app import App
from celestia_tpu.utils import tracing
from celestia_tpu.utils.secp256k1 import PrivateKey


class BFTValidator:
    """One validator: app state, mempool, key and its consensus engine."""

    def __init__(self, name: str, key: PrivateKey, power: int, app: App):
        self.name = name
        self.key = key
        self.power = power
        self.app = app
        self.mempool = Mempool(max_tx_bytes=64 * 1024 * 1024)
        self.engine: Optional[BFTNode] = None
        self.crashed = False  # a crashed validator neither sends nor acts
        self.finalized: Dict[int, bytes] = {}  # height -> app hash

    @property
    def address(self) -> bytes:
        return self.key.public_key().address()


class BFTNetwork:
    """Deterministic in-process transport + fault injection harness."""

    def __init__(
        self,
        n_validators: int = 4,
        chain_id: str = "celestia-tpu-bftnet",
        funded_accounts=None,
        powers: Optional[List[int]] = None,
        block_interval_ns: int = GOAL_BLOCK_TIME_SECONDS * 10**9,
        v2_upgrade_height: Optional[int] = None,
    ):
        self.chain_id = chain_id
        self.block_interval_ns = block_interval_ns
        powers = powers or [100] * n_validators
        keys = [
            PrivateKey.from_seed(b"bftnet-val-%d" % i)
            for i in range(n_validators)
        ]
        genesis = {
            "chain_id": chain_id,
            "genesis_time_ns": 1_700_000_000_000_000_000,
            "accounts": [
                {
                    "address": k.public_key().address().hex(),
                    "balance": 1_000_000_000_000,
                }
                for k in keys
            ]
            + [
                {
                    "address": key.public_key().address().hex(),
                    "balance": balance,
                }
                for key, balance in (funded_accounts or [])
            ],
            "validators": [
                {
                    "address": k.public_key().address().hex(),
                    "self_delegation": p * 1_000_000,
                }
                for k, p in zip(keys, powers)
            ],
        }
        self.genesis = genesis
        self.validators: List[BFTValidator] = []
        valset = {
            k.public_key().address(): p for k, p in zip(keys, powers)
        }
        pubkeys = {
            k.public_key().address(): k.public_key().compressed()
            for k in keys
        }
        for i, (key, power) in enumerate(zip(keys, powers)):
            app = App(
                chain_id=chain_id, v2_upgrade_height=v2_upgrade_height
            )
            app.init_chain(genesis)
            val = BFTValidator(f"val-{i}", key, power, app)
            val.engine = BFTNode(
                chain_id=chain_id,
                key=key,
                validators=valset,
                validate_fn=self._make_validate_fn(val),
                propose_fn=self._make_propose_fn(val),
                on_equivocation=self._record_equivocation,
                pubkeys=pubkeys,
            )
            self.validators.append(val)
        self.blocks: List[Block] = []
        self._tx_index: Dict[bytes, dict] = {}
        self._now_ns = genesis["genesis_time_ns"]
        self._block_ids: Dict[int, bytes] = {}  # height -> decided block id
        self.equivocations: List[Tuple[Vote, Vote]] = []
        # fault injection: (sender_name, receiver_name) pairs to drop;
        # None in either slot = wildcard
        self.drop_rules: Set[Tuple[Optional[str], Optional[str]]] = set()
        self._queue: deque = deque()  # (sender, wire_msg)

    # -- engine hooks ---------------------------------------------------

    def _make_validate_fn(self, val: BFTValidator):
        from celestia_tpu.node.bft import validate_payload_against_chain

        def validate(payload: BlockPayload) -> Tuple[bool, str]:
            # 1. the commit certificate for height-1 must be genuine and
            # prev_app_hash must match our own committed state root
            try:
                expected = val.app.store.committed_hash(payload.height - 1)
            except KeyError:
                expected = None
            ok, why = validate_payload_against_chain(
                val.engine, payload, self._block_ids.get(payload.height - 1),
                expected_prev_app_hash=expected,
                prev_time_ns=self._now_ns,
                # the harness is clock-free: simulated chain time is the
                # validator's clock.  The bound is a small multiple of
                # the block interval so a Byzantine proposer cannot creep
                # chain time forward by a large drift allowance on every
                # block it proposes (honest proposals sit at exactly
                # prev + interval)
                now_ns=self._now_ns,
                max_drift_ns=2 * self.block_interval_ns,
            )
            if not ok:
                return False, f"bad commit certificate: {why}"
            # 2. full ProcessProposal re-validation on our own state
            return val.app.process_proposal(
                list(payload.txs), payload.square_size, payload.data_root
            )

        return validate

    def _make_propose_fn(self, val: BFTValidator):
        def propose(height: int, round_: int) -> Optional[BlockPayload]:
            if val.crashed:
                return None
            mem_txs = val.mempool.reap()
            try:
                proposal = val.app.prepare_proposal([t.raw for t in mem_txs])
            except Exception:
                return None  # broken proposer forfeits the round
            last_commit: Tuple[Vote, ...] = ()
            prev = val.engine.decided.get(height - 1)
            if prev is not None:
                last_commit = tuple(
                    sorted(prev.precommits, key=lambda v: v.validator)
                )
            try:
                prev_app_hash = val.app.store.committed_hash(height - 1)
            except KeyError:
                prev_app_hash = b""
            return BlockPayload(
                height=height,
                time_ns=self._now_ns + self.block_interval_ns,
                square_size=proposal.square_size,
                data_root=proposal.data_root,
                txs=tuple(proposal.block_txs),
                proposer=val.address,
                last_commit=last_commit,
                prev_app_hash=prev_app_hash,
            )

        return propose

    def _record_equivocation(self, a: Vote, b: Vote) -> None:
        self.equivocations.append((a, b))

    # -- transport ------------------------------------------------------

    def _dropped(self, sender: str, receiver: str) -> bool:
        for s, r in self.drop_rules:
            if (s is None or s == sender) and (r is None or r == receiver):
                return True
        return False

    def partition(self, group_a: List[str], group_b: List[str]) -> None:
        """Cut all links between the two groups (both directions)."""
        for a in group_a:
            for b in group_b:
                self.drop_rules.add((a, b))
                self.drop_rules.add((b, a))

    def heal(self) -> None:
        self.drop_rules.clear()

    def _drain_outboxes(self) -> None:
        for val in self.validators:
            if val.engine is None:
                continue
            while val.engine.outbox:
                self._queue.append((val.name, val.engine.outbox.pop(0)))

    def _deliver_all(self, max_msgs: int = 100_000) -> None:
        """Pump queued messages to every (non-partitioned, non-crashed)
        peer until quiescent."""
        delivered = 0
        while self._queue:
            sender, wire = self._queue.popleft()
            for val in self.validators:
                if val.name == sender or val.crashed:
                    continue
                if self._dropped(sender, val.name):
                    continue
                if tracing.enabled():
                    # the in-process analogue of the mesh's envelope
                    # context: sender/receiver attribution on every
                    # delivery, so harness runs read like mesh traces
                    with tracing.span(
                        "bftnet.deliver", cat="gossip",
                        sender=sender, receiver=val.name,
                        kind=str(wire.get("kind", "")),
                        height=int(wire.get("height", 0) or 0),
                    ):
                        val.engine.receive(wire)
                else:
                    val.engine.receive(wire)
            self._drain_outboxes()
            delivered += 1
            if delivered > max_msgs:
                raise RuntimeError("message storm: transport not quiescing")

    def _fire_due_timeouts(self) -> bool:
        """Fire each engine's oldest pending timeout request that is
        still relevant.  Returns True if anything fired."""
        fired = False
        for step in (STEP_PROPOSE, STEP_PREVOTE, STEP_PRECOMMIT):
            for val in self.validators:
                if val.crashed or val.engine is None:
                    continue
                eng = val.engine
                due = [t for t in eng.timeout_requests if t[0] == step]
                eng.timeout_requests = [
                    t for t in eng.timeout_requests if t[0] != step
                ]
                for _, h, r in due:
                    if step == STEP_PROPOSE:
                        eng.on_timeout_propose(h, r)
                    elif step == STEP_PREVOTE:
                        eng.on_timeout_prevote(h, r)
                    else:
                        eng.on_timeout_precommit(h, r)
                    fired = True
            if fired:
                return True  # earlier-step timeouts fire first
        return fired

    # -- block production ----------------------------------------------

    @property
    def height(self) -> int:
        return self.blocks[-1].header.height if self.blocks else 1

    @property
    def total_power(self) -> int:
        return sum(v.power for v in self.validators)

    def live_power(self) -> int:
        return sum(v.power for v in self.validators if not v.crashed)

    def broadcast_tx(self, raw: bytes):
        from celestia_tpu.state.tx import SubmitResult
        from celestia_tpu.da.blob import unmarshal_blob_tx
        from celestia_tpu.state.tx import unmarshal_tx

        code, log = 0, ""
        for val in self.validators:
            if val.crashed:
                continue
            res = val.app.check_tx(raw)
            if res.code == 0:
                btx = unmarshal_blob_tx(raw)
                tx = unmarshal_tx(btx.tx if btx is not None else raw)
                val.mempool.add(raw, tx.fee.gas_price(), self.height)
            else:
                code, log = res.code, res.log
        return SubmitResult(code, log, hashlib.sha256(raw).digest())

    def produce_block(self, max_steps: int = 200) -> Block:
        """Drive one height to a decision on every live validator."""
        height = self.height + 1
        for val in self.validators:
            if not val.crashed:
                val.engine.start_height(height)
        self._drain_outboxes()
        steps = 0
        while True:
            self._deliver_all()
            if all(
                height in val.engine.decided
                for val in self.validators
                if not val.crashed
            ):
                break
            if not self._fire_due_timeouts():
                raise RuntimeError(
                    f"height {height} stalled with no due timeouts: "
                    + ", ".join(
                        f"{v.name}@r{v.engine.round}/{v.engine.step}"
                        for v in self.validators
                        if not v.crashed
                    )
                )
            self._drain_outboxes()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(f"height {height} did not decide")
        return self._finalize_height(height)

    def _finalize_height(self, height: int) -> Block:
        # all live validators decided — the decisions MUST agree
        decisions = {
            val.engine.decided[height].payload.block_id
            for val in self.validators
            if not val.crashed
        }
        if len(decisions) != 1:
            raise ConsensusFailure(
                f"conflicting decisions at height {height}: "
                f"{[d.hex()[:12] for d in decisions]}"
            )
        sample = next(
            val.engine.decided[height]
            for val in self.validators
            if not val.crashed
        )
        payload = sample.payload
        self._block_ids[height] = payload.block_id
        self._now_ns = payload.time_ns
        # LastCommitInfo comes from the PAYLOAD (identical everywhere),
        # not from each node's local certificate
        from celestia_tpu.node.bft import last_commit_vote_pairs

        vote_pairs = last_commit_vote_pairs(
            {v.address: v.power for v in self.validators}, payload
        )
        app_hashes = {}
        results_sample = None
        for val in self.validators:
            if val.crashed:
                continue
            results, _end, app_hash = val.app.finalize_block(
                list(payload.txs), height, payload.time_ns,
                payload.data_root,
                proposer=payload.proposer or None, votes=vote_pairs,
            )
            val.finalized[height] = app_hash
            app_hashes[val.name] = app_hash
            if results_sample is None:
                results_sample = results
        if len(set(app_hashes.values())) != 1:
            raise ConsensusFailure(
                f"app hash divergence at height {height}: "
                f"{ {n: h.hex()[:12] for n, h in app_hashes.items()} }"
            )
        header = BlockHeader(
            height=height,
            time_ns=payload.time_ns,
            chain_id=self.chain_id,
            app_version=next(
                v for v in self.validators if not v.crashed
            ).app.app_version,
            data_hash=payload.data_root,
            app_hash=next(iter(app_hashes.values())),
            square_size=payload.square_size,
        )
        block = Block(
            header, list(payload.txs), results_sample,
            payload.proposer, vote_pairs,
        )
        self.blocks.append(block)
        for raw, res in zip(payload.txs, results_sample):
            h = hashlib.sha256(raw).digest()
            self._tx_index[h] = {
                "code": res.code, "log": res.log, "height": height,
            }
            for val in self.validators:
                val.mempool.remove(h)
        for val in self.validators:
            if not val.crashed:
                val.mempool.recheck(
                    lambda raw, _a=val.app: _a.check_tx(
                        raw, is_recheck=True
                    ).code
                    == 0
                )
            val.mempool.evict_expired(height)
        return block

    def produce_blocks(self, n: int) -> List[Block]:
        return [self.produce_block() for _ in range(n)]

    # -- client surface (Signer-compatible, served by validator 0) ------

    @property
    def app(self) -> App:
        return self.validators[0].app

    def account_info(self, address: bytes):
        acc = self.validators[0].app.accounts.peek(address)
        return acc.account_number, acc.sequence

    def get_tx(self, tx_hash: bytes):
        return self._tx_index.get(tx_hash)

    def simulate(self, raw: bytes) -> int:
        from celestia_tpu.node.testnode import TestNode

        return TestNode._simulate_locked(self, raw)  # type: ignore[arg-type]

    @property
    def chain_id_prop(self):
        return self.chain_id
