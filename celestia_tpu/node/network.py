"""Multi-validator network: real state-machine replication in one process.

VERDICT r1 item #4.  N validators each run their OWN App instance over
independent state; every block goes through the actual BFT-shaped round:

  1. the height's proposer (round-robin, rotating on rejection) reaps its
     mempool and runs PrepareProposal;
  2. EVERY validator independently re-validates the proposal with
     ProcessProposal on its own state and votes accept/reject;
  3. the block commits only with >= 2/3 of voting power accepting
     (Tendermint's commit rule); on commit every validator finalizes and
     the resulting app hashes MUST be identical — any divergence is a
     consensus-safety failure and raises.

Byzantine cases: give a validator a MaliciousApp (node/malicious.py) and its
proposals are rejected by the honest majority, after which the next proposer
produces the canonical block — the scenario the reference covers with its
malicious-app e2e tests (test/util/malicious/app.go:38-42,
test/e2e/simple_test.go shape).

Catch-up: a fresh validator joins mid-chain and replays committed blocks
through the batched extension pipeline (multi-square batch verification) —
or restores from a peer snapshot and replays the tail.

Reference surfaces: test/util/testnode/full_node.go:20-49,
test/e2e/testnet.go:62-96, app/process_proposal.go:24-157.
"""

from __future__ import annotations

import hashlib
import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from celestia_tpu.appconsts import GOAL_BLOCK_TIME_SECONDS
from celestia_tpu.node.mempool import Mempool
from celestia_tpu.node.testnode import Block, BlockHeader
from celestia_tpu.state.app import App, PreparedProposal
from celestia_tpu.state.modules.evidence import vote_sign_bytes
from celestia_tpu.utils.secp256k1 import PrivateKey


class ConsensusFailure(RuntimeError):
    """Committed state diverged between validators (consensus safety)."""


@dataclass
class Vote:
    validator: str
    accept: bool
    reason: str = ""
    # consensus-vote signature over (chain_id, height, block_hash): what
    # makes double-signing provable (x/evidence's Equivocation verifies
    # exactly these bytes).  Reject votes are nil votes — unsigned.
    block_hash: bytes = b""
    signature: bytes = b""


@dataclass
class RoundResult:
    height: int
    proposer: str
    committed: bool
    votes: List[Vote]
    block: Optional[Block] = None


class Validator:
    """One validator: its own app state, key, mempool and voting power."""

    def __init__(self, name: str, key: PrivateKey, power: int, app: App):
        self.name = name
        self.key = key
        self.power = power
        self.app = app
        self.mempool = Mempool(max_tx_bytes=64 * 1024 * 1024)
        # byzantine fixture: also sign a conflicting block hash each
        # height (the double-sign x/evidence exists to punish)
        self.double_signs = False

    @property
    def address(self) -> bytes:
        return self.key.public_key().address()

    def sign_vote(self, chain_id: str, height: int, block_hash: bytes) -> bytes:
        return self.key.sign(vote_sign_bytes(chain_id, height, block_hash))


class ValidatorNetwork:
    """An in-process N-validator devnet with real replication."""

    def __init__(
        self,
        n_validators: int = 4,
        chain_id: str = "celestia-tpu-multinet",
        funded_accounts=None,
        powers: Optional[List[int]] = None,
        block_interval_ns: int = GOAL_BLOCK_TIME_SECONDS * 10**9,
        malicious: Optional[Dict[int, str]] = None,
        app_factory=None,
    ):
        """malicious: {validator index -> malicious handler name} builds
        those validators with a MaliciousApp."""
        self.chain_id = chain_id
        self.block_interval_ns = block_interval_ns
        powers = powers or [100] * n_validators
        keys = [
            PrivateKey.from_seed(b"multinet-val-%d" % i)
            for i in range(n_validators)
        ]
        genesis = {
            "chain_id": chain_id,
            "genesis_time_ns": 1_700_000_000_000_000_000,
            "accounts": [
                {
                    "address": k.public_key().address().hex(),
                    "balance": 1_000_000_000_000,
                }
                for k in keys
            ]
            + [
                {
                    "address": key.public_key().address().hex(),
                    "balance": balance,
                }
                for key, balance in (funded_accounts or [])
            ],
            "validators": [
                {
                    "address": k.public_key().address().hex(),
                    "self_delegation": p * 1_000_000,
                }
                for k, p in zip(keys, powers)
            ],
        }
        self.genesis = genesis
        self.validators: List[Validator] = []
        malicious = malicious or {}
        for i, (key, power) in enumerate(zip(keys, powers)):
            if app_factory is not None:
                app = app_factory(i)
            elif i in malicious:
                from celestia_tpu.node.malicious import MaliciousApp

                app = MaliciousApp(chain_id=chain_id, handler=malicious[i])
            else:
                app = App(chain_id=chain_id)
            app.init_chain(genesis)
            self.validators.append(Validator(f"val-{i}", key, power, app))
        self.blocks: List[Block] = []
        self.rounds: List[RoundResult] = []
        self._tx_index: Dict[bytes, dict] = {}
        self._now_ns = genesis["genesis_time_ns"]
        # gossip-observed conflicting signed votes:
        # (validator, height, hash_a, sig_a, hash_b, sig_b)
        self.observed_double_signs: List[tuple] = []

    # ------------------------------------------------------------------

    @property
    def height(self) -> int:
        return self.blocks[-1].header.height if self.blocks else 1

    @property
    def total_power(self) -> int:
        return sum(v.power for v in self.validators)

    def broadcast_tx(self, raw: bytes):
        """Gossip emulation: CheckTx everywhere; pool on every validator."""
        from celestia_tpu.state.tx import SubmitResult
        from celestia_tpu.da.blob import unmarshal_blob_tx
        from celestia_tpu.state.tx import unmarshal_tx

        code, log = 0, ""
        for val in self.validators:
            res = val.app.check_tx(raw)
            if res.code == 0:
                btx = unmarshal_blob_tx(raw)
                tx = unmarshal_tx(btx.tx if btx is not None else raw)
                val.mempool.add(raw, tx.fee.gas_price(), self.height)
            else:
                code, log = res.code, res.log
        return SubmitResult(code, log, hashlib.sha256(raw).digest())

    # ------------------------------------------------------------------
    # consensus rounds
    # ------------------------------------------------------------------

    def proposer_for(self, height: int, round_: int = 0) -> Validator:
        return self.validators[(height + round_) % len(self.validators)]

    def produce_block(self, max_rounds: int = None) -> Block:
        """Run consensus rounds at the next height until a block commits.

        Each failed round rotates the proposer (Tendermint round
        progression); raises if every validator's proposal is rejected.
        """
        height = self.height + 1
        if max_rounds is None:
            max_rounds = len(self.validators)
        last: Optional[RoundResult] = None
        for round_ in range(max_rounds):
            last = self._run_round(height, round_)
            if last.committed:
                return last.block
        raise RuntimeError(
            f"no block committed at height {height} after {max_rounds} rounds:"
            f" last votes {[(v.validator, v.accept, v.reason) for v in last.votes]}"
        )

    def _run_round(self, height: int, round_: int) -> RoundResult:
        proposer = self.proposer_for(height, round_)
        self._now_ns += self.block_interval_ns
        mem_txs = proposer.mempool.reap()
        try:
            proposal = proposer.app.prepare_proposal([t.raw for t in mem_txs])
        except Exception as e:  # a crashed proposer forfeits its round
            # (the reference's PrepareProposal deliberately panics to halt a
            # broken proposer, app/prepare_proposal.go:58-85; the network
            # moves to the next round)
            result = RoundResult(
                height, proposer.name, False,
                [Vote(proposer.name, False, f"proposer crashed: {e}")],
            )
            self.rounds.append(result)
            return result

        votes: List[Vote] = []
        accept_power = 0
        for val in self.validators:
            if val is proposer:
                ok, reason = True, "proposer"
            else:
                ok, reason = val.app.process_proposal(
                    proposal.block_txs, proposal.square_size, proposal.data_root
                )
            if ok:
                # an accept is a SIGNED vote on the block's data root; a
                # reject is a nil vote (unsigned)
                sig = val.sign_vote(self.chain_id, height, proposal.data_root)
                votes.append(
                    Vote(val.name, True, reason, proposal.data_root, sig)
                )
                if val.double_signs:
                    # byzantine: a second signature on a conflicting hash,
                    # gossiped like any vote — observers collect it as
                    # equivocation evidence
                    fake = hashlib.sha256(b"conflict" + proposal.data_root).digest()
                    self.observed_double_signs.append(
                        (val.address, height,
                         proposal.data_root, sig,
                         fake, val.sign_vote(self.chain_id, height, fake))
                    )
            else:
                votes.append(Vote(val.name, False, reason))
        # only votes whose signature verifies over THIS proposal's data
        # root count toward the quorum — a validly-signed vote on some
        # other hash is a nil vote here (and evidence fodder elsewhere)
        digest = vote_sign_bytes(self.chain_id, height, proposal.data_root)
        for val, vote in zip(self.validators, votes):
            if not vote.accept:
                continue
            if vote.block_hash == proposal.data_root and val.key.public_key(
            ).verify(digest, vote.signature):
                accept_power += val.power
            else:
                vote.accept = False
                vote.reason = "vote signature invalid for this block"
        committed = accept_power * 3 >= self.total_power * 2
        result = RoundResult(height, proposer.name, committed, votes)
        if committed:
            result.block = self._commit(height, proposal, proposer, votes)
        self.rounds.append(result)
        return result

    def _commit(
        self,
        height: int,
        proposal: PreparedProposal,
        proposer: Validator,
        votes: List[Vote],
    ) -> Block:
        # the commit's proposer + votes feed x/distribution (proposer
        # reward, power-weighted allocation) and x/slashing (liveness
        # window) in every validator's BeginBlocker — identical inputs are
        # a consensus requirement, like the block txs themselves
        vote_pairs = [
            (val.address, vote.accept)
            for val, vote in zip(self.validators, votes)
        ]
        app_hashes = []
        results_per_val = []
        for val in self.validators:
            results, _end, app_hash = val.app.finalize_block(
                proposal.block_txs, height, self._now_ns, proposal.data_root,
                proposer=proposer.address, votes=vote_pairs,
            )
            app_hashes.append(app_hash)
            results_per_val.append(results)
        if len(set(app_hashes)) != 1:
            raise ConsensusFailure(
                f"app hash divergence at height {height}: "
                f"{[h.hex()[:16] for h in app_hashes]}"
            )
        header = BlockHeader(
            height=height,
            time_ns=self._now_ns,
            chain_id=self.chain_id,
            app_version=self.validators[0].app.app_version,
            data_hash=proposal.data_root,
            app_hash=app_hashes[0],
            square_size=proposal.square_size,
        )
        block = Block(
            header, proposal.block_txs, results_per_val[0],
            proposer.address, vote_pairs,
        )
        self.blocks.append(block)
        for raw, res in zip(proposal.block_txs, results_per_val[0]):
            h = hashlib.sha256(raw).digest()
            self._tx_index[h] = {
                "code": res.code, "log": res.log, "height": height,
            }
            for val in self.validators:
                val.mempool.remove(h)
        for val in self.validators:
            val.mempool.evict_expired(height)
        return block

    def produce_blocks(self, n: int) -> List[Block]:
        return [self.produce_block() for _ in range(n)]

    # ------------------------------------------------------------------
    # client surface (Signer-compatible, routed via validator 0)
    # ------------------------------------------------------------------

    @property
    def app(self) -> App:
        """Validator 0's app — the state any client RPC would serve from."""
        return self.validators[0].app

    def account_info(self, address: bytes):
        # non-mutating: a query must never write one validator's state
        acc = self.validators[0].app.accounts.peek(address)
        return acc.account_number, acc.sequence

    def get_tx(self, tx_hash: bytes) -> Optional[dict]:
        return self._tx_index.get(tx_hash)

    def simulate(self, raw: bytes) -> int:
        from celestia_tpu.node.testnode import TestNode

        # reuse the lock-free body (this class has no service lock; the
        # simulation runs on a discarded branch of validator 0's state)
        return TestNode._simulate_locked(self, raw)  # type: ignore[arg-type]

    # ------------------------------------------------------------------
    # catch-up
    # ------------------------------------------------------------------

    def join_validator(
        self, name: str = None, power: int = 100, batch: int = 8
    ) -> Validator:
        """A fresh node joins: init from genesis, replay committed blocks
        verifying data roots with the BATCHED extension pipeline (multi-
        square batch parallelism — SURVEY §2.3 'validator catch-up'), then
        execute the blocks to rebuild state; it must land on the same app
        hash as the network."""
        import numpy as np

        from celestia_tpu.da import dah as dah_mod
        from celestia_tpu.da.square import construct as construct_square
        from celestia_tpu.ops import nmt as nmt_ops
        from celestia_tpu.ops import rs

        key = PrivateKey.from_seed(b"joiner-%d" % len(self.validators))
        app = App(chain_id=self.chain_id)
        app.init_chain(self.genesis)
        # phase 1: batched DA verification of all committed blocks
        squares_by_size: Dict[int, List[Tuple[int, "np.ndarray"]]] = {}
        for blk in self.blocks:
            # reconstruct with the size bound recorded in the header (the
            # gov bound may have changed since the block was built)
            square, _txs, _w = construct_square(
                blk.txs, blk.header.square_size
            )
            squares_by_size.setdefault(square.size, []).append(
                (
                    blk.header.height,
                    square.to_array().reshape(square.size, square.size, -1),
                )
            )
        roots_by_height: Dict[int, bytes] = {}
        for size, items in squares_by_size.items():
            for i in range(0, len(items), batch):
                chunk = items[i : i + batch]
                stacked = np.stack([sq for _, sq in chunk])
                # the extended squares stay on device — root reduction
                # runs on the device value and only the 90-byte roots
                # cross, in ONE batched fetch (the two sequential
                # np.asarray round trips this replaces pulled the whole
                # EDS batch host-side just to discard it)
                import jax

                eds_b = rs.extend_squares_batched(stacked)
                roots_dev = jax.vmap(nmt_ops.eds_nmt_roots)(eds_b)
                (roots_b,) = jax.device_get((roots_dev,))
                for (h, _), roots in zip(chunk, roots_b):
                    all_roots = roots.reshape(-1, 90)
                    droot = bytes(
                        nmt_ops.rfc6962_root_np([bytes(r) for r in all_roots])
                    )
                    roots_by_height[h] = droot
        for blk in self.blocks:
            if roots_by_height[blk.header.height] != blk.header.data_hash:
                raise ConsensusFailure(
                    f"catch-up: data root mismatch at height {blk.header.height}"
                )
        # phase 2: execute blocks to rebuild state (replaying each block's
        # recorded commit info so distribution/slashing writes reproduce)
        for blk in self.blocks:
            _res, _end, app_hash = app.finalize_block(
                blk.txs, blk.header.height, blk.header.time_ns,
                blk.header.data_hash,
                proposer=blk.proposer or None, votes=blk.votes,
            )
            if app_hash != blk.header.app_hash:
                raise ConsensusFailure(
                    f"catch-up: app hash mismatch at height {blk.header.height}"
                )
        val = Validator(name or f"val-{len(self.validators)}", key, power, app)
        self.validators.append(val)
        return val
