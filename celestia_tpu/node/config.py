"""Layered node configuration: defaults < config.toml < env < flags.

Parity role: the reference's cobra/viper layering with the CELESTIA env
prefix (cmd/celestia-appd/cmd/root.go:44-113) over celestia-flavoured
default comet/app configs (app/default_overrides.go:217-300).  The same
precedence order is implemented here with stdlib tomllib; env vars use the
``CELESTIA_`` prefix with ``__`` as the section separator
(e.g. ``CELESTIA_MEMPOOL__TTL_BLOCKS=10``).
"""

from __future__ import annotations

import json
import os
import time

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: the API-compatible backport
    import tomli as tomllib
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Any, Dict, Optional

ENV_PREFIX = "CELESTIA_"


@dataclass
class MempoolConfig:
    # prioritized mempool v1 with a 5-block TTL, MaxTxBytes from the max
    # square (default_overrides.go:258-284)
    ttl_blocks: int = 5
    max_tx_bytes: int = 128 * 128 * 482


@dataclass
class GrpcConfig:
    enable: bool = True
    address: str = "127.0.0.1:9090"


@dataclass
class SnapshotConfig:
    # state-sync snapshots every 1500 blocks, keep 2
    # (default_overrides.go:296-297)
    interval: int = 1500
    keep_recent: int = 2


@dataclass
class ConsensusConfig:
    # 15s goal block time (appconsts/consensus_consts.go:5-12)
    block_interval_s: float = 15.0
    create_empty_blocks: bool = True


@dataclass
class LogConfig:
    level: str = "info"
    format: str = "plain"  # plain | json
    to_file: str = ""


@dataclass
class NodeConfig:
    chain_id: str = "celestia-tpu-1"
    # 0.002utia floor (x/minfee, v2/app_consts.go:5-9)
    min_gas_price: float = 0.002
    v2_upgrade_height: Optional[int] = None
    mempool: MempoolConfig = field(default_factory=MempoolConfig)
    grpc: GrpcConfig = field(default_factory=GrpcConfig)
    snapshot: SnapshotConfig = field(default_factory=SnapshotConfig)
    consensus: ConsensusConfig = field(default_factory=ConsensusConfig)
    log: LogConfig = field(default_factory=LogConfig)

    def to_toml(self) -> str:
        lines = ["# celestia-tpu node configuration", ""]
        top, sections = {}, {}
        for key, val in asdict(self).items():
            if isinstance(val, dict):
                sections[key] = val
            else:
                top[key] = val
        for key, val in top.items():
            if val is None:
                continue
            lines.append(f"{key} = {_toml_value(val)}")
        for name, sec in sections.items():
            lines.append("")
            lines.append(f"[{name}]")
            for key, val in sec.items():
                if val is None:
                    continue
                lines.append(f"{key} = {_toml_value(val)}")
        return "\n".join(lines) + "\n"


def _toml_value(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return repr(v)
    return json.dumps(str(v))


def _apply(cfg: NodeConfig, section: Optional[str], key: str, value: Any) -> None:
    target = getattr(cfg, section) if section else cfg
    if not hasattr(target, key):
        raise ValueError(
            f"unknown config key: {section + '.' if section else ''}{key}"
        )
    cur = getattr(target, key)
    if cur is not None and not isinstance(value, type(cur)):
        # coerce strings from env vars to the field's type
        if isinstance(cur, bool):
            value = str(value).lower() in ("1", "true", "yes", "on")
        elif isinstance(cur, int):
            value = int(value)
        elif isinstance(cur, float):
            value = float(value)
        else:
            value = str(value)
    setattr(target, key, value)


def load_config(
    home: Optional[str] = None,
    env: Optional[Dict[str, str]] = None,
    overrides: Optional[Dict[str, Any]] = None,
) -> NodeConfig:
    """Resolve config with precedence: defaults < file < env < overrides."""
    cfg = NodeConfig()
    if home:
        path = Path(home) / "config" / "config.toml"
        if path.exists():
            with open(path, "rb") as f:
                data = tomllib.load(f)
            for key, val in data.items():
                if isinstance(val, dict):
                    for k2, v2 in val.items():
                        _apply(cfg, key, k2, v2)
                else:
                    _apply(cfg, None, key, val)
    for name, val in (env if env is not None else os.environ).items():
        if not name.startswith(ENV_PREFIX):
            continue
        spec = name[len(ENV_PREFIX):].lower()
        if "__" in spec:
            section, key = spec.split("__", 1)
            try:
                _apply(cfg, section, key, val)
            except (AttributeError, ValueError):
                continue  # unrelated CELESTIA_* env var
        else:
            try:
                _apply(cfg, None, spec, val)
            except ValueError:
                continue
    for spec, val in (overrides or {}).items():
        if "." in spec:
            section, key = spec.split(".", 1)
            _apply(cfg, section, key, val)
        else:
            _apply(cfg, None, spec, val)
    return cfg


def init_home(
    home: str,
    chain_id: str = "celestia-tpu-1",
    overwrite: bool = False,
    extra_accounts: Optional[list] = None,  # [(address_bytes, balance)]
) -> Path:
    """``celestia-tpu init`` — create home layout: config + genesis + keys.

    Mirrors the reference's init command output (config/, data/ dirs,
    genesis.json, node key) at cmd/celestia-appd/cmd/root.go:126-161.
    """
    root = Path(home)
    cfg_dir = root / "config"
    data_dir = root / "data"
    if cfg_dir.exists() and not overwrite:
        if (cfg_dir / "genesis.json").exists():
            raise FileExistsError(f"{home} is already initialised")
    cfg_dir.mkdir(parents=True, exist_ok=True)
    data_dir.mkdir(parents=True, exist_ok=True)
    cfg = NodeConfig(chain_id=chain_id)
    (cfg_dir / "config.toml").write_text(cfg.to_toml())

    from celestia_tpu.utils.secp256k1 import PrivateKey

    val_key = PrivateKey.from_seed(os.urandom(32))
    (cfg_dir / "priv_validator_key.json").write_text(
        json.dumps({"priv_key": val_key.d.to_bytes(32, "big").hex()}, indent=1)
    )
    from celestia_tpu.ops.gf256 import CODEC_LEOPARD

    val_addr = val_key.public_key().address()
    genesis = {
        "chain_id": chain_id,
        # a CONCRETE genesis time, pinned at init: 0 means "unset" to the
        # node (it would substitute per-node wall clock — diverging app
        # hashes across a shared-genesis ceremony)
        "genesis_time_ns": time.time_ns(),
        # the codec is written EXPLICITLY so "no codec key" always means
        # a pre-ADR-012 file (migrate-genesis pins those to lagrange);
        # leaving it implicit would make that inference ambiguous
        "codec": CODEC_LEOPARD,
        "accounts": [
            {"address": val_addr.hex(), "balance": 1_000_000_000_000}
        ]
        + [
            {"address": addr.hex(), "balance": balance}
            for addr, balance in (extra_accounts or [])
        ],
        "validators": [
            {"address": val_addr.hex(), "self_delegation": 100_000_000_000}
        ],
    }
    (cfg_dir / "genesis.json").write_text(json.dumps(genesis, indent=1))
    return root
