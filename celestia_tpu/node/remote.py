"""gRPC client: a network-remote node with the same surface as TestNode.

The Signer (client/signer.py) binds to anything exposing broadcast_tx /
account_info / simulate / get_tx / chain_id — in-process TestNode or this
class over a real network boundary.  Parity role: the gRPC connection
pkg/user's Signer holds (pkg/user/signer.go:31-55, broadcast :268-309,
ConfirmTx poll :365-395).

Lives in node/ (moved from client/, celint R8): the mesh itself is this
class's heaviest user — gossip links, catch-up pulls, state-sync chunk
fetches are all a NODE acting as an RPC client — and node/ may not
import client/.  client/remote.py re-exports the public surface for the
wallet/CLI tier, so existing client-side imports are unchanged.
"""

from __future__ import annotations

import json
from typing import List, Optional

import grpc

from celestia_tpu.state.tx import SubmitResult
from celestia_tpu.utils import tracing
from celestia_tpu.utils.telemetry import Telemetry, snake_case

SERVICE = "celestia.tpu.v1.Node"

# Client-side RPC byte/count telemetry: one process-wide Telemetry for
# every RemoteNode (gossip links, catch-up pulls, CLI tools) — counters
# only, named rpc_client_{method}_{calls,bytes_in,bytes_out}.  The node
# Metrics RPC appends these via client_rpc_exposition(), so a node's
# OWN outbound traffic (state-sync, catch-up) is scrapeable next to its
# serving-side counters.
RPC_TELEMETRY = Telemetry()


def client_rpc_exposition() -> List[str]:
    """Prometheus lines for the client-side RPC counters.  Hand-built
    from the counter map (never Telemetry.export_prometheus(): that
    would re-emit the shared cache-registry/span sections a node's own
    export already carries, and duplicate samples are malformed)."""
    counters, _gauges, _timings = RPC_TELEMETRY._snapshot()
    lines: List[str] = []
    for name, val in sorted(counters.items()):
        metric = f"celestia_tpu_{name}_total"
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric} {val}")
    return lines


class RemoteError(RuntimeError):
    pass


class RemoteNode:
    """Client handle to a celestia-tpu node's gRPC service."""

    # Hard transport bound on any single response (ADVICE r5 state-sync
    # DoS): grpc's own default is 4 MiB but IMPLICIT — pin it explicitly
    # so a future channel tweak cannot silently remove the only layer
    # that stops a hostile peer flooding an unbounded message.  Every
    # legitimate RPC (snapshot chunks are <= 1 MiB on the wire, 2 MiB as
    # hex) fits comfortably.
    MAX_RECV_BYTES = 4 * 1024 * 1024

    def __init__(self, address: str, timeout_s: float = 30.0):
        self.address = address
        self.timeout_s = timeout_s
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_receive_message_length", self.MAX_RECV_BYTES)
            ],
        )
        self._methods: dict = {}
        status = self.status()
        self.chain_id = status["chain_id"]

    def close(self) -> None:
        self._channel.close()

    def _call(self, method: str, payload: bytes) -> bytes:
        fn = self._methods.get(method)
        if fn is None:
            fn = self._channel.unary_unary(
                f"/{SERVICE}/{method}",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            self._methods[method] = fn
        prefix = f"rpc_client_{snake_case(method)}"
        RPC_TELEMETRY.incr(f"{prefix}_calls")
        RPC_TELEMETRY.incr(f"{prefix}_bytes_out", len(payload))
        try:
            resp = fn(payload, timeout=self.timeout_s)
        except grpc.RpcError as e:
            RPC_TELEMETRY.incr(f"{prefix}_errors")
            raise RemoteError(f"{method}: {e.code().name} {e.details()}") from e
        RPC_TELEMETRY.incr(f"{prefix}_bytes_in", len(resp) if resp else 0)
        return resp

    def _call_json(self, method: str, obj: dict) -> dict:
        return json.loads(self._call(method, json.dumps(obj).encode()))

    def _call_stream(self, method: str, payload: bytes):
        """Server-streaming call: yields response messages as bytes.
        Same byte/count telemetry as :meth:`_call`, accumulated per
        received message."""
        fn = self._methods.get(("stream", method))
        if fn is None:
            fn = self._channel.unary_stream(
                f"/{SERVICE}/{method}",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            self._methods[("stream", method)] = fn
        prefix = f"rpc_client_{snake_case(method)}"
        RPC_TELEMETRY.incr(f"{prefix}_calls")
        RPC_TELEMETRY.incr(f"{prefix}_bytes_out", len(payload))
        try:
            for resp in fn(payload, timeout=self.timeout_s):
                RPC_TELEMETRY.incr(
                    f"{prefix}_bytes_in", len(resp) if resp else 0
                )
                yield resp
        except grpc.RpcError as e:
            RPC_TELEMETRY.incr(f"{prefix}_errors")
            raise RemoteError(
                f"{method}: {e.code().name} {e.details()}"
            ) from e

    @staticmethod
    def _attach_tc(payload: dict, tc=None, height: int = 0) -> dict:
        """Attach the optional cross-node trace context: an explicit
        ``tc`` (a context being FORWARDED, e.g. the coordinator relaying
        the proposer's prepare context) wins over the ambient one; with
        tracing disabled and no explicit context the envelope is
        byte-identical to the pre-context wire format."""
        if tc is None:
            tc = tracing.wire_context(height=height)
        if tc:
            payload["_tc"] = tc
        return payload

    # -- TestNode-compatible client surface ----------------------------

    def status(self) -> dict:
        return self._call_json("Status", {})

    @property
    def height(self) -> int:
        return int(self.status()["height"])

    def account_info(self, address: bytes):
        out = self._call_json("AccountInfo", {"address": address.hex()})
        return out["account_number"], out["sequence"]

    def broadcast_tx(self, raw: bytes) -> SubmitResult:
        out = json.loads(self._call("Broadcast", raw))
        return SubmitResult(
            out["code"], out["log"], bytes.fromhex(out["txhash"])
        )

    def broadcast_txs_batch(self, raws) -> list:
        """Batched submission through the server's BroadcastBatch RPC:
        one check_txs_batch pass node-side, per-tx results in order."""
        out = self._call_json(
            "BroadcastBatch", {"txs": [r.hex() for r in raws]}
        )
        return [
            SubmitResult(e["code"], e["log"], bytes.fromhex(e["txhash"]))
            for e in out["results"]
        ]

    def get_tx(self, tx_hash: bytes) -> Optional[dict]:
        try:
            out = self._call_json("GetTx", {"hash": tx_hash.hex()})
        except RemoteError as e:
            if "DEADLINE_EXCEEDED" in str(e):
                # the node is busy (e.g. a cold XLA compile inside block
                # production holds the service lock); treat as "not yet"
                # so confirm loops keep polling instead of dying
                return None
            raise
        if not out.pop("found"):
            return None
        return out

    def simulate(self, raw: bytes) -> int:
        out = json.loads(self._call("Simulate", raw))
        if "gas" not in out:
            raise ValueError(out.get("log", "simulation failed"))
        return int(out["gas"])

    def block(self, height: int) -> dict:
        out = self._call_json("Block", {"height": height})
        if not out.pop("found"):
            raise KeyError(f"no block at height {height}")
        return out

    def data_root(self, height: int) -> bytes:
        return bytes.fromhex(self.block(height)["data_root"])

    def abci_query(self, path: str, data: dict):
        out = self._call_json("Query", {"path": path, "data": data})
        if out.get("code"):
            raise RemoteError(out.get("log", "query failed"))
        return out["value"]

    # -- observability plane --------------------------------------------

    def metrics(self) -> str:
        """The node's Prometheus text exposition (the ``Metrics`` RPC):
        counters, gauges, bounded histograms, cache registry."""
        return self._call("Metrics", b"{}").decode()

    def trace_dump(self, last: Optional[int] = None) -> dict:
        """The node's last N block traces: ``{"enabled", "blocks",
        "trace"}``; ``trace`` is Chrome trace-event JSON — write it to a
        file and open it in Perfetto (ui.perfetto.dev) unchanged."""
        payload: dict = {}
        if last is not None:
            payload["last"] = int(last)
        return self._call_json("TraceDump", payload)

    def time_series(self, last: Optional[int] = None) -> dict:
        """The node's continuous-telemetry ring + alert verdicts (the
        ``TimeSeries`` RPC): ``{"snapshots", "rates", "alerts",
        "samples_kept", ...}``.  The server records one fresh sample per
        call, so calling twice always yields >= 2 snapshots with a
        computable rate."""
        payload: dict = {}
        if last is not None:
            payload["last"] = int(last)
        return self._call_json("TimeSeries", payload)

    def block_scorecard(self, last: Optional[int] = None) -> dict:
        """The node's per-height block scorecard ring (the
        ``BlockScorecard`` RPC): ``{"node_id", "height", "rows"}`` —
        one row per height with prepare/process walls, extend leg,
        propagation delay, commit lag and the critical-path top
        contributors.  The server ingests freshly completed traces
        before answering, so a row exists right after its block."""
        payload: dict = {}
        if last is not None:
            payload["last"] = int(last)
        return self._call_json("BlockScorecard", payload)

    def host_profile(self, top: int = 25, folded: int = 200) -> dict:
        """The node's host sampling-profiler view (the ``HostProfile``
        RPC): ``{"stats", "top_frames", "folded"}`` — folded stacks are
        bounded to the top N by count."""
        return self._call_json(
            "HostProfile", {"top": int(top), "folded": int(folded)}
        )

    def flight_list(self) -> dict:
        """Kept incident-bundle manifests + recorder ring stats (the
        ``FlightList`` RPC); ``{"enabled": false}`` on a node running
        without --flight-dir."""
        return self._call_json("FlightList", {})

    def flight_fetch(self, incident_id: str = "") -> dict:
        """One full incident bundle (the ``FlightFetch`` RPC): manifest
        plus every artifact as text.  Empty id fetches the newest.
        Large bundles arrive file-by-file (the server answers
        ``files_inline: false`` when the inline form would blow this
        channel's 4 MiB receive cap); the per-file fetches are folded
        back into the inline shape, so callers never see the split."""
        out = self._call_json("FlightFetch", {"id": incident_id})
        if not out.get("found") or out.get("files_inline") is not False:
            return out
        files = {}
        inc_id = out["manifest"]["id"]
        for entry in out["manifest"].get("files", []):
            name = entry.get("name", "")
            part = self._call_json(
                "FlightFetch", {"id": inc_id, "file": name}
            )
            if part.get("found"):
                files[name] = part.get("content", "")
                if part.get("truncated"):
                    files[name] += "\n<truncated by transport cap>"
        return {"found": True, "manifest": out["manifest"], "files": files}

    def clock_probe(self) -> dict:
        """One peer telemetry-clock read: ``{"ts", "node_id",
        "height"}`` (the ClockProbe RPC)."""
        return self._call_json("ClockProbe", {})

    def clock_offset(self, samples: int = 5) -> dict:
        """Midpoint-estimate this peer's clock offset/RTT
        (``{"offset_s", "rtt_s", "samples"}``; see
        tracing.estimate_clock_offset).  Raises RemoteError against an
        un-upgraded peer without the ClockProbe RPC — callers treat
        that as offset unknown (0)."""
        return tracing.estimate_clock_offset(
            lambda: self.clock_probe()["ts"], samples=samples
        )

    # -- consensus surface (used by node/coordinator.py) ----------------

    def cons_prepare(self) -> dict:
        out = self._call_json("ConsPrepare", self._attach_tc({}))
        result = {
            "block_txs": [bytes.fromhex(t) for t in out["block_txs"]],
            "square_size": out["square_size"],
            "data_root": bytes.fromhex(out["data_root"]),
        }
        # the proposer's prepare-root trace context, when its tracer is
        # on: the coordinator forwards this into cons_process/commit so
        # validator-side spans carry the PROPOSER as their cross-node
        # parent (old servers simply never return it)
        if out.get("_tc"):
            result["_tc"] = out["_tc"]
        return result

    def cons_process(
        self, block_txs, square_size: int, data_root: bytes, tc=None
    ):
        out = self._call_json(
            "ConsProcess",
            self._attach_tc(
                {
                    "block_txs": [t.hex() for t in block_txs],
                    "square_size": square_size,
                    "data_root": data_root.hex(),
                },
                tc=tc,
            ),
        )
        return out["accept"], out.get("reason", "")

    def cons_commit(
        self, block_txs, height: int, time_ns: int, data_root: bytes,
        square_size: int, proposer: bytes = b"", votes=None, tc=None,
    ) -> bytes:
        out = self._call_json(
            "ConsCommit",
            self._attach_tc(
                {
                    "block_txs": [t.hex() for t in block_txs],
                    "height": height,
                    "time_ns": time_ns,
                    "data_root": data_root.hex(),
                    "square_size": square_size,
                    "proposer": proposer.hex(),
                    "votes": (
                        [[a.hex(), bool(ok)] for a, ok in votes]
                        if votes is not None
                        else None
                    ),
                },
                tc=tc,
                height=height,
            ),
        )
        return bytes.fromhex(out["app_hash"])

    # -- two-phase BFT surface (dumb-relay transport, node/bft.py) ------

    def bft_start(self, height: int) -> None:
        self._call_json("BftStart", {"height": height})

    def bft_msg(self, wire: dict) -> None:
        # the relay forwards wires verbatim (no outer envelope), so the
        # trace context rides INSIDE the wire dict under "_tc": old
        # receivers hand it to an engine that ignores unknown keys, new
        # receivers strip it before delivery.  Never mutate the caller's
        # dict — the relay re-forwards the same object to other peers.
        if tracing.enabled():
            wire = dict(
                wire,
                _tc=tracing.wire_context(
                    height=int(wire.get("height", 0) or 0)
                ),
            )
        self._call_json("BftMsg", wire)

    def bft_timeout(self, step: str, height: int, round_: int) -> None:
        self._call_json(
            "BftTimeout", {"step": step, "height": height, "round": round_}
        )

    def bft_drain(self) -> dict:
        return self._call_json("BftDrain", {})

    def bft_decided(self, height: int) -> Optional[dict]:
        out = self._call_json("BftDecided", {"height": height})
        return out["decided"] if out["found"] else None

    def bft_catchup(self, decided_wire: dict) -> bool:
        return bool(self._call_json("BftCatchup", decided_wire)["ok"])

    # -- p2p gossip mesh surface (node/gossip.py) -----------------------

    def gossip_msg(self, payload: dict) -> bool:
        """Deliver a flooded consensus message: {"wire", "sender"}.  The
        dedup id is always computed receiver-side from the wire content —
        a sender-supplied id would be a censorship vector."""
        return bool(self._call_json("GossipMsg", payload).get("new"))

    def tx_have(self, hashes) -> list:
        """Announce pooled tx hashes; returns the subset the peer wants."""
        out = self._call_json(
            "TxHave", {"hashes": [h.hex() for h in hashes]}
        )
        return [bytes.fromhex(h) for h in out.get("want", [])]

    def tx_push(self, raws) -> int:
        out = self._call_json("TxPush", {"txs": [r.hex() for r in raws]})
        return int(out.get("admitted", 0))

    def peer_exchange(self, sender: str, peers) -> list:
        """PEX: offer our address + known peers, learn the callee's."""
        out = self._call_json(
            "PeerExchange", {"sender": sender, "peers": list(peers)}
        )
        return list(out.get("peers", []))

    def das_sample(
        self, height: int, row: int, col: int, *, policy=None, peer=None
    ):
        """One DAS cell + proof from the node's serving plane.

        A shed response (load shedding or an injected serving fault) is
        retried through the unified RetryPolicy, honoring the server's
        ``retry_after_ms`` pushback; returns the sample dict
        ``{"proof": ..., "data_root": ...}``.  The final shed attempt
        raises :class:`faults.Overloaded` — the caller's signal that the
        plane is saturated, not broken.

        ``peer`` (optional) stamps a client-asserted identity on the
        envelope for the server's per-peer QoS accounting; omitted =
        anonymous, and old servers ignore the field (version-tolerant
        envelopes)."""
        from celestia_tpu.utils import faults

        if policy is None:
            policy = faults.RetryPolicy(
                attempts=6, base_s=0.02, cap_s=0.25,
                deadline_s=self.timeout_s,
            )

        def attempt():
            payload = {"height": height, "row": row, "col": col}
            if peer:
                payload["peer"] = str(peer)
            out = self._call_json(
                "DasSample",
                self._attach_tc(payload, height=height),
            )
            if out.get("shed"):
                raise faults.Overloaded(
                    out.get("log") or "DAS serving plane shed the request",
                    retry_after_ms=float(out.get("retry_after_ms", 25.0)),
                )
            if out.get("code"):
                raise RemoteError(out.get("log", "das sample failed"))
            return out

        return policy.run(attempt, retry_on=(faults.Overloaded,))

    def das_sample_batch(
        self, height: int, coords, *, policy=None, chunk: int = 0,
        peer=None,
    ) -> dict:
        """n DAS cells + proofs in ONE streaming request (the
        DasSampleBatch RPC): the server proves row-grouped chunks and
        streams them back, re-passing its load-shed gate per chunk.

        A mid-stream shed carries ``served`` (cells already streamed)
        and ``retry_after_ms``; this client keeps every proof it has,
        drops the served prefix, and retries ONLY the remainder through
        the unified RetryPolicy — honest pushback costs re-requesting
        nothing.  Returns ``{"proofs": [...], "data_root": hex}`` with
        proofs in the requested coordinate order; the final shed attempt
        raises :class:`faults.Overloaded`.  ``peer`` stamps the optional
        client-asserted identity for per-peer QoS accounting (see
        :meth:`das_sample`)."""
        from celestia_tpu.utils import faults

        if policy is None:
            policy = faults.RetryPolicy(
                attempts=6, base_s=0.02, cap_s=0.25,
                deadline_s=self.timeout_s,
            )
        remaining = [(int(r), int(c)) for r, c in coords]
        proofs: list = []
        state = {"data_root": ""}

        def attempt():
            payload = {
                "height": int(height),
                "coords": [[r, c] for r, c in remaining],
            }
            if chunk:
                payload["chunk"] = int(chunk)
            if peer:
                payload["peer"] = str(peer)
            stream = self._call_stream(
                "DasSampleBatch",
                json.dumps(
                    self._attach_tc(payload, height=int(height))
                ).encode(),
            )
            while True:
                try:
                    resp = next(stream)
                except StopIteration:
                    break
                except RemoteError as e:
                    # a transport drop MID-conversation (some chunks
                    # already landed, this attempt or an earlier one) is
                    # retried like shed load — partial progress is kept
                    # and only the remainder re-requested, exactly as a
                    # clean early EOF would be.  A server that never
                    # answered at all stays a hard RemoteError.
                    if proofs:
                        raise faults.Overloaded(
                            f"DAS batch stream dropped: {e}",
                            retry_after_ms=25.0,
                        ) from e
                    raise
                out = json.loads(resp)
                if out.get("shed"):
                    # every chunk already streamed trimmed `remaining`
                    # below, so the retry asks only for the rest
                    raise faults.Overloaded(
                        out.get("log")
                        or "DAS serving plane shed the batch",
                        retry_after_ms=float(
                            out.get("retry_after_ms", 25.0)
                        ),
                    )
                if out.get("code"):
                    raise RemoteError(
                        out.get("log", "das sample batch failed")
                    )
                got = out.get("proofs", [])
                proofs.extend(got)
                del remaining[: len(got)]
                root = out.get("data_root", "")
                if state["data_root"] and root != state["data_root"]:
                    raise RemoteError(
                        "data_root changed mid-stream"
                    )
                state["data_root"] = root
            if remaining:
                # stream ended without a shed marker but short: treat
                # as overload (a crashing server must not look like a
                # complete answer)
                raise faults.Overloaded(
                    "DAS batch stream ended early",
                    retry_after_ms=25.0,
                )
            return {"proofs": proofs, "data_root": state["data_root"]}

        return policy.run(attempt, retry_on=(faults.Overloaded,))

    def genesis(self):
        """The peer's genesis document, or None (download-genesis)."""
        out = self._call_json("Genesis", {})
        return out.get("genesis") if out.get("found") else None

    # -- state-sync (snapshot serving) ----------------------------------

    def snapshot_list(self) -> list:
        """Snapshot metadata dicts the peer can serve (state-sync)."""
        return list(self._call_json("SnapshotList", {}).get("snapshots", []))

    def snapshot_chunk(self, height: int, fmt: int, idx: int):
        out = self._call_json(
            "SnapshotChunk",
            self._attach_tc(
                {"height": height, "format": fmt, "idx": idx}, height=height
            ),
        )
        if not out.get("found"):
            return None
        data = out["data"]
        # size-bound the HEX payload before decoding.  The transport cap
        # (MAX_RECV_BYTES on the channel — the layer that actually stops
        # an arbitrarily large response from being buffered) has already
        # bounded the message; this check catches a hostile-but-small
        # oversized chunk early, with the precise SnapshotLimitError the
        # sync engine uses to back the peer off (ADVICE r5)
        from celestia_tpu.node.snapshots import (
            MAX_WIRE_CHUNK_BYTES,
            SnapshotLimitError,
        )

        if len(data) > 2 * MAX_WIRE_CHUNK_BYTES:
            raise SnapshotLimitError(
                f"snapshot chunk {idx} hex payload is {len(data)} chars "
                f"(cap {2 * MAX_WIRE_CHUNK_BYTES})"
            )
        return bytes.fromhex(data)

    def wait_for_height(self, h: int, timeout_s: float = 60.0) -> None:
        from celestia_tpu.utils.faults import RetryPolicy

        RetryPolicy(base_s=0.05, cap_s=0.2, deadline_s=timeout_s).poll(
            lambda: self.height >= h, what=f"height {h}"
        )
