"""In-process single-node network: the workhorse test/devnet driver.

Parity with /root/reference/test/util/testnode/ (full_node.go:20-49 spins a
consensus node + app in one process via a local ABCI client; network.go:19-69
+ node_interaction_api.go:40-151 provide the fluent config, funded accounts
and WaitForHeight/PostData helpers).  Here the consensus engine is an
in-process block-production loop that drives the App's ABCI surface exactly
the way celestia-core does: reap mempool by priority -> PrepareProposal ->
ProcessProposal (every block self-validated, so a Prepare/Process divergence
fails loudly) -> finalize + commit.
"""

from __future__ import annotations

import hashlib
import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from celestia_tpu.appconsts import (
    CONTINUATION_SPARSE_SHARE_CONTENT_SIZE,
    GOAL_BLOCK_TIME_SECONDS,
)
from celestia_tpu.client.signer import SubmitResult
from celestia_tpu.da.blob import unmarshal_blob_tx
from celestia_tpu.node.mempool import Mempool
from celestia_tpu.state.ante import AnteContext, AnteError, run_ante
from celestia_tpu.state.app import App, TxResult
from celestia_tpu.state.auth import AccountKeeper
from celestia_tpu.state.bank import BankKeeper
from celestia_tpu.state.params import ParamsKeeper
from celestia_tpu.state.tx import unmarshal_tx
from celestia_tpu.utils.secp256k1 import PrivateKey


@dataclass
class BlockHeader:
    height: int
    time_ns: int
    chain_id: str
    app_version: int
    data_hash: bytes
    app_hash: bytes  # state root AFTER this block
    square_size: int


@dataclass
class Block:
    header: BlockHeader
    txs: List[bytes]
    tx_results: List[TxResult] = field(default_factory=list)


class TestNode:
    """Single-process node exposing the client surface the Signer needs."""

    __test__ = False  # not a pytest class

    def __init__(
        self,
        chain_id: str = "celestia-tpu-devnet",
        funded_accounts: Optional[List[Tuple[PrivateKey, int]]] = None,
        genesis_time_ns: Optional[int] = None,
        block_interval_ns: int = GOAL_BLOCK_TIME_SECONDS * 10**9,
        auto_produce: bool = True,
        **app_kwargs,
    ):
        self.app = App(chain_id=chain_id, **app_kwargs)
        self.chain_id = chain_id
        self.block_interval_ns = block_interval_ns
        self.auto_produce = auto_produce
        max_bytes = (
            self.app.max_effective_square_size() ** 2
            * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
        )
        self.mempool = Mempool(max_tx_bytes=max_bytes)
        self.blocks: List[Block] = []
        self._tx_index: Dict[bytes, dict] = {}
        genesis = {
            "chain_id": chain_id,
            "genesis_time_ns": genesis_time_ns or _time.time_ns(),
            "accounts": [],
            "validators": [],
        }
        self._validator_key = PrivateKey.from_seed(b"testnode-validator")
        val_addr = self._validator_key.public_key().address()
        genesis["accounts"].append(
            {"address": val_addr.hex(), "balance": 1_000_000_000_000}
        )
        genesis["validators"].append(
            {"address": val_addr.hex(), "self_delegation": 100_000_000_000}
        )
        for key, balance in funded_accounts or []:
            genesis["accounts"].append(
                {"address": key.public_key().address().hex(), "balance": balance}
            )
        self.app.init_chain(genesis)
        self._now_ns = self.app.genesis_time_ns

    # ------------------------------------------------------------------
    # client surface (what pkg/user's gRPC connection provides)
    # ------------------------------------------------------------------

    @property
    def height(self) -> int:
        return self.blocks[-1].header.height if self.blocks else 1

    def account_info(self, address: bytes) -> Tuple[int, int]:
        acc = self.app.accounts.get_or_create(address)
        return acc.account_number, acc.sequence

    def broadcast_tx(self, raw: bytes) -> SubmitResult:
        """BroadcastMode_SYNC parity: CheckTx, then admit to the mempool."""
        res = self.app.check_tx(raw)
        tx_hash = hashlib.sha256(raw).digest()
        if res.code != 0:
            return SubmitResult(res.code, res.log, tx_hash)
        btx = unmarshal_blob_tx(raw)
        tx = unmarshal_tx(btx.tx if btx is not None else raw)
        self.mempool.add(raw, tx.fee.gas_price(), self.height)
        return SubmitResult(0, "", tx_hash)

    def get_tx(self, tx_hash: bytes) -> Optional[dict]:
        info = self._tx_index.get(tx_hash)
        if info is None and self.auto_produce and len(self.mempool):
            # emulate chain progress for poll-confirm clients: a pending
            # mempool makes the (virtual) proposer cut the next block
            self.produce_block()
            info = self._tx_index.get(tx_hash)
        return info

    def simulate(self, raw: bytes) -> int:
        """Gas estimation via simulated ante + 20% margin (signer.go
        EstimateGas shape)."""
        tx = unmarshal_tx(raw)
        branch = self.app.store.branch()
        ctx = AnteContext(
            tx=tx,
            raw_tx=raw,
            accounts=AccountKeeper(branch.store("auth")),
            bank=BankKeeper(branch.store("bank")),
            params=ParamsKeeper(branch.store("params")),
            chain_id=self.chain_id,
            app_version=self.app.app_version,
            simulate=True,
        )
        try:
            meter = run_ante(ctx)
            base = meter.consumed
        except AnteError:
            base = 100_000
        return int(base * 1.2) + 100_000

    # ------------------------------------------------------------------
    # consensus loop
    # ------------------------------------------------------------------

    def produce_block(self) -> Block:
        """One consensus round: reap -> Prepare -> Process -> finalize."""
        height = self.height + 1
        self._now_ns += self.block_interval_ns
        time_ns = self._now_ns
        mem_txs = self.mempool.reap()
        proposal = self.app.prepare_proposal([t.raw for t in mem_txs])
        accepted, reason = self.app.process_proposal(
            proposal.block_txs, proposal.square_size, proposal.data_root
        )
        if not accepted:
            raise RuntimeError(
                f"node's own proposal rejected at height {height}: {reason}"
            )
        results, _end, app_hash = self.app.finalize_block(
            proposal.block_txs, height, time_ns, proposal.data_root
        )
        header = BlockHeader(
            height=height,
            time_ns=time_ns,
            chain_id=self.chain_id,
            app_version=self.app.app_version,
            data_hash=proposal.data_root,
            app_hash=app_hash,
            square_size=proposal.square_size,
        )
        block = Block(header, proposal.block_txs, results)
        self.blocks.append(block)
        # index included txs + drop them from the mempool
        for raw, res in zip(proposal.block_txs, results):
            h = hashlib.sha256(raw).digest()
            self._tx_index[h] = {"code": res.code, "log": res.log, "height": height}
            self.mempool.remove(h)
        # txs the proposer dropped stay pooled until their TTL expires
        self.mempool.evict_expired(height)
        return block

    def produce_blocks(self, n: int) -> List[Block]:
        return [self.produce_block() for _ in range(n)]

    def wait_for_height(self, h: int) -> None:
        while self.height < h:
            self.produce_block()

    # ------------------------------------------------------------------
    # queries (node_interaction_api.go helpers)
    # ------------------------------------------------------------------

    def block(self, height: int) -> Block:
        for b in self.blocks:
            if b.header.height == height:
                return b
        raise KeyError(f"no block at height {height}")

    def data_root(self, height: int) -> bytes:
        return self.block(height).header.data_hash

    def fill_block(self, square_size: int, signer) -> SubmitResult:
        """Post a blob sized to produce a square of ``square_size``
        (node_interaction_api.go FillBlock)."""
        from celestia_tpu.da.blob import Blob
        from celestia_tpu.da.namespace import Namespace

        n_shares = square_size * square_size // 2
        size = (n_shares - 1) * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
        blob = Blob(Namespace.v0(b"fill"), b"\xaa" * max(size, 1))
        return signer.submit_pay_for_blob([blob])
