"""In-process single-node network: the workhorse test/devnet driver.

Parity with /root/reference/test/util/testnode/ (full_node.go:20-49 spins a
consensus node + app in one process via a local ABCI client; network.go:19-69
+ node_interaction_api.go:40-151 provide the fluent config, funded accounts
and WaitForHeight/PostData helpers).  Here the consensus engine is an
in-process block-production loop that drives the App's ABCI surface exactly
the way celestia-core does: reap mempool by priority -> PrepareProposal ->
ProcessProposal (every block self-validated, so a Prepare/Process divergence
fails loudly) -> finalize + commit.
"""

from __future__ import annotations

import hashlib
import threading
import time as _time
from typing import Dict, List, Optional, Tuple

from celestia_tpu.appconsts import (
    CONTINUATION_SPARSE_SHARE_CONTENT_SIZE,
    GOAL_BLOCK_TIME_SECONDS,
)
from celestia_tpu.state.tx import SubmitResult
from celestia_tpu.da.blob import unmarshal_blob_tx
from celestia_tpu.node.mempool import Mempool
from celestia_tpu.utils.lru import LruCache
from celestia_tpu.state.ante import AnteContext, AnteError, run_ante
from celestia_tpu.state.app import App
from celestia_tpu.state.auth import AccountKeeper
from celestia_tpu.state.bank import BankKeeper
from celestia_tpu.state.params import ParamsKeeper
from celestia_tpu.state.tx import unmarshal_tx
from celestia_tpu.utils.secp256k1 import PrivateKey


# Block/BlockHeader moved to state/consensus.py (celint R8: the
# persistence layer replays them from state/, below node/); re-exported
# here so node-side callers are unchanged.
from celestia_tpu.state.consensus import Block, BlockHeader  # noqa: F401,E402


class TestNode:
    """Single-process node exposing the client surface the Signer needs."""

    __test__ = False  # not a pytest class

    def __init__(
        self,
        chain_id: str = "celestia-tpu-devnet",
        funded_accounts: Optional[List[Tuple[PrivateKey, int]]] = None,
        genesis_time_ns: Optional[int] = None,
        block_interval_ns: int = GOAL_BLOCK_TIME_SECONDS * 10**9,
        auto_produce: bool = True,
        genesis: Optional[dict] = None,
        validator_key: Optional[PrivateKey] = None,
        snapshot_dir: Optional[str] = None,
        snapshot_interval: int = 0,
        snapshot_keep_recent: int = 2,
        app: Optional[App] = None,
        data_dir: Optional[str] = None,
        state_checkpoint_interval: int = 500,
        **app_kwargs,
    ):
        # One reentrant lock serialises every client-surface entry point:
        # concurrent confirm-polls (get_tx auto-produce), broadcasts and
        # the server's production loop all touch app/mempool/blocks state
        # (pkg/user's Signer is explicitly multi-threaded against one node)
        self._service_lock = threading.RLock()
        # disk-backed persistence (data_dir given): recover a previous
        # chain from the append-only logs, or start fresh and log from
        # genesis.  The block log is the consistency anchor: a crash
        # between the state fsync and the block fsync replays state only
        # up to the last fully-persisted block.
        self.data_dir = data_dir
        self._state_log = None
        self._block_log = None
        # genesis document served to joining peers (download-genesis);
        # set below on the fresh-InitChain path, or by the CLI on the
        # recovery/snapshot-restore paths (which never re-run InitChain)
        self.genesis_doc: Optional[dict] = None
        recovered_blocks: List[Block] = []
        disk_recovered = False
        if data_dir and app is None:
            import os as _os

            from celestia_tpu.state.disk import BlockLog, StateLog

            recovered_blocks = BlockLog.recover(data_dir)
            if recovered_blocks:
                rec = StateLog.recover(
                    data_dir, up_to=recovered_blocks[-1].header.height
                )
                if rec is None:
                    raise RuntimeError(
                        f"data dir {data_dir} has blocks but no intact "
                        "state log"
                    )
                state, h, ah = rec
                if h != recovered_blocks[-1].header.height:
                    raise RuntimeError(
                        f"state log recovered to height {h} but block log "
                        f"ends at {recovered_blocks[-1].header.height}"
                    )
                app = App.restore_from_disk(state, h, ah, **app_kwargs)
                disk_recovered = True
            else:
                # no fully-persisted block survived: a stale state.log
                # (e.g. crash in the first block's fsync window) would
                # poison a fresh chain with duplicate/orphan records —
                # start from a clean slate
                for name in ("state.log", "blocks.log"):
                    p = _os.path.join(data_dir, name)
                    if _os.path.exists(p):
                        _os.remove(p)
        restored = app is not None
        self.app = app if restored else App(chain_id=chain_id, **app_kwargs)
        if data_dir:
            from celestia_tpu.state.disk import BlockLog, StateLog

            self._state_log = StateLog(
                data_dir, checkpoint_interval=state_checkpoint_interval
            )
            self._block_log = BlockLog(data_dir)
            if restored and not disk_recovered:
                # snapshot-restored app adopting a data dir: seed the
                # state log with a base checkpoint so future recoveries
                # replay from here, not from an empty state
                self._state_log.append_checkpoint(
                    self.app.store.last_height,
                    self.app.store.committed_hash(self.app.store.last_height),
                    self.app.store.raw_state(),
                )
            self.app.store.set_persister(self._persist_commit)
        self.chain_id = self.app.chain_id if restored else chain_id
        self.block_interval_ns = block_interval_ns
        self.auto_produce = auto_produce
        self.snapshot_interval = snapshot_interval
        self.snapshot_keep_recent = snapshot_keep_recent
        self.snapshots = None
        if snapshot_dir:
            from celestia_tpu.node.snapshots import SnapshotStore

            self.snapshots = SnapshotStore(snapshot_dir)
        max_bytes = (
            self.app.max_effective_square_size() ** 2
            * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
        )
        self.mempool = Mempool(max_tx_bytes=max_bytes)
        self.blocks: List[Block] = []
        self._tx_index: Dict[bytes, dict] = {}
        # event index: "type" and "type.attr=value" -> tx hashes, serving
        # query-by-event (the reference's tx_search over indexed events,
        # pkg/user/signer.go:365-395 confirm workflows)
        self._event_index: Dict[str, List[bytes]] = {}
        # recent-block EDS/DAH/layout cache: inclusion proofs are served
        # from here without recomputing the extension (the role of
        # pkg/inclusion's EDS subtree cache + pkg/proof query routes)
        self._eds_cache: Dict[int, dict] = {}
        self.eds_cache_blocks = 8
        self._validator_key = validator_key or PrivateKey.from_seed(
            b"testnode-validator"
        )
        self._bft = None  # armed by enable_bft()
        self._bft_decided_log = LruCache("bft_decided_log", 512)
        if recovered_blocks:
            # disk recovery: resume the chain where the logs end
            self.blocks = recovered_blocks
            for blk in recovered_blocks:
                for raw, res in zip(blk.txs, blk.tx_results):
                    self._index_tx(
                        hashlib.sha256(raw).digest(), res, blk.header.height
                    )
            self._now_ns = recovered_blocks[-1].header.time_ns
            return
        if restored:
            # state-sync restore: the app already carries committed state at
            # its snapshot height; no InitChain
            self._now_ns = genesis_time_ns or _time.time_ns()
            return
        if genesis is None:
            genesis = {
                "chain_id": chain_id,
                "genesis_time_ns": genesis_time_ns or _time.time_ns(),
                "accounts": [],
                "validators": [],
            }
            val_addr = self._validator_key.public_key().address()
            genesis["accounts"].append(
                {"address": val_addr.hex(), "balance": 1_000_000_000_000}
            )
            genesis["validators"].append(
                {"address": val_addr.hex(), "self_delegation": 100_000_000_000}
            )
            for key, balance in funded_accounts or []:
                genesis["accounts"].append(
                    {
                        "address": key.public_key().address().hex(),
                        "balance": balance,
                    }
                )
        else:
            genesis = dict(genesis)
            genesis.setdefault("chain_id", chain_id)
            if not genesis.get("genesis_time_ns"):
                genesis["genesis_time_ns"] = genesis_time_ns or _time.time_ns()
        self.app.init_chain(genesis)
        # retained so joining peers can download the genesis document
        # over gRPC (the reference's download-genesis role)
        self.genesis_doc = genesis
        self._now_ns = self.app.genesis_time_ns

    # ------------------------------------------------------------------
    # two-phase BFT mode (node/bft.py engine; the relay is dumb transport)
    # ------------------------------------------------------------------

    def enable_bft(self, valset: List[dict]) -> None:
        """Arm the Tendermint-style consensus engine.  valset entries:
        {"address": hex, "pubkey": hex (33B compressed), "power": int}.
        Once enabled, blocks are produced ONLY by BFT decisions — this
        node prevotes/precommits with its validator key and finalizes
        when IT observes a 2/3 precommit quorum, never because a
        coordinator told it to (VERDICT r2 #5)."""
        from celestia_tpu.node.bft import BFTNode

        validators = {
            bytes.fromhex(v["address"]): int(v["power"]) for v in valset
        }
        pubkeys = {
            bytes.fromhex(v["address"]): bytes.fromhex(v["pubkey"])
            for v in valset
        }
        own = self._validator_key.public_key().address()
        if own not in validators:
            # fail at startup, not as a silent consensus stall later
            raise ValueError(
                f"this node's validator key ({own.hex()}) is not in the "
                "BFT valset — check priv_validator_key.json vs valset.json"
            )
        self._bft_valset = [dict(v) for v in valset]  # for state-sync re-arm
        self._bft_block_ids: Dict[int, bytes] = {}
        self._bft_decided_log = LruCache("bft_decided_log", 512)
        self._bft = BFTNode(
            chain_id=self.chain_id,
            key=self._validator_key,
            validators=validators,
            validate_fn=self._bft_validate,
            propose_fn=self._bft_propose,
            on_decide=self._bft_decide,
            pubkeys=pubkeys,
        )

    def _bft_validate(self, payload):
        from celestia_tpu.node.bft import validate_payload_against_chain

        try:
            expected = self.app.store.committed_hash(payload.height - 1)
        except KeyError:
            expected = None
        # Timestamp anchor: the PREVIOUS BLOCK's header time when this
        # node has it.  Not _now_ns — a snapshot-restored node's _now_ns
        # is wall/genesis time, which can sit far ahead of chain time and
        # would make it nil-prevote every honest proposal forever.  When
        # the previous block is unknown (fresh post-restore) both checks
        # are skipped; they re-arm at the next committed block.  The
        # drift bound is a small multiple of the interval so a Byzantine
        # proposer cannot creep chain time by a large allowance per
        # block (honest proposals sit at exactly prev + interval).
        prev_time = None
        if self.blocks and (
            self.blocks[-1].header.height == payload.height - 1
        ):
            prev_time = self.blocks[-1].header.time_ns
        ok, why = validate_payload_against_chain(
            self._bft, payload, self._bft_block_ids.get(payload.height - 1),
            expected_prev_app_hash=expected,
            prev_time_ns=prev_time,
            now_ns=prev_time,
            max_drift_ns=2 * self.block_interval_ns,
        )
        if not ok:
            return False, f"bad commit certificate: {why}"
        return self.app.process_proposal(
            list(payload.txs), payload.square_size, payload.data_root
        )

    def _bft_propose(self, height: int, round_: int):
        from celestia_tpu.node.bft import BlockPayload

        mem_txs = self.mempool.reap()
        try:
            proposal = self.app.prepare_proposal([t.raw for t in mem_txs])
        except Exception:
            return None
        # keep the proposer's own (EDS, DAH, layout): its validate leg
        # hits the content-addressed EDS cache instead of re-extending,
        # and _bft_decide reuses the artifacts for proof serving (the
        # same wiring the coordinator path has via cons_prepare)
        self._pending_proposal = proposal
        last_commit = ()
        prev = self._bft.decided.get(height - 1)
        if prev is not None:
            last_commit = tuple(
                sorted(prev.precommits, key=lambda v: v.validator)
            )
        try:
            prev_app_hash = self.app.store.committed_hash(height - 1)
        except KeyError:
            prev_app_hash = b""
        return BlockPayload(
            height=height,
            time_ns=self._now_ns + self.block_interval_ns,
            square_size=proposal.square_size,
            data_root=proposal.data_root,
            txs=tuple(proposal.block_txs),
            proposer=self._validator_key.public_key().address(),
            last_commit=last_commit,
            prev_app_hash=prev_app_hash,
        )

    def _bft_decide(self, decided) -> None:
        from celestia_tpu.node.bft import last_commit_vote_pairs

        payload = decided.payload
        self._bft_block_ids[payload.height] = payload.block_id
        for h in [h for h in self._bft_block_ids if h < payload.height - 16]:
            del self._bft_block_ids[h]
        # bounded decided log for laggard catch-up past the engine's
        # prune window (the payload wire carries the full tx list, so
        # the window trades memory for how far behind a peer may fall
        # before needing a snapshot)
        log_max = getattr(self, "bft_decided_log_max", 512)
        if log_max != self._bft_decided_log.max_entries:
            self._bft_decided_log.set_max_entries(log_max)
        self._bft_decided_log.put(payload.height, {
            "payload": payload.to_wire(),
            "precommits": [v.to_wire() for v in decided.precommits],
        })
        # identical LastCommitInfo everywhere: derived from the payload's
        # certificate over the SORTED valset, never from local votes
        vote_pairs = last_commit_vote_pairs(self._bft.validators, payload)
        self._now_ns = payload.time_ns
        artifacts = self._take_pending_artifacts(payload.data_root)
        self._apply_block(
            payload.height, payload.time_ns, list(payload.txs),
            payload.data_root, payload.square_size,
            proposer=payload.proposer, votes=vote_pairs,
            artifacts=artifacts,
        )

    def bft_start(self, height: int) -> None:
        with self._service_lock:
            if self._bft is None:
                raise RuntimeError("BFT mode not enabled")
            if height != self.height + 1:
                return  # stale/duplicate start
            self._bft.start_height(height)

    def bft_msg(self, wire: dict) -> None:
        with self._service_lock:
            if self._bft is not None:
                self._bft.receive(wire)

    def bft_timeout(self, step: str, height: int, round_: int) -> None:
        with self._service_lock:
            if self._bft is None:
                return
            if step == "propose":
                self._bft.on_timeout_propose(height, round_)
            elif step == "prevote":
                self._bft.on_timeout_prevote(height, round_)
            elif step == "precommit":
                self._bft.on_timeout_precommit(height, round_)

    def bft_decided(self, height: int) -> Optional[dict]:
        """Serve a decided block + its precommit certificate for laggard
        catch-up.  The certificate is what makes the replay trustless:
        the receiver verifies the 2/3 signatures, not the sender.
        Backed by the engine's recent window first, then the node's
        bounded decided log (the engine prunes aggressively; a laggard
        more than a few heights behind still needs the certificates —
        beyond the log window, snapshot state-sync takes over)."""
        with self._service_lock:
            if self._bft is None:
                return None
            d = self._bft.decided.get(height)
            if d is not None:
                return {
                    "payload": d.payload.to_wire(),
                    "precommits": [v.to_wire() for v in d.precommits],
                }
            # touch=False: puts arrive in height order, so an untouched
            # LRU evicts lowest-height first — a contiguous sliding
            # window.  A laggard (or monitor) re-reading ancient heights
            # must not refresh them into the retained set and fragment
            # the "how far behind may a peer fall" window.
            return self._bft_decided_log.get(height, touch=False)

    def bft_catchup(self, decided_wire: dict) -> Tuple[bool, str]:
        """Adopt an externally-replayed decided block after verifying
        its commit certificate (engine.adopt_decision)."""
        from celestia_tpu.node.bft import BlockPayload, Vote

        with self._service_lock:
            if self._bft is None:
                return False, "BFT mode not enabled"
            payload = BlockPayload.from_wire(decided_wire["payload"])
            if payload.height != self.height + 1:
                # height-guard BEFORE precommit parsing: a stale
                # (already-adopted) wire with junk precommits stays a
                # benign duplicate instead of raising mid-catch-up
                return payload.height <= self.height, "not the next height"
            precommits = [
                Vote.from_wire(v) for v in decided_wire["precommits"]
            ]
            return self._adopt_parsed(payload, precommits)

    def _adopt_parsed(self, payload, precommits) -> Tuple[bool, str]:
        """Shared adoption tail (caller holds the service lock and has
        already deserialized the wire)."""
        if payload.height != self.height + 1:
            return payload.height <= self.height, "not the next height"
        return self._bft.adopt_decision(payload, precommits)

    def bft_catchup_batch(self, decided_wires: List[dict]) -> Tuple[int, str]:
        """Adopt a WINDOW of externally-replayed decided blocks: the
        state-independent extends of every same-k square in the window
        run as ONE batched mesh dispatch (App.validate_blocks_batched
        warm-only leg, parallel/sharded.extend_and_roots_sharded_batch),
        then each block adopts sequentially through the unchanged
        certificate-verified path — adopt_decision's per-block
        validation (ante, signatures, strict reconstruction, root
        compare) runs against the then-current state and simply hits the
        warm EDS cache on its extend leg.  Trust is untouched: nothing
        is adopted that bft_catchup would not have adopted one at a
        time.  Returns (blocks adopted, reason for the first failed
        adoption verdict or "").  A MALFORMED wire re-raises its parse
        error AFTER the intact prefix has been adopted — the same
        penalty path per-block replay took (the gossip caller's outer
        except drops the serving peer and records a breaker failure;
        swallowing it would leave a peer persistently serving junk
        breaker-healthy and re-pulled forever)."""
        from celestia_tpu.node.bft import BlockPayload, Vote
        from celestia_tpu.utils import faults

        if self._bft is None:
            return 0, "BFT mode not enabled"
        # parse + warm OUTSIDE the service lock: parsing is pure and
        # the warm leg only touches thread-safe surfaces (EDS cache,
        # mesh provider, telemetry) — a cold batched shard_map compile
        # here must not stall every RPC the node serves behind the lock
        parsed = []
        parse_exc = None
        for w in decided_wires:
            try:
                parsed.append(
                    (
                        BlockPayload.from_wire(w["payload"]),
                        [Vote.from_wire(v) for v in w["precommits"]],
                    )
                )
            except Exception as e:
                faults.note("gossip.catchup_batch", e)
                parse_exc = e
                break  # adopt the intact prefix, then re-raise
        # warm keys are stamped with the CURRENT app_version, so a
        # window straddling the predictable v1->v2 upgrade height
        # warms only the pre-upgrade prefix — post-upgrade blocks
        # would validate under v2 keys and miss every warmed entry
        # (signal-based v2+ upgrades can't be foreseen; those
        # blocks just degrade to the per-block extend path)
        warmable = parsed
        if (
            self.app.app_version == 1
            and self.app.v2_upgrade_height is not None
        ):
            warmable = [
                (p, pc)
                for p, pc in parsed
                if p.height < self.app.v2_upgrade_height
            ]
        if len(warmable) > 1:
            try:
                self.app.validate_blocks_batched(
                    [
                        (list(p.txs), p.square_size, p.data_root)
                        for p, _pc in warmable
                    ],
                    warm_only=True,
                )
            except Exception as e:
                # warming is an optimization: a failure degrades to
                # the per-block extends, never blocks adoption
                faults.note("gossip.catchup_batch", e)
        # lock PER BLOCK, as the replaced per-block loop did: a window
        # of full validations (signatures + strict reconstruction +
        # commit, hundreds of ms each) must not stall every RPC behind
        # one continuous hold — _adopt_parsed's height check makes
        # interleaved adoptions (another catch-up source, live commits)
        # benign duplicates, not corruption
        adopted = 0
        for payload, precommits in parsed:
            with self._service_lock:
                if self._bft is None:
                    return adopted, "BFT mode not enabled"
                ok, why = self._adopt_parsed(payload, precommits)
            if not ok:
                return adopted, why
            adopted += 1
        if parse_exc is not None:
            # the prefix's progress is already committed to state;
            # the junk wire still penalizes the peer that served it
            raise parse_exc
        return adopted, ""

    def verify_state_sync_anchor(
        self, meta: dict, decided_wire: dict
    ) -> Tuple[bool, str]:
        """Pre-swap trust check for network state-sync: the decided block
        at meta.height+1 must carry a valid 2/3 commit certificate (over
        this node's valset) AND its prev_app_hash must equal the
        snapshot's app hash — only then is the snapshot state certified
        by the validator set, not merely self-consistent."""
        from celestia_tpu.node.bft import (
            BlockPayload,
            Vote,
            verify_commit_certificate,
        )

        with self._service_lock:
            if self._bft is None:
                return False, "BFT mode not enabled"
            if meta.get("chain_id") != self.chain_id:
                return False, "snapshot is for a different chain"
            if int(meta["height"]) <= self.height:
                return False, "snapshot is not ahead of this node"
            payload = BlockPayload.from_wire(decided_wire["payload"])
            if payload.height != int(meta["height"]) + 1:
                return False, "anchor block is not snapshot height + 1"
            if payload.prev_app_hash != bytes.fromhex(meta["app_hash"]):
                return False, (
                    "anchor certificate does not commit to the snapshot's "
                    "app hash"
                )
            precommits = [
                Vote.from_wire(v) for v in decided_wire["precommits"]
            ]
            return verify_commit_certificate(
                self._bft.chain_id, self._bft.validators,
                self._bft.pubkeys, self._bft.total_power, payload,
                precommits,
            )

    def adopt_state_sync(self, meta: dict, data: dict) -> None:
        """Swap in a snapshot state fetched from the network (AFTER
        verify_state_sync_anchor passed).  The app is rebuilt from the
        chunk payload (restore_from_snapshot re-verifies that the state
        reproduces the recorded app hash), block bookkeeping resets to
        the snapshot height, and the BFT engine is re-armed on the same
        valset so the next catch-up/consensus step starts at height+1."""
        from celestia_tpu.state.app import App

        with self._service_lock:
            if int(meta["height"]) <= self.height:
                # re-checked under the lock: a concurrent catch-up may
                # have advanced us; never regress to an older snapshot
                raise ValueError("snapshot is not ahead of this node")
            app = App.restore_from_snapshot(
                chain_id=meta["chain_id"],
                state=data["state"],
                height=int(meta["height"]),
                expected_app_hash=bytes.fromhex(meta["app_hash"]),
                genesis_time_ns=data.get("genesis_time_ns", 0),
            )
            self.app = app
            self.blocks = []  # height now reads app.store.last_height
            if self._state_log is not None:
                # future recoveries replay from this base, not genesis
                self._state_log.append_checkpoint(
                    app.store.last_height,
                    app.store.committed_hash(app.store.last_height),
                    app.store.raw_state(),
                )
                app.store.set_persister(self._persist_commit)
            if self._bft is not None:
                self.enable_bft(self._bft_valset)

    def bft_drain(self) -> dict:
        """Hand the transport everything outbound: gossip messages and
        due-timeout requests.  The transport forwards messages verbatim
        and echoes timeouts back via bft_timeout — it makes no consensus
        decisions (the 'dumb relay' contract)."""
        with self._service_lock:
            if self._bft is None:
                return {"outbox": [], "timeouts": [], "height": self.height}
            out = list(self._bft.outbox)
            self._bft.outbox.clear()
            timeouts = list(self._bft.timeout_requests)
            self._bft.timeout_requests.clear()
            return {
                "outbox": out,
                "timeouts": [
                    {"step": s, "height": h, "round": r}
                    for s, h, r in timeouts
                ],
                "height": self.height,
            }

    # ------------------------------------------------------------------
    # persistence
    # ------------------------------------------------------------------

    def _index_tx(self, tx_hash: bytes, res, height: int) -> None:
        """Record a delivered tx in the hash index and the event index.
        res.events must already be JSON-safe (normalized in _apply_block
        and by block-log recovery)."""
        self._tx_index[tx_hash] = {
            "code": res.code,
            "log": res.log,
            "height": height,
            "events": res.events,
        }
        # one index entry per tx per key, even when several events of the
        # same type (multi-msg txs) produce the same key
        keys = set()
        for ev in res.events:
            etype = ev.get("type") if isinstance(ev, dict) else None
            if not etype:
                continue
            keys.add(etype)
            for k, v in ev.items():
                if k != "type" and isinstance(v, (str, int, bool)):
                    keys.add(f"{etype}.{k}={v}")
        for key in keys:
            self._event_index.setdefault(key, []).append(tx_hash)

    def _persist_commit(self, height, app_hash, roots, forward) -> None:
        self._state_log.append_commit(
            height,
            app_hash,
            roots,
            forward,
            full_state_fn=self.app.store.raw_state,
        )

    def close(self) -> None:
        """Release the disk logs (restart tests reopen the data dir)."""
        if self._state_log is not None:
            self._state_log.close()
            self._state_log = None
        if self._block_log is not None:
            self._block_log.close()
            self._block_log = None
        self.app.store.set_persister(None)

    # ------------------------------------------------------------------
    # client surface (what pkg/user's gRPC connection provides)
    # ------------------------------------------------------------------

    @property
    def height(self) -> int:
        if self.blocks:
            return self.blocks[-1].header.height
        # restored-from-snapshot nodes resume at the snapshot height
        return max(1, self.app.store.last_height)

    def account_info(self, address: bytes) -> Tuple[int, int]:
        with self._service_lock:
            acc = self.app.accounts.peek(address)
            return acc.account_number, acc.sequence

    def broadcast_tx(self, raw: bytes) -> SubmitResult:
        """BroadcastMode_SYNC parity: CheckTx, then admit to the mempool."""
        with self._service_lock:
            return self._broadcast_tx_locked(raw)

    def _broadcast_tx_locked(self, raw: bytes) -> SubmitResult:
        res = self.app.check_tx(raw)
        return self._admit_checked_locked(raw, res)

    def _admit_checked_locked(self, raw: bytes, res) -> SubmitResult:
        tx_hash = hashlib.sha256(raw).digest()
        if res.code != 0:
            return SubmitResult(res.code, res.log, tx_hash)
        btx = unmarshal_blob_tx(raw)
        tx = unmarshal_tx(btx.tx if btx is not None else raw)
        self.mempool.add(raw, tx.fee.gas_price(), self.height)
        return SubmitResult(0, "", tx_hash)

    def broadcast_txs_batch(self, raws: List[bytes]) -> List[SubmitResult]:
        """Batched BroadcastMode_SYNC: one service-lock hold, one
        ``check_txs_batch`` pass (single verify_batch over all fresh
        single-key signatures), then mempool admission per admitted tx.
        Results are positionally identical to looping broadcast_tx."""
        with self._service_lock:
            results = self.app.check_txs_batch(list(raws))
            out: List[SubmitResult] = []
            for raw, res in zip(raws, results):
                try:
                    out.append(self._admit_checked_locked(raw, res))
                except ValueError as e:
                    # mempool admission error (e.g. oversize): isolate it
                    # per tx — the rest of the drained queue still lands
                    out.append(
                        SubmitResult(1, str(e), hashlib.sha256(raw).digest())
                    )
            return out

    def get_tx(self, tx_hash: bytes) -> Optional[dict]:
        with self._service_lock:
            return self._get_tx_locked(tx_hash)

    def _get_tx_locked(self, tx_hash: bytes) -> Optional[dict]:
        info = self._tx_index.get(tx_hash)
        if info is None and self.auto_produce and len(self.mempool):
            # emulate chain progress for poll-confirm clients: a pending
            # mempool makes the (virtual) proposer cut the next block
            self.produce_block()
            info = self._tx_index.get(tx_hash)
        return info

    def simulate(self, raw: bytes) -> int:
        """Gas estimation via simulated ante + 20% margin (signer.go
        EstimateGas shape)."""
        with self._service_lock:
            return self._simulate_locked(raw)

    def _simulate_locked(self, raw: bytes) -> int:
        tx = unmarshal_tx(raw)
        branch = self.app.store.branch()
        ctx = AnteContext(
            tx=tx,
            raw_tx=raw,
            accounts=AccountKeeper(branch.store("auth")),
            bank=BankKeeper(branch.store("bank")),
            params=ParamsKeeper(branch.store("params")),
            chain_id=self.chain_id,
            app_version=self.app.app_version,
            simulate=True,
        )
        try:
            meter = run_ante(ctx)
            base = meter.consumed
        except AnteError:
            base = 100_000
        return int(base * 1.2) + 100_000

    # ------------------------------------------------------------------
    # consensus loop
    # ------------------------------------------------------------------

    def produce_block(self) -> Block:
        """One consensus round: reap -> Prepare -> Process -> finalize."""
        with self._service_lock:
            return self._produce_block_locked()

    def _produce_block_locked(self) -> Block:
        height = self.height + 1
        self._now_ns += self.block_interval_ns
        time_ns = self._now_ns
        mem_txs = self.mempool.reap()
        proposal = self.app.prepare_proposal([t.raw for t in mem_txs])
        accepted, reason = self.app.process_proposal(
            proposal.block_txs, proposal.square_size, proposal.data_root
        )
        if not accepted:
            raise RuntimeError(
                f"node's own proposal rejected at height {height}: {reason}"
            )
        val_addr = self._validator_key.public_key().address()
        return self._apply_block(
            height, time_ns, proposal.block_txs, proposal.data_root,
            proposal.square_size, artifacts=proposal,
            proposer=val_addr, votes=[(val_addr, True)],
        )

    def _take_pending_artifacts(self, data_root: bytes):
        """Consume the proposer's own PreparedProposal if it matches the
        block being committed: when WE proposed this block, commit with
        the prepared (EDS/DAH/layout) so proof queries serve from the
        cache without a reconstruct+re-extend.  The data-root match
        guards staleness (a restarted round that re-prepared different
        txs); the pending slot is cleared either way."""
        pending = getattr(self, "_pending_proposal", None)
        self._pending_proposal = None
        if pending is not None and pending.data_root == data_root:
            return pending
        return None

    def _apply_block(
        self,
        height: int,
        time_ns: int,
        block_txs: List[bytes],
        data_root: bytes,
        square_size: int,
        artifacts: Optional[object] = None,
        proposer: bytes = b"",
        votes: Optional[List[Tuple[bytes, bool]]] = None,
    ) -> Block:
        """Shared commit tail: finalize + header/block append, EDS cache,
        tx index, mempool maintenance, snapshotting.  Used by both the
        self-producing path and the coordinator's cons_commit.  proposer +
        votes are the previous commit's info (ABCI LastCommitInfo role):
        they feed x/distribution and x/slashing, so every replica of one
        block must receive identical values."""
        results, _end, app_hash = self.app.finalize_block(
            block_txs, height, time_ns, data_root,
            proposer=proposer or None, votes=votes,
        )
        header = BlockHeader(
            height=height,
            time_ns=time_ns,
            chain_id=self.chain_id,
            app_version=self.app.app_version,
            data_hash=data_root,
            app_hash=app_hash,
            square_size=square_size,
        )
        block = Block(header, list(block_txs), results, proposer, votes)
        self.blocks.append(block)
        if self._block_log is not None:
            self._block_log.append_block(block)
        # retain the proposal's EDS + layout for proof queries (bounded);
        # non-proposers reconstruct on demand via _block_artifacts
        if artifacts is not None:
            self._eds_cache[height] = {
                "eds": artifacts.eds,
                "dah": artifacts.dah,
                "square": artifacts.square,
                "wrappers": artifacts.wrappers,
            }
            for h in [
                h for h in self._eds_cache
                if h <= height - self.eds_cache_blocks
            ]:
                del self._eds_cache[h]
        # normalize events to JSON-safe form ONCE; the tx index, the
        # event index, the block log and the gRPC surface all share it
        from celestia_tpu.state.app import jsonable_events

        for res in results:
            res.events[:] = jsonable_events(res.events)
        # index included txs + drop them from the mempool
        for raw, res in zip(block_txs, results):
            h = hashlib.sha256(raw).digest()
            self._index_tx(h, res, height)
            self.mempool.remove(h)
        # comet recheck parity: the block just moved state under every
        # still-pooled tx — re-run CheckTx (recheck mode, fresh check
        # state branched off the new commit) and evict what no longer
        # passes, instead of letting stale txs linger until TTL
        if len(self.mempool):
            self.mempool.recheck(
                lambda raw: self.app.check_tx(raw, is_recheck=True).code == 0
            )
        # txs the proposer dropped stay pooled until their TTL expires
        self.mempool.evict_expired(height)
        if (
            self.snapshots is not None
            and self.snapshot_interval > 0
            and height % self.snapshot_interval == 0
        ):
            self.snapshots.create(self.app)
            self.snapshots.prune(self.snapshot_keep_recent)
        return block

    # ------------------------------------------------------------------
    # consensus surface for an EXTERNAL coordinator (multi-process
    # replication): a coordinator drives N such nodes over gRPC through
    # prepare/process/commit, this node never self-produces
    # ------------------------------------------------------------------

    def cons_prepare(self) -> dict:
        """Proposer half of a round: reap own mempool, PrepareProposal.
        Returns native bytes; the gRPC handler does the wire encoding."""
        with self._service_lock:
            mem_txs = self.mempool.reap()
            proposal = self.app.prepare_proposal([t.raw for t in mem_txs])
            self._pending_proposal = proposal  # reuse EDS on self-commit
            return {
                "block_txs": list(proposal.block_txs),
                "square_size": proposal.square_size,
                "data_root": proposal.data_root,
            }

    def cons_process(
        self, block_txs: List[bytes], square_size: int, data_root: bytes
    ) -> Tuple[bool, str]:
        """Validator half: vote on a foreign proposal."""
        with self._service_lock:
            return self.app.process_proposal(block_txs, square_size, data_root)

    def cons_commit(
        self,
        block_txs: List[bytes],
        height: int,
        time_ns: int,
        data_root: bytes,
        square_size: int,
        proposer: bytes = b"",
        votes: Optional[List[Tuple[bytes, bool]]] = None,
    ) -> bytes:
        """Finalize a quorum-committed block; returns the app hash."""
        with self._service_lock:
            if height != self.height + 1:
                raise ValueError(
                    f"commit height {height} != expected {self.height + 1}"
                )
            artifacts = self._take_pending_artifacts(data_root)
            block = self._apply_block(
                height, time_ns, block_txs, data_root, square_size,
                artifacts=artifacts, proposer=proposer, votes=votes,
            )
            return block.header.app_hash

    @classmethod
    def from_snapshot(
        cls,
        snapshot_dir: str,
        block_interval_ns: int = GOAL_BLOCK_TIME_SECONDS * 10**9,
        auto_produce: bool = True,
        snapshot_interval: int = 0,
        snapshot_keep_recent: int = 2,
        validator_key: Optional[PrivateKey] = None,
        data_dir: Optional[str] = None,
        **app_kwargs,
    ) -> "TestNode":
        """Boot a node from the latest state-sync snapshot (the restart
        path of the reference's snapshot subsystem).  Snapshotting keeps
        running at the given interval after restore.  With ``data_dir``
        the restored node also logs every block to disk from here on
        (seeded with a base checkpoint at the snapshot height)."""
        from celestia_tpu.node.snapshots import SnapshotStore

        store = SnapshotStore(snapshot_dir)
        info = store.latest()
        if info is None:
            raise FileNotFoundError(f"no snapshots in {snapshot_dir}")
        app = store.restore_app(info, **app_kwargs)
        return cls(
            app=app,
            block_interval_ns=block_interval_ns,
            auto_produce=auto_produce,
            snapshot_dir=snapshot_dir,
            snapshot_interval=snapshot_interval,
            snapshot_keep_recent=snapshot_keep_recent,
            validator_key=validator_key,
            data_dir=data_dir,
        )

    def produce_blocks(self, n: int) -> List[Block]:
        return [self.produce_block() for _ in range(n)]

    def wait_for_height(self, h: int) -> None:
        while self.height < h:
            self.produce_block()

    # ------------------------------------------------------------------
    # queries (node_interaction_api.go helpers)
    # ------------------------------------------------------------------

    def block(self, height: int) -> Block:
        for b in self.blocks:
            if b.header.height == height:
                return b
        raise KeyError(f"no block at height {height}")

    def _block_artifacts(self, height: int) -> dict:
        """EDS/DAH/layout for a block: cache hit, or reconstruct from txs
        (older blocks fall out of the bounded cache but stay provable)."""
        art = self._eds_cache.get(height)
        if art is not None:
            return art
        from celestia_tpu.da import dah as dah_mod
        from celestia_tpu.da.square import construct as construct_square

        blk = self.block(height)
        # the bound in effect when the block was built is its own recorded
        # square size — the CURRENT gov bound may have changed since
        square, _txs, wrappers = construct_square(
            blk.txs, blk.header.square_size
        )
        eds, dah = dah_mod.extend_block(square)
        if dah.hash != blk.header.data_hash:
            raise RuntimeError(
                f"reconstructed data root mismatch at height {height}"
            )
        art = {"eds": eds, "dah": dah, "square": square, "wrappers": wrappers}
        self._eds_cache[height] = art
        return art

    def abci_query(self, path: str, data: dict):
        """ABCI-style query routes (JSON-safe result values).

        Parity targets: the proof query routes registered at
        app/app.go:622-623 (pkg/proof/querier.go:28,72), plus the
        bank/auth/params gRPC queries the reference serves via module
        queriers (app/app.go:826-852).
        """
        from celestia_tpu.da import proof as proof_mod
        from celestia_tpu.da.blob import unmarshal_blob_tx as _ubt

        if path == "store/bank/balance":
            addr = bytes.fromhex(data["address"])
            if data.get("height"):
                # height-pinned read against the committed version window
                from celestia_tpu.state.bank import BankKeeper

                raw = self.app.store.get_at(
                    "bank", BankKeeper.balance_key(addr), int(data["height"])
                )
                return int.from_bytes(raw, "big") if raw else 0
            return self.app.bank.balance(addr)
        if path == "store/proof":
            # generic merkleized-state query: any (store, key) at a pinned
            # height, with the membership proof a client verifies against
            # that block's app hash (state.merkle.verify_query_proof) —
            # the reference's `--prove` ABCI query over IAVL
            height = int(data["height"]) if data.get("height") else None
            return self.app.store.prove(
                data["store"], bytes.fromhex(data["key"]), height
            )
        if path == "custom/auth/account":
            acc = self.app.accounts.peek(bytes.fromhex(data["address"]))
            return {
                "account_number": acc.account_number,
                "sequence": acc.sequence,
                "pubkey": acc.pubkey.hex() if acc.pubkey else "",
            }
        if path == "custom/params/param":
            return self.app.params.get(data["subspace"], data["key"])
        if path == "custom/staking/validators":
            return [
                {"operator": v.operator.hex(), "power": v.power}
                for v in self.app.staking.bonded_validators()
            ]
        if path == "custom/upgrade/status":
            tally = self.app.upgrade.tally_voting_power(self.app.app_version + 1)
            return {
                "app_version": self.app.app_version,
                "next_version_power": tally[0],
                "total_power": tally[1],
            }
        if path == "custom/blobstream/attestation":
            att = self.app.blobstream.attestation(int(data["nonce"]))
            return {"found": att is not None, "attestation": att}
        if path == "custom/blobstream/latest_nonce":
            return {"nonce": self.app.blobstream.latest_nonce()}
        if path == "custom/blobstream/data_commitment_range":
            att = self.app.blobstream.data_commitment_for_height(
                int(data["height"])
            )
            return {"found": att is not None, "data_commitment": att}
        if path == "custom/blobstream/data_root_inclusion":
            return self.app.blobstream.data_root_inclusion_proof(
                int(data["height"]), int(data["begin"]), int(data["end"])
            )
        if path == "custom/distribution/rewards":
            delegator = bytes.fromhex(data["delegator"])
            validator = bytes.fromhex(data["validator"])
            return {
                "pending": self.app.distribution.pending_rewards(
                    delegator, validator
                )
            }
        if path == "custom/distribution/commission":
            return {
                "commission": self.app.distribution.commission(
                    bytes.fromhex(data["validator"])
                )
            }
        if path == "custom/distribution/community-pool":
            return {"pool": self.app.distribution.community_pool()}
        if path == "custom/slashing/signing-info":
            operator = bytes.fromhex(data["validator"])
            info = self.app.slashing.signing_info(operator)
            v = self.app.staking.validator(operator)
            return {
                "missed_blocks": info.missed_blocks if info else 0,
                "index_offset": info.index_offset if info else 0,
                "jailed": bool(v and v.jailed),
                "tombstoned": bool(v and v.tombstoned),
                "jailed_until_ns": v.jailed_until_ns if v else 0,
            }
        if path == "custom/feegrant/allowance":
            a = self.app.feegrant.get(
                bytes.fromhex(data["granter"]), bytes.fromhex(data["grantee"])
            )
            if a is None:
                return {"found": False}
            return {
                "found": True, "kind": a.kind, "spend_limit": a.spend_limit,
                "expiration_ns": a.expiration_ns,
                "period_can_spend": a.period_can_spend,
            }
        if path == "custom/authz/grant":
            g = self.app.authz.get(
                bytes.fromhex(data["granter"]), bytes.fromhex(data["grantee"]),
                int(data["msg_type"]),
            )
            if g is None:
                return {"found": False}
            return {
                "found": True, "msg_type": g.msg_type,
                "spend_limit": g.spend_limit, "expiration_ns": g.expiration_ns,
            }
        if path == "custom/crisis/invariants":
            from celestia_tpu.state.invariants import assert_invariants

            return assert_invariants(self.app)
        if path == "custom/tx/search":
            # query-by-event: "transfer", "transfer.recipient=<hex>", ...
            hashes = self._event_index.get(data["event"], [])
            return [
                {"hash": h.hex(), **self._tx_index[h]} for h in hashes
            ]
        if path == "custom/namespace/shares":
            # GetSharesByNamespace: all shares of one namespace + proofs,
            # with the DAH so a light client can verify completeness
            # against its trusted data root
            from celestia_tpu.da import namespace_data as nsd

            height = int(data["height"])
            art = self._block_artifacts(height)
            result = nsd.get_shares_by_namespace(
                art["eds"], art["dah"], bytes.fromhex(data["namespace"])
            )
            return {
                "data": result.to_dict(),
                "dah": {
                    "row_roots": [r.hex() for r in art["dah"].row_roots],
                    "col_roots": [c.hex() for c in art["dah"].col_roots],
                },
                "data_root": self.data_root(height).hex(),
            }
        if path == "custom/das/sample":
            # DAS serving surface: one EDS cell + proof to the data root
            from celestia_tpu.da import das as das_mod

            height = int(data["height"])
            art = self._block_artifacts(height)
            proof = das_mod.sample_proof(
                art["eds"], art["dah"], int(data["row"]), int(data["col"])
            )
            return {
                "proof": proof.to_dict(),
                "data_root": self.data_root(height).hex(),
            }
        if path == "custom/das/sample_batch":
            # vectorized serving surface: n cells in one row-grouped
            # pass (shared row stacks + one root tree; da/das.py).
            # Chunking/shedding live at the RPC layer (node/server.py
            # DasSampleBatch) — this query proves whatever it is handed.
            from celestia_tpu.da import das as das_mod

            height = int(data["height"])
            art = self._block_artifacts(height)
            proofs = das_mod.sample_proofs_batch(
                art["eds"], art["dah"],
                [(int(r), int(c)) for r, c in data["coords"]],
            )
            return {
                "proofs": [p.to_dict() for p in proofs],
                "data_root": self.data_root(height).hex(),
            }
        if path == "custom/proof/share":
            height = int(data["height"])
            art = self._block_artifacts(height)
            proof = proof_mod.new_share_inclusion_proof(
                art["eds"], art["dah"], int(data["start"]), int(data["end"])
            )
            return {
                "proof": proof.to_dict(),
                "data_root": self.data_root(height).hex(),
            }
        if path == "custom/proof/tx":
            height = int(data["height"])
            art = self._block_artifacts(height)
            blk = self.block(height)
            normal = [t for t in blk.txs if _ubt(t) is None]
            wrapped = [w.marshal() for w in art["wrappers"]]
            proof = proof_mod.new_tx_inclusion_proof(
                art["square"], art["eds"], art["dah"], normal, wrapped,
                int(data["tx_index"]),
            )
            return {
                "proof": proof.to_dict(),
                "data_root": self.data_root(height).hex(),
            }
        raise ValueError(f"unknown query path: {path}")

    def data_root(self, height: int) -> bytes:
        return self.block(height).header.data_hash

    def fill_block(self, square_size: int, signer) -> SubmitResult:
        """Post a blob sized to produce a square of ``square_size``
        (node_interaction_api.go FillBlock)."""
        from celestia_tpu.da.blob import Blob
        from celestia_tpu.da.namespace import Namespace

        n_shares = square_size * square_size // 2
        size = (n_shares - 1) * CONTINUATION_SPARSE_SHARE_CONTENT_SIZE
        blob = Blob(Namespace.v0(b"fill"), b"\xaa" * max(size, 1))
        return signer.submit_pay_for_blob([blob])
