"""P2P gossip mesh for the two-phase BFT validator tier (VERDICT r3 #4).

Flood-with-dedup of consensus messages plus content-addressed want/have
transaction admission between validator processes, driven by node-local
wall-clock timers — no central relay in the critical path.  Each
validator process runs one :class:`GossipEngine`:

- **Consensus flood with BOUNDED fanout.**  The engine drains its own
  BFT engine's outbox and floods every message to at most ``fanout``
  randomly-sampled peers (default min(N-1, 8)); a received message is
  delivered to the local engine once (dedup by locally-computed content
  hash — never by a sender-supplied id, which a malicious relayer could
  use to poison the dedup set and censor real messages) and re-flooded
  onward, so coverage comes from multi-hop epidemic spread rather than
  O(N²) direct links.  Round timeouts + the status-poll catch-up are
  the liveness backstop for the (rare) sampling gaps.
- **Peer exchange (PEX).**  ``--peers`` needs only one seed: engines
  periodically swap peer lists with a random peer (the comet
  p2p/addrbook role, /root/reference/cmd/celestia-appd/cmd/root.go:141),
  merging new addresses up to ``max_peers``.  Killing the seed after
  bootstrap does not affect the mesh.
- **Per-peer sender threads.**  Every peer gets its own outbound queue
  and worker; a hung or black-holed peer blocks only its own link,
  never the pump loop or the round timers.
- **Own timers.**  Tendermint's liveness comes from timeouts; the engine
  schedules each requested (step, height, round) timeout on its own wall
  clock with the standard round-escalating duration, so a dead peer or
  a dead relay never freezes the round clock (timers fire FIRST in the
  pump, before any RPC work).
- **Want/have tx gossip** (specs/cat_pool.md "Gossip"): a pooled tx is
  ANNOUNCED by hash; peers reply with the subset they lack; only those
  raw bytes are pushed, and the receiver re-announces onward.  A pushed
  tx that fails CheckTx is NOT marked seen — admission can succeed later
  (e.g. a sequence gap fills), and the periodic full-pool re-announce
  heals any such gap.
- **Certificate-verified catch-up.**  A validator that sees traffic for
  heights ahead of its own pulls the decided blocks from peers and
  adopts them ONLY after verifying the 2/3 precommit certificate
  (``bft_catchup`` -> ``engine.adopt_decision``) — peers are untrusted.

The ``bft-relay`` CLI demotes to bootstrap/observer: kill it mid-run and
the mesh keeps committing (tests/test_gossip_mesh.py).

Reference role: celestia-core's p2p reactors — consensus gossip + the
CAT mempool protocol (SURVEY §2.2 consensus engine row;
/root/reference's specs/src/specs/cat_pool.md).
"""

from __future__ import annotations

import hashlib
import json
import random as _random
import threading
import time as _time
from collections import deque
from typing import Dict, List, Optional, Tuple

from celestia_tpu.utils import faults, tracing
from celestia_tpu.utils.logging import Logger
from celestia_tpu.utils.lru import LruCache, bytes_len_weigher

_log = Logger(level="warn")


def wire_id(wire: dict) -> bytes:
    """Content address of a consensus wire message (dedup key)."""
    return hashlib.sha256(
        json.dumps(wire, sort_keys=True).encode()
    ).digest()


class _SeenSet:
    """Bounded membership set (flood dedup) on the unified LRU.

    ``add`` is the atomic check-then-insert the flood path needs
    (LruCache.add_if_absent); a re-announce of a seen id refreshes its
    recency, so actively flooded messages outlive one-shot noise."""

    def __init__(self, maxlen: int = 65536, name: str = "gossip_seen"):
        self._lru = LruCache(name, maxlen, weigher=bytes_len_weigher)

    def add(self, key: bytes) -> bool:
        """True if newly added, False if already present."""
        return self._lru.add_if_absent(key)

    def __contains__(self, key: bytes) -> bool:
        return key in self._lru


class _PeerLink:
    """One peer's outbound lane: a bounded queue + worker thread.  All
    RPCs to this peer happen here, so a hung peer stalls only itself."""

    def __init__(self, engine: "GossipEngine", addr: str, maxlen: int = 4096):
        self.engine = engine
        self.addr = addr
        self._q: deque = deque(maxlen=maxlen)  # drop-oldest on overflow
        self.dropped = 0  # messages shed by backpressure (observable)
        self._qlock = threading.Lock()
        self._event = threading.Event()
        self._stop = threading.Event()
        self._client = None
        self._thread = threading.Thread(
            target=self._run, name=f"gossip-peer-{addr}", daemon=True
        )
        self._thread.start()

    def send(self, kind: str, data) -> None:
        # a full deque sheds its oldest item on append; count it so
        # silent consensus-message loss on a congested link shows up in
        # logs/telemetry instead of only as mysterious round timeouts.
        # Producers (pump + gRPC threads) and the consumer both take
        # _qlock, so the len check is exact, not check-then-act.
        with self._qlock:
            if len(self._q) == self._q.maxlen:
                self.dropped += 1
                dropped = self.dropped
            else:
                dropped = 0
            self._q.append((kind, data))
        if dropped and (dropped == 1 or dropped % 256 == 0):
            self.engine.log.warn(
                "gossip peer backpressure: dropping oldest",
                peer=self.addr, dropped=dropped,
            )
        self._event.set()

    def stop(self) -> None:
        self._stop.set()
        self._event.set()
        self._thread.join(timeout=5)

    def _ensure_client(self):
        if self._client is None:
            from celestia_tpu.node.remote import RemoteNode

            try:
                self._client = RemoteNode(
                    self.addr, timeout_s=self.engine.client_timeout_s
                )
            except Exception:
                self._client = None
        return self._client

    def _drop_client(self) -> None:
        if self._client is not None:
            try:
                self._client.close()
            except Exception as e:
                faults.note("gossip.link", e)
            self._client = None

    def _run(self) -> None:
        while not self._stop.is_set():
            if not self._q:
                self._event.wait(timeout=0.2)
                self._event.clear()
                continue
            with self._qlock:
                try:
                    kind, data = self._q.popleft()
                except IndexError:
                    continue
            cli = self._ensure_client()
            if cli is None:
                # peer down; the item is dropped (flood re-sends) and the
                # failure counts toward PEX-learned-address eviction
                self.engine._peer_failed(self.addr)
                continue
            try:
                if kind == "msg":
                    cli.gossip_msg(data)
                elif kind == "announce":
                    hashes, by_hash = data
                    want = cli.tx_have(hashes)
                    if want:
                        cli.tx_push(
                            [by_hash[h] for h in want if h in by_hash]
                        )
                elif kind == "pex":
                    learned = cli.peer_exchange(
                        self.engine._self_name(), data
                    )
                    self.engine._merge_peers([self.addr] + list(learned))
                self.engine._peer_ok(self.addr)
            except Exception:
                self._drop_client()
                self.engine._peer_failed(self.addr)


class GossipEngine:
    """One validator process's p2p overlay: floods consensus messages,
    runs the round timers, gossips txs want/have, and self-paces block
    production.  Attach to a BFT-enabled TestNode; the NodeServer routes
    the Gossip*/Tx* RPCs here via ``node.gossip_engine``."""

    def __init__(
        self,
        node,
        peer_addrs: List[str],
        *,
        tick_s: float = 0.02,
        base_timeout_s: float = 0.4,
        timeout_delta_s: float = 0.2,
        block_gap_s: float = 0.0,
        client_timeout_s: float = 5.0,
        reannounce_s: float = 2.0,
        fanout: int = 8,
        max_peers: int = 64,
        pex_interval_s: float = 1.0,
        chunk_retry_deadline_s: float = 10.0,
        catchup_batch: Optional[int] = None,
        logger=None,
    ):
        self.node = node
        self.log = logger if logger is not None else _log
        self.peer_addrs = list(dict.fromkeys(peer_addrs))
        # operator-configured addresses are never evicted; PEX-learned
        # ones are dropped after repeated delivery failures so a poisoned
        # address book drains instead of eclipsing honest peers forever
        self._static_peers = set(self.peer_addrs)
        self._peer_failures: Dict[str, int] = {}
        self._evict_after = 5
        self.fanout = max(1, fanout)
        self.max_peers = max_peers
        self.pex_interval_s = pex_interval_s
        self._last_pex = 0.0
        self._pex_rr = 0  # round-robin cursor over peers for PEX
        self._catch_up_thread: Optional[threading.Thread] = None
        # per-peer circuit breakers over the catch-up/state-sync pulls
        # (the unified policy layer, utils/faults.py): one failure opens
        # the peer for 10 s (the PR 4 cooldown semantics), resource-bound
        # violations trip it for 60 s via trip()
        self._breakers = faults.BreakerRegistry(
            failures_to_open=1, cooldown_s=10.0
        )
        self.chunk_retry_deadline_s = chunk_retry_deadline_s
        # decided blocks adopted per batched catch-up step: the window's
        # same-k extends run as ONE mesh dispatch (BASELINE config #5 —
        # node.bft_catchup_batch); 1 restores the per-block behavior.
        # Default sized to the warmable EDS-cache budget (max_entries
        # minus the reserved min-DAH slot) so a full window's warm never
        # truncates — a window one larger would pay a per-block extend
        # for its last block on EVERY step and fire the truncation
        # telemetry continuously during normal catch-up
        if catchup_batch is None:
            from celestia_tpu.da import eds_cache

            catchup_batch = min(8, eds_cache.CACHE.max_entries - 1)
        self.catchup_batch = max(1, catchup_batch)
        # drops from links that no longer exist (evicted peers) — keeps
        # dropped_total monotonic for monitoring deltas
        self._dropped_closed = 0
        self.tick_s = tick_s
        self.base_timeout_s = base_timeout_s
        self.timeout_delta_s = timeout_delta_s
        self.block_gap_s = block_gap_s
        self.client_timeout_s = client_timeout_s
        self.reannounce_s = reannounce_s
        self._links: Dict[str, _PeerLink] = {}
        self._pull_clients: Dict[str, object] = {}
        self._seen = _SeenSet(name="gossip_seen")
        self._seen_tx = _SeenSet(name="gossip_seen_tx")
        self._announced = _SeenSet(name="gossip_announced")
        # timers: (due, step, height, round); key-dedup in _timer_keys
        self._timers: List[Tuple[float, str, int, int]] = []
        self._timer_keys: set = set()
        self._behind_hint = 0  # highest height seen in foreign traffic
        self._last_start = 0.0
        self._last_reannounce = 0.0
        self._last_status_poll = 0.0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        node.gossip_engine = self

    # -- peer links ------------------------------------------------------

    def _link(self, addr: str) -> _PeerLink:
        # pump + gRPC server threads both create links; the lock keeps a
        # racing double-create from orphaning a worker thread
        with self._lock:
            link = self._links.get(addr)
            if link is None:
                link = _PeerLink(self, addr)
                self._links[addr] = link
            return link

    def _peers_snapshot(self, exclude: Optional[str] = None) -> List[str]:
        with self._lock:
            return [a for a in self.peer_addrs if a != exclude]

    # at most this many NEW addresses are admitted per PEX exchange, so
    # one malicious reply cannot fill the whole book in a single swap
    _PEX_BATCH_LIMIT = 8

    @staticmethod
    def _normalize_addr(addr: str) -> Optional[str]:
        """Canonical dialable form, or None for junk: rejects wildcard
        binds (0.0.0.0 / ::) that would make a peer dial itself, and
        folds the localhost alias so the self-filter can't be bypassed
        by spelling."""
        if not isinstance(addr, str) or len(addr) > 128 or ":" not in addr:
            return None
        host, _, port = addr.rpartition(":")
        if not port.isdigit():
            return None
        if host in ("0.0.0.0", "::", "[::]", ""):
            return None
        if host == "localhost":
            host = "127.0.0.1"
        return f"{host}:{port}"

    def _merge_peers(self, addrs) -> None:
        """Admit newly-learned peer addresses (PEX): normalized, bounded
        per exchange (_PEX_BATCH_LIMIT) and in total (max_peers); dead
        entries are evicted by _peer_failed, so garbage costs bounded
        slots for a bounded time, not permanent book space."""
        me = self._self_name()
        admitted = 0
        with self._lock:
            known = set(self.peer_addrs)
            for addr in addrs:
                addr = self._normalize_addr(addr)
                if addr is None or addr == me or addr in known:
                    continue
                if (
                    len(self.peer_addrs) >= self.max_peers
                    or admitted >= self._PEX_BATCH_LIMIT
                ):
                    break
                self.peer_addrs.append(addr)
                known.add(addr)
                admitted += 1

    def _peer_ok(self, addr: str) -> None:
        with self._lock:
            self._peer_failures.pop(addr, None)

    def _peer_failed(self, addr: str) -> None:
        """Called by a peer's link worker after a failed delivery.  A
        PEX-learned address that keeps failing is evicted (its link
        worker winds down on its own); operator-configured seeds are
        kept — the flood keeps retrying them."""
        with self._lock:
            n = self._peer_failures.get(addr, 0) + 1
            self._peer_failures[addr] = n
            if addr in self._static_peers or n < self._evict_after:
                return
            if addr in self.peer_addrs:
                self.peer_addrs.remove(addr)
            self._peer_failures.pop(addr, None)
            link = self._links.pop(addr, None)
            if link is not None:
                self._dropped_closed += link.dropped
        if link is not None:
            self._breakers.drop(addr)
            link._stop.set()  # worker exits on its own; never join here
            link._event.set()
            # drop the cached catch-up client too: an evicted address
            # must not keep an open channel/fd behind (its cost really
            # is "bounded slots for a bounded time")
            self._drop_pull_client(addr)
            self.log.warn("evicted unresponsive PEX-learned peer", peer=addr)

    def _flood(self, wire: dict, exclude: Optional[str] = None) -> None:
        payload = {"wire": wire, "sender": self._self_name()}
        if tracing.enabled():
            # optional envelope trace context (NEVER inside `wire`: the
            # dedup id is a content hash of the wire, and a per-hop
            # context stamped into it would defeat flood dedup).  Old
            # peers read wire/sender only and drop this silently.
            tc = tracing.wire_context(
                height=int(wire.get("height", 0) or 0)
            )
            if tc:
                payload["_tc"] = tc
        peers = self._peers_snapshot(exclude)
        if len(peers) > self.fanout:
            # epidemic spread: each hop re-floods to its own sample, so
            # a random subset per message covers the mesh w.h.p. while
            # links stay O(N * fanout) instead of O(N^2)
            peers = _random.sample(peers, self.fanout)
        for addr in peers:
            self._link(addr).send("msg", payload)

    # -- inbound RPC surface (called from server threads) ---------------

    def _wire_ok(self, wire: dict) -> bool:
        """Structural + signature validation BEFORE propagation: the
        sender is untrusted, so only messages signed by a known validator
        are delivered, re-flooded, or allowed into the dedup set — junk
        must neither amplify across the mesh nor evict legitimate dedup
        entries."""
        from celestia_tpu.node.bft import (
            Proposal,
            msg_from_wire,
            proposal_sign_bytes,
            vote_sign_bytes,
        )
        from celestia_tpu.utils.secp256k1 import PublicKey

        eng = self.node._bft
        if eng is None:
            return False
        try:
            msg = msg_from_wire(wire)
            if isinstance(msg, Proposal):
                pk = eng.pubkeys.get(msg.proposer)
                if pk is None:
                    return False
                digest = proposal_sign_bytes(
                    eng.chain_id, msg.height, msg.round, msg.pol_round,
                    msg.payload.block_id,
                )
                return PublicKey.from_compressed(pk).verify(
                    digest, msg.signature
                )
            pk = eng.pubkeys.get(msg.validator)
            if pk is None:
                return False
            digest = vote_sign_bytes(
                eng.chain_id, msg.height, msg.round, msg.vtype, msg.block_id
            )
            return PublicKey.from_compressed(pk).verify(digest, msg.signature)
        except Exception:
            return False

    def on_gossip(self, wire: dict, sender: str, tc=None) -> bool:
        """Deliver a flooded consensus message once; queue the re-flood.
        The dedup id is computed HERE from the wire bytes — a sender-
        supplied id could poison the dedup set (censorship) — and only
        validator-signed messages propagate.  Returns True if the
        message was new and valid.  ``tc`` is the optional envelope
        trace context of the SENDING hop (specs/observability.md): it
        only decorates the deliver span, never consensus handling."""
        msg_id = wire_id(wire)
        if msg_id in self._seen:
            return False
        if not self._wire_ok(wire):
            return False  # unsigned junk: not delivered, not flooded
        if not self._seen.add(msg_id):
            return False
        with self._lock:
            h = int(wire.get("height", 0) or 0)
            if h > self._behind_hint:
                # a hint, not a fact: _catch_up verifies against peers'
                # actual heights (a Byzantine validator can sign a vote
                # at any height it likes)
                self._behind_hint = h
        # span args are built only when the tracer is on: this is the
        # per-message flood hot path, and a NULL_SPAN must cost nothing
        span = (
            tracing.rpc_span(
                "gossip.deliver", tc, cat="gossip",
                kind=str(wire.get("kind", "")), height=h,
            )
            if tracing.enabled()
            else tracing.NULL_SPAN
        )
        with span:
            try:
                self.node.bft_msg(wire)
            except Exception as e:
                # engine rejects bad messages; a raise must not kill the RPC
                # thread — but the failure lands in telemetry, never silently
                faults.note("gossip.deliver", e)
            self._flood(wire, exclude=sender)
        return True

    def stats(self) -> dict:
        """Operational snapshot for the status RPC: address-book size,
        PEX-learned vs operator-configured split, and total messages
        shed by per-peer backpressure (the observable form of the
        drop-oldest queues)."""
        with self._lock:
            peers = len(self.peer_addrs)
            static = len(self._static_peers & set(self.peer_addrs))
            links = list(self._links.values())
            dropped_closed = self._dropped_closed
        return {
            "peers": peers,
            "static_peers": static,
            "pex_learned": peers - static,
            "fanout": self.fanout,
            # monotonic: includes links already closed by eviction
            "dropped_total": dropped_closed
            + sum(link.dropped for link in links),
            # per-peer circuit-breaker states over the pull plane
            "pull_breakers": self._breakers.stats(),
        }

    def on_peer_exchange(self, sender: str, peers: List[str]) -> List[str]:
        """PEX inbound: learn the sender + its peers, return our list so
        the exchange is symmetric.  Called from gRPC server threads."""
        self._merge_peers([sender] + list(peers))
        return self._peers_snapshot()

    def on_tx_have(self, hashes: List[bytes]) -> List[bytes]:
        """want/have: return the subset of announced tx hashes this node
        does not hold."""
        want = []
        pool = self.node.mempool
        # snapshot the key set under the node lock once: CheckTx
        # admissions and commit-time removals mutate the dict from other
        # gRPC threads (same discipline as _announce_txs)
        with self.node._service_lock:
            pooled = set(pool._txs)
        for h in hashes:
            if h in pooled or h in self._seen_tx:
                continue
            if self.node.get_tx(h) is not None:
                continue  # already committed
            want.append(h)
        return want

    def on_tx_push(self, raws: List[bytes]) -> int:
        """Admit pushed txs through the batched CheckTx plane; re-announce
        admitted ones.  The whole pending push drains through ONE
        ``broadcast_txs_batch`` call (single verify_batch pass over all
        fresh signatures) instead of looping per-tx CheckTx.  A failed
        admission is NOT marked seen: it may succeed later (sequence
        gaps), and the periodic re-announce retries it."""
        fresh: List[bytes] = []
        fresh_hashes: List[bytes] = []
        for raw in raws:
            h = hashlib.sha256(raw).digest()
            if h in self._seen_tx:
                continue
            fresh.append(raw)
            fresh_hashes.append(h)
        if not fresh:
            return 0
        admitted = 0
        try:
            results = self.node.broadcast_txs_batch(fresh)
        except Exception as e:
            # batch-layer failure (not a per-tx verdict): note it and
            # degrade to the per-tx loop so one poisoned raw cannot
            # starve its neighbors
            faults.note("gossip.txpush", e)
            results = []
            for raw in fresh:
                try:
                    results.append(self.node.broadcast_tx(raw))
                except Exception as e:  # noqa: PERF203 - per-tx isolation
                    faults.note("gossip.txpush", e)
                    results.append(None)
        for h, res in zip(fresh_hashes, results):
            if res is not None and res.code == 0:
                self._seen_tx.add(h)
                admitted += 1
        return admitted

    # -- the pump loop ---------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="gossip-pump", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        t = self._catch_up_thread
        if t is not None and t.is_alive():
            t.join(timeout=5)
        for link in self._links.values():
            link.stop()
        self._links.clear()
        for addr in list(self._pull_clients):
            self._drop_pull_client(addr)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._pump_once()
            except Exception as e:
                # the mesh must survive transient RPC storms — recorded,
                # never silently dropped (celint R5 contract)
                faults.note("gossip.pump", e)
            # fixed-cadence pump tick, not a retry loop
            # celint: allow(sanctioned-retry) — the pump's pacing sleep: timers/floods tick at tick_s by design
            _time.sleep(self.tick_s)

    def _pump_once(self) -> None:
        now = _time.time()
        # 1. fire due timers FIRST — liveness must not wait on any RPC
        with self._lock:
            due_now = [t for t in self._timers if t[0] <= now]
            self._timers = [t for t in self._timers if t[0] > now]
            for _, s, h, r in due_now:
                self._timer_keys.discard((s, h, r))
        for _, step, height, round_ in due_now:
            try:
                self.node.bft_timeout(step, height, round_)
            except Exception as e:
                faults.note("gossip.timer", e)
        # 2. start the next height when the current one is decided
        if self.node._bft is not None and (
            now - self._last_start >= self.block_gap_s
        ):
            target = self.node.height + 1
            if self.node._bft.height < target:
                try:
                    self.node.bft_start(target)
                    self._last_start = now
                except Exception as e:
                    faults.note("gossip.start", e)
        # 3. drain own outbox + timeout requests; enqueue floods
        d = self.node.bft_drain()
        for wire in d["outbox"]:
            self._seen.add(wire_id(wire))  # don't re-deliver our own
            self._flood(wire)
        with self._lock:
            for t in d["timeouts"]:
                key = (t["step"], t["height"], t["round"])
                if key not in self._timer_keys:
                    self._timer_keys.add(key)
                    due = now + self.base_timeout_s + (
                        self.timeout_delta_s * t["round"]
                    )
                    self._timers.append((due, *key))
        # 4. announce pooled txs (fresh every tick; full pool periodically)
        self._announce_txs(now)
        # 5. catch-up pull when traffic shows we're behind — on its OWN
        # thread: peer addresses can be PEX-learned (untrusted), and a
        # book full of black holes must never stall the pump loop whose
        # first job is firing the round timers
        self._maybe_catch_up()
        # 6. PEX: swap peer lists with one peer (round-robin) per
        # interval — the exchange runs on that peer's link worker, so a
        # dead peer can never stall the pump or the round timers
        if now - self._last_pex >= self.pex_interval_s:
            self._last_pex = now
            peers = self._peers_snapshot()
            if peers:
                target = peers[self._pex_rr % len(peers)]
                self._pex_rr += 1
                self._link(target).send("pex", peers)

    def _self_name(self) -> str:
        return getattr(self.node, "_server_address", "") or "peer"

    def _announce_txs(self, now: float) -> None:
        pool = self.node.mempool
        full = now - self._last_reannounce >= self.reannounce_s
        if full:
            self._last_reannounce = now
        # snapshot under the node lock: gRPC workers mutate the pool
        # concurrently (CheckTx admissions, commit-time removals)
        with self.node._service_lock:
            items = [(h, t.raw) for h, t in pool._txs.items()]
        batch = []
        for h, raw in items:
            if self._announced.add(h) or full:
                batch.append((h, raw))
        if not batch:
            return
        hashes = [h for h, _ in batch]
        by_hash = dict(batch)
        peers = self._peers_snapshot()
        if len(peers) > self.fanout:
            # receivers re-announce admitted txs and the periodic full
            # re-announce rotates samples, so fanout-bounded want/have
            # still reaches everyone
            peers = _random.sample(peers, self.fanout)
        for addr in peers:
            self._link(addr).send("announce", (hashes, by_hash))

    def _pull_client(self, addr: str):
        cli = self._pull_clients.get(addr)
        if cli is None:
            from celestia_tpu.node.remote import RemoteNode

            try:
                cli = RemoteNode(addr, timeout_s=self.client_timeout_s)
            except Exception:
                return None
            self._pull_clients[addr] = cli
        return cli

    def _drop_pull_client(self, addr: str) -> None:
        cli = self._pull_clients.pop(addr, None)
        if cli is not None:
            try:
                cli.close()
            except Exception as e:
                faults.note("gossip.link", e)

    def _maybe_catch_up(self) -> None:
        """Spawn at most one background catch-up worker when behind.
        The pump thread never blocks on a peer RPC."""
        now = _time.time()
        with self._lock:
            behind = self._behind_hint
        if self.node.height + 1 >= behind:
            return
        if now - self._last_status_poll < 0.5:
            return
        t = self._catch_up_thread
        if t is not None and t.is_alive():
            return
        self._last_status_poll = now
        t = threading.Thread(
            target=self._catch_up, name="gossip-catchup", daemon=True
        )
        self._catch_up_thread = t
        t.start()

    def _pull_rpc(self, fn, *args):
        """Every catch-up/state-sync pull RPC funnels through here: the
        ``gossip.fetch`` fault point lives at the top, so the chaos suite
        can make any pull flaky without touching peer code — and the
        ``gossip.fetch`` span makes every pull visible on the trace."""
        with tracing.span(
            "gossip.fetch", cat="gossip",
            rpc=getattr(fn, "__name__", "rpc"),
        ):
            faults.fire("gossip.fetch")
            return fn(*args)

    def _catch_up(self) -> None:
        """Pull decided blocks we're missing (background worker, direct
        blocking RPCs).  Unreachable peers open their circuit breaker so
        a poisoned address book costs each poll a bounded set of dial
        attempts (utils/faults.BreakerRegistry — the unified policy
        layer; resource-bound violators are tripped for 60 s).

        The wire-derived hint only TRIGGERS the check; the pull target
        is the peers' actually-reported best height (rate-limited status
        poll), so a Byzantine validator signing sky-high vote heights
        cannot lock the mesh into a permanent catch-up loop — a hint no
        reachable peer corroborates is discarded."""
        best = 0
        peers = [
            a for a in self._peers_snapshot() if self._breakers.allow(a)
        ]
        for addr in peers:
            cli = self._pull_client(addr)
            if cli is None:
                self._breakers.record_failure(addr)
                continue
            try:
                best = max(
                    best, int(self._pull_rpc(cli.status).get("height", 0))
                )
                self._breakers.record_ok(addr)
            except Exception as e:
                faults.note("gossip.fetch", e)
                self._drop_pull_client(addr)
                self._breakers.record_failure(addr)
        if best <= self.node.height:
            with self._lock:
                # nobody is actually ahead: the hint was noise
                self._behind_hint = self.node.height
            return
        target = best
        for addr in peers:
            if self.node.height >= target:
                return
            if not self._breakers.available(addr):
                continue  # opened by the status poll above
            cli = self._pull_client(addr)
            if cli is None:
                self._breakers.record_failure(addr)
                continue
            try:
                while self.node.height < target:
                    # pull a WINDOW of decided blocks, then adopt them in
                    # one batched step: the window's same-k extends run
                    # as one mesh dispatch instead of one per block
                    # (testnode.bft_catchup_batch; the RPCs stay one per
                    # block — the device dispatch is what batches)
                    wires = []
                    lo = self.node.height + 1
                    hi = min(target, lo + self.catchup_batch - 1)
                    for h in range(lo, hi + 1):
                        try:
                            d = self._pull_rpc(cli.bft_decided, h)
                        except Exception:
                            if not wires:
                                raise  # same failure path as per-block
                            # a mid-window RPC failure must not discard
                            # the wires already pulled: adopt the
                            # partial window; the next window (or the
                            # empty-window raise above) retries h
                            break
                        if d is None:
                            break
                        wires.append(d)
                    if not wires:
                        # the peer has pruned past our height: a node
                        # offline longer than the decided-log window
                        # state-syncs from a served snapshot, then
                        # resumes certificate replay from there
                        if not self._try_state_sync(cli, addr):
                            break
                        continue
                    adopted, _why = self.node.bft_catchup_batch(wires)
                    if adopted < len(wires):
                        break
            except Exception as e:
                faults.note("gossip.fetch", e)
                self._drop_pull_client(addr)
                self._breakers.record_failure(addr)

    def _alt_snapshot_clients(self, exclude: str, limit: int = 2) -> list:
        """Up to ``limit`` other reachable peers' pull clients — the
        re-fetch sources for a chunk the primary served corrupt."""
        out = []
        for addr in self._peers_snapshot(exclude=exclude or None):
            if len(out) >= limit:
                break
            if not self._breakers.available(addr):
                continue
            cli = self._pull_client(addr)
            if cli is not None:
                out.append(cli)
        return out

    def _fetch_snapshot_chunks(self, cli, meta: dict, alt_clis=()) -> list:
        """Download one snapshot's chunks with per-chunk resource bounds
        (ADVICE r5): every chunk is size-capped BEFORE its hash check —
        the writer never produces a chunk above MAX_WIRE_CHUNK_BYTES, so
        an oversized payload is hostile and raises SnapshotLimitError
        immediately (never retried).

        A TRANSFER-corrupt or missing chunk, by contrast, is transient:
        the chunk is marked bad and re-fetched — from a DIFFERENT peer
        first when alternates exist — under the unified RetryPolicy, and
        the download aborts only once a chunk exhausts its deadline
        budget (``chunk_retry_deadline_s``)."""
        from celestia_tpu.node.snapshots import (
            MAX_WIRE_CHUNK_BYTES,
            SnapshotLimitError,
        )

        n_chunks = int(meta["chunks"])
        sources = [cli, *alt_clis]
        chunks = []
        for i in range(n_chunks):
            turn = [0]

            def fetch_once(i=i, turn=turn):
                # rotate sources: attempt 0 is the primary, each retry
                # moves to the next peer (wrapping), so a peer serving
                # bit-flipped bytes cannot fail the restore on its own
                src = sources[turn[0] % len(sources)]
                turn[0] += 1
                with tracing.span(
                    "snapshot.chunk_fetch", cat="gossip",
                    chunk=i, attempt=turn[0],
                    height=int(meta["height"]),
                ):
                    faults.fire("snapshots.chunk")
                    c = src.snapshot_chunk(
                        int(meta["height"]), int(meta.get("format", 1)), i
                    )
                    if c is None:
                        raise ValueError(f"peer missing chunk {i}")
                    if len(c) > MAX_WIRE_CHUNK_BYTES:
                        raise SnapshotLimitError(
                            f"chunk {i} is {len(c)} bytes "
                            f"(cap {MAX_WIRE_CHUNK_BYTES})"
                        )
                    c = faults.corrupt("snapshots.chunk", c)
                    if hashlib.sha256(c).hexdigest() != meta["chunk_hashes"][i]:
                        raise ValueError(f"chunk {i} corrupt in transfer")
                    return c

            policy = faults.RetryPolicy(
                attempts=max(2, 2 * len(sources)),
                base_s=0.02,
                cap_s=0.5,
                deadline_s=self.chunk_retry_deadline_s,
            )
            chunks.append(
                policy.run(
                    fetch_once,
                    no_retry_on=(SnapshotLimitError,),
                    on_retry=lambda n, e, i=i: self.log.warn(
                        "snapshot chunk re-fetch", chunk=i, attempt=n,
                        err=str(e)[:120],
                    ),
                )
            )
        return chunks

    def _try_state_sync(self, cli, addr: str = "") -> bool:
        """Network state-sync (VERDICT r4 #4; the reference serves
        snapshots to syncing peers, root.go:227-243 +
        default_overrides.go:296-297).  Trust order matters: the
        anchoring certificate (decided block at snapshot height + 1,
        2/3-signed, committing to the snapshot's app hash via
        prev_app_hash) is verified BEFORE any chunk is applied — a
        malicious snapshot can never swap state in."""
        from celestia_tpu.node.snapshots import (
            SnapshotLimitError,
            SnapshotStore,
        )

        try:
            metas = self._pull_rpc(cli.snapshot_list)
        except Exception as e:
            faults.note("gossip.fetch", e)
            return False
        metas = [
            m for m in metas if int(m.get("height", 0)) > self.node.height
        ]
        # the metas LIST is peer-supplied and unbounded: only try the few
        # newest (honest servers keep ~2 recent snapshots), so one peer
        # cannot chain hundreds of 512 MiB download attempts
        metas = sorted(metas, key=lambda m: -int(m.get("height", 0)))[:3]
        for meta in metas:
            downloaded = False
            try:
                anchor = cli.bft_decided(int(meta["height"]) + 1)
                if anchor is None:
                    continue
                ok, why = self.node.verify_state_sync_anchor(meta, anchor)
                if not ok:
                    self.log.warn(
                        "state-sync snapshot rejected", reason=why,
                        height=meta.get("height"),
                    )
                    continue
                n_chunks = int(meta["chunks"])
                # the chunk COUNT is peer-supplied and not covered by the
                # anchor certificate: bound it so one peer cannot force
                # unbounded download/memory per sync attempt (with the
                # per-chunk byte bound in _fetch_snapshot_chunks this
                # caps a sync attempt at 512 MiB on the wire, far above
                # any real app state)
                if n_chunks > 512 or len(meta.get("chunk_hashes", [])) != (
                    n_chunks
                ):
                    raise ValueError(
                        f"implausible snapshot shape: {n_chunks} chunks"
                    )
                chunks = self._fetch_snapshot_chunks(
                    cli, meta, self._alt_snapshot_clients(addr)
                )
                downloaded = True
                data = SnapshotStore.assemble(meta, chunks)
                self.node.adopt_state_sync(meta, data)
                self.node.bft_catchup(anchor)  # apply the anchor block
                self.log.warn(
                    "state-synced from peer snapshot",
                    height=meta["height"],
                )
                return True
            except SnapshotLimitError as e:
                # resource-bound violation: no honest peer serves this —
                # abort the whole sync attempt and cool the peer down
                # much longer than a transient failure
                self.log.warn(
                    "state-sync peer exceeded resource bounds; backing off",
                    err=str(e)[:200], peer=addr,
                )
                if addr:
                    self._breakers.trip(addr, 60.0)
                return False
            except Exception as e:
                self.log.warn("state-sync attempt failed", err=str(e)[:200])
                if downloaded:
                    # the peer served a COMPLETE, hash-consistent snapshot
                    # that still failed to apply (bad app hash / state):
                    # hostile or corrupt — don't burn another full
                    # download on its next meta this attempt
                    if addr:
                        self._breakers.trip(addr, 60.0)
                    return False
                continue
        return False
