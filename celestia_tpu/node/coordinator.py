"""Multi-process consensus coordinator: replication across node PROCESSES.

The wire-level upgrade of node/network.py's in-process replication
(ADR 005): N ``celestia-tpu start`` processes expose the consensus surface
(ConsPrepare / ConsProcess / ConsCommit) over gRPC; this coordinator
sequences the Tendermint-shaped round across them —

  1. the height's proposer (round-robin, rotating on rejection) prepares a
     proposal from ITS OWN mempool;
  2. every other validator votes by re-validating on its own state;
  3. on >= 2/3 of voting power accepting, every validator commits and the
     returned app hashes MUST agree (``ConsensusFailure`` otherwise).

Tx gossip is emulated by broadcasting client txs to every validator
(gossip_tx).  The coordinator holds no state of its own beyond the block
log — all chain state lives in the validator processes, which is what makes
this a real replication test: the processes share nothing but their
genesis file and these RPCs.

Reference analogue: celestia-core's consensus driving N nodes over p2p
(test/e2e/testnet.go:62-96 shape); SURVEY §2.3 state-machine replication.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from celestia_tpu.node.network import ConsensusFailure, RoundResult, Vote


@dataclass
class PeerValidator:
    name: str
    client: object  # RemoteNode (or any object with the cons_* surface)
    power: int = 100
    height: int = 0  # last height this peer committed (coordinator view)
    address: bytes = b""  # staking operator address (from node status)


class ProcessCoordinator:
    """Drives consensus rounds across remote validator processes."""

    def __init__(self, peers: Sequence[PeerValidator], block_interval_ns: int = 10**9):
        if not peers:
            raise ValueError("need at least one validator peer")
        self.peers = list(peers)
        self.block_interval_ns = block_interval_ns
        status = self.peers[0].client.status()
        self.height = int(status["height"])
        # block timestamps continue the CHAIN's clock, not the wall clock:
        # a wall-clock jump would make time-based inflation mint the gap in
        # one block and break parity with the other consensus drivers
        self._now_ns = int(status.get("time_ns") or 0)
        if self._now_ns == 0:
            self._now_ns = int(status.get("genesis_time_ns") or _time.time_ns())
        for peer in self.peers:
            peer_status = peer.client.status()
            peer.height = int(peer_status["height"])
            if not peer.address:
                peer.address = bytes.fromhex(
                    peer_status.get("validator_address", "") or ""
                )
        self.rounds: List[RoundResult] = []
        self.blocks: List[dict] = []

    @property
    def total_power(self) -> int:
        return sum(p.power for p in self.peers)

    def gossip_tx(self, raw: bytes):
        """Broadcast a tx to every validator's mempool (gossip emulation).
        Returns the FIRST non-zero result if any validator rejects."""
        first_bad = None
        for peer in self.peers:
            res = peer.client.broadcast_tx(raw)
            if res.code != 0 and first_bad is None:
                first_bad = res
        return first_bad

    def produce_block(self, max_rounds: Optional[int] = None):
        height = self.height + 1
        if max_rounds is None:
            max_rounds = len(self.peers)
        last = None
        for round_ in range(max_rounds):
            last = self._run_round(height, round_)
            if last.committed:
                return last
        raise RuntimeError(
            f"no block committed at height {height} after {max_rounds} rounds: "
            f"{[(v.validator, v.accept, v.reason) for v in last.votes]}"
        )

    def catch_up(self, peer: PeerValidator) -> bool:
        """Replay blocks a peer missed through its consensus surface;
        True if the peer reaches the coordinator's height."""
        for blk in self.blocks:
            if blk["height"] <= peer.height:
                continue
            try:
                app_hash = peer.client.cons_commit(
                    blk["block_txs"], blk["height"], blk["time_ns"],
                    blk["data_root"], blk["square_size"],
                    proposer=blk.get("proposer_address", b""),
                    votes=blk.get("votes"),
                )
            except Exception:
                return False
            if app_hash != blk["app_hash"]:
                raise ConsensusFailure(
                    f"{peer.name} diverged during catch-up at height "
                    f"{blk['height']}"
                )
            peer.height = blk["height"]
        return peer.height == self.height

    def _run_round(self, height: int, round_: int) -> RoundResult:
        proposer = self.peers[(height + round_) % len(self.peers)]
        self._now_ns += self.block_interval_ns
        # stale peers (missed commits) must not vote on state they don't
        # have: try to catch them up first; still-stale peers sit out
        current = []
        for peer in self.peers:
            if peer.height == self.height or self.catch_up(peer):
                current.append(peer)
        if proposer not in current:
            result = RoundResult(
                height, proposer.name, False,
                [Vote(proposer.name, False, "proposer is stale/unreachable")],
            )
            self.rounds.append(result)
            return result
        try:
            proposal = proposer.client.cons_prepare()
        except Exception as e:  # crashed proposer forfeits its round
            result = RoundResult(
                height, proposer.name, False,
                [Vote(proposer.name, False, f"proposer crashed: {e}")],
            )
            self.rounds.append(result)
            return result
        votes: List[Vote] = []
        accept_power = 0
        for peer in self.peers:
            if peer not in current:
                votes.append(Vote(peer.name, False, "stale: sitting out"))
                continue
            if peer is proposer:
                ok, reason = True, "proposer"
            else:
                try:
                    ok, reason = peer.client.cons_process(
                        proposal["block_txs"],
                        proposal["square_size"],
                        proposal["data_root"],
                    )
                except Exception as e:  # unreachable validator = NO vote
                    ok, reason = False, f"vote failed: {e}"
            votes.append(Vote(peer.name, ok, reason))
            if ok:
                accept_power += peer.power
        committed = accept_power * 3 >= self.total_power * 2
        result = RoundResult(height, proposer.name, committed, votes)
        if committed:
            # the commit info every replica must apply identically (ABCI
            # LastCommitInfo role: distribution + slashing inputs)
            vote_pairs = [
                (peer.address, vote.accept)
                for peer, vote in zip(self.peers, votes)
                if peer.address
            ]
            app_hashes = {}
            missed = []
            for peer in self.peers:
                if peer not in current:
                    missed.append(peer.name)
                    continue
                try:
                    app_hashes[peer.name] = peer.client.cons_commit(
                        proposal["block_txs"], height, self._now_ns,
                        proposal["data_root"], proposal["square_size"],
                        proposer=proposer.address, votes=vote_pairs,
                    )
                    peer.height = height
                except Exception:
                    # an unreachable validator misses the commit and must
                    # catch up next round — the quorum's block stands
                    missed.append(peer.name)
            if not app_hashes:
                raise ConsensusFailure(
                    f"no validator could commit height {height}"
                )
            if len(set(app_hashes.values())) != 1:
                raise ConsensusFailure(
                    f"app hash divergence at height {height}: "
                    f"{{ {', '.join(f'{n}: {h.hex()[:12]}' for n, h in app_hashes.items())} }}"
                )
            self.height = height
            self.blocks.append(
                {
                    "height": height,
                    "time_ns": self._now_ns,
                    "block_txs": proposal["block_txs"],
                    "square_size": proposal["square_size"],
                    "data_root": proposal["data_root"],
                    "app_hash": next(iter(app_hashes.values())),
                    "proposer": proposer.name,
                    "proposer_address": proposer.address,
                    "votes": vote_pairs,
                    "n_txs": len(proposal["block_txs"]),
                    "missed": missed,
                }
            )
        self.rounds.append(result)
        return result

    def produce_blocks(self, n: int) -> List[dict]:
        out = []
        for _ in range(n):
            self.produce_block()
            out.append(self.blocks[-1])
        return out
