"""Multi-process consensus coordinator: replication across node PROCESSES.

The wire-level upgrade of node/network.py's in-process replication
(ADR 005): N ``celestia-tpu start`` processes expose the consensus surface
(ConsPrepare / ConsProcess / ConsCommit) over gRPC; this coordinator
sequences the Tendermint-shaped round across them —

  1. the height's proposer (round-robin, rotating on rejection) prepares a
     proposal from ITS OWN mempool;
  2. every other validator votes by re-validating on its own state;
  3. on >= 2/3 of voting power accepting, every validator commits and the
     returned app hashes MUST agree (``ConsensusFailure`` otherwise).

Tx gossip is emulated by broadcasting client txs to every validator
(gossip_tx).  The coordinator holds no state of its own beyond the block
log — all chain state lives in the validator processes, which is what makes
this a real replication test: the processes share nothing but their
genesis file and these RPCs.

Proposal-lifecycle caching (PR 5): the coordinator deliberately stays
dumb — the redundant-work elimination lives in the validator processes.
A proposer's ``cons_prepare`` populates its content-addressed EDS cache
(da/eds_cache.py) and pins the PreparedProposal for its own
``cons_commit`` (testnode._pending_proposal); a round restart where the
SAME proposer re-prepares an unchanged mempool is an EDS-cache hit, and
every validator's ``cons_process`` of a re-gossiped block it has already
validated skips the re-extend the same way.  The coordinator never
carries EDS bytes over the wire — only (txs, square_size, data_root).

Reference analogue: celestia-core's consensus driving N nodes over p2p
(test/e2e/testnet.go:62-96 shape); SURVEY §2.3 state-machine replication.
"""

from __future__ import annotations

import time as _time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import functools
import inspect

from celestia_tpu.node.network import ConsensusFailure, RoundResult, Vote
from celestia_tpu.utils import faults


@functools.lru_cache(maxsize=None)
def _type_accepts_tc(cls: type, method: str) -> bool:
    """Whether ``cls.<method>`` declares the optional trace-context
    kwarg (RemoteNode does; the in-process TestNode surface does not —
    hand it only to clients that declare it).  Cached by type: the
    answer is constant per client class, and inspect.signature is too
    reflective for the per-block consensus loop."""
    fn = getattr(cls, method, None)
    if fn is None:
        return False
    try:
        return "tc" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


def _accepts_tc(bound_method) -> bool:
    owner = getattr(bound_method, "__self__", None)
    if owner is None:
        return False
    return _type_accepts_tc(type(owner), bound_method.__name__)


@dataclass
class PeerValidator:
    name: str
    client: object  # RemoteNode (or any object with the cons_* surface)
    power: int = 100
    height: int = 0  # last height this peer committed (coordinator view)
    address: bytes = b""  # staking operator address (from node status)


class ProcessCoordinator:
    """Drives consensus rounds across remote validator processes."""

    def __init__(self, peers: Sequence[PeerValidator], block_interval_ns: int = 10**9):
        if not peers:
            raise ValueError("need at least one validator peer")
        self.peers = list(peers)
        self.block_interval_ns = block_interval_ns
        status = self.peers[0].client.status()
        self.height = int(status["height"])
        # block timestamps continue the CHAIN's clock, not the wall clock:
        # a wall-clock jump would make time-based inflation mint the gap in
        # one block and break parity with the other consensus drivers
        self._now_ns = int(status.get("time_ns") or 0)
        if self._now_ns == 0:
            self._now_ns = int(status.get("genesis_time_ns") or _time.time_ns())
        for peer in self.peers:
            peer_status = peer.client.status()
            peer.height = int(peer_status["height"])
            if not peer.address:
                peer.address = bytes.fromhex(
                    peer_status.get("validator_address", "") or ""
                )
        self.rounds: List[RoundResult] = []
        self.blocks: List[dict] = []

    @property
    def total_power(self) -> int:
        return sum(p.power for p in self.peers)

    def gossip_tx(self, raw: bytes):
        """Broadcast a tx to every validator's mempool (gossip emulation).
        Returns the FIRST non-zero result if any validator rejects."""
        first_bad = None
        for peer in self.peers:
            res = peer.client.broadcast_tx(raw)
            if res.code != 0 and first_bad is None:
                first_bad = res
        return first_bad

    def produce_block(self, max_rounds: Optional[int] = None):
        height = self.height + 1
        if max_rounds is None:
            max_rounds = len(self.peers)
        last = None
        for round_ in range(max_rounds):
            last = self._run_round(height, round_)
            if last.committed:
                return last
        raise RuntimeError(
            f"no block committed at height {height} after {max_rounds} rounds: "
            f"{[(v.validator, v.accept, v.reason) for v in last.votes]}"
        )

    def catch_up(self, peer: PeerValidator) -> bool:
        """Replay blocks a peer missed through its consensus surface;
        True if the peer reaches the coordinator's height."""
        for blk in self.blocks:
            if blk["height"] <= peer.height:
                continue
            try:
                app_hash = peer.client.cons_commit(
                    blk["block_txs"], blk["height"], blk["time_ns"],
                    blk["data_root"], blk["square_size"],
                    proposer=blk.get("proposer_address", b""),
                    votes=blk.get("votes"),
                )
            except Exception:
                return False
            if app_hash != blk["app_hash"]:
                raise ConsensusFailure(
                    f"{peer.name} diverged during catch-up at height "
                    f"{blk['height']}"
                )
            peer.height = blk["height"]
        return peer.height == self.height

    def _run_round(self, height: int, round_: int) -> RoundResult:
        proposer = self.peers[(height + round_) % len(self.peers)]
        self._now_ns += self.block_interval_ns
        # stale peers (missed commits) must not vote on state they don't
        # have: try to catch them up first; still-stale peers sit out
        current = []
        for peer in self.peers:
            if peer.height == self.height or self.catch_up(peer):
                current.append(peer)
        if proposer not in current:
            result = RoundResult(
                height, proposer.name, False,
                [Vote(proposer.name, False, "proposer is stale/unreachable")],
            )
            self.rounds.append(result)
            return result
        try:
            proposal = proposer.client.cons_prepare()
        except Exception as e:  # crashed proposer forfeits its round
            result = RoundResult(
                height, proposer.name, False,
                [Vote(proposer.name, False, f"proposer crashed: {e}")],
            )
            self.rounds.append(result)
            return result
        # the proposer's prepare-root trace context (when its tracer is
        # on): forwarded into every validator's process/commit RPC so
        # their spans name the PROPOSER as cross-node parent — the
        # coordinator is glue, not the causal origin.  Absent against
        # un-upgraded or untraced proposers; clients that don't declare
        # the kwarg (in-process TestNode surface) are never handed it.
        tc = proposal.get("_tc")

        def tc_kwargs(fn):
            return {"tc": tc} if tc and _accepts_tc(fn) else {}

        votes: List[Vote] = []
        accept_power = 0
        for peer in self.peers:
            if peer not in current:
                votes.append(Vote(peer.name, False, "stale: sitting out"))
                continue
            if peer is proposer:
                ok, reason = True, "proposer"
            else:
                try:
                    ok, reason = peer.client.cons_process(
                        proposal["block_txs"],
                        proposal["square_size"],
                        proposal["data_root"],
                        **tc_kwargs(peer.client.cons_process),
                    )
                except Exception as e:  # unreachable validator = NO vote
                    ok, reason = False, f"vote failed: {e}"
            votes.append(Vote(peer.name, ok, reason))
            if ok:
                accept_power += peer.power
        committed = accept_power * 3 >= self.total_power * 2
        result = RoundResult(height, proposer.name, committed, votes)
        if committed:
            # the commit info every replica must apply identically (ABCI
            # LastCommitInfo role: distribution + slashing inputs)
            vote_pairs = [
                (peer.address, vote.accept)
                for peer, vote in zip(self.peers, votes)
                if peer.address
            ]
            app_hashes = {}
            missed = []
            for peer in self.peers:
                if peer not in current:
                    missed.append(peer.name)
                    continue
                try:
                    app_hashes[peer.name] = peer.client.cons_commit(
                        proposal["block_txs"], height, self._now_ns,
                        proposal["data_root"], proposal["square_size"],
                        proposer=proposer.address, votes=vote_pairs,
                        **tc_kwargs(peer.client.cons_commit),
                    )
                    peer.height = height
                except Exception:
                    # an unreachable validator misses the commit and must
                    # catch up next round — the quorum's block stands
                    missed.append(peer.name)
            if not app_hashes:
                raise ConsensusFailure(
                    f"no validator could commit height {height}"
                )
            if len(set(app_hashes.values())) != 1:
                raise ConsensusFailure(
                    f"app hash divergence at height {height}: "
                    f"{{ {', '.join(f'{n}: {h.hex()[:12]}' for n, h in app_hashes.items())} }}"
                )
            self.height = height
            self.blocks.append(
                {
                    "height": height,
                    "time_ns": self._now_ns,
                    "block_txs": proposal["block_txs"],
                    "square_size": proposal["square_size"],
                    "data_root": proposal["data_root"],
                    "app_hash": next(iter(app_hashes.values())),
                    "proposer": proposer.name,
                    "proposer_address": proposer.address,
                    "votes": vote_pairs,
                    "n_txs": len(proposal["block_txs"]),
                    "missed": missed,
                }
            )
        self.rounds.append(result)
        return result

    def produce_blocks(self, n: int) -> List[dict]:
        out = []
        for _ in range(n):
            self.produce_block()
            out.append(self.blocks[-1])
        return out


class BFTRelay:
    """Dumb message transport for the two-phase BFT tier (VERDICT r2 #5).

    Unlike ProcessCoordinator above (which SEQUENCES consensus: it counts
    votes and orders commits), this relay only (a) announces the next
    height, (b) forwards each node's outbound gossip verbatim to every
    other node, and (c) echoes due-timeout requests back to the node
    that asked for them when the network is quiescent — the shared-clock
    role.  It never reads message contents, never counts votes, never
    tells a node to commit: every validator process decides from the
    2/3 precommit quorum its OWN engine verified (node/bft.py), and the
    relay merely observes the resulting heights converge.
    """

    def __init__(self, peers: Sequence[PeerValidator]):
        if not peers:
            raise ValueError("need at least one validator peer")
        self.peers = list(peers)
        self.heights: List[int] = []

    def _heights(self) -> List[int]:
        out = []
        for p in self.peers:
            try:
                out.append(int(p.client.status()["height"]))
            except Exception as e:
                faults.note("relay.status", e)
                continue  # unreachable peers just don't report
        return out

    def _catch_up_laggards(self, target: int) -> None:
        """Replay decided blocks to peers behind the pack.  The relay
        only MOVES the (payload, certificate) pairs; each laggard
        verifies the 2/3 signatures itself (bft_catchup) — trustless."""
        peer_heights = []
        for p in self.peers:
            try:
                peer_heights.append((p, int(p.client.status()["height"])))
            except Exception as e:
                faults.note("relay.status", e)
                continue
        if not peer_heights:
            return
        best = max(h for _, h in peer_heights)
        sources = [p for p, h in peer_heights if h == best]
        for peer, h in peer_heights:
            while h < best:
                replayed = False
                for src in sources:
                    try:
                        d = src.client.bft_decided(h + 1)
                    except Exception as e:
                        faults.note("relay.catchup", e)
                        continue
                    if d is None:
                        continue
                    try:
                        if peer.client.bft_catchup(d):
                            h += 1
                            replayed = True
                            break
                    except Exception:
                        break
                if not replayed:
                    break  # decision pruned everywhere or peer down

    def produce_block(self, max_steps: int = 300) -> int:
        """Drive one height to a decision on every reachable peer;
        returns the new height."""
        heights = self._heights()
        if not heights:
            # unified retry layer (utils/faults.py): jittered 0.5-2 s
            # polls under a 30 s budget replace the hand-rolled
            # sleep(1.0) loop this relay shipped with
            try:
                heights = faults.RetryPolicy(
                    base_s=0.5, cap_s=2.0, deadline_s=30.0
                ).poll(self._heights, what="any validator peer")
            except TimeoutError:
                raise RuntimeError(
                    "no validator peer reachable: "
                    + ", ".join(p.name for p in self.peers)
                )
        start = max(heights)
        if min(heights) < start:
            self._catch_up_laggards(start)
        target = start + 1
        for peer in self.peers:
            try:
                peer.client.bft_start(target)
            except Exception as e:
                faults.note("relay.start", e)  # unreachable: misses the round
        steps = 0
        pending_timeouts: List[tuple] = []  # (peer, {step,height,round})
        while True:
            moved = False
            drained = []
            for peer in self.peers:
                try:
                    drained.append((peer, peer.client.bft_drain()))
                except Exception as e:
                    faults.note("relay.drain", e)
                    continue
            for sender, d in drained:
                pending_timeouts.extend((sender, t) for t in d["timeouts"])
                for wire in d["outbox"]:
                    moved = True
                    for peer in self.peers:
                        if peer is sender:
                            continue
                        try:
                            peer.client.bft_msg(wire)
                        except Exception as e:
                            faults.note("relay.forward", e)
                            continue
            if drained and all(d["height"] >= target for _, d in drained):
                return target
            if not moved:
                # a quiescent network where SOME peer reached the target
                # means the height is decided; stragglers are replayed
                # the certificate at the next produce_block (catch-up)
                if any(d["height"] >= target for _, d in drained):
                    return target
                # quiescent: tick the clocks — echo every buffered due
                # timeout back to its own node (stale ones are no-ops,
                # the engine guards by height/round/step)
                if not pending_timeouts:
                    raise RuntimeError(
                        f"height {target} stalled with no due timeouts; "
                        f"peer heights {self._heights()}"
                    )
                for peer, t in pending_timeouts:
                    try:
                        peer.client.bft_timeout(
                            t["step"], t["height"], t["round"]
                        )
                    except Exception as e:
                        faults.note("relay.timeout", e)
                        continue
                pending_timeouts.clear()
            steps += 1
            if steps > max_steps:
                raise RuntimeError(
                    f"height {target} did not decide after {steps} steps; "
                    f"peer heights {self._heights()}"
                )

    def produce_blocks(self, n: int) -> List[int]:
        return [self.produce_block() for _ in range(n)]
