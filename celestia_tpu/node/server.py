"""gRPC node service: the network boundary between clients and a node.

Role parity: the reference node exposes gRPC/RPC services that pkg/user's
Signer talks to (app/app.go:826-852 wires the API/gRPC services;
pkg/user/signer.go:278-309 broadcasts over gRPC and polls GetTx).  Here the
same surface is served with grpc generic handlers (no codegen): every method
is bytes -> bytes, with JSON envelopes for control-plane calls and raw tx
bytes for broadcast.

Methods (service ``celestia.tpu.v1.Node``):
  Broadcast    raw BlobTx/Tx bytes        -> {code, log, txhash}
  BroadcastBatch {"txs": [hex, ...]}      -> {"results": [{code, log,
               txhash}, ...]}: batched admission — one check_txs_batch
               pass (single verify_batch over fresh signatures) under
               one service-lock hold
  GetTx        {"hash": hex}              -> tx status or {"found": false}
  AccountInfo  {"address": hex}           -> {account_number, sequence}
  Simulate     raw tx bytes               -> {gas} | {code, log}
  Status       {}                         -> chain/app status
  Block        {"height": N}              -> header + tx hashes
  Query        {"path": str, "data": {}}  -> ABCI-style query routes,
               including the proof routes (custom/proof/share,
               custom/proof/tx — pkg/proof/querier.go parity).
  Metrics      {}                         -> Prometheus text exposition
               (counters, gauges, bounded histograms, cache registry —
               comet's DefaultMetricsProvider role — plus per-RPC
               byte/call counters, client-side RPC counters,
               fault/degradation totals, device-plane gauges
               (celestia_tpu_xla_* / celestia_tpu_device_*), trace-ring
               health and alert states)
  TraceDump    {"last": N}                -> the last N block traces as
               Chrome trace-event JSON (utils/tracing.py; open the
               ``trace`` value directly in Perfetto)
  ClockProbe   {}                         -> {"ts", "node_id", "height"}:
               one telemetry-clock read for the cross-node midpoint
               offset probe (tracing.estimate_clock_offset)
  TimeSeries   {"last": N}                -> {"snapshots", "rates",
               "alerts", ...}: the bounded telemetry time-series ring
               (utils/timeseries.py) + the declarative alert engine's
               verdicts; every call records one fresh sample first, so
               two consecutive calls always yield a computable rate

  HostProfile  {"top": N, "folded": M}    -> the host sampling
               profiler's stats, top self-time frames and folded
               stacks (utils/hostprof.py)
  FlightList   {}                         -> kept incident-bundle
               manifests + recorder ring stats (utils/flight.py)
  FlightFetch  {"id": str}                -> one full incident bundle
               (manifest + every artifact as text; empty id = newest)

The same exposition is optionally served as PLAIN HTTP (``GET
/metrics`` on ``--metrics-port``; off by default) so a stock Prometheus
scrapes the node without speaking the custom gRPC framing, plus a
``GET /healthz`` JSON probe (node id, height, breakers open, alerts
firing, uptime) for load balancers and orchestrators.

Cross-node trace context: consensus, gossip, state-sync and DAS
requests may carry an optional ``"_tc"`` envelope field (specs/
observability.md "Distributed tracing").  Handlers read named keys, so
un-upgraded peers ignore the field and upgraded ones open an
``rpc.*`` span whose ``remote_node``/``remote_span`` args name the
caller's span — the explicit cross-node parent the trace merger folds
into flow events.  Every handler also counts ``rpc_{method}_calls`` and
``rpc_{method}_bytes_{in,out}`` into the node's telemetry.
"""

from __future__ import annotations

import hashlib
import json
import threading
from concurrent import futures
from typing import Dict, Optional

import grpc

from celestia_tpu.utils import faults, tracing

SERVICE = "celestia.tpu.v1.Node"


def _identity(b: bytes) -> bytes:
    return b


class _PeerRegistry:
    """Bounded per-peer DAS serving accounting (specs/da_serving.md
    "QoS lanes & per-peer accounting").

    Peer ids are CLIENT-ASSERTED (an optional ``"peer"`` envelope field
    on DasSample/DasSampleBatch — old clients simply stay anonymous),
    so the server bounds everything about them: ids are truncated to
    ``MAX_PEER_ID`` chars, at most ``max_peers`` peers are tracked on a
    :class:`~celestia_tpu.utils.lru.LruCache` (label cardinality on the
    exposition is bounded by the same cap; an evicted peer's labels
    disappear from the scrape), and per-peer distinct-row tracking
    saturates at ``MAX_ROWS_TRACKED``.  Served/shed/bytes/rows feed the
    per-peer exposition lines and the Jain fairness index."""

    MAX_PEER_ID = 64
    MAX_ROWS_TRACKED = 512

    def __init__(self, max_peers: int = 256):
        from celestia_tpu.utils.lru import LruCache

        self._lock = threading.Lock()
        # entries are mutable dicts mutated only under self._lock
        self._peers = LruCache("das_peers", max_entries=max(1, int(max_peers)))

    @classmethod
    def peer_id(cls, q) -> str:
        """The bounded peer id out of a request envelope ('' = anonymous)."""
        try:
            raw = q.get("peer", "")
        except Exception:
            return ""
        return str(raw or "")[: cls.MAX_PEER_ID]

    def _entry(self, peer: str) -> dict:
        # caller holds self._lock
        st = self._peers.get(peer, count=False)
        if st is None:
            st = {
                "served": 0, "shed": 0, "bytes": 0,
                "rows": set(), "rows_capped": False, "lane": "",
            }
            self._peers.put(peer, st)
        return st

    def record_served(self, peer, cells, bytes_out, rows=(), lane=None):
        if not peer:
            return
        with self._lock:
            st = self._entry(peer)
            st["served"] += int(cells)
            st["bytes"] += int(bytes_out)
            if lane:
                st["lane"] = str(lane)
            seen = st["rows"]
            for key in rows:
                if len(seen) >= self.MAX_ROWS_TRACKED:
                    st["rows_capped"] = True
                    break
                seen.add(key)

    def record_shed(self, peer, lane=None):
        if not peer:
            return
        with self._lock:
            st = self._entry(peer)
            st["shed"] += 1
            if lane:
                st["lane"] = str(lane)

    def snapshot(self) -> dict:
        """peer -> flat counters (no mutable internals escape the lock)."""
        with self._lock:
            out = {}
            for peer in self._peers.keys():
                st = self._peers.peek(peer)
                if st is None:  # raced an eviction
                    continue
                out[peer] = {
                    "served": st["served"],
                    "shed": st["shed"],
                    "bytes": st["bytes"],
                    "rows": len(st["rows"]),
                    "lane": st["lane"],
                }
            return out

    def fairness_index(self) -> Optional[float]:
        """Jain fairness over per-peer SERVED counts; None until at
        least one identified peer has been served (skip-absent: the
        metric must not exist before there is a distribution to judge)."""
        from celestia_tpu.utils.telemetry import jain_fairness_index

        with self._lock:
            served = []
            for peer in self._peers.keys():
                st = self._peers.peek(peer)
                if st is not None:
                    served.append(st["served"])
        return jain_fairness_index(served)

    def exposition_lines(self) -> list:
        """Bounded-label per-peer exposition (cardinality capped by the
        registry's LRU bound, values escaped — always parse-valid)."""
        from celestia_tpu.utils.telemetry import escape_label_value

        snap = self.snapshot()
        if not snap:
            return []
        lines = [
            "# TYPE celestia_tpu_das_peer_served_total counter",
            "# TYPE celestia_tpu_das_peer_shed_total counter",
            "# TYPE celestia_tpu_das_peer_bytes_total counter",
        ]
        for peer in sorted(snap):
            st = snap[peer]
            lbl = escape_label_value(peer)
            lines.append(
                f'celestia_tpu_das_peer_served_total{{peer="{lbl}"}} '
                f'{st["served"]}'
            )
            lines.append(
                f'celestia_tpu_das_peer_shed_total{{peer="{lbl}"}} '
                f'{st["shed"]}'
            )
            lines.append(
                f'celestia_tpu_das_peer_bytes_total{{peer="{lbl}"}} '
                f'{st["bytes"]}'
            )
            lines.append(
                f'celestia_tpu_das_peer_rows{{peer="{lbl}"}} {st["rows"]}'
            )
            if st["lane"]:
                lane = escape_label_value(st["lane"])
                lines.append(
                    f'celestia_tpu_das_peer_lane{{peer="{lbl}",'
                    f'lane="{lane}"}} 1'
                )
        return lines


class _BlockScorecardRing:
    """Bounded ring of per-height block scorecards.

    One row per height, merged from whatever legs THIS node actually
    saw: the proposer contributes prepare wall, a validator contributes
    process wall + the gossip propagation hop, and the commit RPC
    arrival stamps commit lag.  Rows are assembled incrementally (the
    lifecycle reaches a node as separate RPCs), and ``e2e_ms`` is
    always the sum of the parts known so far — a proposer-only row is
    an honest partial, not a lie.  Keys starting with ``_`` are
    internal (raw clock stamps for lag arithmetic) and stripped from
    served rows.
    """

    CAP = 64

    def __init__(self, cap: int = CAP):
        self._cap = int(cap)
        self._lock = threading.Lock()
        # height -> row; heights are monotonic, so height order IS the
        # arrival order and eviction drops the numerically oldest —
        # this is a ring, not a cache (no LRU touch semantics);
        # celint: guarded-by(self._lock)
        self._rows: Dict[int, dict] = {}
        # celint: guarded-by(self._lock)
        self._seen: set = set()

    def first_time(self, key) -> bool:
        """Dedupe gate for trace ingestion (ring re-reads repeat)."""
        with self._lock:
            if key in self._seen:
                return False
            if len(self._seen) > 8 * self._cap:
                self._seen.clear()
            self._seen.add(key)
            return True

    def _recompute(self, row: dict) -> None:
        e2e = 0.0
        for k in ("prepare_ms", "propagation_ms", "process_ms", "commit_lag_ms"):
            v = row.get(k)
            if v is not None:
                e2e += float(v)
        row["e2e_ms"] = round(e2e, 3)
        end = row.get("_end_ts")
        commit = row.get("_commit_ts")
        if end is not None and commit is not None and "commit_lag_ms" not in row:
            row["commit_lag_ms"] = round(max(0.0, commit - end) * 1000.0, 3)
            self._recompute(row)

    def update(self, height: int, **fields) -> dict:
        """Merge fields into the height's row (creating it), recompute
        the e2e rollup, trim the ring; returns a copy of the row."""
        with self._lock:
            row = self._rows.get(height)
            if row is None:
                row = {"height": int(height)}
                self._rows[height] = row
                if len(self._rows) > self._cap:
                    for h in sorted(self._rows)[: len(self._rows) - self._cap]:
                        del self._rows[h]
            row.update({k: v for k, v in fields.items() if v is not None})
            self._recompute(row)
            return dict(row)

    def note_commit(self, height: int, ts: float) -> dict:
        return self.update(height, _commit_ts=ts)

    def rows(self, last: Optional[int] = None) -> list:
        with self._lock:
            rows = [
                {k: v for k, v in self._rows[h].items() if not k.startswith("_")}
                for h in sorted(self._rows)
            ]
        if last is not None:
            rows = rows[-int(last):]
        return rows

    def latest(self) -> Optional[dict]:
        rows = self.rows(last=1)
        return rows[0] if rows else None


# extend-leg span name -> the scorecard's leg label
_EXTEND_LEGS = {
    "extend.native": "native",
    "extend.jax": "jax",
    "extend.sharded": "mesh",
    "extend.device_plane": "device_plane",
}


class NodeService:
    """Method implementations over an in-process node (TestNode surface)."""

    def __init__(
        self, node, das_max_inflight: int = 4, flight=None,
        das_qos: bool = False,
    ):
        from celestia_tpu.utils import timeseries as ts_mod
        from celestia_tpu.utils.telemetry import clock

        self.node = node
        # continuous telemetry: the bounded snapshot ring + the alert
        # engine (stock rules + operator-declared CELESTIA_TPU_ALERT_RULES)
        self.timeseries = ts_mod.TimeSeries()
        self.alert_engine = ts_mod.AlertEngine(ts_mod.default_rules())
        for rule in ts_mod.rules_from_env():
            self.alert_engine.add_rule(rule)
        # block-lifecycle SLO plane (utils/timeseries.py): stock budgets
        # with CELESTIA_TPU_SLO operator overrides — malformed config
        # raises HERE, at boot, not at the first breach.  SLO verdicts
        # ride the same firing-transition path as alert rules, so a
        # breach trips the flight recorder into an incident bundle.
        self.slos = ts_mod.effective_slos()
        # per-height block scorecard ring, fed from completed block
        # traces (prepare/process walls, extend leg, propagation hop,
        # commit lag, critical-path top contributors)
        self.scorecard = _BlockScorecardRing()
        # anomaly flight recorder (utils/flight.py): None unless the
        # operator gave --flight-dir; fed firing transitions from every
        # sampler tick / TimeSeries RPC below
        self.flight = flight
        # service birth (telemetry clock) for the /healthz uptime field
        self._t0 = clock()
        # DAS serving-plane admission (specs/robustness.md): sampling
        # requests above the inflight bound are SHED with a retry-after
        # hint instead of queueing behind the service lock until every
        # gRPC worker is wedged — the plane degrades, it never collapses.
        # The bound must stay BELOW the gRPC worker count (NodeServer
        # max_workers, default 8): with bound == workers no request can
        # ever observe a full gate and shedding silently never happens,
        # while consensus RPCs starve behind queued samples.
        # QoS lanes (opt-in, das_qos=True): the same gate capacity split
        # into a reserved `light` lane plus a shared pool `bulk` and
        # `hostile` compete for, with deterministic recent-usage tier
        # assignment — a flood of over-askers saturates the shared pool
        # but can never starve reserved light-lane admissions.  Off by
        # default: the degenerate single-lane gate is byte-for-byte the
        # pre-QoS weighted gate.
        if das_qos:
            reserved_light = max(1, int(das_max_inflight) // 2)
            self.das_gate = faults.LoadShedGate(
                max_inflight=das_max_inflight,
                retry_after_ms=25.0,
                lanes=(
                    (faults.TierPolicy.LIGHT, reserved_light),
                    (faults.TierPolicy.BULK, 0),
                    (faults.TierPolicy.HOSTILE, 0),
                ),
            )
            self.das_tiers: Optional[faults.TierPolicy] = faults.TierPolicy()
        else:
            self.das_gate = faults.LoadShedGate(
                max_inflight=das_max_inflight, retry_after_ms=25.0
            )
            self.das_tiers = None
        # per-peer serving accounting + per-tier end-to-end latency
        self.das_peers = _PeerRegistry()
        self._das_lat_lock = threading.Lock()
        self._das_lat: dict = {}  # lane -> Log2Histogram
        # backref for collect_node_sample (utils/timeseries.py): the
        # gate/fairness signals live on the service, the collector gets
        # the node
        node._das_service = self

    def _das_lane(self, peer: str, rows: int) -> Optional[str]:
        """Tier-assign one request: note the asked rows (demotion must
        see offered load, served or shed) and return the current lane
        (None when QoS lanes are off — the degenerate gate)."""
        if self.das_tiers is None:
            return None
        if peer:
            self.das_tiers.note(peer, rows=rows)
        return self.das_tiers.lane_for(peer)

    def _observe_das_latency(self, lane: Optional[str], t0: float) -> None:
        from celestia_tpu.utils.telemetry import Log2Histogram, clock

        name = lane or faults.TierPolicy.LIGHT
        with self._das_lat_lock:
            hist = self._das_lat.get(name)
            if hist is None:
                hist = Log2Histogram()
                self._das_lat[name] = hist
        hist.observe(max(0.0, clock() - t0))

    def das_latency_summary(self) -> dict:
        """Per-tier end-to-end sample latency summary (lane ->
        count/p50/p99/... in ms)."""
        with self._das_lat_lock:
            items = sorted(self._das_lat.items())
        return {lane: hist.summary() for lane, hist in items}

    # -- handlers (bytes -> bytes) ------------------------------------

    def broadcast(self, raw: bytes, ctx) -> bytes:
        res = self.node.broadcast_tx(raw)
        return json.dumps(
            {"code": res.code, "log": res.log, "txhash": res.tx_hash.hex()}
        ).encode()

    def broadcast_batch(self, req: bytes, ctx) -> bytes:
        """Batched tx submission: the whole chunk drains through ONE
        check_txs_batch pass (single verify_batch over fresh signatures)
        under one service-lock hold; per-tx results in input order."""
        d = json.loads(req)
        raws = [bytes.fromhex(r) for r in d.get("txs", [])]
        results = self.node.broadcast_txs_batch(raws)
        return json.dumps(
            {
                "results": [
                    {"code": r.code, "log": r.log, "txhash": r.tx_hash.hex()}
                    for r in results
                ]
            }
        ).encode()

    def get_tx(self, req: bytes, ctx) -> bytes:
        q = json.loads(req or b"{}")
        info = self.node.get_tx(bytes.fromhex(q["hash"]))
        if info is None:
            return json.dumps({"found": False}).encode()
        out = {"found": True}
        for key, val in info.items():
            out[key] = val.hex() if isinstance(val, bytes) else val
        return json.dumps(out, default=str).encode()

    def account_info(self, req: bytes, ctx) -> bytes:
        q = json.loads(req or b"{}")
        num, seq = self.node.account_info(bytes.fromhex(q["address"]))
        return json.dumps({"account_number": num, "sequence": seq}).encode()

    def simulate(self, raw: bytes, ctx) -> bytes:
        try:
            gas = self.node.simulate(raw)
            return json.dumps({"gas": gas}).encode()
        except Exception as e:
            return json.dumps({"code": 1, "log": str(e)}).encode()

    def status(self, req: bytes, ctx) -> bytes:
        node = self.node
        blocks = getattr(node, "blocks", [])
        latest = blocks[-1].header if blocks else None
        return json.dumps(
            {
                "chain_id": node.chain_id,
                "height": node.height,
                "app_version": node.app.app_version,
                "app_hash": latest.app_hash.hex() if latest else "",
                "data_root": latest.data_hash.hex() if latest else "",
                "time_ns": latest.time_ns if latest else 0,
                "genesis_time_ns": getattr(node.app, "genesis_time_ns", 0),
                "validator_address": (
                    node._validator_key.public_key().address().hex()
                    if getattr(node, "_validator_key", None)
                    else ""
                ),
                **(
                    {"gossip": node.gossip_engine.stats()}
                    if getattr(node, "gossip_engine", None) is not None
                    else {}
                ),
            }
        ).encode()

    def block(self, req: bytes, ctx) -> bytes:
        q = json.loads(req or b"{}")
        try:
            blk = self.node.block(int(q["height"]))
        except (KeyError, IndexError, ValueError) as e:
            return json.dumps({"found": False, "log": str(e)}).encode()
        h = blk.header
        return json.dumps(
            {
                "found": True,
                "height": h.height,
                "time_ns": h.time_ns,
                "chain_id": h.chain_id,
                "app_version": h.app_version,
                "data_root": h.data_hash.hex(),
                "app_hash": h.app_hash.hex(),
                "square_size": h.square_size,
                "tx_hashes": [
                    hashlib.sha256(t).hexdigest() for t in blk.txs
                ],
            }
        ).encode()

    # -- consensus surface (multi-process replication) -----------------
    #
    # Driven by an external coordinator (node/coordinator.py): this node
    # never self-produces in validator mode; the coordinator sequences
    # prepare -> process votes -> commit across the validator processes.

    def cons_prepare(self, req: bytes, ctx) -> bytes:
        q = json.loads(req or b"{}")
        with tracing.rpc_span("rpc.cons_prepare", q.get("_tc")):
            p = self.node.cons_prepare()
        out = {
            "block_txs": [t.hex() for t in p["block_txs"]],
            "square_size": p["square_size"],
            "data_root": p["data_root"].hex(),
        }
        # hand the caller the prepare root's trace context: the
        # coordinator forwards it to every validator's cons_process so
        # the cross-node parent is the PROPOSER's prepare span, not the
        # coordinator's glue
        tc = tracing.last_block_context("prepare_proposal")
        if tc is not None:
            out["_tc"] = tc
        return json.dumps(out).encode()

    def cons_process(self, req: bytes, ctx) -> bytes:
        q = json.loads(req)
        with tracing.rpc_span("rpc.cons_process", q.get("_tc")):
            ok, reason = self.node.cons_process(
                [bytes.fromhex(t) for t in q["block_txs"]],
                int(q["square_size"]),
                bytes.fromhex(q["data_root"]),
            )
        return json.dumps({"accept": ok, "reason": reason}).encode()

    def cons_commit(self, req: bytes, ctx) -> bytes:
        q = json.loads(req)
        votes = q.get("votes")
        with tracing.rpc_span("rpc.cons_commit", q.get("_tc")):
            app_hash = self.node.cons_commit(
                [bytes.fromhex(t) for t in q["block_txs"]],
                int(q["height"]),
                int(q["time_ns"]),
                bytes.fromhex(q["data_root"]),
                int(q["square_size"]),
                proposer=bytes.fromhex(q.get("proposer", "") or ""),
                votes=(
                    [(bytes.fromhex(a), bool(ok)) for a, ok in votes]
                    if votes is not None
                    else None
                ),
            )
        # commit-lag stamp for the block scorecard: the lifecycle ends
        # here, and the gap between the process/prepare trace's end and
        # this arrival is the consensus glue the waterfall reports
        from celestia_tpu.utils.telemetry import clock

        self.scorecard.note_commit(int(q["height"]), clock())
        try:
            self._scorecard_ingest()
        except Exception as e:
            # scorecard bugs degrade observability, never consensus
            faults.note("scorecard.commit", e)
        return json.dumps({"app_hash": app_hash.hex()}).encode()

    # -- two-phase BFT surface (node/bft.py; the relay is dumb transport)

    def bft_start(self, req: bytes, ctx) -> bytes:
        q = json.loads(req)
        self.node.bft_start(int(q["height"]))
        return b"{}"

    def bft_msg(self, req: bytes, ctx) -> bytes:
        wire = json.loads(req)
        # relay-leg trace context rides INSIDE the wire dict (the relay
        # forwards wires verbatim, so there is no outer envelope to
        # extend); engines ignore unknown keys, and the context is
        # stripped before delivery so re-serialized outbox messages never
        # carry a stale hop's context
        tc, kind = None, ""
        if isinstance(wire, dict):  # the only valid wire shape
            tc = wire.pop("_tc", None)
            kind = str(wire.get("kind", ""))
        with tracing.rpc_span("rpc.bft_msg", tc, kind=kind):
            self.node.bft_msg(wire)
        return b"{}"

    def bft_timeout(self, req: bytes, ctx) -> bytes:
        q = json.loads(req)
        self.node.bft_timeout(q["step"], int(q["height"]), int(q["round"]))
        return b"{}"

    def bft_drain(self, req: bytes, ctx) -> bytes:
        return json.dumps(self.node.bft_drain()).encode()

    def bft_decided(self, req: bytes, ctx) -> bytes:
        q = json.loads(req)
        d = self.node.bft_decided(int(q["height"]))
        return json.dumps({"found": d is not None, "decided": d}).encode()

    def bft_catchup(self, req: bytes, ctx) -> bytes:
        ok, why = self.node.bft_catchup(json.loads(req))
        return json.dumps({"ok": ok, "reason": why}).encode()

    def das_sample(self, req: bytes, ctx) -> bytes:
        """One DAS cell + proof to the data root, behind the load-shed
        gate.  A shed response carries ``retry_after_ms`` so an honest
        light client backs off through the unified RetryPolicy instead
        of hammering a saturated node; the ``server.sample`` fault point
        makes the handler itself injectable for the chaos suite (an
        injected failure is reported as retriable, exactly like shed
        load — the client cannot tell a chaos drill from real pressure).

        The optional client-asserted ``"peer"`` envelope field feeds the
        bounded per-peer accounting + the QoS tier hook; requests
        without it stay anonymous on the pre-QoS path (version-tolerant
        envelopes — old clients need no change)."""
        from celestia_tpu.utils.telemetry import clock

        t0 = clock()
        try:
            q = json.loads(req or b"{}")
        except Exception as e:
            return json.dumps({"code": 1, "log": str(e)}).encode()
        peer = _PeerRegistry.peer_id(q)
        lane = self._das_lane(peer, rows=1)
        if not self.das_gate.try_acquire(lane=lane):
            self.node.app.telemetry.incr("das_sample_shed")
            self.das_peers.record_shed(peer, lane)
            tracing.instant("das_sample.shed", cat="serving")
            shed = {
                "shed": True,
                "retry_after_ms": self.das_gate.retry_after_ms,
            }
            if lane is not None:
                shed["lane"] = lane
            return json.dumps(shed).encode()
        try:
            with tracing.rpc_span(
                "das_sample", q.get("_tc"), cat="serving",
                height=int(q.get("height", 0) or 0),
                row=int(q.get("row", 0) or 0),
                col=int(q.get("col", 0) or 0),
            ):
                faults.fire("server.sample")
                out = self.node.abci_query("custom/das/sample", q)
            self.node.app.telemetry.incr("das_samples_served")
            resp = json.dumps({"shed": False, **out}, default=str).encode()
            self.das_peers.record_served(
                peer, cells=1, bytes_out=len(resp),
                rows=((int(q.get("height", 0) or 0),
                       int(q.get("row", 0) or 0)),),
                lane=lane,
            )
            self._observe_das_latency(lane, t0)
            return resp
        except faults.InjectedFault as e:
            return json.dumps(
                {
                    "shed": True,
                    "retry_after_ms": self.das_gate.retry_after_ms,
                    "log": str(e),
                }
            ).encode()
        except Exception as e:
            return json.dumps({"code": 1, "log": str(e)}).encode()
        finally:
            self.das_gate.release(lane=lane)

    # DasSampleBatch chunking: cells proven (and streamed) per response
    # message.  Bounds BOTH the per-message JSON size (a 10k-cell
    # request never builds one giant blob — a 64-proof chunk is ~100 KiB
    # on the wire, far under the 4 MiB transport cap) and the admission
    # granularity: every chunk re-passes the shed gate, weighted by the
    # distinct rows it proves.
    DAS_BATCH_CHUNK = 64

    def das_sample_batch(self, req: bytes, ctx):
        """Streaming DAS batch prover: one request -> n cells, served as
        chunked responses behind the load-shed gate.

        Each chunk is admitted SEPARATELY with weight = the distinct
        rows it proves (the row level stack is the unit of prover work),
        and chunk boundaries keep that weight STRICTLY below the gate's
        ``max_inflight`` — so every chunk is individually admissible
        under concurrent traffic, while an n-cell batch still consumes
        admission proportional to its size.  Batching therefore cannot
        launder load past the gate, and a saturated node
        sheds mid-stream with ``retry_after_ms`` + the count of cells
        already ``served`` so an honest client resumes the remainder
        through the unified RetryPolicy instead of re-requesting served
        cells.  The ``server.sample`` fault point makes every chunk
        injectable for the chaos suite, reported as retriable exactly
        like shed load."""
        from celestia_tpu.utils.telemetry import clock

        q = json.loads(req or b"{}")
        coords = [(int(r), int(c)) for r, c in q.get("coords", [])]
        height = int(q.get("height", 0) or 0)
        peer = _PeerRegistry.peer_id(q)
        # tier usage counts the batch's ASKED cells up front: a shed
        # over-asker keeps offering load, and demotion must see it (cells
        # not distinct rows — a tiny square caps rows at 2k, which would
        # let an over-asker hide arbitrary cell volume behind few rows)
        if self.das_tiers is not None and peer:
            self.das_tiers.note(peer, rows=len(coords))
        chunk = max(
            1, min(int(q.get("chunk", 0) or self.DAS_BATCH_CHUNK),
                   self.DAS_BATCH_CHUNK)
        )
        # chunk boundaries respect BOTH caps: <= `chunk` cells (message
        # size) AND < max_inflight distinct rows (admission weight).
        # STRICTLY below the gate bound: try_acquire(w) needs
        # inflight + w <= max_inflight once anything is in flight, so a
        # chunk weighing the full bound — like one weighing more — could
        # only ever be admitted idle and would shed under ANY concurrent
        # traffic, starving honest batch clients at modest load
        max_rows = max(1, self.das_gate.max_inflight - 1)
        chunks: list = []
        cur: list = []
        cur_rows: set = set()
        for rc in coords:
            if cur and (
                len(cur) >= chunk
                or (rc[0] not in cur_rows and len(cur_rows) >= max_rows)
            ):
                chunks.append(cur)
                cur, cur_rows = [], set()
            cur.append(rc)
            cur_rows.add(rc[0])
        if cur:
            chunks.append(cur)
        telemetry = self.node.app.telemetry
        telemetry.incr("das_batch_calls")
        served = 0
        with tracing.rpc_span(
            "das_sample_batch", q.get("_tc"), cat="serving",
            height=height, cells=len(coords),
        ):
            for i, part in enumerate(chunks):
                weight = len({r for r, _ in part})
                # lane re-evaluated per chunk: an over-asker's giant
                # batch slides to bulk/hostile MID-STREAM once the usage
                # window catches up — demotion is not per-connection
                lane = (
                    self.das_tiers.lane_for(peer)
                    if self.das_tiers is not None
                    else None
                )
                t0 = clock()
                if not self.das_gate.try_acquire(weight=weight, lane=lane):
                    telemetry.incr("das_batch_shed")
                    self.das_peers.record_shed(peer, lane)
                    tracing.instant("das_sample_batch.shed", cat="serving")
                    shed = {
                        "shed": True,
                        "retry_after_ms": self.das_gate.retry_after_ms,
                        "served": served,
                    }
                    if lane is not None:
                        shed["lane"] = lane
                    yield json.dumps(shed).encode()
                    return
                try:
                    faults.fire("server.sample")
                    out = self.node.abci_query(
                        "custom/das/sample_batch",
                        {"height": height, "coords": part},
                    )
                    telemetry.incr("das_samples_served", len(part))
                    served += len(part)
                    resp = json.dumps(
                        {
                            "shed": False,
                            "done": i == len(chunks) - 1,
                            **out,
                        },
                        default=str,
                    ).encode()
                    self.das_peers.record_served(
                        peer, cells=len(part), bytes_out=len(resp),
                        rows=[(height, r) for r, _ in part],
                        lane=lane,
                    )
                    self._observe_das_latency(lane, t0)
                    yield resp
                except faults.InjectedFault as e:
                    # reported retriable like shed load, but NOT counted
                    # as shed: the shed counters track real gate
                    # pressure (same rule as the single-cell handler),
                    # so a chaos drill never inflates the das_shed
                    # signal dashboards scale out on
                    yield json.dumps(
                        {
                            "shed": True,
                            "retry_after_ms": self.das_gate.retry_after_ms,
                            "served": served,
                            "log": str(e),
                        }
                    ).encode()
                    return
                except Exception as e:
                    yield json.dumps({"code": 1, "log": str(e)}).encode()
                    return
                finally:
                    self.das_gate.release(weight=weight, lane=lane)

    # -- observability plane (utils/telemetry.py + utils/tracing.py) ----

    def metrics_text(self) -> str:
        """The ONE exposition builder (the gRPC ``Metrics`` RPC and the
        plain-HTTP ``/metrics`` endpoint both serve exactly this):
        counters, gauges, the bounded log2 histograms, per-span
        aggregates (when tracing is on) and the unified cache registry.

        Appended sections (all line-parse-valid, the same gate as the
        core export): client-side RPC counters (this node's OWN outbound
        pulls — gossip catch-up, state-sync), fault-note/degradation
        totals (the robustness ladder, so ``cluster-health`` needs no
        second RPC), the node identity as an info gauge, the device
        plane's ``celestia_tpu_xla_*``/``celestia_tpu_device_*`` gauges
        (utils/devprof.py), trace-ring health (span drops + background
        depth — silent truncation must be remotely detectable) and the
        alert engine's per-rule firing states."""
        from celestia_tpu.node import remote as remote_mod
        from celestia_tpu.utils import devprof, faults
        from celestia_tpu.utils.telemetry import escape_label_value

        lines = [self.node.app.telemetry.export_prometheus().rstrip("\n")]
        client_lines = remote_mod.client_rpc_exposition()
        if client_lines:
            lines.extend(client_lines)
        fs = faults.fault_stats()
        notes_total = sum(v["count"] for v in fs["notes"].values())
        lines.append("# TYPE celestia_tpu_fault_notes_total counter")
        lines.append(f"celestia_tpu_fault_notes_total {notes_total}")
        lines.append("# TYPE celestia_tpu_degradations_total counter")
        lines.append(
            f"celestia_tpu_degradations_total {len(fs['degradations'])}"
        )
        nid = tracing.node_id()
        if nid:
            lines.append(
                'celestia_tpu_node_info{node_id="%s"} 1'
                % escape_label_value(nid)
            )
        # device plane (XLA cost table, per-chip busy ms, mem watermark)
        lines.extend(devprof.exposition_lines())
        # host profiler (sampler rates + measured self-overhead)
        from celestia_tpu.utils import hostprof

        lines.extend(hostprof.exposition_lines())
        # flight recorder: lifetime incident seq (cluster_health reads
        # the per-peer count straight off the scrape) + kept-ring depth
        if self.flight is not None:
            fst = self.flight.stats()
            lines.append(
                "# TYPE celestia_tpu_flight_incidents_total counter"
            )
            lines.append(
                "celestia_tpu_flight_incidents_total "
                f"{fst['incidents_total']}"
            )
            lines.append(
                f"celestia_tpu_flight_incidents_kept {fst['incidents_kept']}"
            )
        # multi-chip mesh plane (parallel/mesh.py): whether live extends
        # shard, how many have, and how many squares fell back — a
        # degraded (poisoned) mesh shows as active 0 with extends frozen
        from celestia_tpu.parallel import mesh as mesh_mod

        ms = mesh_mod.stats()
        lines.append(
            f"celestia_tpu_mesh_active {1 if ms['active'] else 0}"
        )
        lines.append(
            "# TYPE celestia_tpu_mesh_sharded_extends_total counter"
        )
        lines.append(
            f"celestia_tpu_mesh_sharded_extends_total "
            f"{ms['sharded_extends']}"
        )
        lines.append(
            "# TYPE celestia_tpu_mesh_fallback_squares_total counter"
        )
        lines.append(
            f"celestia_tpu_mesh_fallback_squares_total "
            f"{ms['fallback_squares']}"
        )
        # DAS serving plane (da/das.py + the das_gate): admission stats
        # as explicit gauges/counters (the das_rows cache's hits/misses
        # already ride the unified cache registry lines with
        # cache="das_rows"; the served/shed request counters ride the
        # telemetry export as celestia_tpu_das_*_total) plus the rows
        # hit rate as a ready-made gauge for dashboards/alerts
        from celestia_tpu.da import das as das_mod

        gate = self.das_gate.stats()
        lines.append(f"celestia_tpu_das_gate_inflight {gate['inflight']}")
        lines.append(
            f"celestia_tpu_das_gate_max_inflight {gate['max_inflight']}"
        )
        lines.append("# TYPE celestia_tpu_das_gate_admitted_total counter")
        lines.append(
            f"celestia_tpu_das_gate_admitted_total {gate['admitted']}"
        )
        lines.append("# TYPE celestia_tpu_das_gate_shed_total counter")
        lines.append(f"celestia_tpu_das_gate_shed_total {gate['shed']}")
        # QoS lanes (when configured): per-lane reserved/inflight plus
        # admitted/shed counters — the fairness story per tier
        lane_table = gate.get("lanes")
        if lane_table:
            lines.append(
                "# TYPE celestia_tpu_das_lane_admitted_total counter"
            )
            lines.append("# TYPE celestia_tpu_das_lane_shed_total counter")
            for lane_name in sorted(lane_table):
                lst = lane_table[lane_name]
                ll = escape_label_value(lane_name)
                lines.append(
                    f'celestia_tpu_das_lane_reserved{{lane="{ll}"}} '
                    f'{lst["reserved"]}'
                )
                lines.append(
                    f'celestia_tpu_das_lane_inflight{{lane="{ll}"}} '
                    f'{lst["inflight"]}'
                )
                lines.append(
                    f'celestia_tpu_das_lane_admitted_total{{lane="{ll}"}} '
                    f'{lst["admitted"]}'
                )
                lines.append(
                    f'celestia_tpu_das_lane_shed_total{{lane="{ll}"}} '
                    f'{lst["shed"]}'
                )
        # per-tier end-to-end sample latency (lane folded into the
        # metric name: lane names are server-defined, so the family set
        # is bounded; Log2Histogram renders proper cumulative buckets)
        from celestia_tpu.utils.telemetry import sanitize_metric_name

        with self._das_lat_lock:
            lat_items = sorted(self._das_lat.items())
        for lane_name, hist in lat_items:
            lines.extend(
                hist.prometheus_lines(
                    "celestia_tpu_das_latency_"
                    f"{sanitize_metric_name(lane_name)}_seconds"
                )
            )
        # per-peer accounting (bounded labels — see _PeerRegistry) + the
        # Jain fairness index (skip-absent until a peer has been served)
        lines.extend(self.das_peers.exposition_lines())
        fairness = self.das_peers.fairness_index()
        if fairness is not None:
            lines.append(
                f"celestia_tpu_das_fairness_index {round(fairness, 6)}"
            )
        rows = das_mod.rows_cache().stats()
        lines.append(
            f"celestia_tpu_das_rows_hit_rate {round(rows['hit_rate'], 6)}"
        )
        # trace-ring health (satellite: remote truncation detectability)
        rs = tracing.ring_stats()
        lines.append(
            "# TYPE celestia_tpu_trace_span_drops_total counter"
        )
        lines.append(
            f"celestia_tpu_trace_span_drops_total {rs['span_drops_total']}"
        )
        lines.append(
            f"celestia_tpu_trace_background_depth {rs['background_depth']}"
        )
        # alert states: one 0/1 gauge per rule + the firing total, so
        # cluster_health flags a degrading node from the scrape alone
        # (SLO burn-rate verdicts ride the same gauge family)
        firing_total = 0
        for verdict in self._evaluate_all():
            label = escape_label_value(verdict["name"])
            val = 1 if verdict["firing"] else 0
            firing_total += val
            lines.append(f'celestia_tpu_alert_firing{{rule="{label}"}} {val}')
        lines.append(f"celestia_tpu_alerts_firing_total {firing_total}")
        lines.append(f"celestia_tpu_timeseries_samples {len(self.timeseries)}")
        return "\n".join(lines) + "\n"

    def metrics(self, req: bytes, ctx) -> bytes:
        """Prometheus text exposition (see :meth:`metrics_text`).  Raw
        text bytes — point a scraper straight at the RPC."""
        return self.metrics_text().encode()

    def _evaluate_all(self):
        """Alert-rule verdicts + SLO burn-rate verdicts, one list.  The
        flight recorder keys on verdict name/firing, so SLO breaches
        transition into incident bundles through the unchanged path."""
        verdicts = self.alert_engine.evaluate(self.timeseries)
        verdicts.extend(s.evaluate(self.timeseries) for s in self.slos)
        return verdicts

    def _scorecard_ingest(self) -> None:
        """Fold newly completed block traces into the scorecard ring.

        Called on every sampler tick, scorecard RPC and commit (the
        trace ring is tiny, ingestion dedupes on root span id, so
        repeated calls are cheap no-ops).  Per trace: wall + slowest
        phase from ``phase_breakdown``, extend leg + cache verdict from
        the extend spans, the propagation hop from the critical-path
        report (``_tc`` send ts, offset 0 on a single node's own axis —
        clamped at 0 with ``celestia_tpu_clock_skew_clamped_total``
        counting the skew), and the top-3 critical-path contributors.
        The e2e/propagation observations feed the SLO metrics and the
        ``celestia_tpu_block_{e2e,propagation}_seconds`` histograms."""
        from celestia_tpu.utils import critpath, faults

        t = self.node.app.telemetry
        for tr in tracing.block_traces():
            if not tr.complete or not tr.spans:
                continue
            if not self.scorecard.first_time((tr.name, tr.height, tr.root_id)):
                continue
            try:
                report = critpath.critical_path(tr)
                breakdown = tracing.TRACER.phase_breakdown(tr)
            except Exception as e:
                faults.note("scorecard.ingest", e)
                continue
            root = next(
                (s for s in tr.spans if s.span_id == tr.root_id), None
            )
            leg, cache = "", ""
            for s in tr.spans:
                if s.name == "extend":
                    cache = s.args.get("eds_cache", cache)
                elif s.name in _EXTEND_LEGS:
                    leg = _EXTEND_LEGS[s.name]
            if cache == "hit" and not leg:
                leg = "cache"
            phases = {
                k: v
                for k, v in breakdown.items()
                if k.endswith("_ms") and k != "total_ms"
            }
            slowest = max(phases, key=phases.get) if phases else ""
            fields = {
                "slowest_phase": slowest[:-3] if slowest else "",
                "top_contributors": report["top_contributors"],
                "_end_ts": root.t1 if root is not None else None,
            }
            if leg:
                fields["extend_leg"] = leg
            if cache:
                fields["eds_cache"] = cache
            wall = report["root_wall_ms"]
            prop = report["propagation_delay_ms"]
            if tr.name == "prepare_proposal":
                fields["prepare_ms"] = wall
            else:
                fields["process_ms"] = wall
            if prop is not None:
                fields["propagation_ms"] = prop
                t.observe("block_propagation", prop)
            if report["clock_skew_clamped"]:
                fields["propagation_clamped"] = report["clock_skew_clamped"]
                t.incr("clock_skew_clamped", report["clock_skew_clamped"])
            row = self.scorecard.update(tr.height, **fields)
            t.observe("block_e2e", row["e2e_ms"])
            obs = {"block_e2e_ms": row["e2e_ms"]}
            if prop is not None:
                obs["block_propagation_ms"] = prop
            self.timeseries.record(obs)

    def block_scorecard(self, req: bytes, ctx) -> bytes:
        """The per-height scorecard ring (``query block-scorecard``).
        Ingests any freshly completed traces first, so a scorecard
        fetched right after a block always has that height's row."""
        q = json.loads(req or b"{}")
        from celestia_tpu.utils import faults

        try:
            self._scorecard_ingest()
        except Exception as e:
            faults.note("scorecard.rpc", e)
        last = q.get("last")
        return json.dumps(
            {
                "node_id": tracing.node_id(),
                "height": int(getattr(self.node, "height", 0) or 0),
                "rows": self.scorecard.rows(
                    int(last) if last is not None else None
                ),
            }
        ).encode()

    def sample_timeseries(self):
        """Record ONE snapshot of the node's operational signals into
        the ring (the sampler thread's tick; also the on-demand sample
        every TimeSeries RPC takes before answering).  Returns the
        alert verdicts the flight tick computed (None when no recorder
        is armed) so the TimeSeries RPC never evaluates the engine a
        second time for the same tick."""
        from celestia_tpu.utils import faults, timeseries as ts_mod

        try:
            # scorecard first: freshly completed traces contribute the
            # block_e2e_ms/block_propagation_ms observations the SLO
            # verdicts below are judged on
            self._scorecard_ingest()
        except Exception as e:
            faults.note("scorecard.tick", e)
        try:
            self.timeseries.record(ts_mod.collect_node_sample(self.node))
        except Exception as e:
            # a collector bug degrades the ring, never the node
            faults.note("timeseries.sample", e)
        verdicts = None
        if self.flight is not None:
            verdicts = self._evaluate_all()
            self.flight_tick(verdicts)
        return verdicts

    def flight_tick(self, verdicts=None) -> None:
        """Feed the flight recorder: alert firing TRANSITIONS over the
        fresh sample trigger an incident bundle, and the newest block
        trace is judged against the slow-block threshold.  A recorder
        bug degrades to a fault note, never the node.  A caller that
        has already evaluated the engine passes its ``verdicts`` so one
        tick never evaluates twice."""
        if self.flight is None:
            return
        from celestia_tpu.utils import faults

        try:
            if verdicts is None:
                verdicts = self._evaluate_all()
            inc = self.flight.on_alerts(
                verdicts,
                height=int(getattr(self.node, "height", 0) or 0),
                # callables: resolved only when a bundle actually dumps,
                # so the steady-state tick never builds an exposition
                metrics_text=self.metrics_text,
                timeseries_snapshots=self.timeseries.samples,
            )
            if inc is None and self.flight.slow_block_ms is not None:
                for tr in tracing.block_traces(last=1):
                    breakdown = tracing.TRACER.phase_breakdown(tr)
                    self.flight.on_block(
                        tr.height, breakdown.get("total_ms", 0.0),
                        metrics_text=self.metrics_text,
                        timeseries_snapshots=self.timeseries.samples,
                    )
        except Exception as e:
            faults.note("flight.tick", e)

    def time_series(self, req: bytes, ctx) -> bytes:
        """The continuous-telemetry ring + alert verdicts.  One fresh
        sample is recorded per call, so two consecutive RPCs always
        return >= 2 snapshots with a computable rate — a fresh node is
        queryable immediately, no waiting on the sampler cadence."""
        q = json.loads(req or b"{}")
        verdicts = self.sample_timeseries()
        if verdicts is None:  # no recorder armed: the tick skipped it
            verdicts = self._evaluate_all()
        last = q.get("last")
        snapshots = self.timeseries.samples(
            int(last) if last is not None else None
        )
        return json.dumps(
            {
                "node_id": tracing.node_id(),
                "samples_kept": len(self.timeseries),
                "max_samples": self.timeseries.max_samples,
                "snapshots": snapshots,
                "rates": self.timeseries.rates(),
                "alerts": verdicts,
            }
        ).encode()

    def clock_probe(self, req: bytes, ctx) -> bytes:
        """One sanctioned telemetry-clock read for the cross-node
        midpoint offset probe (utils/tracing.estimate_clock_offset):
        merged cluster timelines subtract the estimated offset so N
        nodes' spans line up on one axis."""
        from celestia_tpu.utils.telemetry import clock

        return json.dumps(
            {
                "ts": clock(),
                "node_id": tracing.node_id(),
                "height": self.node.height,
            }
        ).encode()

    def trace_dump(self, req: bytes, ctx) -> bytes:
        """The last N block traces (plus the background ring) as a Chrome
        trace-event document: ``{"enabled", "blocks", "trace"}`` where
        ``trace`` opens as-is in Perfetto / chrome://tracing."""
        q = json.loads(req or b"{}")
        last = q.get("last")
        dump = tracing.trace_dump(int(last) if last is not None else None)
        return json.dumps(
            {
                "enabled": tracing.enabled(),
                "blocks": dump.get("otherData", {}).get("blocks", []),
                "trace": dump,
            }
        ).encode()

    def host_profile(self, req: bytes, ctx) -> bytes:
        """The host sampling profiler's state (utils/hostprof.py):
        sampler stats, top self-time frames and the folded stacks
        (bounded to the top N by count so the response stays under the
        transport cap even on a long-running node)."""
        from celestia_tpu.utils import hostprof

        q = json.loads(req or b"{}")
        top = int(q.get("top", 25) or 25)
        folded = sorted(
            hostprof.folded_stacks().items(), key=lambda kv: (-kv[1], kv[0])
        )[: max(1, int(q.get("folded", 200) or 200))]
        return json.dumps(
            {
                "node_id": tracing.node_id(),
                "stats": hostprof.stats(),
                "top_frames": hostprof.top_frames(top),
                "folded": dict(folded),
            }
        ).encode()

    def flight_list(self, req: bytes, ctx) -> bytes:
        """Manifest summaries of every kept incident bundle (oldest
        first), plus the recorder's ring stats.  ``enabled: false`` when
        the node runs without --flight-dir."""
        if self.flight is None:
            return json.dumps(
                {"enabled": False, "incidents": [], "stats": {}}
            ).encode()
        return json.dumps(
            {
                "enabled": True,
                "incidents": self.flight.list_incidents(),
                "stats": self.flight.stats(),
            }
        ).encode()

    # stay safely under RemoteNode.MAX_RECV_BYTES (4 MiB): a bundle
    # whose artifacts exceed this is served file-by-file instead of
    # inline, and a single oversized artifact is truncated with a
    # marker rather than made irretrievable
    FLIGHT_INLINE_MAX = 2 * 1024 * 1024
    FLIGHT_FILE_MAX = 3 * 1024 * 1024

    def flight_fetch(self, req: bytes, ctx) -> bytes:
        """One incident bundle by id ({"id": ...}; empty id = the
        newest).  Small bundles return manifest + every artifact
        inline; a bundle that would blow the client's 4 MiB transport
        cap returns ``files_inline: false`` and the client re-fetches
        each artifact with ``{"id", "file": <name>}``."""
        q = json.loads(req or b"{}")
        if self.flight is None:
            return json.dumps({"found": False, "enabled": False}).encode()
        incident_id = str(q.get("id", "") or "")
        if not incident_id:
            incidents = self.flight.list_incidents()
            if not incidents:
                return json.dumps({"found": False}).encode()
            incident_id = incidents[-1]["id"]
        bundle = self.flight.load_bundle(incident_id)
        if bundle is None:
            return json.dumps({"found": False, "id": incident_id}).encode()
        name = str(q.get("file", "") or "")
        if name:
            content = bundle["files"].get(name)
            if content is None:
                return json.dumps(
                    {"found": False, "id": incident_id, "file": name}
                ).encode()
            truncated = len(content) > self.FLIGHT_FILE_MAX
            if truncated:
                content = content[: self.FLIGHT_FILE_MAX]
            return json.dumps(
                {
                    "found": True, "id": incident_id, "file": name,
                    "content": content, "truncated": truncated,
                }
            ).encode()
        total = sum(len(v) for v in bundle["files"].values())
        if total > self.FLIGHT_INLINE_MAX:
            return json.dumps(
                {
                    "found": True,
                    "manifest": bundle["manifest"],
                    "files_inline": False,
                }
            ).encode()
        return json.dumps({"found": True, **bundle}).encode()

    def healthz(self) -> dict:
        """The load-balancer / orchestrator probe body (plain-HTTP
        ``GET /healthz`` on --metrics-port): one small JSON answering
        "is this node serving and is anything on fire" without the full
        exposition."""
        from celestia_tpu.utils.telemetry import clock

        breakers_open = 0
        eng = getattr(self.node, "gossip_engine", None)
        if eng is not None:
            try:
                breakers = eng.stats().get("pull_breakers", {})
                breakers_open = sum(
                    1 for s in breakers.values() if s != "closed"
                )
            except Exception as e:
                faults.note("healthz.breakers", e)
        firing = [
            a["name"] for a in self._evaluate_all() if a["firing"]
        ]
        # DAS serving health without a metrics scrape: gate shed totals,
        # per-lane inflight, and the current fairness index (omitted
        # until an identified peer has been served — skip-absent)
        gate = self.das_gate.stats()
        das = {
            "gate_shed": gate["shed"],
            "gate_admitted": gate["admitted"],
            "lanes": (
                {n: st["inflight"] for n, st in gate["lanes"].items()}
                if "lanes" in gate
                else {"default": gate["inflight"]}
            ),
        }
        fairness = self.das_peers.fairness_index()
        if fairness is not None:
            das["fairness_index"] = round(fairness, 4)
        # block-lifecycle health: the last scored height's e2e and its
        # slowest phase, straight off the scorecard ring
        block = {}
        last_row = self.scorecard.latest()
        if last_row is not None:
            block = {
                "height": last_row.get("height"),
                "e2e_ms": last_row.get("e2e_ms"),
                "slowest_phase": last_row.get("slowest_phase", ""),
            }
        return {
            "status": "degraded" if firing else "ok",
            "node_id": tracing.node_id(),
            "chain_id": getattr(self.node, "chain_id", ""),
            "height": int(getattr(self.node, "height", 0) or 0),
            "breakers_open": breakers_open,
            "alerts_firing": firing,
            "uptime_s": round(
                max(0.0, clock() - self._t0), 3
            ),
            "incidents_kept": (
                len(self.flight.list_incidents())
                if self.flight is not None
                else 0
            ),
            "das": das,
            "block": block,
        }

    def query(self, req: bytes, ctx) -> bytes:
        q = json.loads(req or b"{}")
        path = q.get("path", "")
        data = q.get("data", {})
        try:
            result = self.node.abci_query(path, data)
            return json.dumps({"code": 0, "value": result}, default=str).encode()
        except Exception as e:
            return json.dumps({"code": 1, "log": str(e)}).encode()

    # -- p2p gossip mesh (node/gossip.py) -------------------------------

    def gossip_msg(self, req: bytes, ctx) -> bytes:
        d = json.loads(req)
        eng = getattr(self.node, "gossip_engine", None)
        if eng is None:
            # no mesh engine on this node: deliver directly (lets a
            # meshed peer talk to a relay-driven node during rollout)
            self.node.bft_msg(d["wire"])
            return json.dumps({"new": True}).encode()
        # dedup id is computed engine-side from the wire content; a
        # sender-supplied id is never trusted.  "_tc" is the OPTIONAL
        # envelope trace context (version-tolerant: an old engine simply
        # never sees it, an old sender simply never sends it)
        new = eng.on_gossip(d["wire"], d.get("sender", ""), tc=d.get("_tc"))
        return json.dumps({"new": new}).encode()

    def tx_have(self, req: bytes, ctx) -> bytes:
        d = json.loads(req)
        eng = getattr(self.node, "gossip_engine", None)
        hashes = [bytes.fromhex(h) for h in d.get("hashes", [])]
        want = eng.on_tx_have(hashes) if eng is not None else []
        return json.dumps({"want": [h.hex() for h in want]}).encode()

    def genesis(self, req: bytes, ctx) -> bytes:
        """Serve the chain's genesis document (download-genesis role,
        cmd/root.go:131-142).  The caller should validate it and, for a
        real deployment, cross-check the chain id / app hash out of
        band — a single serving peer is not a trust anchor."""
        doc = getattr(self.node, "genesis_doc", None)
        return json.dumps(
            {"found": doc is not None, "genesis": doc or {}}
        ).encode()

    def snapshot_list(self, req: bytes, ctx) -> bytes:
        """State-sync serving (root.go:227-243 role): metadata of the
        snapshots this node can serve, incl. per-chunk hashes."""
        store = getattr(self.node, "snapshots", None)
        metas = store.list_wire() if store is not None else []
        return json.dumps({"snapshots": metas}).encode()

    def snapshot_chunk(self, req: bytes, ctx) -> bytes:
        d = json.loads(req)
        store = getattr(self.node, "snapshots", None)
        chunk = None
        with tracing.rpc_span(
            "rpc.snapshot_chunk", d.get("_tc"),
            height=int(d.get("height", 0) or 0), idx=int(d.get("idx", 0) or 0),
        ):
            if store is not None:
                chunk = store.chunk_bytes(
                    int(d["height"]), int(d.get("format", 1)), int(d["idx"])
                )
        return json.dumps(
            {"found": chunk is not None,
             "data": chunk.hex() if chunk is not None else ""}
        ).encode()

    def peer_exchange(self, req: bytes, ctx) -> bytes:
        """PEX (comet p2p/addrbook role): learn the caller + its peers,
        return ours."""
        d = json.loads(req)
        eng = getattr(self.node, "gossip_engine", None)
        if eng is None:
            return json.dumps({"peers": []}).encode()
        peers = eng.on_peer_exchange(
            str(d.get("sender", "")), list(d.get("peers", []))
        )
        return json.dumps({"peers": peers}).encode()

    def tx_push(self, req: bytes, ctx) -> bytes:
        d = json.loads(req)
        eng = getattr(self.node, "gossip_engine", None)
        raws = [bytes.fromhex(r) for r in d.get("txs", [])]
        if eng is not None:
            n = eng.on_tx_push(raws)
        elif raws:
            # no gossip engine: drain the push through the batched
            # admission plane directly (one verify_batch pass), degrading
            # to the per-tx loop on a batch-layer failure
            try:
                results = self.node.broadcast_txs_batch(raws)
                n = sum(1 for r in results if r.code == 0)
            except Exception as e:
                faults.note("server.txpush", e)
                n = 0
                for raw in raws:
                    try:
                        if self.node.broadcast_tx(raw).code == 0:
                            n += 1
                    except Exception as e:  # noqa: PERF203 - per-tx isolation
                        faults.note("server.txpush", e)
                        continue
        else:
            n = 0
        return json.dumps({"admitted": n}).encode()

    # -- grpc wiring ---------------------------------------------------

    def handlers(self) -> grpc.GenericRpcHandler:
        rpcs = {
            "Broadcast": self.broadcast,
            "BroadcastBatch": self.broadcast_batch,
            "GetTx": self.get_tx,
            "AccountInfo": self.account_info,
            "Simulate": self.simulate,
            "Status": self.status,
            "Block": self.block,
            "Query": self.query,
            "Metrics": self.metrics,
            "BlockScorecard": self.block_scorecard,
            "TraceDump": self.trace_dump,
            "ClockProbe": self.clock_probe,
            "TimeSeries": self.time_series,
            "HostProfile": self.host_profile,
            "FlightList": self.flight_list,
            "FlightFetch": self.flight_fetch,
            "DasSample": self.das_sample,
            "ConsPrepare": self.cons_prepare,
            "ConsProcess": self.cons_process,
            "ConsCommit": self.cons_commit,
            "BftStart": self.bft_start,
            "BftMsg": self.bft_msg,
            "BftTimeout": self.bft_timeout,
            "BftDrain": self.bft_drain,
            "BftDecided": self.bft_decided,
            "BftCatchup": self.bft_catchup,
            "GossipMsg": self.gossip_msg,
            "TxHave": self.tx_have,
            "TxPush": self.tx_push,
            "PeerExchange": self.peer_exchange,
            "SnapshotList": self.snapshot_list,
            "SnapshotChunk": self.snapshot_chunk,
            "Genesis": self.genesis,
        }
        method_handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                self._counted(name, fn),
                request_deserializer=_identity, response_serializer=_identity
            )
            for name, fn in rpcs.items()
        }
        # the one server-streaming method: a DAS batch arrives as ONE
        # request and leaves as chunked responses (each independently
        # gate-admitted), so a 10k-cell answer never materializes as a
        # single JSON blob on either side of the wire
        method_handlers["DasSampleBatch"] = grpc.unary_stream_rpc_method_handler(
            self._counted_stream("DasSampleBatch", self.das_sample_batch),
            request_deserializer=_identity, response_serializer=_identity
        )
        return grpc.method_handlers_generic_handler(SERVICE, method_handlers)

    def _counted(self, name: str, fn):
        """Per-RPC byte/count telemetry: ``rpc_{method}_calls`` plus
        ``rpc_{method}_bytes_{in,out}`` counters on the node's telemetry
        (three locked dict increments — cheap enough for the gossip
        flood path, and the cluster-health rollup reads them straight
        off the Metrics exposition).  The telemetry is read per call,
        never captured: a state-sync restore REPLACES node.app (and its
        Telemetry), and counters bound to the old instance would freeze
        out of the Metrics export."""
        from celestia_tpu.utils.telemetry import snake_case

        prefix = f"rpc_{snake_case(name)}"

        def handler(req: bytes, ctx, _fn=fn, _p=prefix):
            t = self.node.app.telemetry
            t.incr(f"{_p}_calls")
            t.incr(f"{_p}_bytes_in", len(req) if req else 0)
            resp = _fn(req, ctx)
            t.incr(f"{_p}_bytes_out", len(resp) if resp else 0)
            return resp

        return handler

    def _counted_stream(self, name: str, fn):
        """The streaming-method twin of :meth:`_counted`: one ``_calls``
        per stream, ``bytes_out`` accumulated per yielded message (the
        telemetry is re-read per message for the same state-sync-restore
        reason)."""
        from celestia_tpu.utils.telemetry import snake_case

        prefix = f"rpc_{snake_case(name)}"

        def handler(req: bytes, ctx, _fn=fn, _p=prefix):
            t = self.node.app.telemetry
            t.incr(f"{_p}_calls")
            t.incr(f"{_p}_bytes_in", len(req) if req else 0)
            for resp in _fn(req, ctx):
                self.node.app.telemetry.incr(
                    f"{_p}_bytes_out", len(resp) if resp else 0
                )
                yield resp

        return handler


class _MetricsHTTPServer:
    """Plain-HTTP ``/metrics`` endpoint (stdlib ``http.server`` on its
    own daemon thread) so a stock Prometheus scrapes the node without
    speaking the custom gRPC framing.  Serves EXACTLY
    ``NodeService.metrics_text()`` — one exposition builder, two
    transports.  Off by default; explicit shutdown path."""

    def __init__(self, service: "NodeService", host: str, port: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        svc = service

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib handler contract
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    # the orchestrator/load-balancer probe: small JSON,
                    # never the full exposition (a probe every second
                    # must not pay for histogram rendering)
                    try:
                        body = json.dumps(svc.healthz()).encode()
                    except Exception as e:  # noqa: BLE001 — probe gets 500
                        self.send_error(500, str(e)[:200])
                        return
                    ctype = "application/json; charset=utf-8"
                elif path in ("/metrics", "/"):
                    try:
                        body = svc.metrics_text().encode()
                    except Exception as e:  # noqa: BLE001 — scraper gets 500
                        self.send_error(500, str(e)[:200])
                        return
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                else:
                    self.send_error(404, "only /metrics and /healthz are served")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self.address = f"{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="metrics-http", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        # shutdown() waits on an event only serve_forever() sets: calling
        # it on a constructed-but-never-started server would hang forever
        # (e.g. teardown after the gRPC bind raised before start())
        if self._thread.is_alive():
            self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


class NodeServer:
    """A running node + its gRPC service + a block-production loop
    (+ the optional plain-HTTP metrics endpoint and the continuous
    telemetry sampler)."""

    def __init__(
        self,
        node,
        address: str = "127.0.0.1:0",
        block_interval_s: Optional[float] = None,
        max_workers: int = 8,
        das_max_inflight: int = 4,
        das_qos: bool = False,
        metrics_port: Optional[int] = None,
        timeseries_interval_s: Optional[float] = 5.0,
        host_profile_hz: Optional[float] = None,
        flight_dir: Optional[str] = None,
    ):
        self.node = node
        # anomaly flight recorder: armed only by an explicit --flight-dir
        flight = None
        if flight_dir:
            from celestia_tpu.utils.flight import FlightRecorder

            flight = FlightRecorder(flight_dir)
        self.service = NodeService(
            node, das_max_inflight=das_max_inflight, flight=flight,
            das_qos=das_qos,
        )
        # host sampling profiler: started/stopped with the server when a
        # rate is given (the module may also be armed via env — in that
        # case the server leaves ownership with whoever armed it)
        self.host_profile_hz = host_profile_hz
        self._owns_hostprof = False
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers)
        )
        self._server.add_generic_rpc_handlers((self.service.handlers(),))
        self.port = self._server.add_insecure_port(address)
        if self.port == 0:
            raise RuntimeError(f"could not bind gRPC server to {address}")
        host = address.rsplit(":", 1)[0]
        self.address = f"{host}:{self.port}"
        # the gossip engine stamps outbound floods with this (sender
        # exclusion on re-flood)
        node._server_address = self.address
        # stable node identity for the cross-node trace/metrics planes:
        # the bind address is unique per mesh member.  First write wins —
        # CELESTIA_TPU_NODE_ID (pinned at import) or a test override is
        # never clobbered.
        tracing.set_node_id(self.address)
        self.block_interval_s = block_interval_s
        self._stop = threading.Event()
        self._producer: Optional[threading.Thread] = None
        # continuous telemetry sampler (utils/timeseries.py): one cheap
        # snapshot per tick; None/0 disables
        self.timeseries_interval_s = (
            float(timeseries_interval_s)
            if timeseries_interval_s
            else None
        )
        self._sampler: Optional[threading.Thread] = None
        # plain-HTTP /metrics (off unless a port is given; 0 = ephemeral)
        self.metrics_http: Optional[_MetricsHTTPServer] = None
        if metrics_port is not None:
            host = address.rsplit(":", 1)[0] or "127.0.0.1"
            self.metrics_http = _MetricsHTTPServer(
                self.service, host, int(metrics_port)
            )
        # node-internal locking: the production loop and gRPC workers touch
        # the same app state; the TestNode surface is synchronised by this
        # coarse lock installed onto the node.
        if not hasattr(node, "_service_lock"):
            node._service_lock = threading.RLock()
        self._wrap_node_with_lock()

    def _wrap_node_with_lock(self) -> None:
        lock = self.node._service_lock
        for name in (
            "broadcast_tx", "get_tx", "account_info", "simulate",
            "produce_block", "block", "abci_query",
        ):
            fn = getattr(self.node, name, None)
            if fn is None or getattr(fn, "_locked", False):
                continue

            def locked(*a, _fn=fn, **kw):
                with lock:
                    return _fn(*a, **kw)

            locked._locked = True
            setattr(self.node, name, locked)

    def start(self) -> None:
        self._server.start()
        if self.host_profile_hz:
            from celestia_tpu.utils import hostprof

            if not hostprof.enabled():
                self._owns_hostprof = True
            hostprof.start(self.host_profile_hz)
        if self.metrics_http is not None:
            self.metrics_http.start()
        if self.block_interval_s:
            self._producer = threading.Thread(
                target=self._produce_loop, name="block-producer", daemon=True
            )
            self._producer.start()
        if self.timeseries_interval_s:
            self._sampler = threading.Thread(
                target=self._sample_loop, name="timeseries-sampler",
                daemon=True,
            )
            self._sampler.start()

    def _produce_loop(self) -> None:
        while not self._stop.wait(self.block_interval_s):
            try:
                self.node.produce_block()
            except Exception:  # noqa: BLE001 — producer must survive
                import traceback

                traceback.print_exc()

    def _sample_loop(self) -> None:
        # Event.wait paces the cadence (no sleep-in-loop, celint R5);
        # sample_timeseries itself swallows collector bugs via
        # faults.note, so the loop body cannot die.  The seed sample
        # runs HERE, not in start(): the collector's device-plane read
        # initializes the jax backend, and a dead accelerator tunnel can
        # HANG that init for minutes — a daemon sampler may stall, node
        # startup must not (same rationale as the CLI's child-process
        # backend probe).
        self.service.sample_timeseries()
        while not self._stop.wait(self.timeseries_interval_s):
            self.service.sample_timeseries()

    def stop(self, grace: float = 1.0) -> None:
        self._stop.set()
        self._server.stop(grace)
        if self._owns_hostprof:
            from celestia_tpu.utils import hostprof

            hostprof.stop()
            self._owns_hostprof = False
        if self.metrics_http is not None:
            self.metrics_http.stop()
        if self._producer is not None:
            self._producer.join(timeout=5)
        if self._sampler is not None:
            self._sampler.join(timeout=5)

    def __enter__(self) -> "NodeServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
