"""Consensus wire primitives: the data structures and digests a commit
certificate is MADE of, below the engine that assembles them.

Moved out of node/bft.py and node/testnode.py (celint R8): the IBC
07-tendermint light client (state/modules/ibc_client.py) verifies vote
signatures and block ids, and the persistence layer (state/disk.py)
replays Block records — both live in ``state/``, which sits BELOW
``node/`` in the package DAG, so the pure wire/crypto pieces they share
with the BFT engine live here.  node/bft.py and node/testnode.py
re-export every name, so engine-side callers are unchanged.

Everything in this module is a pure function of its inputs (sha256
digests, frozen dataclasses) — no engine state, no clocks, no I/O.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, List, Optional, Tuple

if TYPE_CHECKING:  # pragma: no cover — annotation-only
    from celestia_tpu.state.app import TxResult

NIL = b""  # block_id of a nil vote

PREVOTE = "prevote"
PRECOMMIT = "precommit"


def _varint(n: int) -> bytes:
    if n < 0:
        # a negative int never terminates the shift loop below; every
        # wire decoder range-checks before reaching here, this is the
        # last line of defense against a hang
        raise ValueError(f"varint of negative int {n}")
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def block_id_of(
    height: int,
    time_ns: int,
    square_size: int,
    data_root: bytes,
    proposer: bytes,
    last_commit_digest: bytes,
    prev_app_hash: bytes = b"",
) -> bytes:
    """The consensus block id: commits to EVERY field that feeds
    finalization — height, timestamp, layout, the data root (which
    commits to every tx byte via the DAH), the proposer, the previous
    block's commit certificate (LastCommitInfo feeds distribution and
    slashing, so replicas must agree on it byte-for-byte) and the app
    hash the previous block produced (Tendermint's header.AppHash: this
    is what lets a commit certificate double as a LIGHT-CLIENT proof of
    the chain's state root, the ibc 07-tendermint role)."""
    return hashlib.sha256(
        b"block-id" + _varint(height) + _varint(time_ns)
        + _varint(square_size) + data_root + proposer + last_commit_digest
        + prev_app_hash
    ).digest()


def vote_sign_bytes(
    chain_id: str, height: int, round_: int, vtype: str, block_id: bytes
) -> bytes:
    """Round- and type-scoped vote digest.  Signing two DIFFERENT block
    ids at one (height, round, type) is equivocation; re-voting across
    rounds is legitimate Tendermint behavior and hashes differently."""
    return hashlib.sha256(
        b"bft-vote" + vtype.encode() + b"|" + chain_id.encode()
        + _varint(height) + _varint(round_) + block_id
    ).digest()


def proposal_sign_bytes(
    chain_id: str, height: int, round_: int, pol_round: int, block_id: bytes
) -> bytes:
    return hashlib.sha256(
        b"bft-proposal|" + chain_id.encode() + _varint(height)
        + _varint(round_) + _varint(pol_round + 1) + block_id
    ).digest()


@dataclass(frozen=True)
class Vote:
    vtype: str  # PREVOTE / PRECOMMIT
    height: int
    round: int
    block_id: bytes  # NIL for a nil vote
    validator: bytes
    signature: bytes = b""

    def to_wire(self) -> dict:
        return {
            "kind": "vote",
            "vtype": self.vtype,
            "height": self.height,
            "round": self.round,
            "block_id": self.block_id.hex(),
            "validator": self.validator.hex(),
            "signature": self.signature.hex(),
        }

    @classmethod
    def from_wire(cls, d: dict) -> "Vote":
        height = int(d["height"])
        round_ = int(d["round"])
        if height <= 0 or round_ < 0:
            # negative ints would spin _varint forever in vote_sign_bytes
            raise ValueError("vote fields out of range")
        return cls(
            vtype=d["vtype"],
            height=height,
            round=round_,
            block_id=bytes.fromhex(d["block_id"]),
            validator=bytes.fromhex(d["validator"]),
            signature=bytes.fromhex(d["signature"]),
        )


@dataclass
class BlockHeader:
    height: int
    time_ns: int
    chain_id: str
    app_version: int
    data_hash: bytes
    app_hash: bytes  # state root AFTER this block
    square_size: int


@dataclass
class Block:
    header: BlockHeader
    txs: List[bytes]
    tx_results: List["TxResult"] = field(default_factory=list)
    # the commit info applied with this block (ABCI LastCommitInfo role);
    # replayed verbatim during catch-up so app hashes reproduce
    proposer: bytes = b""
    votes: Optional[List[Tuple[bytes, bool]]] = None
