"""Append-only disk persistence: state diff log + block log with crash
recovery.

Role parity with the reference's LevelDB-backed stores — the commit
multistore's database, the block store and the tx index that let
`celestia-appd start` resume a chain from its data dir
(/root/reference/app/app.go:657-661 LoadLatestVersion;
cmd/celestia-appd/cmd/root.go:219-250 opens the home's data directory).
The format is this repo's own (designed for the append-only commit flow,
not a LevelDB port):

- ``state.log``: one STATE record per commit carrying the height, app
  hash, store roots and the FORWARD diff (key -> new value | delete) of
  that block.  Every ``checkpoint_interval`` commits a CHECKPOINT record
  with the full flattened state is appended, so recovery replays at most
  one interval of diffs instead of the whole chain.
- ``blocks.log``: one BLOCK record per block (header + txs + results +
  commit info), from which the block store and the tx index are rebuilt.

Each record is framed ``magic | type | u32 len | crc32 | payload``; a
torn tail write (crash mid-append) fails its CRC or length check and is
truncated on recovery, so a kill -9 at any instant loses at most the
block being written — never committed history.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

# the magic doubles as the format version: any layout change bumps it, so
# records written by an older layout fail the magic check and scan() stops
# there instead of misparsing (CTL1 -> CTL2: per-result events field)
_MAGIC = b"CTL2"
_T_STATE = 1
_T_CHECKPOINT = 2
_T_BLOCK = 3

_HEADER = struct.Struct("<4sBII")  # magic, type, payload_len, crc32


# --------------------------------------------------------------------------
# primitive codec (length-prefixed, deterministic)
# --------------------------------------------------------------------------


def _pb(out: List[bytes], b: bytes) -> None:
    out.append(struct.pack("<I", len(b)))
    out.append(b)


def _pi(out: List[bytes], i: int) -> None:
    out.append(struct.pack("<q", i))


class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def bytes_(self) -> bytes:
        (n,) = struct.unpack_from("<I", self.buf, self.pos)
        self.pos += 4
        b = self.buf[self.pos : self.pos + n]
        if len(b) != n:
            raise ValueError("truncated field")
        self.pos += n
        return b

    def int_(self) -> int:
        (i,) = struct.unpack_from("<q", self.buf, self.pos)
        self.pos += 8
        return i


def _encode_diffs(diffs: Dict[str, Dict[bytes, Optional[bytes]]]) -> List[bytes]:
    out: List[bytes] = []
    _pi(out, len(diffs))
    for name in sorted(diffs):
        _pb(out, name.encode())
        diff = diffs[name]
        _pi(out, len(diff))
        for k in sorted(diff):
            v = diff[k]
            _pb(out, k)
            if v is None:
                out.append(b"\x00")
            else:
                out.append(b"\x01")
                _pb(out, v)
    return out


def _decode_diffs(r: _Reader) -> Dict[str, Dict[bytes, Optional[bytes]]]:
    diffs: Dict[str, Dict[bytes, Optional[bytes]]] = {}
    for _ in range(r.int_()):
        name = r.bytes_().decode()
        diff: Dict[bytes, Optional[bytes]] = {}
        for _ in range(r.int_()):
            k = r.bytes_()
            flag = r.buf[r.pos : r.pos + 1]
            r.pos += 1
            diff[k] = r.bytes_() if flag == b"\x01" else None
        diffs[name] = diff
    return diffs


# --------------------------------------------------------------------------
# framed append-only log
# --------------------------------------------------------------------------


class _Log:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "ab")

    def append(self, rtype: int, payload: bytes) -> None:
        frame = _HEADER.pack(_MAGIC, rtype, len(payload), zlib.crc32(payload))
        self._f.write(frame + payload)
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        self._f.close()

    @staticmethod
    def scan(path: str) -> Iterator[Tuple[int, bytes, int]]:
        """Yield (type, payload, end_offset) for every intact record; stop
        at the first torn/corrupt frame.

        A log whose FIRST record carries an older format magic (CTL*)
        is a pre-upgrade data dir, not a torn tail — raise instead of
        silently treating the whole chain as garbage (recovery would
        otherwise truncate it to zero and reset to genesis)."""
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            data = f.read()
        pos = 0
        while pos + _HEADER.size <= len(data):
            magic, rtype, n, crc = _HEADER.unpack_from(data, pos)
            if magic != _MAGIC:
                if pos == 0 and magic[:3] == _MAGIC[:3]:
                    raise RuntimeError(
                        f"{path} was written by log format "
                        f"{magic.decode(errors='replace')} but this build "
                        f"reads {_MAGIC.decode()}; refusing to destroy it "
                        "— migrate or move the data dir aside"
                    )
                break
            payload = data[pos + _HEADER.size : pos + _HEADER.size + n]
            if len(payload) != n or zlib.crc32(payload) != crc:
                break
            pos += _HEADER.size + n
            yield rtype, payload, pos

    @staticmethod
    def truncate_to(path: str, offset: int) -> None:
        """Drop a torn tail (crash mid-append)."""
        if os.path.exists(path) and os.path.getsize(path) > offset:
            with open(path, "r+b") as f:
                f.truncate(offset)


# --------------------------------------------------------------------------
# state log
# --------------------------------------------------------------------------


class StateLog:
    """Per-commit forward diffs + periodic full checkpoints."""

    def __init__(self, data_dir: str, checkpoint_interval: int = 500):
        self.path = os.path.join(data_dir, "state.log")
        self.checkpoint_interval = checkpoint_interval
        # resume the checkpoint cadence across restarts: count the diffs
        # already on disk since the last checkpoint, so a node restarted
        # every N < interval blocks still checkpoints eventually
        self._commits_since_checkpoint = 0
        for rtype, _, _ in _Log.scan(self.path):
            if rtype == _T_CHECKPOINT:
                self._commits_since_checkpoint = 0
            else:
                self._commits_since_checkpoint += 1
        self._log = _Log(self.path)

    def append_commit(
        self,
        height: int,
        app_hash: bytes,
        roots: Dict[str, bytes],
        forward: Dict[str, Dict[bytes, Optional[bytes]]],
        full_state_fn=None,
    ) -> None:
        """full_state_fn() -> {store: {key: value}} is only invoked when
        this commit lands on a checkpoint boundary (so the caller doesn't
        flatten state every block)."""
        out: List[bytes] = []
        _pi(out, height)
        _pb(out, app_hash)
        _pi(out, len(roots))
        for name in sorted(roots):
            _pb(out, name.encode())
            _pb(out, roots[name])
        out.extend(_encode_diffs(forward))
        self._log.append(_T_STATE, b"".join(out))
        self._commits_since_checkpoint += 1
        if (
            full_state_fn is not None
            and self._commits_since_checkpoint >= self.checkpoint_interval
        ):
            self.append_checkpoint(height, app_hash, full_state_fn())

    def append_checkpoint(
        self,
        height: int,
        app_hash: bytes,
        state: Dict[str, Dict[bytes, bytes]],
    ) -> None:
        out: List[bytes] = []
        _pi(out, height)
        _pb(out, app_hash)
        out.extend(
            _encode_diffs({n: dict(d) for n, d in state.items()})
        )
        self._log.append(_T_CHECKPOINT, b"".join(out))
        self._commits_since_checkpoint = 0

    def close(self) -> None:
        self._log.close()

    @classmethod
    def recover(
        cls, data_dir: str, up_to: Optional[int] = None
    ) -> Optional[Tuple[Dict[str, Dict[bytes, bytes]], int, bytes]]:
        """Rebuild (state, last_height, last_app_hash) from the log: the
        latest checkpoint, then every later diff.  Returns None when no
        intact record exists.  Truncates any torn tail.

        ``up_to`` ignores records beyond that height — used when the block
        log is one behind the state log (crash between the state fsync and
        the block fsync), so the node restarts on a consistent pair.
        """
        path = os.path.join(data_dir, "state.log")
        records: List[Tuple[int, bytes]] = []
        end = 0
        for rtype, payload, off in _Log.scan(path):
            height = _Reader(payload).int_()
            if up_to is not None and height > up_to:
                continue
            records.append((rtype, payload))
            end = off
        _Log.truncate_to(path, end)
        if not records:
            return None
        # start from the last checkpoint (if any)
        start = 0
        for i in range(len(records) - 1, -1, -1):
            if records[i][0] == _T_CHECKPOINT:
                start = i
                break
        state: Dict[str, Dict[bytes, bytes]] = {}
        last_height = 0
        last_hash = b""
        for rtype, payload in records[start:]:
            r = _Reader(payload)
            height = r.int_()
            app_hash = r.bytes_()
            if rtype == _T_CHECKPOINT:
                state = {
                    n: {k: v for k, v in d.items() if v is not None}
                    for n, d in _decode_diffs(r).items()
                }
            else:
                n_roots = r.int_()
                for _ in range(n_roots):
                    r.bytes_()
                    r.bytes_()
                for name, diff in _decode_diffs(r).items():
                    dst = state.setdefault(name, {})
                    for k, v in diff.items():
                        if v is None:
                            dst.pop(k, None)
                        else:
                            dst[k] = v
            last_height, last_hash = height, app_hash
        return state, last_height, last_hash


# --------------------------------------------------------------------------
# block log
# --------------------------------------------------------------------------


class BlockLog:
    """Append-only block store; rebuilds the block list + tx index."""

    def __init__(self, data_dir: str):
        self.path = os.path.join(data_dir, "blocks.log")
        self._log = _Log(self.path)

    def append_block(self, block) -> None:
        """block: node.testnode.Block (imported lazily to avoid cycles)."""
        from celestia_tpu.state.app import jsonable_events

        h = block.header
        out: List[bytes] = []
        _pi(out, h.height)
        _pi(out, h.time_ns)
        _pb(out, h.chain_id.encode())
        _pi(out, h.app_version)
        _pb(out, h.data_hash)
        _pb(out, h.app_hash)
        _pi(out, h.square_size)
        _pb(out, block.proposer or b"")
        votes = block.votes or []
        _pi(out, len(votes))
        for addr, signed in votes:
            _pb(out, addr)
            out.append(b"\x01" if signed else b"\x00")
        _pi(out, len(block.txs))
        for t in block.txs:
            _pb(out, t)
        results = block.tx_results or []
        _pi(out, len(results))
        for res in results:
            _pi(out, res.code)
            _pb(out, res.log.encode())
            _pi(out, res.gas_wanted)
            _pi(out, res.gas_used)
            _pb(out, json.dumps(jsonable_events(res.events)).encode())
        self._log.append(_T_BLOCK, b"".join(out))

    def close(self) -> None:
        self._log.close()

    @classmethod
    def recover(cls, data_dir: str) -> List[object]:
        """All intact blocks, in order; truncates a torn tail."""
        from celestia_tpu.state.app import TxResult
        from celestia_tpu.state.consensus import Block, BlockHeader

        path = os.path.join(data_dir, "blocks.log")
        blocks: List[object] = []
        end = 0
        for rtype, payload, off in _Log.scan(path):
            end = off
            if rtype != _T_BLOCK:
                continue
            r = _Reader(payload)
            header = BlockHeader(
                height=r.int_(),
                time_ns=r.int_(),
                chain_id=r.bytes_().decode(),
                app_version=r.int_(),
                data_hash=r.bytes_(),
                app_hash=r.bytes_(),
                square_size=r.int_(),
            )
            proposer = r.bytes_()
            votes: List[Tuple[bytes, bool]] = []
            for _ in range(r.int_()):
                addr = r.bytes_()
                flag = r.buf[r.pos : r.pos + 1]
                r.pos += 1
                votes.append((addr, flag == b"\x01"))
            txs = [r.bytes_() for _ in range(r.int_())]
            results = [
                TxResult(
                    code=r.int_(),
                    log=r.bytes_().decode(),
                    gas_wanted=r.int_(),
                    gas_used=r.int_(),
                    events=json.loads(r.bytes_()),
                )
                for _ in range(r.int_())
            ]
            blocks.append(
                Block(header, txs, results, proposer, votes or None)
            )
        _Log.truncate_to(path, end)
        return blocks
