"""Ante handler chain: tx admission checks run before execution.

Parity with /root/reference/app/ante/ante.go:15-80 (the 18-decorator chain),
adapted to this framework's tx model.  Order matters and mirrors the
reference: panic guard (in the runner), msg version gatekeeper, basic
validation, tx-size gas, fee checks (global min gas price from x/minfee,
v2/app_consts.go:5-9) + fee deduction, signature verification against the
account's pubkey/sequence/account-number, sequence increment, then the blob
decorators (MinGasPFBDecorator ante/ante.go:14-48 and BlobShareDecorator
ante/blob_share_decorator.go:17-70) and the gov param filter
(x/paramfilter/gov_handler.go:36-60).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional

from celestia_tpu.appconsts import (
    GLOBAL_MIN_GAS_PRICE_PPM,
    SHARE_SIZE,
    square_size_upper_bound,
)
from celestia_tpu.da.shares import sparse_shares_needed
from celestia_tpu.da.square import subtree_width
from celestia_tpu.state.bank import FEE_COLLECTOR
from celestia_tpu.state.modules.blob import gas_to_consume
from celestia_tpu.state.tx import (
    MsgParamChange,
    MsgPayForBlobs,
    Tx,
)
from celestia_tpu.utils.secp256k1 import PublicKey

TX_SIZE_COST_PER_BYTE = 10
MAX_MEMO_CHARACTERS = 256
MAX_TX_GAS = 50_000_000
SIG_VERIFY_COST_SECP256K1 = 1000  # per signature (SDK default)
TX_SIG_LIMIT = 7  # max signatures per tx (SDK auth param default)


class AnteError(ValueError):
    pass


class OutOfGasError(AnteError):
    pass


class GasMeter:
    def __init__(self, limit: int):
        self.limit = limit
        self.consumed = 0

    def consume(self, amount: int, descriptor: str = "") -> None:
        self.consumed += amount
        if self.consumed > self.limit:
            raise OutOfGasError(
                f"out of gas: {descriptor} needs {self.consumed} > limit {self.limit}"
            )


@dataclass
class AnteContext:
    tx: Tx
    raw_tx: bytes
    accounts: "AccountKeeper"  # noqa: F821
    bank: "BankKeeper"  # noqa: F821
    params: "ParamsKeeper"  # noqa: F821
    chain_id: str
    app_version: int
    gas_meter: GasMeter = None  # type: ignore[assignment]
    is_check_tx: bool = False
    is_recheck: bool = False
    min_gas_price: float = 0.0  # node-local (CheckTx only)
    simulate: bool = False
    # batch pre-verification result (threaded native secp256k1 over the
    # whole proposal at once); None = verify inline
    sig_ok: Optional[bool] = None
    # height the tx would execute at (0 = unknown: timeout not evaluated)
    height: int = 0
    # x/feegrant keeper (None = feegrant not wired; fee_granter txs reject)
    feegrant: Optional[object] = None
    # block time for allowance expiry checks (0 = unknown)
    time_ns: int = 0

    def __post_init__(self):
        if self.gas_meter is None:
            self.gas_meter = GasMeter(self.tx.fee.gas_limit)


def flat_msgs(tx: Tx):
    """The tx's messages with authz MsgExec unwrapped one level (nested
    exec is rejected at decode).  EVERY per-message ante rule must see
    wrapped messages too, or MsgExec becomes a decorator bypass — the
    reference's gatekeeper and blob decorators unwrap the same way."""
    flat = []
    for m in tx.msgs:
        flat.append(m)
        flat.extend(getattr(m, "inner", ()))
    return flat


def ante_footprint(tx: Tx) -> Optional[tuple]:
    """The account addresses whose state the ante chain reads or writes
    for ``tx``: the signer (pubkey/sequence/account-number checks,
    sequence increment, fee payment, vesting-lock reads) and the fee
    granter when set (allowance read + use_grant write).  Params are
    read-only for every tx and FEE_COLLECTOR is credited but never read
    by any verdict, so two txs with disjoint footprints produce the same
    keep/drop verdicts in any interleaving — the independence argument
    the parallel FilterTxs grouping rests on (specs/tx_ingress.md).

    Returns None when the footprint cannot be determined (malformed
    pubkey): callers must treat such a tx as overlapping everything.
    """
    try:
        addrs = [tx.signer_address()]
    except ValueError:
        return None
    if tx.fee_granter:
        addrs.append(bytes(tx.fee_granter))
    return tuple(addrs)


# --- decorators -------------------------------------------------------------


def msg_gatekeeper(ctx: AnteContext) -> None:
    """MsgVersioningGateKeeper (app/ante/msg_gatekeeper.go:1-57): messages
    accepted depend on the app version (ADR-022 multi-version state machine)."""
    from celestia_tpu.state.app_versions import msgs_accepted_at

    accepted = msgs_accepted_at(ctx.app_version)
    for m in flat_msgs(ctx.tx):
        if type(m) not in accepted:
            raise AnteError(
                f"message {type(m).__name__} not accepted at app version "
                f"{ctx.app_version}"
            )


def validate_basic(ctx: AnteContext) -> None:
    tx = ctx.tx
    if not tx.msgs:
        raise AnteError("tx has no messages")
    if not tx.signature and not ctx.simulate:
        raise AnteError("tx is unsigned")
    if len(tx.memo) > MAX_MEMO_CHARACTERS:
        raise AnteError(f"memo exceeds {MAX_MEMO_CHARACTERS} characters")
    if tx.fee.gas_limit == 0:
        raise AnteError("gas limit must be positive")
    if tx.fee.gas_limit > MAX_TX_GAS:
        raise AnteError(f"gas limit {tx.fee.gas_limit} exceeds max {MAX_TX_GAS}")
    if tx.fee.amount < 0:
        raise AnteError("fee must be non-negative")


def check_timeout_height(ctx: AnteContext) -> None:
    """TxTimeoutHeightDecorator: a tx declaring a timeout height must not
    execute in a block above it (SDK ante basic decorator set — the piece
    VERDICT r1 flagged as absent from the chain)."""
    th = ctx.tx.timeout_height
    if th > 0 and ctx.height > 0 and ctx.height > th:
        raise AnteError(
            f"tx timed out: timeout height {th} < block height {ctx.height}"
        )


def consume_tx_size_gas(ctx: AnteContext) -> None:
    ctx.gas_meter.consume(len(ctx.raw_tx) * TX_SIZE_COST_PER_BYTE, "tx size")


def validate_sig_count(ctx: AnteContext) -> None:
    """ValidateSigCountDecorator: a multisig's member pubkeys count against
    the tx signature limit (SDK TxSigLimit default 7)."""
    if not ctx.tx.is_multisig():
        return
    from celestia_tpu.utils.secp256k1 import MultisigPubKey

    try:
        mk = MultisigPubKey.unmarshal(ctx.tx.pubkey)
    except ValueError as e:
        raise AnteError(f"malformed multisig pubkey: {e}") from e
    if len(mk.keys) > TX_SIG_LIMIT:
        raise AnteError(
            f"multisig has {len(mk.keys)} pubkeys > tx signature limit "
            f"{TX_SIG_LIMIT}"
        )


def check_and_deduct_fee(ctx: AnteContext) -> None:
    """ValidateTxFee + DeductFeeDecorator: enforce the network-wide min gas
    price (x/minfee) and the node-local one (CheckTx), then move the fee to
    the fee collector."""
    tx = ctx.tx
    # Consensus-critical comparison in pure integer math (utia-per-gas ppm):
    # fee * 1e6 >= gas_limit * min_ppm.
    min_ppm = int(
        ctx.params.get("minfee", "NetworkMinGasPricePpm", GLOBAL_MIN_GAS_PRICE_PPM)
    )
    if tx.fee.amount * 1_000_000 < tx.fee.gas_limit * min_ppm:
        required = -(-tx.fee.gas_limit * min_ppm // 1_000_000)  # ceil div
        raise AnteError(
            f"insufficient fee: got {tx.fee.amount}utia, required {required}utia "
            f"(network min gas price {min_ppm}ppm)"
        )
    if ctx.is_check_tx and ctx.min_gas_price > 0:
        local_required = tx.fee.gas_limit * ctx.min_gas_price
        if tx.fee.amount < local_required:
            raise AnteError(
                f"insufficient fee for this node: got {tx.fee.amount}utia, "
                f"required {local_required:.0f}utia (min gas price {ctx.min_gas_price})"
            )
    if ctx.simulate:
        return
    signer = tx.signer_address()
    payer = signer
    if tx.fee_granter:
        # the granter's allowance pays (DeductFeeDecorator's feegrant leg)
        if ctx.feegrant is None:
            raise AnteError("fee granter set but feegrant is not available")
        try:
            ctx.feegrant.use_grant(
                tx.fee_granter, signer, tx.fee.amount, ctx.time_ns
            )
        except ValueError as e:
            raise AnteError(f"fee allowance rejected: {e}") from e
        payer = tx.fee_granter
    try:
        ctx.bank.send(payer, FEE_COLLECTOR, tx.fee.amount)
    except ValueError as e:
        raise AnteError(f"fee deduction failed: {e}") from e


def verify_signature(ctx: AnteContext) -> None:
    if ctx.simulate:
        return
    tx = ctx.tx
    signer = tx.signer_address()
    for m in tx.msgs:
        for s in m.signers():
            if s != signer:
                raise AnteError("message signer does not match tx signer")
    acc = ctx.accounts.get_or_create(signer)
    if acc.pubkey and acc.pubkey != tx.pubkey:
        raise AnteError("pubkey does not match account")
    if tx.account_number != acc.account_number:
        raise AnteError(
            f"account number mismatch: expected {acc.account_number}, "
            f"got {tx.account_number}"
        )
    if tx.sequence != acc.sequence:
        # the client-recoverable nonce error (app/errors/nonce_mismatch.go)
        raise AnteError(
            f"account sequence mismatch, expected {acc.sequence}, got {tx.sequence}: "
            f"incorrect account sequence"
        )
    if tx.is_multisig():
        # charge sig-verify gas PER member signature before doing the EC
        # work (SDK SigVerificationDecorator parity) — without this a
        # 255-entry multisig gets hundreds of verifications for free, a
        # CheckTx/FilterTxs CPU DoS vector
        n_entries = max(1, len(tx.signature) // 65)
        ctx.gas_meter.consume(
            n_entries * SIG_VERIFY_COST_SECP256K1, "multisig verify"
        )
    else:
        # SigGasConsumeDecorator: single-key verification costs gas too
        ctx.gas_meter.consume(SIG_VERIFY_COST_SECP256K1, "sig verify")
    sig_ok = ctx.sig_ok
    if sig_ok is None:
        sig_ok = tx.verify_signature(ctx.chain_id)
    if not sig_ok:
        raise AnteError("signature verification failed")
    if not acc.pubkey:
        acc.pubkey = tx.pubkey
        ctx.accounts.set(acc)


def increment_sequence(ctx: AnteContext) -> None:
    if ctx.simulate:
        return
    ctx.accounts.increment_sequence(ctx.tx.signer_address())


def min_gas_pfb(ctx: AnteContext) -> None:
    """MinGasPFBDecorator: the tx must provision at least the blob gas its
    PFB will consume (x/blob/ante/ante.go:14-48)."""
    from celestia_tpu.appconsts import DEFAULT_GAS_PER_BLOB_BYTE

    gas_per_byte = ctx.params.get("blob", "GasPerBlobByte", DEFAULT_GAS_PER_BLOB_BYTE)
    for m in flat_msgs(ctx.tx):
        if isinstance(m, MsgPayForBlobs):
            needed = gas_to_consume(m.blob_sizes, gas_per_byte)
            if ctx.tx.fee.gas_limit < needed:
                raise AnteError(
                    f"gas limit {ctx.tx.fee.gas_limit} below blob gas {needed}"
                )


def blob_share_limit(ctx: AnteContext) -> None:
    """BlobShareDecorator: blobs must fit the max effective square
    (x/blob/ante/blob_share_decorator.go:17-70)."""
    from celestia_tpu.appconsts import DEFAULT_GOV_MAX_SQUARE_SIZE

    gov_max = ctx.params.get("blob", "GovMaxSquareSize", DEFAULT_GOV_MAX_SQUARE_SIZE)
    hard_max = square_size_upper_bound(ctx.app_version)
    k = min(gov_max, hard_max)
    max_shares = k * k
    for m in flat_msgs(ctx.tx):
        if isinstance(m, MsgPayForBlobs):
            total = sum(sparse_shares_needed(s) for s in m.blob_sizes)
            if total > max_shares:
                raise AnteError(
                    f"blob(s) need {total} shares > square capacity {max_shares}"
                )


def gov_param_filter(ctx: AnteContext) -> None:
    """GovProposalDecorator + x/paramfilter: hardfork-only params are
    unchangeable by any governance path, and a direct MsgParamChange is
    NEVER acceptable in a user transaction — its only legitimate authority
    is the gov module account, which holds no key and so cannot sign.
    Param changes go through MsgSubmitProposal
    (x/paramfilter/gov_handler.go:36-60)."""
    from celestia_tpu.state.modules.gov import GOV_MODULE_ADDR
    from celestia_tpu.state.params import ParamBlockList

    block_list = ParamBlockList()
    for m in flat_msgs(ctx.tx):
        if isinstance(m, MsgParamChange):
            if m.authority != GOV_MODULE_ADDR:
                raise AnteError(
                    "MsgParamChange may only be executed by the gov module "
                    "account via a passed proposal"
                )
            block_list.validate_change(m.subspace, m.key)


DEFAULT_ANTE_CHAIN: List[Callable[[AnteContext], None]] = [
    msg_gatekeeper,
    validate_basic,
    check_timeout_height,
    consume_tx_size_gas,
    check_and_deduct_fee,
    validate_sig_count,
    verify_signature,
    increment_sequence,
    min_gas_pfb,
    blob_share_limit,
    gov_param_filter,
]


def run_ante(ctx: AnteContext, chain: Optional[List[Callable]] = None) -> GasMeter:
    """Run the chain; AnteError on rejection.  Panics inside decorators are
    wrapped (HandlePanicDecorator, app/ante/panic.go)."""
    for decorator in chain or DEFAULT_ANTE_CHAIN:
        try:
            decorator(ctx)
        except AnteError:
            raise
        except Exception as e:  # panic guard with tx context
            raise AnteError(
                f"panic in ante decorator {decorator.__name__}: {e!r}"
            ) from e
    return ctx.gas_meter
