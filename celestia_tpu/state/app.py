"""The App: ABCI-shaped state machine around the TPU DA pipeline.

Parity with /root/reference/app/: construction & keeper wiring (app.go:227-
664), CheckTx (check_tx.go:16-54), PrepareProposal (prepare_proposal.go:23-
96), ProcessProposal (process_proposal.go:24-157), FilterTxs
(validate_txs.go:29-97), Begin/EndBlocker + upgrade consumption
(app.go:670-708), InitChainer (app.go:711-726), MaxEffectiveSquareSize
(square_size.go:9-23), and genesis export (export.go:18-45).

The consensus engine above this surface is celestia_tpu/node (testnode-style
single-process driver); the DA compute below it is the fused device pipeline
(da/dah.py).  Every consensus-relevant computation here is integer/bytes
arithmetic or the bit-exact device kernels.
"""

from __future__ import annotations

import hashlib as _hashlib
import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from celestia_tpu.appconsts import (
    DEFAULT_MIN_GAS_PRICE,
    LATEST_VERSION,
    SHARE_SIZE,
    square_size_upper_bound,
)
from celestia_tpu.da import dah as dah_mod
from celestia_tpu.da.blob import BlobTx, unmarshal_blob_tx
from celestia_tpu.da.square import Square, build as build_square, construct as construct_square
from celestia_tpu.state import app_versions
from celestia_tpu.state.ante import AnteContext, AnteError, GasMeter, run_ante
from celestia_tpu.state.auth import AccountKeeper
from celestia_tpu.state.bank import BankKeeper, FEE_COLLECTOR
from celestia_tpu.state.modules.blob import BlobKeeper, validate_blob_tx
from celestia_tpu.state.modules.feegrant import FeeGrantKeeper
from celestia_tpu.state.modules.blobstream import BlobstreamKeeper
from celestia_tpu.state.modules.mint import MintKeeper
from celestia_tpu.state.modules.upgrade import UpgradeKeeper
from celestia_tpu.state.params import ParamBlockList, ParamsKeeper, set_default_params
from celestia_tpu.state.posthandler import PostContext, new_post_handler
from celestia_tpu.state.staking import StakingKeeper
from celestia_tpu.state.store import MultiStore
from celestia_tpu.state.tx import (
    Msg,
    MsgAuthzGrant,
    MsgAuthzRevoke,
    MsgCreateVestingAccount,
    MsgDelegate,
    MsgExec,
    MsgFundCommunityPool,
    MsgGrantAllowance,
    MsgParamChange,
    MsgPayForBlobs,
    MsgRegisterEVMAddress,
    MsgRevokeAllowance,
    MsgSend,
    MsgSetWithdrawAddress,
    MsgSignalVersion,
    MsgSubmitEvidence,
    MsgSubmitProposal,
    MsgTryUpgrade,
    MsgUndelegate,
    MsgUnjail,
    MsgVerifyInvariant,
    MsgVote,
    MsgWithdrawDelegatorReward,
    MsgWithdrawValidatorCommission,
    Tx,
    unmarshal_tx,
)
from celestia_tpu.utils import tracing
from celestia_tpu.utils.lru import LruCache, bytes_len_weigher
from celestia_tpu.utils.telemetry import Telemetry


def _decoded_weigher(key, value) -> int:
    """(tx, raw_inner) entries: the raw inner bytes dominate; the parsed
    Tx holds commitments/signatures, approximated by a flat overhead."""
    _, raw_inner = value
    return len(key) + len(raw_inner) + 512


STORE_NAMES = [
    "auth", "bank", "staking", "params", "blob", "upgrade", "blobstream",
    "mint", "gov", "meta", "feegrant", "authz", "distribution", "slashing",
    "evidence", "ibc",
]

_APP_VERSION_KEY = b"app_version"


@dataclass
class TxResult:
    code: int  # 0 = ok
    log: str
    gas_wanted: int
    gas_used: int
    events: List[dict] = field(default_factory=list)


def jsonable_events(events: List[dict]) -> List[dict]:
    """Typed msg events with bytes fields -> JSON-safe form (hex), for
    the tx index, the event-query routes and the block log."""

    def conv(v):
        if isinstance(v, bytes):
            return v.hex()
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [conv(x) for x in v]
        return v

    return [conv(e) for e in events]


@dataclass
class PreparedProposal:
    block_txs: List[bytes]
    square_size: int
    data_root: bytes
    eds: "dah_mod.ExtendedDataSquare"
    dah: "dah_mod.DataAvailabilityHeader"
    # retained layout artifacts so the node can serve inclusion proofs from
    # the cached EDS without recompute (pkg/inclusion / proof querier role)
    square: Optional[object] = None
    wrappers: Optional[List[object]] = None


class App:
    """The celestia-tpu application (app.go App struct parity)."""

    def __init__(
        self,
        chain_id: str = "celestia-tpu-1",
        min_gas_price: float = DEFAULT_MIN_GAS_PRICE,
        v2_upgrade_height: Optional[int] = None,
    ):
        self.chain_id = chain_id
        self.min_gas_price = min_gas_price  # node-local CheckTx filter
        self.v2_upgrade_height = v2_upgrade_height  # v1 height-based path
        from celestia_tpu.ops import gf256 as _gf256

        self.codec = _gf256.active_codec()  # re-pinned by init_chain
        self.store = MultiStore(STORE_NAMES)
        self._wire_keepers()
        self.telemetry = Telemetry()
        self.block_time_ns = 0
        self.block_height = 0
        self.genesis_time_ns = 0
        # persistent CheckTx state, branched from committed state and reset
        # on every commit (baseapp checkState parity) — lets several pending
        # txs from one account chain their sequences in the mempool
        self._check_state: Optional[MultiStore] = None
        # verified-signature cache (tx-bytes hash -> True), bounded LRU:
        # Prepare->Process on one node and repeat validations of pooled
        # txs skip redundant EC multiplications (comet's tx cache role)
        self._sig_cache = LruCache("sig", 8192, weigher=bytes_len_weigher)
        # validated-tx cache (tx-bytes hash -> (tx, raw_inner)), bounded
        # LRU: BlobTx validation recomputes every blob's share commitment
        # — deterministic in the raw bytes, so CheckTx's verdict is
        # reusable verbatim in Prepare/Process for the same bytes (the
        # reference revalidates at each point; caching by exact bytes is
        # the consensus-safe shortcut).  Values hold only the parsed
        # inner tx (commitments, no blob payloads), so entries are small.
        self._decoded_cache = LruCache(
            "decoded", 8192, weigher=_decoded_weigher
        )
        # post-handler chain (posthandler.go:1-12 parity: empty default)
        self.post_handler = new_post_handler()

    def _wire_keepers(self, rebuild_ibc: bool = True) -> None:
        """Re-point every keeper at the current self.store.

        rebuild_ibc=False (the per-tx branch swap in deliver) reuses the
        existing IBC stack and only swaps its store/bank handles — a full
        rebuild rescans + JSON-decodes the whole "ibc" substore, which
        would be paid twice per delivered tx for state no msg can touch.
        Restores/imports keep the default full rebuild (rehydrate)."""
        self.accounts = AccountKeeper(self.store.store("auth"))
        self.bank = BankKeeper(self.store.store("bank"))
        self.params = ParamsKeeper(self.store.store("params"))
        self.staking = StakingKeeper(self.store.store("staking"), self.bank)
        self.blob = BlobKeeper(self.params)
        self.upgrade = UpgradeKeeper(self.store.store("upgrade"), self.staking)
        self.blobstream = BlobstreamKeeper(
            self.store.store("blobstream"), self.staking, self.params
        )
        self.mint = MintKeeper(self.store.store("mint"), self.bank)
        from celestia_tpu.state.modules.authz import AuthzKeeper
        from celestia_tpu.state.modules.distribution import DistributionKeeper

        self.feegrant = FeeGrantKeeper(self.store.store("feegrant"))
        self.authz = AuthzKeeper(self.store.store("authz"))
        self.distribution = DistributionKeeper(
            self.store.store("distribution"), self.bank, self.staking
        )
        self.distribution.register_hooks()
        from celestia_tpu.state.modules.evidence import EvidenceKeeper
        from celestia_tpu.state.modules.slashing import SlashingKeeper

        self.slashing = SlashingKeeper(self.store.store("slashing"), self.staking)
        self.evidence = EvidenceKeeper(self.store.store("evidence"), self.slashing)
        self.param_block_list = ParamBlockList()
        from celestia_tpu.state.modules.gov import GovKeeper

        self.gov = GovKeeper(
            self.store.store("gov"), self.bank, self.staking, self.params,
            self.param_block_list,
        )
        # IBC transfer stack with the token filter mounted (app.go:71-78);
        # channel handshakes are operator-driven (ibc.open_channel)
        from celestia_tpu.state.modules.ibc import IBCStack

        prior = getattr(self, "ibc", None)
        if not rebuild_ibc and prior is not None:
            prior.rebind(self.store.store("ibc"), self.bank)
        else:
            self.ibc = IBCStack(
                name=self.chain_id, bank=self.bank, filtered=True, app=self,
                store=self.store.store("ibc"),
            )

    # ------------------------------------------------------------------
    # version / sizing
    # ------------------------------------------------------------------

    @property
    def app_version(self) -> int:
        raw = self.store.store("meta").get(_APP_VERSION_KEY)
        return int.from_bytes(raw, "big") if raw else LATEST_VERSION

    def _set_app_version(self, v: int) -> None:
        self.store.store("meta").set(_APP_VERSION_KEY, v.to_bytes(8, "big"))
        # decoded-tx verdicts can be version-dependent (ante/blob rules
        # change across app versions): a version change invalidates them.
        # The signature cache survives — a signature over exact raw bytes
        # is version-independent — and the EDS cache keys on app_version,
        # so its stale entries simply stop matching.
        self._decoded_cache.clear()

    def next_height(self) -> int:
        """Height the next tx would execute at: the in-flight block during
        delivery, or the block about to be built during check/propose."""
        return max(self.block_height, self.store.last_height + 1)

    def max_effective_square_size(self) -> int:
        """min(gov cap, hard cap) — square_size.go:9-23."""
        gov = self.blob.gov_max_square_size()
        return min(gov, square_size_upper_bound(self.app_version))

    # ------------------------------------------------------------------
    # genesis
    # ------------------------------------------------------------------

    def init_chain(self, genesis: dict) -> None:
        """InitChainer parity: seed params, accounts, validators, mint state.

        genesis = {
          "chain_id", "app_version", "genesis_time_ns",
          "accounts": [{"address": hex, "balance": int}],
          "validators": [{"address": hex, "self_delegation": int}],
          "params": {subspace: {key: value}},
        }
        """
        self.chain_id = genesis.get("chain_id", self.chain_id)
        # The share codec is a consensus constant pinned at genesis
        # (ADR-012): "leopard-ff8" (default; parity-byte compatible with
        # the reference chain's Leopard codec) or "lagrange-gf256".
        # Persisted in-store so a disk-recovered node re-activates it
        # without a side channel.
        from celestia_tpu.ops import gf256 as _gf256

        codec = genesis.get("codec", _gf256.CODEC_LEOPARD)
        _gf256.set_active_codec(codec)  # raises on unknown codec
        self.codec = codec
        self.store.store("meta").set(b"codec", codec.encode())
        set_default_params(self.params)
        for subspace, kvs in genesis.get("params", {}).items():
            for k, v in kvs.items():
                self.params.set(subspace, k, v)
        self._set_app_version(genesis.get("app_version", LATEST_VERSION))
        self.genesis_time_ns = genesis.get(
            # celint: allow(consensus-determinism) — operator-side default
            # for a genesis file that omits the timestamp; the chosen value
            # is persisted in-store below and shipped in the genesis dump,
            # so every validator runs from the same recorded instant
            "genesis_time_ns", _time.time_ns()
        )
        # persisted in-store so a disk-recovered node needs no side channel
        # (identical across validators -> app-hash safe)
        self.store.store("meta").set(
            b"genesis_time_ns", self.genesis_time_ns.to_bytes(8, "big")
        )
        self.store.store("meta").set(b"chain_id", self.chain_id.encode())
        self.mint.init_genesis(self.genesis_time_ns)
        for acc in genesis.get("accounts", []):
            addr = bytes.fromhex(acc["address"])
            self.bank.mint(addr, acc["balance"])
            self.accounts.get_or_create(addr)
        for val in genesis.get("validators", []):
            addr = bytes.fromhex(val["address"])
            self.accounts.get_or_create(addr)
            shortfall = val["self_delegation"] - self.bank.balance(addr)
            if shortfall > 0:
                self.bank.mint(addr, shortfall)
            self.staking.create_validator(addr, val["self_delegation"])
        self.store.commit(1)  # genesis state at height 1

    # ------------------------------------------------------------------
    # CheckTx (mempool admission) — check_tx.go:16-54
    # ------------------------------------------------------------------

    def _get_check_state(self) -> MultiStore:
        if self._check_state is None:
            self._check_state = self.store.branch()
        return self._check_state

    def check_tx(self, raw: bytes, is_recheck: bool = False) -> TxResult:
        self.telemetry.incr("check_tx")
        key = _hashlib.sha256(raw).digest()
        btx = unmarshal_blob_tx(raw)
        # run the ante chain on a branch of the persistent check state;
        # only successful checks fold back (failed antes must not burn a
        # pending account's sequence/fee in the check state)
        check_state = self._get_check_state()
        branch = check_state.branch()
        try:
            if btx is not None:
                # reject BlobTx whose PFB is malformed; validate blobs fully
                # on first check only (not recheck)
                if is_recheck:
                    tx = unmarshal_tx(btx.tx)
                else:
                    tx = validate_blob_tx(btx, self.chain_id)
                    # the verdict is deterministic in the raw bytes:
                    # Prepare/Process reuse it instead of re-hashing the
                    # blob payloads (check_tx.go validates, then the
                    # proposal paths validate the same bytes again)
                    self._remember_decoded(key, tx, btx.tx)
                raw_inner = btx.tx
            else:
                tx = unmarshal_tx(raw)
                from celestia_tpu.state.ante import flat_msgs

                if any(isinstance(m, MsgPayForBlobs) for m in flat_msgs(tx)):
                    # PFB without blobs is never admissible (check_tx.go:30)
                    # — including authz-wrapped PFBs
                    return TxResult(1, "MsgPayForBlobs transaction missing blobs", 0, 0)
                raw_inner = raw
            # signature cache, both directions: a recheck / re-submission
            # of exact bytes this node already verified skips the EC
            # multiplication, and a fresh admission remembers its verdict
            # so the prepare/process legs hit (the cache key commits to
            # the FULL raw bytes, so a hit proves the same check)
            sig_ok = None
            if not tx.is_multisig() and self._sig_cache.get(key) is not None:
                sig_ok = True
                self.telemetry.incr("ingress_sig_cache_hit")
            ctx = AnteContext(
                tx=tx,
                raw_tx=raw_inner,
                accounts=AccountKeeper(branch.store("auth")),
                bank=BankKeeper(branch.store("bank")),
                params=ParamsKeeper(branch.store("params")),
                chain_id=self.chain_id,
                app_version=self.app_version,
                is_check_tx=True,
                is_recheck=is_recheck,
                min_gas_price=self.min_gas_price,
                sig_ok=sig_ok,
                height=self.next_height(),
                feegrant=FeeGrantKeeper(branch.store("feegrant")),
                time_ns=self.block_time_ns,
            )
            meter = run_ante(ctx)
            check_state.write_back(branch)
            if sig_ok is None and not tx.is_multisig():
                # ante succeeded => verify_signature verified these exact
                # bytes inline; admission now pre-pays the proposal legs
                self._remember_sig(key)
            return TxResult(0, "", tx.fee.gas_limit, meter.consumed)
        except (AnteError, ValueError) as e:
            self.telemetry.incr("check_tx_rejected")
            return TxResult(1, str(e), 0, 0)

    def check_txs_batch(
        self, raws: List[bytes], is_recheck: bool = False
    ) -> List[TxResult]:
        """Batched CheckTx: decode a chunk of mempool ingress, resolve
        every single-key signature in ONE threaded ``verify_batch`` pass,
        then run the ante chain per tx with the verdict pre-resolved.

        Reuses the ``_decode_proposal_txs`` discipline: decoded-tx cache
        probe by tx-bytes hash, batch commitment warming, a per-call
        ``batch_ok`` map immune to mid-call LRU eviction, sig-cache
        probes resolving to True, multisig falling back to inline
        verification inside the ante chain.  Dedupe is SIG-LEVEL only:
        ante still runs once per input IN ORDER against the shared check
        state, so a duplicated raw fails its second occurrence with the
        same sequence mismatch the sequential loop produces — results
        are positionally identical to ``[check_tx(r) for r in raws]``
        (pinned by tests/test_tx_ingress.py).
        """
        from celestia_tpu.state.ante import flat_msgs
        from celestia_tpu.utils.secp256k1 import verify_batch

        n = len(raws)
        self.telemetry.incr("check_tx", n)
        self.telemetry.incr("ingress_batch_calls")
        self.telemetry.incr("ingress_batch_txs", n)
        with tracing.span("ingress.batch", txs=n):
            # decode phase: check_tx semantics + decoded-cache probe,
            # with every fresh blob commitment warmed in one native call
            keys: List[bytes] = []
            parsed: List[tuple] = []  # (raw, key, btx_or_None, cache_hit)
            warm: List = []
            for raw in raws:
                key = _hashlib.sha256(raw).digest()
                keys.append(key)
                hit = self._decoded_cache.get(key)
                if hit is not None:
                    parsed.append((raw, key, None, hit))
                    continue
                btx = unmarshal_blob_tx(raw)
                if btx is not None and not is_recheck:
                    warm.extend(btx.blobs)
                parsed.append((raw, key, btx, None))
            if warm:
                from celestia_tpu.da.inclusion import warm_commitments

                warm_commitments(warm)
            decoded: List[tuple] = []  # (tx, raw_inner, err)
            for raw, key, btx, hit in parsed:
                if hit is not None:
                    decoded.append((hit[0], hit[1], None))
                    continue
                try:
                    if btx is not None:
                        if is_recheck:
                            tx = unmarshal_tx(btx.tx)
                        else:
                            tx = validate_blob_tx(btx, self.chain_id)
                            self._remember_decoded(key, tx, btx.tx)
                        raw_inner = btx.tx
                    else:
                        tx = unmarshal_tx(raw)
                        if any(
                            isinstance(m, MsgPayForBlobs)
                            for m in flat_msgs(tx)
                        ):
                            raise AnteError(
                                "MsgPayForBlobs transaction missing blobs"
                            )
                        raw_inner = raw
                    decoded.append((tx, raw_inner, None))
                except (AnteError, ValueError) as e:
                    decoded.append((None, None, e))
            # signature phase: batch_ok is THIS call's key -> verdict map
            # (cache hits resolve True, distinct fresh keys verify once,
            # output reads ONLY batch_ok — immune to LRU eviction)
            batch_ok: Dict[bytes, Optional[bool]] = {}
            live: List = []
            live_keys: List[bytes] = []
            for (tx, _raw_inner, err), key in zip(decoded, keys):
                if tx is None or tx.is_multisig() or key in batch_ok:
                    continue
                if self._sig_cache.get(key) is not None:
                    batch_ok[key] = True
                    self.telemetry.incr("ingress_sig_cache_hit")
                else:
                    batch_ok[key] = None
                    live.append(tx)
                    live_keys.append(key)
            if live:
                sig_results = verify_batch(
                    [tx.sign_bytes(self.chain_id) for tx in live],
                    [tx.signature for tx in live],
                    [tx.pubkey for tx in live],
                )
                self.telemetry.incr("ingress_batch_verified", len(live))
                for key, ok in zip(live_keys, sig_results):
                    batch_ok[key] = bool(ok)
                    if ok:
                        self._remember_sig(key)
            # ante phase: sequential, order-preserving, on the shared
            # check state (only successful checks fold back)
            check_state = self._get_check_state()
            results: List[TxResult] = []
            for raw, (tx, raw_inner, err), key in zip(raws, decoded, keys):
                if err is not None:
                    self.telemetry.incr("check_tx_rejected")
                    results.append(TxResult(1, str(err), 0, 0))
                    continue
                if tx.is_multisig():
                    sig_ok: Optional[bool] = None
                    self.telemetry.incr("ingress_multisig_inline")
                else:
                    sig_ok = batch_ok[key]
                branch = check_state.branch()
                try:
                    ctx = AnteContext(
                        tx=tx,
                        raw_tx=raw_inner,
                        accounts=AccountKeeper(branch.store("auth")),
                        bank=BankKeeper(branch.store("bank")),
                        params=ParamsKeeper(branch.store("params")),
                        chain_id=self.chain_id,
                        app_version=self.app_version,
                        is_check_tx=True,
                        is_recheck=is_recheck,
                        min_gas_price=self.min_gas_price,
                        sig_ok=sig_ok,
                        height=self.next_height(),
                        feegrant=FeeGrantKeeper(branch.store("feegrant")),
                        time_ns=self.block_time_ns,
                    )
                    meter = run_ante(ctx)
                    check_state.write_back(branch)
                    results.append(
                        TxResult(0, "", tx.fee.gas_limit, meter.consumed)
                    )
                except (AnteError, ValueError) as e:
                    self.telemetry.incr("check_tx_rejected")
                    results.append(TxResult(1, str(e), 0, 0))
            return results

    # ------------------------------------------------------------------
    # PrepareProposal — prepare_proposal.go:23-96
    # ------------------------------------------------------------------

    def _decode_proposal_txs(self, txs: List[bytes]):
        """Decode every proposal tx, then batch-verify all signatures in one
        threaded native secp256k1 pass (the per-tx EC multiplication is the
        dominant host cost of FilterTxs/ProcessProposal — the reference
        leans on C secp256k1 for the same reason, SURVEY.md §2.2).

        Yields (raw, tx, raw_inner, sig_ok, decode_error) per input tx.

        Verified signatures are cached by tx-bytes hash (bounded LRU):
        a proposer's own ProcessProposal re-check of the block it just
        built, and repeat validations of the same bytes across proposal
        rounds, skip the EC multiplications — the dominant per-block
        host cost.  Only a verifying (pubkey, sign_bytes, signature)
        triple derived from the EXACT raw bytes is ever cached, so a hit
        proves the same signature check.  (CheckTx and check_txs_batch
        populate the same cache on successful admission, so a proposal
        built from batched mempool ingress filters signature-warm.)
        """
        from celestia_tpu.utils.secp256k1 import verify_batch

        # ONE full-data hash per tx, shared by the decoded-tx cache and
        # the signature cache (the raw bytes are the dominant hash cost
        # for blob txs).
        decoded: List[tuple] = []
        tx_keys: List[bytes] = []
        # pass 1: unmarshal envelopes and batch-warm every blob commitment
        # in ONE native call (per-blob recompute inside validate_blob_tx
        # then hits the cache) — at proposal scale the per-blob native
        # crossings were a visible slice of FilterTxs
        parsed: List[tuple] = []  # (raw, key, btx_or_None, cache_hit)
        warm: List = []
        for raw in txs:
            key = _hashlib.sha256(raw).digest()
            tx_keys.append(key)
            hit = self._decoded_cache.get(key)
            if hit is not None:
                parsed.append((raw, key, None, hit))
                continue
            btx = unmarshal_blob_tx(raw)
            if btx is not None:
                warm.extend(btx.blobs)
            parsed.append((raw, key, btx, None))
        if warm:
            from celestia_tpu.da.inclusion import warm_commitments

            warm_commitments(warm)
        for raw, key, btx, hit in parsed:
            if hit is not None:
                decoded.append((raw, hit[0], hit[1], None))
                continue
            try:
                if btx is not None:
                    # full BlobTx validation incl. commitment recompute
                    tx = validate_blob_tx(btx, self.chain_id)
                    raw_inner = btx.tx
                else:
                    tx = unmarshal_tx(raw)
                    from celestia_tpu.state.ante import flat_msgs

                    if any(isinstance(m, MsgPayForBlobs) for m in flat_msgs(tx)):
                        raise AnteError("PFB without blobs")
                    raw_inner = raw
                decoded.append((raw, tx, raw_inner, None))
                self._remember_decoded(key, tx, raw_inner)
            except (AnteError, ValueError) as e:
                decoded.append((raw, None, None, e))
        # single-key txs batch-verify natively; multisig txs fall back to
        # inline verification inside the ante chain (sig_ok=None).
        # batch_ok is THIS call's key -> verdict map: cache hits resolve
        # to True, each distinct fresh key is verified once (duplicates
        # dedupe), and the output loop reads ONLY batch_ok — immune to
        # LRU evictions _remember_sig performs mid-call.
        batch_ok: Dict[bytes, Optional[bool]] = {}
        keys: List[Optional[bytes]] = []
        live: List[tuple] = []
        live_keys: List[bytes] = []
        for d, key in zip(decoded, tx_keys):
            if d[1] is None or d[1].is_multisig():
                keys.append(None)
                continue
            keys.append(key)
            if key in batch_ok:
                continue
            if self._sig_cache.get(key) is not None:
                batch_ok[key] = True
            else:
                batch_ok[key] = None  # to be verified below
                live.append(d)
                live_keys.append(key)
        sig_results = verify_batch(
            [tx.sign_bytes(self.chain_id) for _, tx, _, _ in live],
            [tx.signature for _, tx, _, _ in live],
            [tx.pubkey for _, tx, _, _ in live],
        )
        for key, ok in zip(live_keys, sig_results):
            batch_ok[key] = bool(ok)
            if ok:
                self._remember_sig(key)
        out = []
        for d, key in zip(decoded, keys):
            raw, tx, raw_inner, err = d
            if tx is None:
                sig_ok = False
            elif tx.is_multisig():
                sig_ok = None
            else:
                sig_ok = batch_ok[key]
            out.append((raw, tx, raw_inner, sig_ok, err))
        return out

    def _remember_sig(self, key: bytes) -> None:
        self._sig_cache.put(key, True)

    def _remember_decoded(self, key: bytes, tx, raw_inner: bytes) -> None:
        self._decoded_cache.put(key, (tx, raw_inner))

    # legacy re-cap surface (tests/test_sig_cache.py assigns these): the
    # unified LruCache trims immediately on re-cap, which subsumes the
    # old lazy next-insert eviction
    @property
    def _sig_cache_max(self) -> int:
        return self._sig_cache.max_entries

    @_sig_cache_max.setter
    def _sig_cache_max(self, n: int) -> None:
        self._sig_cache.set_max_entries(n)

    @property
    def _decoded_cache_max(self) -> int:
        return self._decoded_cache.max_entries

    @_decoded_cache_max.setter
    def _decoded_cache_max(self, n: int) -> None:
        self._decoded_cache.set_max_entries(n)

    # below this many proposal txs the signer-grouping + fold overhead
    # outweighs any parallel ante win; the sequential leg is already fast
    _FILTER_PARALLEL_MIN_TXS = 16

    def _filter_txs(
        self, txs: List[bytes], parallel: Optional[bool] = None
    ) -> List[bytes]:
        """FilterTxs parity (validate_txs.go:29-97): run the ante chain over
        each tx on one branched state, in priority order; drop failures.

        ``parallel`` — None auto-routes (multi-core host AND enough txs),
        True/False force a leg.  The parallel leg groups txs by ante
        footprint and runs independent groups through the hostpool; it
        degrades to the sequential leg on any hazard (see
        ``_filter_groups``) and is pinned byte-identical to it by
        tests/test_tx_ingress.py.
        """
        decoded = self._decode_proposal_txs(txs)
        if parallel is None:
            from celestia_tpu.utils import hostpool

            parallel = (
                hostpool.cpu_threads() > 1
                and len(decoded) >= self._FILTER_PARALLEL_MIN_TXS
            )
        if parallel:
            kept = self._filter_txs_parallel(decoded)
            if kept is not None:
                return kept
            self.telemetry.incr("ingress_parallel_fallback")
        return self._filter_txs_sequential(decoded)

    def _filter_txs_sequential(self, decoded: List[tuple]) -> List[bytes]:
        """The reference leg: one shared branch, shared keepers, txs in
        priority order.  NOTE a failed ante leaves its partial writes on
        the shared branch (fee already deducted before the failing
        decorator ran) — later txs from the same payer observe them; the
        parallel leg reproduces this exactly."""
        branch = self.store.branch()
        accounts = AccountKeeper(branch.store("auth"))
        bank = BankKeeper(branch.store("bank"))
        params = ParamsKeeper(branch.store("params"))
        kept: List[bytes] = []
        for raw, tx, raw_inner, sig_ok, err in decoded:
            if err is not None:
                self.telemetry.incr("prepare_proposal_dropped_tx")
                continue
            try:
                ctx = AnteContext(
                    tx=tx,
                    raw_tx=raw_inner,
                    accounts=accounts,
                    bank=bank,
                    params=params,
                    chain_id=self.chain_id,
                    app_version=self.app_version,
                    sig_ok=sig_ok,
                    height=self.next_height(),
                    feegrant=FeeGrantKeeper(branch.store("feegrant")),
                    time_ns=self.block_time_ns,
                )
                run_ante(ctx)
                kept.append(raw)
            except (AnteError, ValueError):
                self.telemetry.incr("prepare_proposal_dropped_tx")
                continue
        return kept

    def _filter_groups(self, decoded: List[tuple]) -> Optional[List[List[int]]]:
        """Union-find over ante footprints -> independent groups of decoded
        indices, or None when a hazard forces the sequential leg.

        The ante chain reads/writes ONLY the tx's footprint accounts
        (signer + fee granter: auth record, bank balance, feegrant key),
        reads params (read-only here), and credits FEE_COLLECTOR (never
        read by any verdict).  Hazards — cases where that independence
        argument does not hold — degrade to sequential:

        * footprint undeterminable (malformed pubkey);
        * a footprint account that does not exist yet: get_or_create
          would allocate from the GLOBAL account-number counter, a
          cross-group write;
        * a footprint naming FEE_COLLECTOR: its balance would then gate
          a verdict.
        """
        from celestia_tpu.state.ante import ante_footprint

        parent: Dict[bytes, bytes] = {}

        def find(a: bytes) -> bytes:
            while parent[a] != a:
                parent[a] = parent[parent[a]]
                a = parent[a]
            return a

        tx_root: List[Optional[bytes]] = [None] * len(decoded)
        for i, (_raw, tx, _raw_inner, _sig_ok, err) in enumerate(decoded):
            if err is not None:
                continue  # pure drop: touches no state, needs no group
            fp = ante_footprint(tx)
            if fp is None:
                return None
            for addr in fp:
                if addr == FEE_COLLECTOR:
                    return None
                if addr not in parent:
                    parent[addr] = addr
                    if self.accounts.get(addr) is None:
                        return None
            ra = find(fp[0])
            for addr in fp[1:]:
                rb = find(addr)
                if ra != rb:
                    parent[rb] = ra
            tx_root[i] = ra
        groups: Dict[bytes, List[int]] = {}
        for i, a in enumerate(tx_root):
            if a is None:
                continue
            groups.setdefault(find(a), []).append(i)
        return list(groups.values())

    def _filter_txs_parallel(
        self, decoded: List[tuple]
    ) -> Optional[List[bytes]]:
        """Hostpool-parallel FilterTxs: ante for independent-footprint
        groups runs concurrently against branch snapshots; verdicts are
        then replayed in a deterministic sequential fold that performs
        the actual write-backs in original priority order.  Returns None
        to degrade (grouping hazard, or a pool-layer failure)."""
        from celestia_tpu.utils import faults, hostpool

        groups = self._filter_groups(decoded)
        if groups is None or len(groups) <= 1:
            return None
        base = self.store.branch()
        height = self.next_height()

        def ante_group(idxs: List[int]) -> List[tuple]:
            # re-runnable after a WorkerDeath self-heal: every mutation is
            # confined to branches created INSIDE this call
            gbranch = base.branch()
            out = []
            for i in idxs:
                _raw, tx, raw_inner, sig_ok, _err = decoded[i]
                sub = gbranch.branch()
                ok = True
                try:
                    ctx = AnteContext(
                        tx=tx,
                        raw_tx=raw_inner,
                        accounts=AccountKeeper(sub.store("auth")),
                        bank=BankKeeper(sub.store("bank")),
                        params=ParamsKeeper(sub.store("params")),
                        chain_id=self.chain_id,
                        app_version=self.app_version,
                        sig_ok=sig_ok,
                        height=height,
                        feegrant=FeeGrantKeeper(sub.store("feegrant")),
                        time_ns=self.block_time_ns,
                    )
                    run_ante(ctx)
                except (AnteError, ValueError):
                    ok = False
                # fold the sub-branch back on failure TOO: the sequential
                # leg's shared keepers keep a failed ante's partial writes
                # (fee deducted before the failing decorator), and later
                # same-payer txs must observe them
                delta = sub.overlay_delta()
                gbranch.write_back(sub)
                out.append((i, ok, delta))
            return out

        with tracing.span(
            "ante.parallel",
            groups=len(groups),
            txs=sum(len(g) for g in groups),
        ):
            try:
                results = hostpool.run_sharded(ante_group, groups)
            except Exception as e:  # pool-layer failure: degrade, don't drop
                faults.note("ingress.parallel", e)
                return None
        verdicts: Dict[int, tuple] = {}
        for group_out in results:
            for i, ok, delta in group_out:
                verdicts[i] = (ok, delta)
        # deterministic sequential fold: write-backs in priority order on
        # ONE branch (discarded like the sequential leg's), kept list and
        # drop counters in original order
        fold = self.store.branch()
        kept: List[bytes] = []
        for i, (raw, tx, _raw_inner, _sig_ok, err) in enumerate(decoded):
            if err is not None or i not in verdicts:
                self.telemetry.incr("prepare_proposal_dropped_tx")
                continue
            ok, delta = verdicts[i]
            fold.apply_overlay_delta(delta)
            if ok:
                kept.append(raw)
            else:
                self.telemetry.incr("prepare_proposal_dropped_tx")
        self.telemetry.incr("ingress_parallel_groups", len(groups))
        return kept

    def _extend_block_cached(
        self, block_txs: List[bytes], square, leg: str
    ) -> Tuple["dah_mod.ExtendedDataSquare", "dah_mod.DataAvailabilityHeader"]:
        """ExtendBlock through the content-addressed EDS cache.

        The key commits to the FULL tx bytes + square size + app version +
        active codec — never to a claimed data root — so only a proposal
        whose square this node already extended honestly can hit.  The
        proposer's own ProcessProposal re-extend, round-restart
        re-proposals and repeated gossip validations of one block all
        collapse to a lookup; everything else (ante, signatures, square
        reconstruction, the root comparison) still runs in the caller.
        """
        from celestia_tpu.da import eds_cache
        from celestia_tpu.ops import gf256 as _gf256

        key = eds_cache.make_key(
            block_txs, square.size, self.app_version, _gf256.active_codec()
        )
        with tracing.span("extend", leg=leg, k=square.size) as sp:
            cached = eds_cache.get(key)
            if cached is not None:
                self.telemetry.incr(f"eds_cache_hit_{leg}")
                sp.annotate(eds_cache="hit")
                tracing.instant("eds_cache.hit", cat="cache", leg=leg)
                return cached
            self.telemetry.incr(f"eds_cache_miss_{leg}")
            sp.annotate(eds_cache="miss")
            tracing.instant("eds_cache.miss", cat="cache", leg=leg)
            eds, dah = self._extend_square_routed(square)
            eds_cache.put(key, eds, dah)
            return eds, dah

    def _extend_square_routed(
        self, square
    ) -> Tuple["dah_mod.ExtendedDataSquare", "dah_mod.DataAvailabilityHeader"]:
        """ExtendBlock through the multi-chip mesh when the provider says
        so (parallel/mesh.py: >1 device visible / explicit --mesh, and
        the square's rows divide the row axis), else the single-device
        path (da/dah.extend_block — host-native fast paths, row memo and
        the jax leg all unchanged).  Byte-identity between the two legs
        is test-pinned, so cache semantics and the data-root compare are
        oblivious to which one ran.

        Degradation ladder (specs/robustness.md): a sharded failure
        mid-flight poisons the mesh one-way (loud — recorded as a
        degradation) and THIS call falls through to the single-device
        path, so the block being extended still commits the same root it
        would have on the mesh."""
        from celestia_tpu.parallel import mesh as mesh_mod

        m = mesh_mod.mesh_for_square(square.size)
        if m is not None:
            from celestia_tpu.parallel import sharded

            try:
                out = sharded.extend_block_sharded(square, m)
            except Exception as e:
                mesh_mod.poison(
                    f"sharded extend failed at k={square.size}: {e!r}"
                )
                self.telemetry.incr("extend_mesh_degraded")
            else:
                self.telemetry.incr("extend_sharded")
                return out
        return dah_mod.extend_block(square)

    # ------------------------------------------------------------------
    # batched multi-block validation (state-sync catch-up leg)
    # ------------------------------------------------------------------

    def warm_extends_batched(
        self, blocks: List[Tuple[List[bytes], int]]
    ) -> int:
        """Pre-extend many blocks' squares in batched mesh dispatches,
        filling the content-addressed EDS cache — BASELINE.json config
        #5 made live: a validator replaying n same-k blocks pays one
        device dispatch per batch instead of one per block.

        ``blocks``: (block_txs, claimed_square_size) pairs.  The extend
        is a pure function of (txs, size, app_version, codec) — state-
        independent — so warming ahead of sequential replay is always
        sound: the per-block validation that follows (ante, signatures,
        strict reconstruction, root compare) runs unchanged and simply
        hits the warm cache on its extend leg.  Entries whose square
        cannot be rebuilt at the claimed size are skipped (the per-block
        validation will reject them with its usual reasons).  Never
        raises — any failure degrades to the per-block path (noted).
        Returns the number of squares warmed."""
        from celestia_tpu.da import eds_cache
        from celestia_tpu.ops import gf256 as _gf256
        from celestia_tpu.parallel import mesh as mesh_mod
        from celestia_tpu.utils import faults

        if mesh_mod.device_mesh() is None:
            return 0
        codec = _gf256.active_codec()
        bound = self.max_effective_square_size()
        # group uncached, rebuildable squares by k (one batch per size)
        by_k: Dict[int, List[Tuple[bytes, object]]] = {}
        cached_hits = 0
        for block_txs, claimed_size in blocks:
            try:
                key = eds_cache.make_key(
                    block_txs, claimed_size, self.app_version, codec
                )
                if eds_cache.CACHE.peek(key) is not None:
                    # counter-free probe that still refreshes recency:
                    # an already-cached window block must not sit
                    # LRU-oldest while the warm puts below evict it
                    cached_hits += 1
                    continue
                square, _txs, _w = construct_square(list(block_txs), bound)
                if square.size != claimed_size:
                    continue  # per-block validation rejects it properly
                by_k.setdefault(square.size, []).append((key, square))
            except Exception as e:
                faults.note("mesh.batch_warm", e)
                continue
        warmed = 0
        # the cache is the hand-off: entries warmed beyond its capacity
        # would evict each other before the per-block validations read
        # them, turning the batched dispatch into pure extra work — ONE
        # budget across every group (a later group's puts evict an
        # earlier group's entries just as surely as its own), with a
        # slot reserved for each already-cached window entry the peek
        # above refreshed (warm puts must not evict those either); the
        # overflow degrades to per-block extends, and the truncation is
        # counted, never silent
        budget = max(0, eds_cache.CACHE.max_entries - 1 - cached_hits)
        for k, items in sorted(by_k.items()):
            if budget <= 0:
                self.telemetry.incr(
                    "extend_batch_warm_truncated", len(items)
                )
                continue
            m = mesh_mod.mesh_for_batch(k, min(len(items), budget))
            if m is None:
                continue  # whole group takes the per-block path
            if len(items) > budget:
                self.telemetry.incr(
                    "extend_batch_warm_truncated", len(items) - budget
                )
                items = items[:budget]
            try:
                from celestia_tpu.parallel import sharded

                arr = np.stack(
                    [
                        sq.to_array().reshape(k, k, SHARE_SIZE)
                        for _key, sq in items
                    ]
                )
                # the shard_map leading dim must divide the data axis,
                # and the jitted program is SHAPE-specialized — pad to a
                # bucketed size (data_ax x next-pow2 chunks) by
                # repeating the last square (pad results dropped), so a
                # varying window never cold-compiles a fresh program
                # per distinct n: at most log2(window) programs per k
                data_ax = int(m.shape["data"])
                chunks = -(-len(items) // data_ax)  # ceil division
                if chunks > 1:
                    chunks = 1 << (chunks - 1).bit_length()
                pad = data_ax * chunks - len(items)
                if pad:
                    arr = np.concatenate([arr, arr[-1:].repeat(pad, 0)])
                pairs = sharded.extend_and_headers_sharded_batch(
                    arr, m, count_squares=len(items)
                )
                for (key, _sq), (eds, dah) in zip(items, pairs):
                    eds_cache.put(key, eds, dah)
                    warmed += 1
                budget -= len(items)
                self.telemetry.incr("extend_batched_blocks", len(items))
            except Exception as e:
                mesh_mod.poison(
                    f"batched sharded extend failed at k={k}: {e!r}"
                )
                self.telemetry.incr("extend_mesh_degraded")
                break  # poisoned: remaining groups take the per-block path
        return warmed

    def validate_blocks_batched(
        self,
        proposals: List[Tuple[List[bytes], int, bytes]],
        warm_only: bool = False,
    ) -> List[Tuple[bool, str]]:
        """ProcessProposal over many blocks with the extends batched:
        one sharded device dispatch per same-k group fills the EDS
        cache, then every block runs the FULL per-block validation
        (ante, signatures, strict reconstruction, root compare) in
        order — nothing is weakened, the extend leg just hits warm.

        ``proposals``: (block_txs, square_size, data_root) triples.
        ``warm_only=True`` skips the per-block validations and returns
        [] — the state-sync catch-up uses this (its adoption path runs
        process_proposal itself per block, against the then-current
        state; verdicts computed here against today's state could
        differ on state-dependent ante checks)."""
        self.warm_extends_batched(
            [(txs, size) for txs, size, _root in proposals]
        )
        if warm_only:
            return []
        return [
            self.process_proposal(list(txs), size, root)
            for txs, size, root in proposals
        ]

    def prepare_proposal(self, txs: List[bytes]) -> PreparedProposal:
        t0 = self.telemetry.clock()
        try:
            # per-height root span (utils/tracing.py): the whole prepare
            # leg with its phases as children, ring-buffered for trace_dump
            with tracing.block_span(
                "prepare_proposal", height=self.next_height(), txs=len(txs)
            ):
                return self._prepare_proposal_traced(txs, t0)
        finally:
            self.telemetry.measure_since("prepare_proposal", t0)

    def _prepare_proposal_traced(
        self, txs: List[bytes], t0: float
    ) -> PreparedProposal:
        with tracing.span("filter_txs", txs=len(txs)):
            kept = self._filter_txs(txs)
        t1 = self.telemetry.clock()
        with tracing.span("square_build", txs=len(kept)):
            square, block_txs, wrappers = build_square(
                kept, self.max_effective_square_size()
            )
        t2 = self.telemetry.clock()
        eds, dah = self._extend_block_cached(block_txs, square, "prepare")
        t3 = self.telemetry.clock()
        # per-phase budget (SURVEY §7 hard part c): host tx filtering,
        # host square assembly, device extension incl. transfer —
        # telemetry + last_prepare_breakdown let the bench isolate
        # the tunnel RTT from real host-side overhead
        self.last_prepare_breakdown = {
            "filter_ms": (t1 - t0) * 1000.0,
            "build_ms": (t2 - t1) * 1000.0,
            "extend_ms": (t3 - t2) * 1000.0,
        }
        for name, v in self.last_prepare_breakdown.items():
            self.telemetry.observe(f"prepare_proposal.{name}", v)
        return PreparedProposal(
            block_txs=block_txs,
            square_size=square.size,
            data_root=dah.hash,
            eds=eds,
            dah=dah,
            square=square,
            wrappers=wrappers,
        )

    # ------------------------------------------------------------------
    # ProcessProposal — process_proposal.go:24-157
    # ------------------------------------------------------------------

    def process_proposal(
        self, block_txs: List[bytes], square_size: int, data_root: bytes
    ) -> Tuple[bool, str]:
        """Returns (accept, reason).  Panics are caught -> REJECT
        (process_proposal.go:26-34)."""
        t0 = self.telemetry.clock()
        try:
            with tracing.block_span(
                "process_proposal",
                height=self.next_height(),
                txs=len(block_txs),
            ):
                return self._process_proposal_traced(
                    block_txs, square_size, data_root
                )
        except Exception as e:
            self.telemetry.incr("process_proposal_panic_reject")
            return False, f"proposal rejected: {e}"
        finally:
            self.telemetry.measure_since("process_proposal", t0)

    def _process_proposal_traced(
        self, block_txs: List[bytes], square_size: int, data_root: bytes
    ) -> Tuple[bool, str]:
        branch = self.store.branch()
        accounts = AccountKeeper(branch.store("auth"))
        bank = BankKeeper(branch.store("bank"))
        params = ParamsKeeper(branch.store("params"))
        with tracing.span("decode_and_ante", txs=len(block_txs)):
            for raw, tx, raw_inner, sig_ok, err in self._decode_proposal_txs(
                block_txs
            ):
                if err is not None:
                    return False, f"invalid tx in proposal: {err}"
                ctx = AnteContext(
                    tx=tx,
                    raw_tx=raw_inner,
                    accounts=accounts,
                    bank=bank,
                    params=params,
                    chain_id=self.chain_id,
                    app_version=self.app_version,
                    sig_ok=sig_ok,
                    height=self.next_height(),
                    feegrant=FeeGrantKeeper(branch.store("feegrant")),
                    time_ns=self.block_time_ns,
                )
                run_ante(ctx)
        # strict reconstruction — NOT skippable on a cache hit: the
        # square must be re-derivable from the tx bytes under the
        # CURRENT size bound, and only that reconstruction makes the
        # cached (txs -> EDS/DAH) mapping apply to this proposal
        with tracing.span("square_build", txs=len(block_txs)):
            square, re_txs, _ = construct_square(
                block_txs, self.max_effective_square_size()
            )
        if square.size != square_size:
            return False, (
                f"square size mismatch: computed {square.size}, "
                f"header says {square_size}"
            )
        _, dah = self._extend_block_cached(block_txs, square, "process")
        if dah.hash != data_root:
            self.telemetry.incr("process_proposal_rejected_data_root")
            return False, (
                f"data root mismatch: computed {dah.hash.hex()}, "
                f"header says {data_root.hex()}"
            )
        return True, ""

    # ------------------------------------------------------------------
    # Block execution (Begin/Deliver/End/Commit)
    # ------------------------------------------------------------------

    def begin_block(
        self,
        height: int,
        time_ns: int,
        proposer: Optional[bytes] = None,
        votes: Optional[List[Tuple[bytes, bool]]] = None,
    ) -> None:
        """BeginBlocker: mint this block's provision, then allocate the fee
        collector (previous block's fees + the fresh provision) through
        x/distribution using the previous commit's proposer/votes — the SDK
        mint-before-distribution BeginBlock order."""
        self.block_time_ns = time_ns
        self.block_height = height
        # the deterministic clock vesting locks are evaluated at — every
        # state branch (check/ante/deliver) reads it from the bank store
        self.bank.set_block_time(time_ns)
        self.mint.begin_blocker(time_ns)
        self.distribution.allocate_tokens(proposer, votes)
        if votes is not None:
            # liveness window update + downtime jailing (slashing BeginBlocker)
            self.slashing.begin_blocker(votes, height, time_ns)

    def deliver_tx(self, raw: bytes) -> TxResult:
        """Execute one block tx (blob txs execute their inner PFB only —
        blobs never touch state; keeper.go:42-57).

        Decode-once: the protobuf decode done by CheckTx / the proposal
        legs is reused by raw-bytes hash.  READ-ONLY consult — delivery
        skips blob validation by design (committed blobs never touch
        state), so it must never seed the cache the proposal legs treat
        as proof of full BlobTx validation."""
        key = _hashlib.sha256(raw).digest()
        hit = self._decoded_cache.get(key)
        if hit is not None:
            self.telemetry.incr("decoded_cache_hit_deliver")
            tx, raw_inner = hit
        else:
            btx = unmarshal_blob_tx(raw)
            if btx is not None:
                tx = unmarshal_tx(btx.tx)
                raw_inner = btx.tx
            else:
                tx = unmarshal_tx(raw)
                raw_inner = raw
        # Phase 1 (SDK runTx parity): the ante chain runs on its own branch;
        # on success its writes (fee deduction, sequence bump) persist even
        # if message execution later fails.
        ante_branch = self.store.branch()
        ctx = AnteContext(
            tx=tx,
            raw_tx=raw_inner,
            accounts=AccountKeeper(ante_branch.store("auth")),
            bank=BankKeeper(ante_branch.store("bank")),
            params=ParamsKeeper(ante_branch.store("params")),
            chain_id=self.chain_id,
            app_version=self.app_version,
            height=self.next_height(),
            feegrant=FeeGrantKeeper(ante_branch.store("feegrant")),
            time_ns=self.block_time_ns,
        )
        try:
            meter = run_ante(ctx)
        except AnteError as e:
            return TxResult(1, str(e), tx.fee.gas_limit, 0)
        self.store.write_back(ante_branch)
        # Phase 2: messages execute on a cache-wrap; a failure discards ALL
        # message writes (atomic tx execution) while keeping the ante's.
        msg_branch = self.store.branch()
        saved_store = self.store
        self.store = msg_branch
        self._wire_keepers(rebuild_ibc=False)
        events: List[dict] = []
        try:
            for m in tx.msgs:
                events.append(self._execute_msg(m, meter))
            # post-handler chain (app/posthandler parity): runs on the
            # message branch AFTER execution; a raise rolls the whole tx
            # back with the same atomicity as a message failure
            self.post_handler(
                PostContext(tx=tx, app=self, events=events, gas_meter=meter)
            )
        except Exception as e:
            return TxResult(
                2, f"msg execution failed: {e}", tx.fee.gas_limit, meter.consumed
            )
        else:
            saved_store.write_back(msg_branch)
            return TxResult(0, "", tx.fee.gas_limit, meter.consumed, events)
        finally:
            self.store = saved_store
            self._wire_keepers(rebuild_ibc=False)

    def _execute_msg(self, msg: Msg, gas_meter: GasMeter) -> dict:
        if isinstance(msg, MsgSend):
            self.bank.send(msg.from_addr, msg.to_addr, msg.amount)
            # a recipient seeing funds for the first time gets its auth
            # account here, deterministically in-block (the SDK's bank ->
            # auth.NewAccount behavior): clients can then query a stable
            # account number before signing their first tx
            self.accounts.get_or_create(msg.to_addr)
            return {
                "type": "transfer",
                "amount": msg.amount,
                "sender": msg.from_addr.hex(),
                "recipient": msg.to_addr.hex(),
            }
        if isinstance(msg, MsgPayForBlobs):
            return self.blob.pay_for_blobs(msg, gas_meter)
        if isinstance(msg, MsgDelegate):
            self.staking.delegate(msg.delegator, msg.validator, msg.amount)
            return {"type": "delegate", "amount": msg.amount}
        if isinstance(msg, MsgUndelegate):
            self.staking.undelegate(msg.delegator, msg.validator, msg.amount)
            return {"type": "undelegate", "amount": msg.amount}
        if isinstance(msg, MsgSignalVersion):
            self.upgrade.signal_version(msg.validator, msg.version, self.app_version)
            return {"type": "signal_version", "version": msg.version}
        if isinstance(msg, MsgTryUpgrade):
            scheduled = self.upgrade.try_upgrade(self.app_version)
            return {"type": "try_upgrade", "scheduled": scheduled}
        if isinstance(msg, MsgRegisterEVMAddress):
            self.blobstream.register_evm_address(msg.validator, msg.evm_address)
            return {"type": "register_evm_address"}
        if isinstance(msg, MsgParamChange):
            from celestia_tpu.state.modules.gov import GOV_MODULE_ADDR

            # Only the gov module account may execute a param change — the
            # reference routes ALL param changes through a passed proposal
            # (x/paramfilter/gov_handler.go:36-60); a user-signed
            # MsgParamChange must never write state.
            if msg.authority != GOV_MODULE_ADDR:
                raise ValueError(
                    "param change authority must be the gov module account; "
                    "submit a MsgSubmitProposal instead"
                )
            self.param_block_list.validate_change(msg.subspace, msg.key)
            import json as _json

            self.params.set(msg.subspace, msg.key, _json.loads(msg.value))
            return {"type": "param_change", "key": f"{msg.subspace}/{msg.key}"}
        if isinstance(msg, MsgSubmitProposal):
            pid = self.gov.submit_proposal(msg, self.block_height)
            return {"type": "submit_proposal", "proposal_id": pid}
        if isinstance(msg, MsgVote):
            self.gov.vote(msg, self.block_height)
            return {"type": "vote", "proposal_id": msg.proposal_id}
        if isinstance(msg, MsgGrantAllowance):
            from celestia_tpu.state.modules.feegrant import Allowance

            self.feegrant.grant(
                msg.granter,
                msg.grantee,
                Allowance(
                    kind=msg.kind,
                    spend_limit=msg.spend_limit,
                    expiration_ns=msg.expiration_ns,
                    period_ns=msg.period_ns,
                    period_spend_limit=msg.period_spend_limit,
                ),
            )
            return {"type": "grant_allowance"}
        if isinstance(msg, MsgRevokeAllowance):
            self.feegrant.revoke(msg.granter, msg.grantee)
            return {"type": "revoke_allowance"}
        if isinstance(msg, MsgAuthzGrant):
            from celestia_tpu.state.modules.authz import Authorization

            self.authz.grant(
                msg.granter,
                msg.grantee,
                Authorization(
                    msg_type=msg.msg_type,
                    spend_limit=msg.spend_limit,
                    expiration_ns=msg.expiration_ns,
                ),
            )
            return {"type": "authz_grant"}
        if isinstance(msg, MsgAuthzRevoke):
            self.authz.revoke(msg.granter, msg.grantee, msg.msg_type)
            return {"type": "authz_revoke"}
        if isinstance(msg, MsgWithdrawDelegatorReward):
            amount = self.distribution.withdraw_delegator_reward(
                msg.delegator, msg.validator
            )
            return {"type": "withdraw_rewards", "amount": amount}
        if isinstance(msg, MsgWithdrawValidatorCommission):
            amount = self.distribution.withdraw_validator_commission(msg.validator)
            return {"type": "withdraw_commission", "amount": amount}
        if isinstance(msg, MsgFundCommunityPool):
            self.distribution.fund_community_pool(msg.depositor, msg.amount)
            return {"type": "fund_community_pool", "amount": msg.amount}
        if isinstance(msg, MsgSetWithdrawAddress):
            self.distribution.set_withdraw_address(
                msg.delegator, msg.withdraw_address
            )
            return {"type": "set_withdraw_address"}
        if isinstance(msg, MsgUnjail):
            self.slashing.unjail(msg.validator, self.block_time_ns)
            return {"type": "unjail"}
        if isinstance(msg, MsgSubmitEvidence):
            from celestia_tpu.state.modules.evidence import Equivocation

            # the msg path is permissionless, so the evidence must PROVE
            # the double-sign against the validator's registered pubkey
            val_acc = self.accounts.get(msg.validator)
            slashed = self.evidence.submit(
                Equivocation(
                    msg.validator, msg.height, msg.time_ns,
                    msg.block_hash_a, msg.sig_a,
                    msg.block_hash_b, msg.sig_b,
                ),
                self.block_height,
                self.block_time_ns,
                chain_id=self.chain_id,
                pubkey=val_acc.pubkey if val_acc else b"",
            )
            return {"type": "submit_evidence", "slashed": slashed}
        if isinstance(msg, MsgVerifyInvariant):
            from celestia_tpu.state.invariants import (
                DEFAULT_INVARIANTS,
                GAS_COST_PER_INVARIANT,
                assert_invariants,
            )

            names = [msg.invariant] if msg.invariant else None
            gas_meter.consume(
                GAS_COST_PER_INVARIANT
                * (len(names) if names else len(DEFAULT_INVARIANTS)),
                "verify invariant",
            )
            results = assert_invariants(self, names)
            return {"type": "verify_invariant", "results": results}
        if isinstance(msg, MsgCreateVestingAccount):
            # fund a fresh account under a vesting schedule (the SDK's
            # MsgCreateVestingAccount: start = block time)
            self.bank.set_vesting_schedule(
                msg.to_addr, msg.amount, self.block_time_ns,
                msg.end_time_ns, msg.delayed,
            )
            self.bank.send(msg.from_addr, msg.to_addr, msg.amount)
            self.accounts.get_or_create(msg.to_addr)
            return {"type": "create_vesting_account", "amount": msg.amount}
        if isinstance(msg, MsgExec):
            inner_events = []
            for im in msg.inner:
                # every inner signer must have granted the grantee this
                # message type (authz MsgExec dispatch)
                for signer in im.signers():
                    self.authz.check_and_consume(
                        signer, msg.grantee, im, self.block_time_ns
                    )
                inner_events.append(self._execute_msg(im, gas_meter))
            return {"type": "exec", "inner": inner_events}
        raise ValueError(f"no handler for message {type(msg).__name__}")

    def end_block(self, height: int, time_ns: int) -> dict:
        """EndBlocker parity (app.go:675-708): module end-blockers, then
        upgrade consumption (v1 height-based or v2 signal-based)."""
        attestations = self.blobstream.end_blocker(height, time_ns)
        gov_events = self.gov.end_blocker(height, self)
        upgraded_to = None
        if self.app_version == 1 and self.v2_upgrade_height is not None:
            if height == self.v2_upgrade_height - 1:
                upgraded_to = 2
        else:
            pending = self.upgrade.should_upgrade()
            if pending is not None and pending > self.app_version:
                if pending in app_versions.supported_versions():
                    upgraded_to = pending
                else:
                    # quorum reached but this binary can't run the new
                    # version: keep the upgrade pending (operators must
                    # restart with the release that supports it)
                    self.telemetry.incr("upgrade_pending_unsupported")
        if upgraded_to is not None:
            log = app_versions.run_migrations(self, self.app_version, upgraded_to)
            self._set_app_version(upgraded_to)
            self.upgrade.consume_upgrade()
            self.telemetry.incr("upgrades")
            return {
                "attestations": attestations,
                "gov": gov_events,
                "upgraded_to": upgraded_to,
                "migrations": log,
            }
        return {"attestations": attestations, "gov": gov_events}

    def finalize_block(
        self,
        block_txs: List[bytes],
        height: int,
        time_ns: int,
        data_root: bytes,
        proposer: Optional[bytes] = None,
        votes: Optional[List[Tuple[bytes, bool]]] = None,
    ) -> Tuple[List[TxResult], dict, bytes]:
        """Begin -> deliver all -> end -> record data root -> commit.

        Returns (tx results, end-block response, app hash)."""
        self.begin_block(height, time_ns, proposer, votes)
        results = [self.deliver_tx(raw) for raw in block_txs]
        self.blobstream.record_data_root(height, data_root)
        end = self.end_block(height, time_ns)
        app_hash = self.store.commit(height)
        # reset the CheckTx state to the fresh committed state (baseapp
        # resets checkState on Commit; pending mempool txs get recheck'd)
        self._check_state = None
        return results, end, app_hash

    # ------------------------------------------------------------------
    # export / load (checkpoint-resume surface)
    # ------------------------------------------------------------------

    def export_genesis(self) -> dict:
        """ExportAppStateAndValidators parity (export.go:18-45)."""
        return {
            "chain_id": self.chain_id,
            "app_version": self.app_version,
            "genesis_time_ns": self.genesis_time_ns,
            "codec": self.codec,
            "state": self.store.export(),
        }

    def _restore_codec_from_meta(self) -> None:
        """Re-activate the codec a restored state was created under.
        Legacy state (pre-ADR-012, no persisted codec) was ALWAYS the
        lagrange codec — defaulting it to leopard would silently change
        parity bytes against the chain's own committed roots."""
        from celestia_tpu.ops import gf256 as _gf256

        raw = self.store.store("meta").get(b"codec")
        self.codec = raw.decode() if raw else _gf256.CODEC_LAGRANGE
        _gf256.set_active_codec(self.codec)

    @classmethod
    def import_genesis(cls, dump: dict, **kwargs) -> "App":
        app = cls(chain_id=dump["chain_id"], **kwargs)
        app.store = MultiStore.import_state(dump["state"])
        for name in STORE_NAMES:
            app.store.ensure_store(name)
        app._restore_codec_from_meta()
        if "codec" in dump:  # explicit dump key wins (they should agree)
            from celestia_tpu.ops import gf256 as _gf256

            app.codec = dump["codec"]
            _gf256.set_active_codec(app.codec)
        app._wire_keepers()
        app.genesis_time_ns = dump.get("genesis_time_ns", 0)
        app.store.commit(1)
        return app

    def load_height(self, height: int) -> None:
        """Roll back to a committed height (app.go:729 LoadHeight)."""
        self.store.load_height(height)
        self._wire_keepers()

    @classmethod
    def restore_from_snapshot(
        cls,
        chain_id: str,
        state: dict,
        height: int,
        expected_app_hash: bytes,
        genesis_time_ns: int = 0,
        **kwargs,
    ) -> "App":
        """Rebuild an App from a state-sync snapshot (the restore half of
        the reference's snapshot subsystem, root.go:227-243).  The restored
        multistore must reproduce the snapshot's recorded app hash."""
        app = cls(chain_id=chain_id, **kwargs)
        app.store = MultiStore.import_state(state)
        for name in STORE_NAMES:
            app.store.ensure_store(name)
        app._restore_codec_from_meta()
        app._wire_keepers()
        app.genesis_time_ns = genesis_time_ns
        got = app.store.app_hash()
        if got != expected_app_hash:
            raise ValueError(
                f"snapshot restore hash mismatch: state hashes to "
                f"{got.hex()}, snapshot recorded {expected_app_hash.hex()}"
            )
        app.store.commit_at(height, got)
        return app

    @classmethod
    def restore_from_disk(
        cls,
        state: "Dict[str, Dict[bytes, bytes]]",
        height: int,
        expected_app_hash: bytes,
        **kwargs,
    ) -> "App":
        """Rebuild an App from a recovered state.log (state.disk), the
        LoadLatestVersion role of app/app.go:657-661.  The replayed state
        must reproduce the last committed app hash or recovery refuses."""
        app = cls(**kwargs)
        app.store = MultiStore.from_raw(state)
        for name in STORE_NAMES:
            app.store.ensure_store(name)
        # identity first: _wire_keepers bakes chain_id into the IBC stack
        meta = app.store.store("meta")
        raw_ts = meta.get(b"genesis_time_ns")
        app.genesis_time_ns = int.from_bytes(raw_ts, "big") if raw_ts else 0
        raw_cid = meta.get(b"chain_id")
        if raw_cid:
            app.chain_id = raw_cid.decode()
        app._restore_codec_from_meta()
        app._wire_keepers()
        got = app.store.app_hash()
        if got != expected_app_hash:
            raise ValueError(
                f"disk recovery hash mismatch: replayed state hashes to "
                f"{got.hex()}, log recorded {expected_app_hash.hex()}"
            )
        app.store.commit_at(height, got)
        app.block_height = height
        return app
