"""x/auth equivalent: account records (pubkey, account number, sequence).

Parity role: cosmos-sdk auth keeper as used by the reference's ante chain
(sig verification + nonce increment, SURVEY.md §2.1 "Ante chain").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from celestia_tpu.da.shares import _read_varint, _varint
from celestia_tpu.state.store import KVStore

_ACCOUNT_PREFIX = b"acc/"
_GLOBAL_NUM_KEY = b"next_account_number"


@dataclass
class Account:
    address: bytes
    pubkey: bytes  # 33-byte compressed, b"" until first tx
    account_number: int
    sequence: int

    def marshal(self) -> bytes:
        out = bytearray()
        out += _varint(len(self.pubkey))
        out += self.pubkey
        out += _varint(self.account_number)
        out += _varint(self.sequence)
        return bytes(out)

    @classmethod
    def unmarshal(cls, address: bytes, raw: bytes) -> "Account":
        n, pos = _read_varint(raw, 0)
        pubkey = raw[pos : pos + n]
        pos += n
        num, pos = _read_varint(raw, pos)
        seq, pos = _read_varint(raw, pos)
        return cls(address, pubkey, num, seq)


class AccountKeeper:
    def __init__(self, store: KVStore):
        self.store = store

    def get(self, address: bytes) -> Optional[Account]:
        raw = self.store.get(_ACCOUNT_PREFIX + address)
        if raw is None:
            return None
        return Account.unmarshal(address, raw)

    def set(self, acc: Account) -> None:
        self.store.set(_ACCOUNT_PREFIX + acc.address, acc.marshal())

    def peek(self, address: bytes) -> "Account":
        """Non-mutating read for query paths: the existing account, or the
        account AS IT WOULD BE CREATED (next global number, sequence 0)
        without writing anything.  Queries must never touch consensus
        state — a query-created account would fork the app hash between
        nodes that did and didn't serve it."""
        acc = self.get(address)
        if acc is not None:
            return acc
        num_raw = self.store.get(_GLOBAL_NUM_KEY)
        num = int.from_bytes(num_raw, "big") if num_raw else 0
        return Account(address, b"", num, 0)

    def get_or_create(self, address: bytes) -> Account:
        acc = self.get(address)
        if acc is None:
            num_raw = self.store.get(_GLOBAL_NUM_KEY)
            num = int.from_bytes(num_raw, "big") if num_raw else 0
            self.store.set(_GLOBAL_NUM_KEY, (num + 1).to_bytes(8, "big"))
            acc = Account(address, b"", num, 0)
            self.set(acc)
        return acc

    def increment_sequence(self, address: bytes) -> None:
        acc = self.get(address)
        if acc is None:
            raise KeyError(f"unknown account {address.hex()}")
        acc.sequence += 1
        self.set(acc)
