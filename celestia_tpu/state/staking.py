"""Staking keeper (lite): validators, voting power, delegations, unbonding,
and staking hooks.

Parity role: the cosmos-sdk staking keeper surface the reference actually
depends on — validator set + powers for x/upgrade's 5/6 quorum tally
(x/upgrade/keeper.go:137 TallyVotingPower) and for x/blobstream valsets
(keeper_valset.go GetCurrentValset), plus AfterValidatorBeginUnbonding /
AfterValidatorCreated hooks that trigger valset attestations
(x/blobstream/keeper/hooks.go:24-43).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from celestia_tpu.da.shares import _read_varint, _varint
from celestia_tpu.state.bank import BONDED_POOL, NOT_BONDED_POOL, BankKeeper
from celestia_tpu.state.store import KVStore

_VAL_PREFIX = b"val/"
_DEL_PREFIX = b"del/"

POWER_REDUCTION = 1_000_000  # utia per unit of consensus power


DEFAULT_COMMISSION_PPM = 100_000  # 10% validator commission


@dataclass
class Validator:
    operator: bytes  # 20-byte address
    tokens: int  # bonded utia
    jailed: bool = False
    commission_ppm: int = DEFAULT_COMMISSION_PPM
    # ns timestamp until which a jailed validator cannot unjail (x/slashing)
    jailed_until_ns: int = 0
    # tombstoned validators (double-signers) can never unjail
    tombstoned: bool = False

    @property
    def power(self) -> int:
        return self.tokens // POWER_REDUCTION

    def marshal(self) -> bytes:
        out = bytearray()
        out += _varint(self.tokens)
        out += _varint(1 if self.jailed else 0)
        out += _varint(self.commission_ppm)
        out += _varint(self.jailed_until_ns)
        out += _varint(1 if self.tombstoned else 0)
        return bytes(out)

    @classmethod
    def unmarshal(cls, operator: bytes, raw: bytes) -> "Validator":
        tokens, pos = _read_varint(raw, 0)
        jailed, pos = _read_varint(raw, pos)
        commission, pos = _read_varint(raw, pos)
        jailed_until, pos = _read_varint(raw, pos)
        tombstoned, pos = _read_varint(raw, pos)
        return cls(
            operator, tokens, bool(jailed), commission, jailed_until,
            bool(tombstoned),
        )


class StakingKeeper:
    def __init__(self, store: KVStore, bank: BankKeeper):
        self.store = store
        self.bank = bank
        # blobstream subscribes to these (x/blobstream/keeper/hooks.go)
        self.hooks_after_validator_created: List[Callable[[bytes], None]] = []
        self.hooks_after_unbonding_initiated: List[Callable[[bytes], None]] = []
        # x/distribution subscribes: rewards must be settled before a
        # delegation's stake changes, and the reference point re-anchored
        # at the new stake afterwards (F1 period semantics)
        self.hooks_before_delegation_modified: List[
            Callable[[bytes, bytes], None]
        ] = []
        self.hooks_after_delegation_modified: List[
            Callable[[bytes, bytes], None]
        ] = []

    # --- validators -------------------------------------------------------

    def validator(self, operator: bytes) -> Optional[Validator]:
        raw = self.store.get(_VAL_PREFIX + operator)
        return Validator.unmarshal(operator, raw) if raw is not None else None

    def set_validator(self, v: Validator) -> None:
        self.store.set(_VAL_PREFIX + v.operator, v.marshal())

    def validators(self) -> List[Validator]:
        return [
            Validator.unmarshal(k[len(_VAL_PREFIX):], v)
            for k, v in self.store.iterate(_VAL_PREFIX)
        ]

    def bonded_validators(self) -> List[Validator]:
        return [v for v in self.validators() if not v.jailed and v.power > 0]

    def total_power(self) -> int:
        return sum(v.power for v in self.bonded_validators())

    def create_validator(self, operator: bytes, self_delegation: int) -> Validator:
        if self.validator(operator) is not None:
            raise ValueError("validator already exists")
        v = Validator(operator, 0)
        self.set_validator(v)
        self.delegate(operator, operator, self_delegation)
        for hook in self.hooks_after_validator_created:
            hook(operator)
        return self.validator(operator)

    # --- delegations ------------------------------------------------------

    def delegation(self, delegator: bytes, operator: bytes) -> int:
        raw = self.store.get(_DEL_PREFIX + delegator + operator)
        return int.from_bytes(raw, "big") if raw else 0

    def delegate(self, delegator: bytes, operator: bytes, amount: int) -> None:
        v = self.validator(operator)
        if v is None:
            raise ValueError(f"unknown validator {operator.hex()}")
        for hook in self.hooks_before_delegation_modified:
            hook(delegator, operator)
        self.bank.send(delegator, BONDED_POOL, amount)
        v.tokens += amount
        self.set_validator(v)
        self.store.set(
            _DEL_PREFIX + delegator + operator,
            (self.delegation(delegator, operator) + amount).to_bytes(16, "big"),
        )
        for hook in self.hooks_after_delegation_modified:
            hook(delegator, operator)

    def undelegate(self, delegator: bytes, operator: bytes, amount: int) -> None:
        """Begin unbonding; tokens move to the not-bonded pool immediately
        (unbonding period bookkeeping is tracked by consumers via hooks)."""
        v = self.validator(operator)
        if v is None:
            raise ValueError(f"unknown validator {operator.hex()}")
        cur = self.delegation(delegator, operator)
        if cur < amount:
            raise ValueError("undelegate amount exceeds delegation")
        for hook in self.hooks_before_delegation_modified:
            hook(delegator, operator)
        self.store.set(
            _DEL_PREFIX + delegator + operator, (cur - amount).to_bytes(16, "big")
        )
        v.tokens -= amount
        self.set_validator(v)
        self.bank.send(BONDED_POOL, NOT_BONDED_POOL, amount)
        for hook in self.hooks_after_delegation_modified:
            hook(delegator, operator)
        # delegator claim tracked out-of-band; release at maturity not modeled
        for hook in self.hooks_after_unbonding_initiated:
            hook(operator)

    def powers_snapshot(self) -> Dict[bytes, int]:
        return {v.operator: v.power for v in self.bonded_validators()}

    # --- punitive surface (x/slashing & x/evidence call these) ------------

    def slash(self, operator: bytes, fraction_ppm: int) -> int:
        """Burn fraction_ppm of the validator's bonded tokens (the SDK
        Slash path: tokens leave the bonded pool and the supply).

        Every DELEGATION to the validator is cut by the same fraction and
        the validator's tokens drop by exactly the sum of the cuts, so
        delegations always sum to validator tokens and the bonded pool
        stays 1:1 backed — without this, a post-slash undelegate would
        withdraw pre-slash amounts, draining other validators' backing
        (the SDK gets the same effect through its shares exchange rate).
        Returns the burned amount."""
        v = self.validator(operator)
        if v is None:
            raise ValueError(f"unknown validator {operator.hex()}")
        burn = 0
        for key, raw in list(self.store.iterate(_DEL_PREFIX)):
            if not key.endswith(operator):
                continue
            delegation = int.from_bytes(raw, "big")
            cut = delegation * fraction_ppm // 1_000_000
            if cut == 0:
                continue
            delegator = key[len(_DEL_PREFIX) : len(_DEL_PREFIX) + 20]
            # settle rewards at the pre-slash stake and re-anchor after —
            # a stale F1 reference point would over-pay rewards on stake
            # that no longer exists
            for hook in self.hooks_before_delegation_modified:
                hook(delegator, operator)
            self.store.set(key, (delegation - cut).to_bytes(16, "big"))
            burn += cut
            for hook in self.hooks_after_delegation_modified:
                hook(delegator, operator)
        if burn == 0:
            return 0
        v.tokens -= burn
        self.set_validator(v)
        self.bank.burn(BONDED_POOL, burn)
        return burn

    def jail(self, operator: bytes, until_ns: int = 0) -> None:
        v = self.validator(operator)
        if v is None:
            raise ValueError(f"unknown validator {operator.hex()}")
        v.jailed = True
        v.jailed_until_ns = max(v.jailed_until_ns, until_ns)
        self.set_validator(v)

    def unjail(self, operator: bytes, now_ns: int) -> None:
        v = self.validator(operator)
        if v is None:
            raise ValueError(f"unknown validator {operator.hex()}")
        if not v.jailed:
            raise ValueError("validator is not jailed")
        if v.tombstoned:
            raise ValueError("validator is tombstoned (double-sign); cannot unjail")
        if now_ns < v.jailed_until_ns:
            raise ValueError(
                f"validator jailed until t={v.jailed_until_ns}ns (now {now_ns}ns)"
            )
        v.jailed = False
        self.set_validator(v)

    def tombstone(self, operator: bytes) -> None:
        v = self.validator(operator)
        if v is None:
            raise ValueError(f"unknown validator {operator.hex()}")
        v.jailed = True
        v.tombstoned = True
        self.set_validator(v)
